"""Checkpoint save/restore/import/trim (SURVEY.md §2.12, §2.29, §3.5)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sat_tpu.config import Config
from sat_tpu.models.captioner import init_variables
from sat_tpu.train.checkpoint import (
    latest_checkpoint,
    load_flat,
    load_pretrained_cnn,
    restore_checkpoint,
    save_checkpoint,
    state_to_flat,
    trim_checkpoint,
)
from sat_tpu.train.step import create_train_state, make_jit_train_step


TINY = dict(
    image_size=32,
    vocabulary_size=50,
    dim_embedding=8,
    num_lstm_units=8,
    dim_initialize_layer=8,
    dim_attend_layer=8,
    dim_decode_layer=16,
    max_caption_length=5,
    compute_dtype="float32",
)


def _tiny_config(**kw):
    return Config(**{**TINY, **kw})


def _batch(config, rng, B=2):
    T = config.max_caption_length
    return {
        "images": jnp.asarray(
            rng.normal(size=(B, config.image_size, config.image_size, 3)).astype(
                np.float32
            )
        ),
        "word_idxs": jnp.asarray(
            rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
        ),
        "masks": jnp.ones((B, T), jnp.float32),
    }


def test_save_restore_roundtrip(tmp_path, rng):
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)
    state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(1))

    path = save_checkpoint(state, config)
    assert path.endswith("1.npz")
    assert latest_checkpoint(str(tmp_path)) == path

    fresh = create_train_state(jax.random.PRNGKey(7), config)
    restored, count = restore_checkpoint(fresh, save_dir=str(tmp_path))
    assert count > 0
    assert int(restored.step) == 1

    want = state_to_flat(state)
    got = state_to_flat(restored)
    assert set(want) == set(got)
    for k in want:
        np.testing.assert_allclose(want[k], got[k], err_msg=k)

    # restored state must keep training (optimizer slots intact)
    restored2, _ = step(restored, _batch(config, rng), jax.random.PRNGKey(2))
    assert int(restored2.step) == 2


def test_restore_latest_picks_newest(tmp_path, rng):
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)
    save_checkpoint(state, config)                     # 0.npz
    state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(1))
    save_checkpoint(state, config)                     # 1.npz
    assert latest_checkpoint(str(tmp_path)).endswith("1.npz")


def test_trimmed_checkpoint_partial_restores(tmp_path, rng):
    """Trim drops optimizer slots; the slim file still restores params —
    the reference's trim_model.py + tolerant load path."""
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)
    state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(1))
    path = save_checkpoint(state, config)

    slim = str(tmp_path / "slim.npz")
    kept = trim_checkpoint(path, slim)
    flat = load_flat(slim)
    assert kept == len(flat)
    assert not any(k.startswith("optimizer/") for k in flat)
    assert any(k.startswith("params/") for k in flat)

    fresh = create_train_state(jax.random.PRNGKey(9), config)
    restored, count = restore_checkpoint(fresh, model_file=slim)
    assert count > 0
    want = state_to_flat(state)
    got = state_to_flat(restored)
    for k in want:
        if k.startswith("params/") or k == "global_step":
            np.testing.assert_allclose(want[k], got[k], err_msg=k)


@pytest.mark.parametrize("cnn", ["vgg16", "resnet50"])
def test_pretrained_cnn_import(tmp_path, cnn):
    """Nested {op: {param: arr}} npy import — the reference's
    vgg16_no_fc.npy / resnet50_no_fc.npy format (base_model.py:280-297)."""
    config = _tiny_config(cnn=cnn, image_size=64)
    variables = init_variables(jax.random.PRNGKey(0), config)

    if cnn == "vgg16":
        kshape = tuple(variables["params"]["cnn"]["conv1_1"]["conv"]["kernel"].shape)
        nested = {
            "conv1_1": {
                "weights": np.full(kshape, 0.5, np.float32),
                "biases": np.full((kshape[-1],), 0.25, np.float32),
            },
            "not_a_layer": {"weights": np.zeros((3, 3, 1, 1), np.float32)},
        }
        want_loaded = 2
    else:
        k1 = tuple(variables["params"]["cnn"]["conv1"]["conv"]["kernel"].shape)
        k2 = tuple(
            variables["params"]["cnn"]["res2a"]["res2a_branch2a"]["conv"]["kernel"].shape
        )
        c = k1[-1]
        nested = {
            "conv1": {"weights": np.full(k1, 0.5, np.float32)},
            "bn_conv1": {
                "scale": np.full((c,), 2.0, np.float32),
                "offset": np.full((c,), 0.1, np.float32),
                "mean": np.full((c,), 0.3, np.float32),
                "variance": np.full((c,), 0.9, np.float32),
            },
            "res2a_branch2a": {"weights": np.full(k2, 0.25, np.float32)},
        }
        want_loaded = 6

    path = str(tmp_path / f"{cnn}_no_fc.npy")
    np.save(path, np.array(nested, dtype=object), allow_pickle=True)

    new_vars, count = load_pretrained_cnn(variables, path)
    assert count == want_loaded

    if cnn == "vgg16":
        np.testing.assert_allclose(
            np.asarray(new_vars["params"]["cnn"]["conv1_1"]["conv"]["kernel"]), 0.5
        )
        np.testing.assert_allclose(
            np.asarray(new_vars["params"]["cnn"]["conv1_1"]["conv"]["bias"]), 0.25
        )
    else:
        np.testing.assert_allclose(
            np.asarray(new_vars["params"]["cnn"]["bn_conv1"]["scale"]), 2.0
        )
        np.testing.assert_allclose(
            np.asarray(new_vars["batch_stats"]["bn_conv1"]["mean"]), 0.3
        )
        np.testing.assert_allclose(
            np.asarray(
                new_vars["params"]["cnn"]["res2a"]["res2a_branch2a"]["conv"]["kernel"]
            ),
            0.25,
        )


def test_vgg16_no_fc_real_layout_imports_fully(tmp_path):
    """Import the layout-exact vgg16_no_fc.npy twin (all 13 convs,
    weights/biases names, HWIO shapes) — every tensor must land."""
    from tests.ref_layouts import make_vgg16_no_fc

    config = _tiny_config(cnn="vgg16", image_size=224)
    variables = init_variables(jax.random.PRNGKey(0), config)
    path = str(tmp_path / "vgg16_no_fc.npy")
    nested = make_vgg16_no_fc(path)

    new_vars, count = load_pretrained_cnn(variables, path)
    assert count == 26  # 13 convs × (weights, biases)
    for op in ("conv1_1", "conv3_2", "conv5_3"):
        np.testing.assert_array_equal(
            np.asarray(new_vars["params"]["cnn"][op]["conv"]["kernel"]),
            nested[op]["weights"],
        )
        np.testing.assert_array_equal(
            np.asarray(new_vars["params"]["cnn"][op]["conv"]["bias"]),
            nested[op]["biases"],
        )


def test_resnet50_no_fc_real_layout_imports_fully(tmp_path):
    """resnet50_no_fc.npy twin: 53 bias-free convs + 53 BN entries with
    caffe mean/variance/scale/offset names."""
    from tests.ref_layouts import make_resnet50_no_fc

    config = _tiny_config(cnn="resnet50", image_size=224)
    variables = init_variables(jax.random.PRNGKey(0), config)
    path = str(tmp_path / "resnet50_no_fc.npy")
    nested = make_resnet50_no_fc(path)

    new_vars, count = load_pretrained_cnn(variables, path)
    assert count == 53 + 53 * 4  # convs + BN {scale,offset,mean,variance}
    np.testing.assert_array_equal(
        np.asarray(
            new_vars["params"]["cnn"]["res4c"]["res4c_branch2b"]["conv"]["kernel"]
        ),
        nested["res4c_branch2b"]["weights"],
    )
    np.testing.assert_array_equal(
        np.asarray(new_vars["batch_stats"]["res3a"]["bn3a_branch1"]["mean"]),
        nested["bn3a_branch1"]["mean"],
    )
    np.testing.assert_array_equal(
        np.asarray(new_vars["params"]["cnn"]["res5c"]["bn5c_branch2c"]["scale"]),
        nested["bn5c_branch2c"]["scale"],
    )


def test_reference_train_checkpoint_decoder_logit_parity(tmp_path):
    """Import a flat TF1-name checkpoint (lstm/lstm_cell concatenated
    kernel, i-j-f-o gates) and check our decoder reproduces, bit-for-math,
    a numpy oracle computing the reference semantics straight from the
    checkpoint arrays — the 'silently wrong gate order' trap (SURVEY §7)."""
    from sat_tpu.models.decoder import decoder_step, init_state
    from sat_tpu.train.checkpoint import import_reference_checkpoint
    from tests.ref_layouts import make_reference_train_checkpoint

    config = _tiny_config()  # vgg16 @ 32px → N=4, D=512
    path = str(tmp_path / "1234.npy")
    flat = make_reference_train_checkpoint(path, config, include_cnn=True)

    state = create_train_state(jax.random.PRNGKey(0), config)
    new_state, count = import_reference_checkpoint(state, path)
    # decoder: emb 1 + initialize 8 + attend 5 + lstm 2 + decode 4 = 20
    # cnn: 26.  Optimizer slots skipped.
    assert count == 46
    # the foreign step counter is NOT adopted by default (it would drive
    # the resume fast-forward); opt-in via restore_step
    assert int(new_state.step) == 0
    stepped, _ = import_reference_checkpoint(state, path, restore_step=True)
    assert int(stepped.step) == 1234

    B, N, D = 3, config.num_ctx, config.dim_ctx
    rng = np.random.default_rng(3)
    contexts = rng.normal(0, 1, (B, N, D)).astype(np.float32)
    word = np.asarray([1, 4, 7], np.int32)

    # ---- numpy oracle from the raw checkpoint arrays ----
    def dense(name, x, tanh=False):
        y = x @ flat[f"{name}/kernel:0"]
        if f"{name}/bias:0" in flat:
            y = y + flat[f"{name}/bias:0"]
        return np.tanh(y) if tanh else y

    def sigmoid(x):
        return 1.0 / (1.0 + np.exp(-x))

    ctx_mean = contexts.mean(axis=1)
    memory0 = dense("initialize/fc_a2", dense("initialize/fc_a1", ctx_mean, True))
    output0 = dense("initialize/fc_b2", dense("initialize/fc_b1", ctx_mean, True))

    t1 = dense("attend/fc_1a", contexts, True)               # [B,N,da]
    t2 = dense("attend/fc_1b", output0, True)                # [B,da]
    att_logits = dense("attend/fc_2", t1 + t2[:, None, :])[..., 0]
    e = np.exp(att_logits - att_logits.max(-1, keepdims=True))
    alpha = e / e.sum(-1, keepdims=True)
    context = (contexts * alpha[..., None]).sum(axis=1)

    emb = flat["word_embedding/weights:0"][word]
    z = (
        np.concatenate([context, emb, output0], axis=-1)
        @ flat["lstm/lstm_cell/kernel:0"]
        + flat["lstm/lstm_cell/bias:0"]
    )
    i, j, f, o = np.split(z, 4, axis=-1)
    c1 = sigmoid(f + 1.0) * memory0 + sigmoid(i) * np.tanh(j)
    h1 = sigmoid(o) * np.tanh(c1)
    expanded = np.concatenate([h1, context, emb], axis=-1)
    want_logits = dense("decode/fc_2", dense("decode/fc_1", expanded, True))

    # ---- our decoder with the imported params ----
    params = jax.tree_util.tree_map(np.asarray, new_state.params)["decoder"]
    state0 = init_state(params, config, jnp.asarray(contexts), train=False)
    np.testing.assert_allclose(np.asarray(state0.memory), memory0, atol=1e-4)
    np.testing.assert_allclose(np.asarray(state0.output), output0, atol=1e-4)
    state1, got_logits, got_alpha = decoder_step(
        params, config, jnp.asarray(contexts), state0, jnp.asarray(word),
        train=False,
    )
    np.testing.assert_allclose(np.asarray(got_alpha), alpha, atol=1e-4)
    np.testing.assert_allclose(np.asarray(state1.memory), c1, atol=1e-4)
    np.testing.assert_allclose(np.asarray(state1.output), h1, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got_logits), want_logits, atol=1e-3)


def test_torn_config_json_falls_back_to_scan(tmp_path, rng):
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    path = save_checkpoint(state, config)
    with open(tmp_path / "config.json", "w") as f:
        f.write('{"phase": "tr')  # torn mid-write
    assert latest_checkpoint(str(tmp_path)) == path


def test_global_step_alone_is_not_a_restore(tmp_path, rng):
    """count==0 must mean 'no tensors restored' — the always-present
    global_step entry may not inflate the count."""
    np.savez(tmp_path / "7.npz", global_step=np.asarray(7, np.int32))

    config = _tiny_config(save_dir=str(tmp_path))
    fresh = create_train_state(jax.random.PRNGKey(1), config)
    restored, count = restore_checkpoint(fresh, model_file=str(tmp_path / "7.npz"))
    assert count == 0
    assert int(restored.step) == 7


def test_stale_config_pointer_does_not_shadow_newer_checkpoint(tmp_path, rng):
    """Preemption between the npz rename and the config.json update must
    not lose the newest checkpoint."""
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)
    save_checkpoint(state, config)                     # 0.npz + pointer→0
    state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(1))
    save_checkpoint(state, config)                     # 1.npz + pointer→1
    config.replace(global_step=0).save(str(tmp_path / "config.json"))  # stale
    assert latest_checkpoint(str(tmp_path)).endswith("1.npz")


@pytest.mark.parametrize("cnn", ["vgg16", "resnet50"])
def test_export_import_reference_roundtrip(tmp_path, cnn):
    """export_reference_checkpoint is the exact inverse of
    import_reference_checkpoint: a state exported to the reference's flat
    TF1 layout and imported into a differently-seeded fresh state must
    reproduce every param (and BN stat) bit-for-bit — the migration path
    in both directions, proven on real trees of both encoder families."""
    from sat_tpu.train.checkpoint import (
        export_reference_checkpoint,
        import_reference_checkpoint,
    )

    config = _tiny_config(cnn=cnn, train_cnn=True)
    src = create_train_state(jax.random.PRNGKey(0), config)
    path = str(tmp_path / "ref_export.npy")
    n_written = export_reference_checkpoint(src, path)

    # every param leaf + every BN stat leaf must have been exported
    n_leaves = len(jax.tree_util.tree_leaves(src.params)) + len(
        jax.tree_util.tree_leaves(src.batch_stats)
    )
    assert n_written == n_leaves

    dst = create_train_state(jax.random.PRNGKey(7), config)
    before = jax.tree_util.tree_leaves(dst.params)
    after_src = jax.tree_util.tree_leaves(src.params)
    assert any(
        not np.array_equal(a, b) for a, b in zip(before, after_src)
    ), "seeds produced identical params; test is vacuous"

    imported, n_loaded = import_reference_checkpoint(dst, path)
    assert n_loaded == n_written
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(src.params)[0],
        jax.tree_util.tree_flatten_with_path(imported.params)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(src.batch_stats)[0],
        jax.tree_util.tree_flatten_with_path(imported.batch_stats)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=str(pa))


def test_async_writer_matches_sync_save(tmp_path, rng):
    """AsyncCheckpointWriter must produce byte-equivalent checkpoints to
    the synchronous path, in submission order, and close() must drain."""
    from sat_tpu.train.checkpoint import AsyncCheckpointWriter

    config = _tiny_config(save_dir=str(tmp_path / "async"))
    os.makedirs(config.save_dir, exist_ok=True)
    sync_dir = str(tmp_path / "sync")
    os.makedirs(sync_dir, exist_ok=True)

    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)

    with AsyncCheckpointWriter() as w:
        for i in range(3):
            state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(i))
            w.save(state, config)
        save_checkpoint(state, config, save_dir=sync_dir)
    # post-close: all three landed, newest wins, contents match sync
    assert latest_checkpoint(config.save_dir).endswith("3.npz")
    a = dict(np.load(os.path.join(config.save_dir, "3.npz")))
    s = dict(np.load(os.path.join(sync_dir, "3.npz")))
    assert set(a) == set(s)
    for k in a:
        np.testing.assert_array_equal(a[k], s[k], err_msg=k)
    # config.json sidecar carries the latest step
    import json
    assert json.load(open(os.path.join(config.save_dir, "config.json")))[
        "global_step"
    ] == 3


def test_async_writer_surfaces_write_failure(tmp_path, rng):
    """A worker failure (unwritable dir) must raise on close, not vanish."""
    import pytest

    from sat_tpu.train.checkpoint import AsyncCheckpointWriter

    # a FILE where the save dir should be: the write itself must fail
    # (atomic_write creates missing directories, so a merely-absent dir
    # would succeed)
    blocker = tmp_path / "blocker"
    blocker.write_text("not a directory")
    config = _tiny_config(save_dir=str(blocker / "sub"))
    state = create_train_state(jax.random.PRNGKey(0), config)

    w = AsyncCheckpointWriter()
    w.save(state, config)  # queues a write that cannot land
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        w.close()


def test_async_writer_failure_is_permanent(tmp_path, rng):
    """Once a write fails, EVERY subsequent save/close re-raises — a
    failure raised from save() must not be cleared so that close()
    reports success (ADVICE r3: the old code popped _error on read)."""
    import pytest

    from sat_tpu.train.checkpoint import AsyncCheckpointWriter

    blocker = tmp_path / "blocker"
    blocker.write_text("not a directory")
    bad = _tiny_config(save_dir=str(blocker / "sub"))
    good = _tiny_config(save_dir=str(tmp_path / "ok"))
    state = create_train_state(jax.random.PRNGKey(0), bad)

    w = AsyncCheckpointWriter()
    w.save(state, bad)
    # wait for the worker to consume the doomed item and record the error
    import time

    for _ in range(100):
        if w._error is not None:
            break
        time.sleep(0.05)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        w.save(state, good)  # surfaced here first...
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        w.save(state, good)  # ...and permanently thereafter
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        w.close()


def test_train_loop_async_checkpoints_restore(coco_fixture, tmp_path):
    """runtime.train with async_checkpoint on: periodic + final saves all
    land, and the final checkpoint restores to the final step."""
    from sat_tpu import runtime

    cfg = coco_fixture["config"].replace(
        **{**TINY,
           "max_caption_length": 20,  # TINY's 5 filters out every caption
           # private cache paths: TINY's vocabulary_size=50 must not
           # rebuild the session-shared fixture caches other tests load
           "vocabulary_file": str(tmp_path / "vocab.csv"),
           "temp_annotation_file": str(tmp_path / "anns.csv"),
           "temp_data_file": str(tmp_path / "data.npy"),
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "save_period": 2,
           "max_steps": 5,
           "num_epochs": 50,
           "async_checkpoint": True}
    )
    state = runtime.train(cfg)
    names = sorted(os.listdir(cfg.save_dir))
    assert "2.npz" in names and "4.npz" in names and "5.npz" in names
    fresh = create_train_state(jax.random.PRNGKey(3), cfg)
    restored, n = restore_checkpoint(fresh, save_dir=cfg.save_dir)
    assert n > 0 and int(restored.step) == 5
