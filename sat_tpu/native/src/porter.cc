// Porter stemmer — the original 1980 algorithm.
//
// Native replacement half of the METEOR scorer (the reference delegates
// stemming to the external meteor-1.5.jar, /root/reference/utils/coco/
// pycocoevalcap/meteor/meteor.py:15-19).  Implemented from the published
// algorithm description (Porter, "An algorithm for suffix stripping",
// Program 14(3) 1980); kept in lockstep with nltk's ORIGINAL_ALGORITHM
// mode, which the Python fallback uses (sat_tpu/evalcap/meteor.py).

#include <cctype>
#include <string>

namespace sat_native {

namespace {

bool is_consonant(const std::string& w, int i) {
  char c = w[i];
  if (c == 'a' || c == 'e' || c == 'i' || c == 'o' || c == 'u') return false;
  if (c == 'y') return i == 0 ? true : !is_consonant(w, i - 1);
  return true;
}

// measure m(): number of VC sequences in w[0..end]
int measure(const std::string& w) {
  int m = 0;
  int i = 0;
  int n = static_cast<int>(w.size());
  while (i < n && is_consonant(w, i)) i++;          // leading C*
  while (i < n) {
    while (i < n && !is_consonant(w, i)) i++;       // V+
    if (i >= n) break;
    while (i < n && is_consonant(w, i)) i++;        // C+
    m++;
  }
  return m;
}

bool contains_vowel(const std::string& w) {
  for (int i = 0; i < static_cast<int>(w.size()); i++)
    if (!is_consonant(w, i)) return true;
  return false;
}

bool double_consonant(const std::string& w) {
  int n = static_cast<int>(w.size());
  return n >= 2 && w[n - 1] == w[n - 2] && is_consonant(w, n - 1);
}

// *o: stem ends cvc where the final c is not w, x or y
bool ends_cvc(const std::string& w) {
  int n = static_cast<int>(w.size());
  if (n < 3) return false;
  char c = w[n - 1];
  return is_consonant(w, n - 3) && !is_consonant(w, n - 2) &&
         is_consonant(w, n - 1) && c != 'w' && c != 'x' && c != 'y';
}

bool ends_with(const std::string& w, const std::string& suf) {
  return w.size() >= suf.size() &&
         w.compare(w.size() - suf.size(), suf.size(), suf) == 0;
}

std::string chop(const std::string& w, size_t k) {
  return w.substr(0, w.size() - k);
}

// apply first matching (suffix, replacement) rule whose stem measure
// condition holds; returns true if a rule's suffix matched (even if the
// condition failed — Porter's rules stop at the first suffix match)
struct Rule {
  const char* suf;
  const char* rep;
  int min_m;  // condition: m(stem) > min_m  (−1 = unconditional)
};

bool apply_rules(std::string* w, const Rule* rules, int n_rules) {
  for (int r = 0; r < n_rules; r++) {
    const std::string suf = rules[r].suf;
    if (ends_with(*w, suf)) {
      std::string stem = chop(*w, suf.size());
      if (rules[r].min_m < 0 || measure(stem) > rules[r].min_m) {
        *w = stem + rules[r].rep;
      }
      return true;
    }
  }
  return false;
}

}  // namespace

std::string porter_stem(const std::string& input) {
  std::string w = input;
  // nltk's PorterStemmer.stem() lowercases before the steps; match it
  // (ASCII only — callers gate non-ASCII to the Python path).
  for (char& c : w) c = std::tolower(static_cast<unsigned char>(c));
  if (w.empty()) return w;
  // No short-word guard: nltk's ORIGINAL_ALGORITHM mode (which the Python
  // fallback is pinned to) applies the steps to words of every length.

  // ---- step 1a
  if (ends_with(w, "sses")) w = chop(w, 2);
  else if (ends_with(w, "ies")) w = chop(w, 2);
  else if (ends_with(w, "ss")) { /* unchanged */ }
  else if (ends_with(w, "s")) w = chop(w, 1);

  // ---- step 1b
  bool did_1b_23 = false;
  if (ends_with(w, "eed")) {
    if (measure(chop(w, 3)) > 0) w = chop(w, 1);
  } else if (ends_with(w, "ed")) {
    if (contains_vowel(chop(w, 2))) { w = chop(w, 2); did_1b_23 = true; }
  } else if (ends_with(w, "ing")) {
    if (contains_vowel(chop(w, 3))) { w = chop(w, 3); did_1b_23 = true; }
  }
  if (did_1b_23) {
    if (ends_with(w, "at") || ends_with(w, "bl") || ends_with(w, "iz")) {
      w += "e";
    } else if (double_consonant(w) && !ends_with(w, "l") &&
               !ends_with(w, "s") && !ends_with(w, "z")) {
      w = chop(w, 1);
    } else if (measure(w) == 1 && ends_cvc(w)) {
      w += "e";
    }
  }

  // ---- step 1c
  if (ends_with(w, "y") && contains_vowel(chop(w, 1))) {
    w = chop(w, 1) + "i";
  }

  // ---- step 2  (condition m > 0)
  static const Rule step2[] = {
      {"ational", "ate", 0}, {"tional", "tion", 0}, {"enci", "ence", 0},
      {"anci", "ance", 0},   {"izer", "ize", 0},    {"abli", "able", 0},
      {"alli", "al", 0},     {"entli", "ent", 0},   {"eli", "e", 0},
      {"ousli", "ous", 0},   {"ization", "ize", 0}, {"ation", "ate", 0},
      {"ator", "ate", 0},    {"alism", "al", 0},    {"iveness", "ive", 0},
      {"fulness", "ful", 0}, {"ousness", "ous", 0}, {"aliti", "al", 0},
      {"iviti", "ive", 0},   {"biliti", "ble", 0},
  };
  apply_rules(&w, step2, sizeof(step2) / sizeof(Rule));

  // ---- step 3  (condition m > 0)
  static const Rule step3[] = {
      {"icate", "ic", 0}, {"ative", "", 0}, {"alize", "al", 0},
      {"iciti", "ic", 0}, {"ical", "ic", 0}, {"ful", "", 0}, {"ness", "", 0},
  };
  apply_rules(&w, step3, sizeof(step3) / sizeof(Rule));

  // ---- step 4  (condition m > 1; 'ion' additionally needs stem ending s/t)
  for (const char* suf :
       {"al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
        "ment", "ent", "ion", "ou", "ism", "ate", "iti", "ous", "ive",
        "ize"}) {
    std::string s = suf;
    if (ends_with(w, s)) {
      std::string stem = chop(w, s.size());
      if (measure(stem) > 1) {
        if (s == "ion") {
          if (!stem.empty() &&
              (stem.back() == 's' || stem.back() == 't')) {
            w = stem;
          }
        } else {
          w = stem;
        }
      }
      break;  // first suffix match wins
    }
  }

  // ---- step 5a
  if (ends_with(w, "e")) {
    std::string stem = chop(w, 1);
    int m = measure(stem);
    if (m > 1 || (m == 1 && !ends_cvc(stem))) w = stem;
  }
  // ---- step 5b
  if (measure(w) > 1 && double_consonant(w) && ends_with(w, "l")) {
    w = chop(w, 1);
  }
  return w;
}

}  // namespace sat_native
