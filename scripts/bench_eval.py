"""Eval-side decode throughput: images/sec at beam_size=3.

BASELINE.md declares this a to-be-measured metric (the reference publishes
none; its host-side beam loop does ~beam×20 sess.run round-trips per image,
/root/reference/base_model.py:184-212).  Measures the full on-device
pipeline per batch: VGG16 encode + batched beam-search scan, one dispatch.

Usage: python scripts/bench_eval.py [--batch 32] [--beam 3] [--iters 20]
       (add --cpu --image-size 64 for a smoke run off-TPU)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench_stamp() -> dict:
    # imported lazily: the stamp reads jax device facts only when the
    # bench already initialized a backend (sat_tpu.telemetry.bench_stamp)
    from sat_tpu.telemetry import bench_stamp

    return bench_stamp()

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--beam", type=int, default=3)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--cpu", action="store_true")
    # A/B control for the search's exact early exit (ops/beam_search.py);
    # note the random-init model here never emits eos from the top-K set,
    # so both arms measure the full-T worst case — the flag exists for
    # trained-checkpoint measurements via --params
    ap.add_argument("--no-early-exit", action="store_true")
    ap.add_argument(
        "--params",
        default=None,
        help="checkpoint .npz to decode with (a trained model terminates "
        "early; random init is the worst case)",
    )
    ap.add_argument(
        "--vocab",
        default=None,
        help="vocabulary CSV of the checkpoint's run — required with "
        "--params: derives the real '.' eos id and the valid_size mask "
        "the production decode applies (runtime.py decode_dataset)",
    )
    ap.add_argument(
        "--vocab-size",
        type=int,
        default=None,
        help="the checkpoint run's config.vocabulary_size (logit width) "
        "when it differs from the default",
    )
    ap.add_argument(
        "--encoder-quant",
        choices=("off", "bf16", "int8"),
        default="off",
        help="A/B the PTQ encoder (sat_tpu/nn/quant.py): measures the "
        "fp32 arm first, then the quantized arm over the SAME weights, "
        "emitting a second eval_images_per_sec_<mode> row",
    )
    args = ap.parse_args()
    if args.params and not args.vocab:
        ap.error("--params requires --vocab (eos id + valid_size must come "
                 "from the run's vocabulary, not a fixed index)")

    if args.cpu:
        # both mechanisms: the env's sitecustomize imports jax itself and
        # re-pins the platform (see tests/conftest.py)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")

    import jax

    from sat_tpu.config import Config
    from sat_tpu.models.captioner import init_variables

    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)}", file=sys.stderr, flush=True)

    config = Config(
        batch_size=args.batch, beam_size=args.beam, image_size=args.image_size
    )
    B = args.batch
    rng = np.random.default_rng(0)
    images = jax.device_put(
        rng.normal(size=(B, args.image_size, args.image_size, 3)).astype(np.float32)
    )
    eos = 1  # any fixed vocab index; random init never tops it → worst case
    valid_size = None
    if args.params:
        from sat_tpu.data.vocabulary import Vocabulary
        from sat_tpu.runtime import _eos_id
        from sat_tpu.train.step import create_train_state

        if args.vocab_size:
            config = config.replace(vocabulary_size=args.vocab_size)
        # width set BEFORE loading: Vocabulary clamps its word list to
        # size, so the default width would truncate a larger run's CSV
        vocab = Vocabulary(config.vocabulary_size, save_file=args.vocab)
        eos = _eos_id(vocab)
        valid_size = len(vocab.words)
        skeleton = create_train_state(jax.random.PRNGKey(0), config)
        # partial restore guard: a shape-skipped decoder would silently
        # benchmark random weights as "trained" (restore skips
        # mismatches), so count the params group by itself — the total
        # from restore_checkpoint also includes optimizer slots, which
        # would mask a skipped leaf
        from sat_tpu.train.checkpoint import _assign_leaves, load_flat

        flat = load_flat(args.params)
        params, n_p = _assign_leaves(skeleton.params, "params/", flat)
        n_params = len(jax.tree_util.tree_leaves(skeleton.params))
        if n_p < n_params:
            print(
                f"checkpoint covered {n_p}/{n_params} param leaves — wrong "
                "config/--vocab-size for this checkpoint?",
                file=sys.stderr,
            )
            return 2
        batch_stats, _ = _assign_leaves(skeleton.batch_stats, "batch_stats/", flat)
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
    else:
        variables = init_variables(jax.random.PRNGKey(0), config)

    from sat_tpu.utils.benchmarking import (
        make_chained_decode,
        time_decode_windows,
    )

    decode = make_chained_decode(
        config, eos=eos, beam_size=args.beam, valid_size=valid_size,
        early_exit=not args.no_early_exit,
    )
    compile_s, windows_ms, _ = time_decode_windows(
        decode, variables, images, args.iters, windows=1
    )
    print(f"compile+first: {compile_s:.1f}s", file=sys.stderr, flush=True)

    images_per_sec = 1e3 * B / windows_ms[0]
    common = {
        "unit": f"images/sec @ beam={args.beam}",
        "batch_size": B,
        "early_exit": not args.no_early_exit,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        **_bench_stamp(),
    }
    print(
        json.dumps(
            {
                "metric": "eval_images_per_sec",
                "value": round(images_per_sec, 2),
                "batch_ms": round(windows_ms[0], 1),
                "encoder_quant": "off",
                **common,
            }
        ),
        flush=True,
    )

    if args.encoder_quant != "off":
        # quantized arm: same weights through the PTQ pass, same decode —
        # the row pair is the encode-path A/B the PERF table quotes
        import time as _time

        from sat_tpu.nn import quant

        qconfig = config.replace(encoder_quant=args.encoder_quant)
        t0 = _time.perf_counter()
        qcnn = quant.quantize_encoder(variables, qconfig)
        quantize_s = _time.perf_counter() - t0
        qvars = {
            "params": {"decoder": variables["params"]["decoder"]},
            "qcnn": qcnn,
        }
        qdecode = make_chained_decode(
            qconfig, eos=eos, beam_size=args.beam, valid_size=valid_size,
            early_exit=not args.no_early_exit,
        )
        q_compile_s, q_windows_ms, _ = time_decode_windows(
            qdecode, qvars, images, args.iters, windows=1
        )
        print(
            f"quant arm ({args.encoder_quant}) compile+first: "
            f"{q_compile_s:.1f}s (quantize {quantize_s:.2f}s)",
            file=sys.stderr, flush=True,
        )
        print(
            json.dumps(
                {
                    "metric": f"eval_images_per_sec_{args.encoder_quant}",
                    "value": round(1e3 * B / q_windows_ms[0], 2),
                    "batch_ms": round(q_windows_ms[0], 1),
                    "encoder_quant": args.encoder_quant,
                    "quantize_seconds": round(quantize_s, 3),
                    "fp32_images_per_sec": round(images_per_sec, 2),
                    **common,
                }
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
