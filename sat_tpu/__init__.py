"""sat_tpu — a TPU-native Show, Attend and Tell framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
Cheng-Lin-Li/show-attend-and-tell (TF1): VGG16/ResNet50 encoders, the
soft-attention LSTM decoder, masked-CE + doubly-stochastic-attention
training, on-device batched beam search, COCO data/vocabulary pipeline,
BLEU/METEOR/ROUGE-L/CIDEr evaluation, npy-compatible checkpointing, and
SPMD data/context-parallel training over a jax.sharding.Mesh.
"""

from .config import Config

__version__ = "0.2.0"

__all__ = ["Config", "train", "evaluate", "test", "evaluate_sweep"]


def __getattr__(name: str):
    # lazy: the runtime pulls in jax; `import sat_tpu` for Config alone
    # (host-side tooling, config parsing) stays light
    if name in ("train", "evaluate", "test", "evaluate_sweep"):
        from . import runtime

        return getattr(runtime, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
