"""Fused soft-attention step as a Pallas TPU kernel.

At decode time the attention step is, per image (reference attend,
/root/reference/model.py:395-436, 2-layer variant):

    temp   = t1 + t2[None, :]        # [N, da]  (t1 = tanh(fc_1a(ctx)), hoisted)
    logits = temp @ w2               # [N]
    alpha  = softmax(logits)         # [N]
    ctx    = alpha @ contexts        # [D]

Unfused, XLA materializes temp/logits/alpha between HBM round-trips per
scan step.  This kernel performs the whole chain in one VMEM residency per
batch row: the [N,da]×[da,1] scoring matmul rides the MXU, softmax and the
weighted sum run on the VPU, and only the [D] context vector and [N] alpha
leave chip memory.

Mosaic layout notes: the context-grid axis N (196 for VGG16) is padded to
a sublane-aligned multiple of 8 and kept as the *sublane* dimension
throughout — logits/alpha live as [N_pad, 1] columns so every reduction is
over an aligned axis, and a -inf logit bias masks the padding rows out of
the softmax.

Used at inference (beam search / greedy); training keeps the XLA path
(per-step dropout on contexts makes the hoisted t1 invalid there, and XLA
fuses the rest fine in the backward pass).  ``interpret=True`` runs the
same kernel on CPU for tests.

Measured on v5e-1 at the reference shapes (N=196, da=D=512, batch 48):
XLA's fully-fused scan decodes a 16-image batch in ~0.24 ms once the t1
hoist is in place, while this kernel's per-image grid serializes 48 tiny
programs per step and lands ~300x slower — so ``use_pallas_attention``
defaults to False and the kernel is kept as the building block for larger
context grids (bigger images / finer feature maps), where one image's
attention alone fills the MXU and the fusion pays off.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30

# Test hook: route attend_with_precomputed through the kernel in interpret
# mode even off-TPU (production non-TPU uses the XLA fallback instead).
FORCE_INTERPRET = False


def _make_kernel(compute_dtype):
    dt = jnp.dtype(compute_dtype)

    def _kernel(t1_ref, t2_ref, w2_ref, bias_ref, ctx_ref,
                out_ctx_ref, out_alpha_ref):
        # blocks: t1 [1,Np,da], t2 [1,1,da], w2 [da,1], bias [Np,1],
        #         ctx [1,Np,D], out_ctx [1,1,D], out_alpha [1,Np,1]
        temp = t1_ref[0] + t2_ref[0]                               # [Np, da]
        # scoring matvec in the model's compute dtype (mirrors _dense:
        # bf16 MXU inputs, fp32 accumulate — Mosaic requires a 32-bit
        # acc — then round the result through dt like XLA's bf16 matmul)
        logits = (
            jnp.dot(
                temp.astype(dt), w2_ref[:, :].astype(dt),
                preferred_element_type=jnp.float32,
            )
            .astype(dt)
            .astype(jnp.float32)
        )
        logits = logits + bias_ref[:, :]                           # [Np, 1]
        m = jnp.max(logits, axis=0, keepdims=True)                 # [1, 1]
        e = jnp.exp(logits - m)                                    # [Np, 1]
        s = jnp.sum(e, axis=0, keepdims=True)                      # [1, 1]
        alpha = e / s                                              # [Np, 1]
        out_alpha_ref[0, :, :] = alpha
        # weighted sum over the aligned sublane axis (VPU, fp32)
        out_ctx_ref[0, 0, :] = jnp.sum(alpha * ctx_ref[0], axis=0)  # [D]

    return _kernel


@partial(jax.jit, static_argnames=("compute_dtype", "interpret"))
def fused_attend(
    t1: jnp.ndarray,
    t2: jnp.ndarray,
    w2: jnp.ndarray,
    contexts: jnp.ndarray,
    compute_dtype: str = "float32",
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(context [B,D], alpha [B,N]) from hoisted attention inputs.

    t1: [B, N, da] fp32 — tanh(fc_1a(contexts)), loop-invariant.
    t2: [B, da]    fp32 — tanh(fc_1b(output)) for the current step.
    w2: [da, 1]    fp32 — second-layer projection.
    contexts: [B, N, D] fp32.
    compute_dtype: the scoring matmul dtype (the model's MXU dtype).
    """
    B, N, da = t1.shape
    D = contexts.shape[-1]
    n_pad = (-N) % 8
    Np = N + n_pad

    t1 = jnp.pad(t1.astype(jnp.float32), ((0, 0), (0, n_pad), (0, 0)))
    contexts_p = jnp.pad(
        contexts.astype(jnp.float32), ((0, 0), (0, n_pad), (0, 0))
    )
    t2 = t2.astype(jnp.float32).reshape(B, 1, da)
    w2 = w2.astype(jnp.float32)
    # padding rows get -inf logits so they vanish from the softmax
    bias = jnp.where(
        (jnp.arange(Np) < N)[:, None], 0.0, _NEG_INF
    ).astype(jnp.float32)                                          # [Np, 1]

    out_ctx, out_alpha = pl.pallas_call(
        _make_kernel(compute_dtype),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Np, da), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 1, da), lambda b: (b, 0, 0)),
            pl.BlockSpec((da, 1), lambda b: (0, 0)),
            pl.BlockSpec((Np, 1), lambda b: (0, 0)),
            pl.BlockSpec((1, Np, D), lambda b: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, D), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, Np, 1), lambda b: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, 1, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Np, 1), jnp.float32),
        ],
        interpret=interpret,
    )(t1, t2, w2, bias, contexts_p)
    return out_ctx[:, 0], out_alpha[:, :N, 0]


def fused_attend_reference(
    t1: jnp.ndarray,
    t2: jnp.ndarray,
    w2: jnp.ndarray,
    contexts: jnp.ndarray,
    compute_dtype: str = "float32",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Plain-XLA twin of :func:`fused_attend` (correctness oracle)."""
    dt = jnp.dtype(compute_dtype)
    temp = t1.astype(jnp.float32) + t2.astype(jnp.float32)[:, None, :]
    logits = (
        temp.astype(dt) @ w2.astype(dt)
    ).astype(jnp.float32)[..., 0]
    alpha = jax.nn.softmax(logits, axis=-1)
    ctx = jnp.einsum("bn,bnd->bd", alpha, contexts.astype(jnp.float32))
    return ctx, alpha
