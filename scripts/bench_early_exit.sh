#!/bin/bash
# Trained-model early-exit A/B on the current backend: the beam-search
# while_loop's exact early exit (sat_tpu/ops/beam_search.py run_search)
# only pays off when the model actually terminates captions, which a
# random init never does — so train a quick flagship-shape model on the
# self-contained corpus, then run scripts/bench_eval.py on its checkpoint
# with and without the exit.  Artifact = two JSON lines on stdout
# (early_exit true/false), consumed by tpu_retry.sh as stage
# "bench_early_exit".
#
# Usage: bash scripts/bench_early_exit.sh [outdir]
# Env knobs (CPU smoke: EE_CPU=1 EE_IMAGE_SIZE=64 EE_STEPS=30 EE_BATCH=4):
#   EE_IMAGE_SIZE (default 224), EE_STEPS (400), EE_BATCH (bench batch,
#   32), EE_CPU=1 (pin the CPU backend everywhere).
set -u
OUT=${1:-/root/repo/runs/tpu_session_r3}
IMG=${EE_IMAGE_SIZE:-224}
STEPS=${EE_STEPS:-400}
# cache dir keyed on EVERY knob that shapes corpus + checkpoint —
# including the backend, so a CPU smoke run with default sizes can't be
# mistaken for the production (TPU-trained) artifacts
BACKEND=$([ "${EE_CPU:-0}" = "1" ] && echo cpu || echo dev)
DIR="$OUT/ee_run_${IMG}px_${STEPS}s_${BACKEND}"
BATCH=${EE_BATCH:-32}
CPU_FLAG=""
[ "${EE_CPU:-0}" = "1" ] && { CPU_FLAG="--cpu"; export JAX_PLATFORMS=cpu; }
cd "$(dirname "$0")/.."
mkdir -p "$OUT"

if [ ! -f "$DIR/captions.json" ]; then
  timeout 300 python scripts/quality_run.py --corpus-only \
    --image-size "$IMG" --out "$DIR" \
    >"$OUT/ee_corpus.log" 2>&1 || { echo "corpus gen failed" >&2; exit 1; }
fi

if ! ls "$DIR"/models/*.npz >/dev/null 2>&1; then
  timeout 700 python -m sat_tpu.cli --phase=train \
    --set train_image_dir="$DIR/images" \
    --set train_caption_file="$DIR/captions.json" \
    --set vocabulary_file="$DIR/vocabulary_basic.csv" \
    --set temp_annotation_file="$DIR/anns_basic.csv" \
    --set temp_data_file="$DIR/data_basic.npy" \
    --set save_dir="$DIR/models" \
    --set summary_dir="$DIR/summary" \
    --set image_size="$IMG" \
    --set max_train_ann_num=none --set batch_size=16 --set num_epochs=200 \
    --set max_steps="$STEPS" --set save_period=0 \
    --set initial_learning_rate=3e-4 \
    >"$OUT/ee_train.log" 2>&1 || { echo "train failed" >&2; exit 1; }
fi

CKPT=$(ls -t "$DIR"/models/*.npz | head -1)
for arm in "" "--no-early-exit"; do
  timeout 400 python scripts/bench_eval.py --batch "$BATCH" --iters 10 \
    --image-size "$IMG" $CPU_FLAG \
    --params "$CKPT" --vocab "$DIR/vocabulary_basic.csv" $arm \
    2>>"$OUT/ee_bench.log" || { echo "bench arm failed" >&2; exit 1; }
done
