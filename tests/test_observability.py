"""Fleet-observability tests (docs/OBSERVABILITY.md, ISSUE 9).

Pins the contracts of the request-scoped tracing / exposition / live
profiling / SLO stack:

* trace-id plumbing: inbound ``X-Request-Id`` honored (sanitized) and
  echoed on EVERY reply — 200s, 400s, sheds; minted when absent;
* ``access.jsonl``: one record per terminal reply with all five phase
  timings, their sum bounded by the total; size-capped rotation;
* the Chrome trace gains one lane per retained request (synthetic tid +
  ``thread_name`` metadata + per-phase child spans);
* ``GET /metrics`` renders Prometheus text format 0.0.4 that a minimal
  in-test parser accepts, on both the caption server and the train-side
  ``MetricsListener``;
* ``POST /profile``: bounded capture into ``<tdir>/profiles/<ts>/``,
  single-capture latch (second request → 409), hard duration cap;
* the SLO engine: fast+slow burn windows, ok↔burning transitions into
  ``slo.jsonl``, ``/healthz`` degrading with the objective named, and
  ``scripts/check_slo.py`` turning the log into CI exit codes;
* heartbeat payloads carry ``schema_version``; ``_percentiles_ms`` edge
  cases (empty span, single sample, ring wraparound).

The e2e half boots a real CaptionServer on a tiny trained model (same
fixture recipe as tests/test_serve.py) — CPU, ephemeral port.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from sat_tpu import runtime, telemetry
from sat_tpu.data.vocabulary import Vocabulary
from sat_tpu.serve.engine import ServeEngine, load_serving_state
from sat_tpu.serve.server import CaptionServer, _percentiles_ms
from sat_tpu.telemetry import (
    SCHEMA_VERSION,
    exporters,
    heartbeat,
    profwin,
    promtext,
    slo,
    tracectx,
)

from tests.test_runtime import SMALL_MODEL

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# tracectx: ids, phase records, Perfetto lanes
# ---------------------------------------------------------------------------


class TestTraceIds:
    def test_minted_id_is_16_hex(self):
        rid = tracectx.ensure_id(None)
        assert len(rid) == 16
        int(rid, 16)  # raises if not hex

    def test_inbound_id_honored_and_sanitized(self):
        assert tracectx.ensure_id("abc-123") == "abc-123"
        # header injection / whitespace stripped, length bounded
        assert tracectx.ensure_id("  a b\r\nc!! ") == "abc"
        assert len(tracectx.ensure_id("x" * 500)) == 128

    def test_garbage_only_id_gets_minted_replacement(self):
        rid = tracectx.ensure_id("\r\n\r\n")
        assert len(rid) == 16
        int(rid, 16)

    def test_distinct_mints(self):
        assert tracectx.ensure_id(None) != tracectx.ensure_id(None)


class TestRequestTracer:
    def test_finish_record_carries_all_five_phases(self, tmp_path):
        path = str(tmp_path / "access.jsonl")
        tracer = tracectx.RequestTracer(path=path)
        trace = tracer.begin("req-1")
        t0 = trace.t_start_ns
        trace.mark("queue_wait", t0, 1_000_000)
        trace.mark("dispatch", t0 + 1_000_000, 2_000_000)
        rec = tracer.finish(trace, 200, 10_000_000, bucket=4)
        assert rec["trace_id"] == "req-1"
        assert rec["status"] == 200 and rec["bucket"] == 4
        assert rec["total_ms"] == 10.0
        phases = rec["phases"]
        assert set(phases) == {f"{p}_ms" for p in tracectx.PHASES}
        assert phases["queue_wait_ms"] == 1.0
        assert phases["dispatch_ms"] == 2.0
        assert phases["detok_ms"] == 0.0  # unmarked phases present as 0
        # the line landed on disk verbatim
        on_disk = json.loads(open(path).read().strip())
        assert on_disk == rec

    def test_negative_durations_clamp_to_zero(self):
        trace = tracectx.RequestTrace("t")
        trace.mark("drain", 0, -5)
        assert trace.phase_ms()["drain_ms"] == 0.0

    def test_retention_ring_is_bounded(self):
        tracer = tracectx.RequestTracer(keep=4)
        for i in range(10):
            tracer.finish(tracer.begin(f"r{i}"), 200, 1)
        kept = tracer.finished()
        assert len(kept) == 4
        assert kept[-1]["trace_id"] == "r9"

    def test_trace_events_one_lane_per_request(self):
        tracer = tracectx.RequestTracer()
        trace = tracer.begin("lane-test")
        trace.t_start_ns = 5_000_000
        trace.mark("queue_wait", 5_000_000, 1_000_000)
        trace.mark("dispatch", 6_000_000, 2_000_000)
        tracer.finish(trace, 200, 4_000_000)
        events = tracer.trace_events(anchor_ns=0, pid=7)
        names = [e["name"] for e in events]
        assert names == [
            "thread_name", "request lane-test", "queue_wait", "dispatch",
        ]
        meta, parent, child, _ = events
        assert meta["ph"] == "M"
        assert meta["args"]["name"] == "request lane-test"
        # all events share one synthetic lane, clear of real thread ids
        assert len({e["tid"] for e in events}) == 1
        assert parent["tid"] >= tracectx._LANE_BASE
        assert parent["ph"] == "X" and parent["ts"] == 5_000.0
        assert parent["dur"] == 4_000.0  # total_ms * 1e3
        assert child["ts"] == 5_000.0 and child["dur"] == 1_000.0

    def test_lanes_merge_into_chrome_trace(self, tmp_path):
        tel = telemetry.Telemetry(capacity=64)
        with tel.span("serve/request"):
            pass
        tracer = tracectx.RequestTracer()
        tracer.finish(tracer.begin("merged"), 200, 1_000_000)
        path = str(tmp_path / "trace.json")
        exporters.export_chrome_trace(
            tel, path,
            extra_events=tracer.trace_events(tel.anchor_ns),
        )
        doc = json.load(open(path))
        names = [e["name"] for e in doc["traceEvents"]]
        assert "serve/request" in names  # process spans still there
        assert "request merged" in names  # plus the request lane


# ---------------------------------------------------------------------------
# rotating sink (satellite: size-capped telemetry logs)
# ---------------------------------------------------------------------------


class TestRotatingAppend:
    def test_append_creates_parents_and_newline(self, tmp_path):
        path = str(tmp_path / "deep" / "log.jsonl")
        assert exporters.rotating_append(path, '{"a": 1}')
        assert open(path).read() == '{"a": 1}\n'

    def test_rollover_at_cap(self, tmp_path):
        path = str(tmp_path / "log.jsonl")
        line = "x" * 100
        cap = 350
        for _ in range(8):
            assert exporters.rotating_append(path, line, cap_bytes=cap)
        # a single .1 generation, primary kept under the cap
        assert os.path.exists(path + ".1")
        assert os.path.getsize(path) <= cap
        assert not os.path.exists(path + ".2")
        # nothing was lost in the most recent generation pair
        total = sum(
            1 for p in (path, path + ".1") for _ in open(p)
        )
        assert total >= cap // len(line)

    def test_failure_degrades_returns_false(self, tmp_path):
        target = tmp_path / "is_a_dir"
        target.mkdir()
        tel = telemetry.Telemetry(capacity=64)
        assert not exporters.rotating_append(str(target), "line", tel=tel)
        assert tel.counters().get("telemetry/export_errors") == 1


# ---------------------------------------------------------------------------
# promtext: exposition + a minimal Prometheus text parser
# ---------------------------------------------------------------------------


def parse_prometheus(text):
    """Minimal text-format 0.0.4 parser: {(metric, labels_str): value}.
    Raises on any line that is neither a comment nor a valid sample."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        assert name_part, f"unparsable sample line: {line!r}"
        value = float(value_part)  # raises on malformed values
        if "{" in name_part:
            metric, _, rest = name_part.partition("{")
            assert rest.endswith("}"), f"unclosed labels: {line!r}"
            labels = rest[:-1]
        else:
            metric, labels = name_part, ""
        assert metric.replace("_", "").isalnum(), f"bad metric: {metric!r}"
        samples[(metric, labels)] = value
    return samples


class TestPromText:
    def test_render_families_and_values(self):
        tel = telemetry.Telemetry(capacity=64)
        tel.count("serve/completed", 3)
        tel.gauge("serve/queue_depth", 2)
        tel.record("serve/request", 0, 2_000_000_000)
        text = promtext.render(tel, extra={"steps_per_s": 1.5, "run_id": "x"})
        assert text.endswith("sat_up 1\n")
        samples = parse_prometheus(text)
        assert samples[("sat_counter_total", 'name="serve/completed"')] == 3
        assert samples[("sat_gauge", 'name="serve/queue_depth"')] == 2
        # numeric extra rides the gauge family; the string one is skipped
        assert samples[("sat_gauge", 'name="steps_per_s"')] == 1.5
        assert ("sat_gauge", 'name="run_id"') not in samples
        assert samples[("sat_span_seconds_count", 'span="serve/request"')] == 1
        assert samples[("sat_span_seconds_sum", 'span="serve/request"')] == 2.0
        assert samples[("sat_up", "")] == 1

    def test_label_escaping(self):
        tel = telemetry.Telemetry(capacity=64)
        tel.count('weird"name\\with\nstuff')
        text = promtext.render(tel)
        assert '\\"' in text and "\\\\" in text and "\\n" in text
        # document still line-parses (the raw newline was escaped away)
        parse_prometheus(text)

    def test_metrics_listener_ephemeral_port(self):
        tel = telemetry.Telemetry(capacity=64)
        tel.count("train/steps", 5)
        ml = promtext.MetricsListener(
            "127.0.0.1", 0, tel, payload_fn=lambda: {"step": 12}
        )
        assert ml.start()
        try:
            assert ml.port > 0  # read back from the ephemeral bind
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ml.port}/metrics", timeout=10
            ) as r:
                assert r.status == 200
                assert r.headers["Content-Type"] == promtext.CONTENT_TYPE
                samples = parse_prometheus(r.read().decode())
            assert samples[("sat_counter_total", 'name="train/steps"')] == 5
            assert samples[("sat_gauge", 'name="step"')] == 12  # payload extra
            with urllib.request.urlopen(
                f"http://127.0.0.1:{ml.port}/healthz", timeout=10
            ) as r:
                assert json.loads(r.read()) == {"step": 12}
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ml.port}/nope", timeout=10
                )
            assert exc.value.code == 404
        finally:
            ml.stop()

    def test_listener_bind_failure_degrades(self):
        tel = telemetry.Telemetry(capacity=64)
        ml = promtext.MetricsListener("127.0.0.1", 0, tel)
        assert ml.start()
        try:
            clash = promtext.MetricsListener("127.0.0.1", ml.port, tel)
            assert clash.start() is False  # warns, returns False, no raise
        finally:
            ml.stop()


# ---------------------------------------------------------------------------
# SLO engine: windows, transitions, slo.jsonl, check_slo.py
# ---------------------------------------------------------------------------


def _fake_clocks():
    """Deterministic mono+wall clocks advanced together by the test."""
    state = {"ns": 0}

    def advance(s):
        state["ns"] += int(s * 1e9)

    return state, advance, lambda: state["ns"], lambda: state["ns"] / 1e9


class TestSLOEngine:
    def test_objective_validation(self):
        with pytest.raises(ValueError):
            slo.Objective(name="x", kind="nope", target=1.0, source="s")
        with pytest.raises(ValueError):
            slo.Objective(
                name="x", kind="latency_p99", target=0.0, source="s"
            )

    def test_latency_burn_cycle_and_transitions(self, tmp_path):
        tel = telemetry.Telemetry(capacity=4096)
        _, advance, clock_ns, wall = _fake_clocks()
        path = str(tmp_path / "slo.jsonl")
        eng = slo.SLOEngine(
            tel,
            [slo.Objective(
                name="p99", kind="latency_p99", target=10.0,
                source="serve/request",
            )],
            jsonl_path=path,
            fast_s=2.0,
            slow_s=4.0,
            clock_ns=clock_ns,
            wall_clock=wall,
        )
        # healthy traffic: 5 ms requests
        for _ in range(6):
            advance(0.2)
            tel.record("serve/request", clock_ns(), 5_000_000)
            eng.tick()
        assert eng.burning() == []
        assert tel.gauges().get("slo/p99_burn") == 0.5
        # sustained violation: 50 ms requests fill BOTH windows
        for _ in range(25):
            advance(0.2)
            tel.record("serve/request", clock_ns(), 50_000_000)
            eng.tick()
        assert eng.burning() == ["p99"]
        assert tel.gauges().get("slo/p99_burning") == 1
        assert tel.gauges().get("slo/burning_total") == 1
        # recovery: healthy again until both windows forget the incident
        for _ in range(30):
            advance(0.2)
            tel.record("serve/request", clock_ns(), 5_000_000)
            eng.tick()
        assert eng.burning() == []
        events = [json.loads(l) for l in open(path)]
        assert [e["event"] for e in events] == ["burning", "ok"]
        assert all(e["name"] == "p99" for e in events)
        assert all(e["schema_version"] == SCHEMA_VERSION for e in events)
        assert events[0]["burn_fast"] >= 1.0

    def test_min_events_guard(self):
        """Fewer than MIN_EVENTS samples in a window is unmeasurable —
        one or two outliers cannot page; the third violating sample can."""
        tel = telemetry.Telemetry(capacity=4096)
        _, advance, clock_ns, wall = _fake_clocks()
        eng = slo.SLOEngine(
            tel,
            [slo.Objective(
                name="p99", kind="latency_p99", target=10.0,
                source="serve/request",
            )],
            fast_s=2.0, slow_s=4.0, clock_ns=clock_ns, wall_clock=wall,
        )
        for _ in range(slo.MIN_EVENTS - 1):
            advance(0.2)
            tel.record("serve/request", clock_ns(), 500_000_000)
            eng.tick()
        assert eng.burning() == []  # 2 samples: below the evidence bar
        advance(0.2)
        tel.record("serve/request", clock_ns(), 500_000_000)
        eng.tick()
        assert eng.burning() == ["p99"]  # 3rd sustained violation pages

    def test_error_ratio_and_rate_floor(self):
        tel = telemetry.Telemetry(capacity=256)
        _, advance, clock_ns, wall = _fake_clocks()
        eng = slo.SLOEngine(
            tel,
            [
                slo.Objective(
                    name="errors", kind="error_ratio", target=0.1,
                    source="serve/http_5xx", denom="serve/http_requests",
                ),
                slo.Objective(
                    name="rate", kind="rate_floor", target=100.0,
                    source="train/step", scale=10.0,
                ),
            ],
            fast_s=2.0, slow_s=4.0, clock_ns=clock_ns, wall_clock=wall,
        )
        step = 0
        # healthy: no errors, 20 steps/s * scale 10 = 200 >= 100
        for _ in range(30):
            advance(0.2)
            step += 4
            tel.gauge("train/step", step)
            tel.count("serve/http_requests", 5)
            eng.tick()
        assert eng.burning() == []
        # degraded: half the requests 5xx, training stalled
        for _ in range(30):
            advance(0.2)
            tel.gauge("train/step", step)  # flat = rate 0
            tel.count("serve/http_requests", 4)
            tel.count("serve/http_5xx", 2)
            eng.tick()
        assert eng.burning() == ["errors", "rate"]

    def test_age_ceiling(self):
        tel = telemetry.Telemetry(capacity=64)
        _, advance, clock_ns, wall = _fake_clocks()
        eng = slo.SLOEngine(
            tel,
            [slo.Objective(
                name="ckpt", kind="age_ceiling", target=60.0,
                source="ckpt/last_save_unix",
            )],
            fast_s=2.0, slow_s=4.0, clock_ns=clock_ns, wall_clock=wall,
        )
        eng.tick()  # gauge absent: unmeasurable, not burning
        assert eng.burning() == []
        tel.gauge("ckpt/last_save_unix", wall())
        advance(30)
        eng.tick()
        assert eng.burning() == []  # 30 s old, ceiling 60
        advance(90)
        eng.tick()
        assert eng.burning() == ["ckpt"]

    def test_objectives_from_config_gated_by_targets(self):
        from sat_tpu.config import Config

        assert slo.objectives_from_config(Config(), "serve") == []
        assert slo.objectives_from_config(Config(), "train") == []
        config = Config(
            slo_serve_p99_ms=250.0,
            slo_error_ratio=0.05,
            slo_captions_per_s=100.0,
            slo_ckpt_age_s=900.0,
        )
        serve_names = [
            o.name for o in slo.objectives_from_config(config, "serve")
        ]
        train_names = [
            o.name for o in slo.objectives_from_config(config, "train")
        ]
        assert serve_names == ["serve_p99_ms", "error_ratio"]
        assert train_names == ["captions_per_s", "ckpt_age_s"]

    def test_config_validates_slo_knobs(self):
        from sat_tpu.config import Config

        with pytest.raises(ValueError):
            Config(slo_error_ratio=2.0)
        with pytest.raises(ValueError):
            Config(slo_window_fast_s=300.0, slo_window_slow_s=60.0)
        with pytest.raises(ValueError):
            Config(metrics_port=-1)


class TestCheckSLOScript:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "check_slo.py"),
             *argv],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )

    def _write(self, tmp_path, records, name="slo.jsonl"):
        path = tmp_path / name
        path.write_text(
            "".join(json.dumps(r) + "\n" for r in records)
        )
        return str(path)

    def _rec(self, event, name="p99"):
        return {
            "schema_version": SCHEMA_VERSION, "name": name, "event": event,
            "kind": "latency_p99", "target": 10.0, "measured_fast": 50.0,
            "burn_fast": 5.0, "burn_slow": 5.0,
        }

    def test_empty_log_passes(self, tmp_path):
        path = self._write(tmp_path, [])
        proc = self._run(path)
        assert proc.returncode == 0
        assert "no transitions" in proc.stdout

    def test_recovered_passes_default_fails_strict(self, tmp_path):
        path = self._write(
            tmp_path, [self._rec("burning"), self._rec("ok")]
        )
        assert self._run(path).returncode == 0
        assert self._run(path, "--strict").returncode == 2

    def test_ended_burning_fails(self, tmp_path):
        path = self._write(tmp_path, [self._rec("burning")])
        proc = self._run(path)
        assert proc.returncode == 2
        assert "p99" in proc.stderr

    def test_schema_mismatch_refused_exit_3(self, tmp_path):
        bad = self._rec("ok")
        bad["schema_version"] = SCHEMA_VERSION + 99
        path = self._write(tmp_path, [bad])
        proc = self._run(path)
        assert proc.returncode == 3
        assert "REFUSED" in proc.stderr

    def test_torn_line_tolerated(self, tmp_path):
        path = self._write(tmp_path, [self._rec("ok")])
        with open(path, "a") as f:
            f.write('{"torn": ')
        assert self._run(path).returncode == 0

    def test_missing_file_exit_1(self, tmp_path):
        assert self._run(str(tmp_path / "absent.jsonl")).returncode == 1


# ---------------------------------------------------------------------------
# profiler windows (unit: latch semantics; capture e2e below)
# ---------------------------------------------------------------------------


class TestProfileLatch:
    def test_second_start_refused_then_released(self, tmp_path):
        latch = profwin.ProfileLatch(str(tmp_path))
        ok, out_dir = latch.start(duration_ms=200.0)
        assert ok, out_dir
        assert out_dir.startswith(os.path.join(str(tmp_path), "profiles"))
        ok2, reason = latch.start(duration_ms=200.0)
        assert not ok2 and "in progress" in reason
        deadline = time.time() + 10.0
        while latch.busy and time.time() < deadline:
            time.sleep(0.02)
        assert not latch.busy  # timer released the latch
        assert latch.captures == 1
        assert os.path.isdir(out_dir)

    def test_stop_now_releases_early(self, tmp_path):
        latch = profwin.ProfileLatch(str(tmp_path))
        ok, _ = latch.start(duration_ms=profwin.HARD_CAP_MS)  # clamped max
        assert ok
        latch.stop_now()
        assert not latch.busy
        latch.stop_now()  # idempotent when idle

    def test_signal_trigger_pops_once(self):
        trig = profwin.SignalTrigger()
        assert not trig.pop()
        trig.fire()
        assert trig.pop()
        assert not trig.pop()  # latched, not level


# ---------------------------------------------------------------------------
# heartbeat schema + _percentiles_ms edges
# ---------------------------------------------------------------------------


def test_heartbeat_payload_carries_schema_version(tmp_path):
    tel = telemetry.Telemetry(capacity=64)
    hb = heartbeat.Heartbeat(
        str(tmp_path / "heartbeat.json"), 60.0, tel, static={"phase": "t"}
    )
    payload = hb.payload()
    assert payload["schema_version"] == SCHEMA_VERSION
    assert payload["phase"] == "t"
    json.dumps(payload)  # must be a JSON document end to end


class TestPercentilesEdges:
    def test_empty_span_returns_none(self):
        tel = telemetry.Telemetry(capacity=64)
        assert _percentiles_ms(tel, "serve/never_recorded") is None

    def test_single_sample(self):
        tel = telemetry.Telemetry(capacity=64)
        tel.record("serve/one", 0, 7_000_000)
        p = _percentiles_ms(tel, "serve/one")
        assert p["count"] == 1
        assert p["p50"] == p["p95"] == p["p99"] == 7.0

    def test_ring_wraparound_keeps_newest(self):
        """More records than capacity: percentiles reflect the survivors
        (the newest window), not a corrupted mixture."""
        tel = telemetry.Telemetry(capacity=256)
        for _ in range(300):
            tel.record("serve/wrap", 0, 1_000_000)  # evicted era: 1 ms
        for _ in range(300):
            tel.record("serve/wrap", 0, 9_000_000)  # surviving era: 9 ms
        p = _percentiles_ms(tel, "serve/wrap")
        assert 0 < p["count"] <= 256
        assert p["p50"] == p["p99"] == 9.0


# ---------------------------------------------------------------------------
# e2e: served model, tracing through the wire, /metrics, /profile, SLO burn
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def obs_served(coco_fixture, tmp_path_factory):
    """Tiny trained model + warmed engine + a telemetry_dir of its own
    (the observability artifacts — access.jsonl, slo.jsonl, profiles/ —
    land somewhere this module can inspect)."""
    root = tmp_path_factory.mktemp("obs_serve")
    train_config = coco_fixture["config"].replace(
        **SMALL_MODEL,
        save_dir=os.path.join(str(root), "models"),
        summary_dir=os.path.join(str(root), "summary"),
    )
    runtime.train(train_config)

    config = train_config.replace(
        phase="serve",
        beam_size=2,
        serve_buckets=(1, 4),
        serve_max_batch=4,
        serve_max_wait_ms=30.0,
        serve_queue_depth=8,
        heartbeat_interval=0.2,
        telemetry_dir=os.path.join(str(root), "telemetry"),
    )
    tel = telemetry.enable(capacity=16384)
    runtime._install_compile_listener()
    vocabulary = Vocabulary(config.vocabulary_size, config.vocabulary_file)
    state, _ = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    yield {"config": config, "engine": engine, "tel": tel}
    telemetry.disable()


def _jpeg(obs_served):
    d = obs_served["config"].eval_image_dir
    f = sorted(os.listdir(d))[0]
    return open(os.path.join(d, f), "rb").read()


def _post(port, path, data, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=data,
        method="POST",
        headers={"Content-Type": "image/jpeg", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _get(port, path, timeout=30):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_e2e_trace_id_phases_metrics_profile(obs_served, tmp_path):
    config, engine = obs_served["config"], obs_served["engine"]
    server = CaptionServer(config, engine, port=0).start()
    try:
        port = server.port
        jpeg = _jpeg(obs_served)

        # -- inbound id honored: header AND body echo it -----------------
        status, headers, payload = _post(
            port, "/caption", jpeg, headers={"X-Request-Id": "abc"}
        )
        assert status == 200
        assert headers["X-Request-Id"] == "abc"
        assert payload["request_id"] == "abc"
        assert payload["captions"]

        # no inbound id: one is minted and still echoed
        status, headers, payload = _post(port, "/caption", jpeg)
        assert status == 200
        minted = headers["X-Request-Id"]
        assert len(minted) == 16 and payload["request_id"] == minted

        # -- the access log record: all five phases, sum bounded ---------
        records = server.tracer.finished()
        rec = next(r for r in records if r["trace_id"] == "abc")
        assert rec["status"] == 200 and rec["bucket"] == 1
        phases = rec["phases"]
        assert set(phases) == {f"{p}_ms" for p in tracectx.PHASES}
        # a real dispatched request timed real work
        assert phases["dispatch_ms"] > 0.0
        assert phases["drain_ms"] > 0.0
        # disjoint sub-intervals: the sum never exceeds the total
        assert sum(phases.values()) <= rec["total_ms"] + 1e-6
        access = os.path.join(config.telemetry_dir, "access.jsonl")
        on_disk = [json.loads(l) for l in open(access)]
        assert any(r["trace_id"] == "abc" for r in on_disk)

        # -- X-Request-Id echoes on error replies too (satellite b) ------
        status, headers, payload = _post(
            port, "/caption", b"not a jpeg",
            headers={"X-Request-Id": "bad-input-1"},
        )
        assert status == 400
        assert headers["X-Request-Id"] == "bad-input-1"
        assert payload["request_id"] == "bad-input-1"
        status, headers, _ = _get(port, "/nope")
        assert status == 404 and "X-Request-Id" in headers

        # -- Chrome trace carries the request lane ------------------------
        trace_path = str(tmp_path / "trace.json")
        assert server.export_trace(trace_path) == trace_path
        doc = json.load(open(trace_path))
        lane = [
            e for e in doc["traceEvents"]
            if e.get("args", {}).get("trace_id") == "abc"
        ]
        kinds = [e["name"] for e in lane]
        assert "request abc" in kinds
        assert {"queue_wait", "dispatch", "drain", "detok"} <= set(kinds)
        tids = {e["tid"] for e in lane}
        assert len(tids) == 1  # one lane per request
        meta = [
            e for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("tid") in tids
        ]
        assert meta and meta[0]["args"]["name"] == "request abc"

        # -- GET /metrics: content type + parses ---------------------------
        status, headers, body = _get(port, "/metrics")
        assert status == 200
        assert headers["Content-Type"] == promtext.CONTENT_TYPE
        samples = parse_prometheus(body.decode())
        assert samples[
            ("sat_counter_total", 'name="serve/http_requests"')
        ] >= 3
        assert (
            samples[("sat_span_seconds_count", 'span="serve/request"')] >= 2
        )
        assert samples[("sat_up", "")] == 1
        # heartbeat numerics ride in as gauges
        assert samples[("sat_gauge", 'name="model_step"')] == engine.step

        # -- POST /profile: capture window + 409 latch ---------------------
        status, headers, payload = _post(
            port, "/profile?duration_ms=300", b""
        )
        assert status == 200, payload
        prof_dir = payload["profile_dir"]
        assert prof_dir.startswith(
            os.path.join(config.telemetry_dir, "profiles")
        )
        # a second capture while the window is open: 409, latch holds
        status, headers, second = _post(
            port, "/profile?duration_ms=300", b""
        )
        assert status == 409 and "in progress" in second["error"]
        # run some traffic INSIDE the window so the trace has content
        _post(port, "/caption", jpeg)
        deadline = time.time() + 15.0
        while server.profiles.busy and time.time() < deadline:
            time.sleep(0.05)
        assert not server.profiles.busy
        # the capture produced a non-empty profile directory
        captured = [
            os.path.join(dirpath, f)
            for dirpath, _, files in os.walk(prof_dir)
            for f in files
        ]
        assert captured, f"profiler window wrote nothing under {prof_dir}"
        status, headers, bad = _post(port, "/profile?duration_ms=abc", b"")
        assert status == 400

        # -- /stats grew the observability fields --------------------------
        status, _, body = _get(port, "/stats")
        stats = json.loads(body)
        assert stats["profile_captures"] >= 1
        assert "slo" in stats
    finally:
        server.shutdown()


def test_e2e_slo_burn_degrades_health(obs_served, monkeypatch, tmp_path):
    """Injected serve latency (SAT_FI_SLOW_SERVE_MS) violates a tight p99
    objective: the SLO engine flips to burning, /healthz degrades with
    the objective named, slo.jsonl records the transition, and
    check_slo.py turns the log into a non-zero exit."""
    engine = obs_served["engine"]
    config = obs_served["config"].replace(
        telemetry_dir=str(tmp_path / "slo_tel"),
        slo_serve_p99_ms=5.0,       # every request will violate this
        # windows sized for slow boxes: a serial closed loop at
        # ~250ms/request must still land MIN_EVENTS samples inside the
        # fast window, or p99 never measures and burning can't flip
        slo_window_fast_s=2.0,
        slo_window_slow_s=4.0,
    )
    # the batcher captures its FaultPlan at construction: arm BEFORE
    monkeypatch.setenv("SAT_FI_SLOW_SERVE_MS", "50")
    server = CaptionServer(config, engine, port=0).start()
    try:
        port = server.port
        jpeg = _jpeg(obs_served)
        # enough traffic to fill both burn windows with violating p99s
        deadline = time.time() + 30.0
        burning = []
        while time.time() < deadline:
            status, _, _ = _post(port, "/caption", jpeg)
            assert status == 200
            burning = server.slo.burning()
            if burning:
                break
        assert burning == ["serve_p99_ms"], "SLO never flipped to burning"

        code, _, body = _get(port, "/healthz")
        health = json.loads(body)
        assert code == 503
        assert health["status"] == "degraded"
        assert health["slo_burning"] == ["serve_p99_ms"]

        slo_log = os.path.join(config.telemetry_dir, "slo.jsonl")
        events = [json.loads(l) for l in open(slo_log)]
        assert any(
            e["event"] == "burning" and e["name"] == "serve_p99_ms"
            for e in events
        )
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "check_slo.py"),
             slo_log],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "serve_p99_ms" in proc.stderr

        # the injected latency landed in the drain phase of the access log
        recent = server.tracer.finished()[-1]
        assert recent["phases"]["drain_ms"] >= 50.0
    finally:
        monkeypatch.delenv("SAT_FI_SLOW_SERVE_MS", raising=False)
        server.shutdown()
    # recovery sanity: with the fault gone and fresh windows, a new
    # engine-backed server starts un-degraded (state is per-server)
    clean = CaptionServer(obs_served["config"], engine, port=0)
    assert clean.slo.burning() == []
