"""bench.py orchestrator contract tests.

The driver's only window into performance is bench.py's stdout; r01/r02
produced no parsed artifact because the tunneled backend hung before any
JSON landed.  These tests pin the resilience contract: the orchestrator
never imports jax itself, emits a machine-readable error line when the
backend is unreachable within budget, and the probe child really
round-trips a computation.
"""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def test_orchestrator_emits_error_json_when_budget_exhausted():
    # A 1-second budget is below the minimum run reserve, so the probe
    # loop never starts: the orchestrator must still print a parseable
    # JSON line naming the failure (VERDICT r02 §next-round #1c) and exit
    # with a distinct code.
    env = dict(os.environ, BENCH_WATCHDOG_S="1")
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=env,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 4
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    assert len(lines) == 1
    parsed = json.loads(lines[0])
    assert parsed["error"] == "device_unreachable"
    assert parsed["metric"] == "train_captions_per_sec"
    assert parsed["value"] is None


def test_probe_round_trips_a_computation_on_cpu():
    env = dict(os.environ, BENCH_CPU="1", JAX_PLATFORMS="cpu")
    env.pop("BENCH_PROBE_MICRO", None)
    proc = subprocess.run(
        [sys.executable, BENCH, "--probe"],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "probe ok" in proc.stderr
    # micro-bench defaults off on CPU: a smoke probe stays a fast liveness
    # check and prints no metric line
    assert not [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]


def test_probe_micro_emits_provisional_metric():
    # VERDICT r04 weak #1 / next-round #7: a live probe window alone must
    # land a parseable non-null metric, so a flapping tunnel that stays up
    # ~60s still produces a non-null BENCH artifact.
    env = dict(
        os.environ,
        BENCH_CPU="1",
        JAX_PLATFORMS="cpu",
        BENCH_PROBE_MICRO="1",
        BENCH_BATCH="2",
        BENCH_IMAGE_SIZE="32",
    )
    proc = subprocess.run(
        [sys.executable, BENCH, "--probe"],
        env=env,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    assert len(lines) == 1, proc.stdout
    parsed = json.loads(lines[0])
    assert parsed["metric"] == "train_captions_per_sec"
    assert parsed["value"] is not None and parsed["value"] > 0
    assert parsed["window"] == "probe"


def test_orchestrator_keeps_probe_metric_when_child_fails():
    # The probe's provisional line must survive as a valid LAST JSON line:
    # a child that keeps crashing (bogus BENCH_STEPS parses in the child
    # only — the micro-bench doesn't read it) must neither retry forever
    # nor append an error line after the metric.
    env = dict(
        os.environ,
        BENCH_CPU="1",
        JAX_PLATFORMS="cpu",
        BENCH_PROBE_MICRO="1",
        BENCH_BATCH="2",
        BENCH_IMAGE_SIZE="32",
        BENCH_STEPS="bogus",
        BENCH_WATCHDOG_S="300",
    )
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=env,
        capture_output=True,
        text=True,
        timeout=330,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    assert lines, proc.stdout
    parsed = json.loads(lines[-1])
    assert parsed.get("error") is None
    assert parsed["value"] is not None and parsed["window"] == "probe"


def test_orchestrator_reports_deterministic_child_failure_as_bench_failed():
    # A healthy probe followed by a bench child that crashes fast (bogus
    # BENCH_CNN -> Config validation error) must NOT be retried until the
    # budget burns and then mislabeled device_unreachable: after two fast
    # failures the orchestrator emits bench_failed with the child's rc.
    env = dict(
        os.environ,
        BENCH_CPU="1",
        JAX_PLATFORMS="cpu",
        BENCH_CNN="bogus_cnn",
        BENCH_WATCHDOG_S="300",
    )
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=env,
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert proc.returncode == 4, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    parsed = json.loads(lines[-1])
    assert parsed["error"] == "bench_failed"
    assert parsed["child_rc"] not in (None, 0)


RETRY = os.path.join(os.path.dirname(__file__), "..", "scripts", "tpu_retry.sh")


def _run_retry(tmp_path, stage_cmd, probe_cmd="true", stages="stage_a",
               max_attempts="3", timeout=60, poll="0", max_wait="30"):
    env = dict(
        os.environ,
        RETRY_STAGES=stages,
        RETRY_STAGE_CMD=stage_cmd,
        RETRY_PROBE_CMD=probe_cmd,
        MAX_ATTEMPTS=max_attempts,
    )
    return subprocess.run(
        ["bash", RETRY, str(tmp_path), poll, max_wait],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_retry_success_writes_artifact_and_exits_zero(tmp_path):
    proc = _run_retry(tmp_path, stage_cmd="echo '{\"value\": 1}'")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "landed" in proc.stdout
    with open(tmp_path / "stage_a.json") as f:
        assert json.load(f)["value"] == 1


def test_retry_gives_up_on_deterministic_failure(tmp_path):
    """A stage failing with the probe green must stop at MAX_ATTEMPTS —
    not burn the whole deadline re-running the same OOM/crash — and its
    failure output must land in the (appended) log, never the artifact."""
    proc = _run_retry(tmp_path, stage_cmd="sh -c 'echo junk-output; exit 7'")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "giving up" in proc.stdout
    # artifact slot must stay empty: junk stdout is not a measurement
    assert not (tmp_path / "stage_a.json").exists()
    log = (tmp_path / "stage_a.log").read_text()
    assert log.count("--- attempt") == 3
    assert "junk-output" in log


def test_retry_polls_while_device_unreachable(tmp_path):
    """With the probe failing the stage must never run; the deadline
    expiry reports the stage as still pending."""
    proc = _run_retry(
        tmp_path,
        stage_cmd="echo should-not-run",
        probe_cmd="false",
        poll="1",
        max_wait="2",
        timeout=60,
    )
    assert proc.returncode == 1
    assert "still pending: stage_a" in proc.stdout
    assert "device unreachable" in proc.stdout
    assert not (tmp_path / "stage_a.json").exists()


def test_retry_unknown_stage_fails_stage_not_script(tmp_path):
    """A typo'd stage name must burn its attempts and be given up on —
    the eval'd fallback exits a SUBSHELL, not the retry loop."""
    env = dict(
        os.environ,
        RETRY_STAGES="bench_resnet5O",  # typo
        RETRY_PROBE_CMD="true",
        MAX_ATTEMPTS="2",
    )
    proc = subprocess.run(
        ["bash", RETRY, str(tmp_path), "0", "20"],
        env=env, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "giving up" in proc.stdout
    assert not (tmp_path / "bench_resnet5O.json").exists()
    assert "unknown stage" in (tmp_path / "bench_resnet5O.log").read_text()


def test_eval_ab_emits_summary_contract(tmp_path):
    """bench_eval_ab's parent: interleaved fresh/resident subprocess arms,
    one summary JSON line with the per-arm means and the clean-process
    number as `value` (the PERF.md 802-vs-620 discrepancy protocol)."""
    import subprocess
    import sys

    out = tmp_path / "ab.json"
    proc = subprocess.run(
        [sys.executable, "scripts/bench_eval_ab.py", "--cpu",
         "--image-size", "32", "--batch", "2", "--beam", "2",
         "--iters", "1", "--windows", "2", "--steps", "1",
         "--repeats", "1", "--budget-s", "300", "--out", str(out)],
        # outer > sum of child budgets (2 arms x 300s), repo convention
        capture_output=True, text=True, timeout=700,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    summary = json.loads(out.read_text())
    assert summary["metric"] == "eval_images_per_sec"
    assert summary["value"] == summary["fresh_mean"] > 0
    assert summary["resident_mean"] > 0
    assert summary["resident_over_fresh"] > 0
    arms = sorted(r["arm"] for r in summary["rows"])
    assert arms == ["fresh", "resident"]
    for r in summary["rows"]:
        assert len(r["windows_batch_ms"]) == 2
