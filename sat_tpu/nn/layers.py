"""NN primitives with the reference's initialization/regularization semantics.

Equivalent of the reference's NN wrapper class (/root/reference/utils/nn.py):

* conv kernels: Xavier/Glorot-uniform init (utils/nn.py:15);
* fc kernels + embeddings: uniform(-0.08, 0.08) init (utils/nn.py:29-31);
* L2 kernel regularization is *not* baked into layers here — JAX losses are
  functional, so `regularization_loss` below walks the param pytree and
  reproduces the reference's accounting (utils/nn.py:17-43): fc kernels
  always regularized in training, conv kernels only when the CNN is
  trainable, biases and LSTM internals never.
* batch norm: TF1 defaults momentum=0.99 eps=1e-3, batch statistics only
  when the CNN trains (utils/nn.py:116-125).

All matmul/conv compute runs in ``compute_dtype`` (bfloat16 on TPU → MXU),
params stay ``param_dtype`` (fp32 master copies).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any

conv_kernel_init = nn.initializers.glorot_uniform()


def fc_kernel_init(scale: float = 0.08) -> Callable:
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

    return init


class Conv(nn.Module):
    """'same'-padded conv2d, optional relu (reference utils/nn.py:45-70)."""

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    activation: Optional[str] = "relu"
    use_bias: bool = True
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            features=self.features,
            kernel_size=self.kernel_size,
            strides=self.strides,
            padding="SAME",
            use_bias=self.use_bias,
            kernel_init=conv_kernel_init,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="conv",
        )(x)
        if self.activation == "relu":
            x = nn.relu(x)
            # L1 activity hook (reference utils/nn.py:23-26,55-57: the
            # activity regularizer attaches only to *activated* convs —
            # ResNet convs pass activation=None and never collect).  sow
            # is a no-op (and the sum DCE'd) unless the caller requests
            # the 'activity' collection as mutable.
            self.sow(
                "activity", "l1", jnp.abs(x.astype(jnp.float32)).sum(),
                reduce_fn=lambda a, b: a + b, init_fn=lambda: jnp.float32(0),
            )
        return x


def max_pool2d(x, pool_size=(2, 2), strides=(2, 2)):
    """'same'-padded max pool (reference utils/nn.py:72-83)."""
    return nn.max_pool(x, window_shape=pool_size, strides=strides, padding="SAME")


def dropout(x, rate: float, deterministic: bool, rng=None):
    """Inverted dropout matching tf.layers.dropout semantics."""
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


# ---------------------------------------------------------------------------
# Regularization accounting (functional replacement for TF's collection of
# per-layer regularizers, reference utils/nn.py:17-43 + model.py:328).
# ---------------------------------------------------------------------------


def regularization_loss(
    params,
    fc_scale: float,
    conv_scale: float,
    train_cnn: bool,
    exclude_substrings: Sequence[str] = ("lstm",),
) -> jnp.ndarray:
    """0.5 * scale * sum(w**2) per kernel — TF's l2_regularizer semantics.

    Rank-4 kernels are conv kernels (counted only when the CNN trains, since
    frozen-CNN runs exclude them from the loss in the reference); rank-2
    'kernel'/'embedding' leaves are fc kernels.  LSTM internals are excluded
    (the reference's LSTMCell has an initializer but no regularizer,
    model.py:228-230).
    """
    total = jnp.asarray(0.0, jnp.float32)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
        leaf_name = str(names[-1]) if names else ""
        full = "/".join(str(n) for n in names).lower()
        if any(s in full for s in exclude_substrings):
            continue
        # 'weights' catches the embedding table (reference regularizes it,
        # model.py:219-225); biases and BN scales/offsets never count.
        if leaf_name not in ("kernel", "embedding", "weights"):
            continue
        w = leaf.astype(jnp.float32)
        if w.ndim == 4:
            if train_cnn and conv_scale > 0:
                total = total + 0.5 * conv_scale * jnp.sum(w * w)
        elif w.ndim >= 2:
            if fc_scale > 0:
                total = total + 0.5 * fc_scale * jnp.sum(w * w)
    return total
