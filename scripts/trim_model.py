"""Strip optimizer slots from a checkpoint for slim inference files.

CLI twin of the reference's offline trim tool
(/root/reference/data/models/trim_model.py:11-18) over our npz format.

Usage: python scripts/trim_model.py in.npz out.npz
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sat_tpu.train.checkpoint import trim_checkpoint  # noqa: E402


def main() -> int:
    if len(sys.argv) != 3:
        print(__doc__)
        return 2
    kept = trim_checkpoint(sys.argv[1], sys.argv[2])
    print(f"{kept} entries kept -> {sys.argv[2]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
