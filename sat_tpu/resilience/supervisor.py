"""Crash-only supervisor: restart a wedged/crashed run from LAST_GOOD.

``scripts/tpu_retry.sh`` grew ad-hoc restart logic because nothing in
the runtime could do it; this module is that logic as a first-class
subsystem.  ``python -m sat_tpu.cli --supervise ...`` keeps the parent
process **jax-free forever** (the r02/r05 failure was ``import jax`` +
device init hanging uninterruptibly — the supervisor must outlive
exactly that) and runs the real work in a child process:

* the child is the identical CLI invocation minus ``--supervise``;
* a nonzero child exit — the watchdog's ``WATCHDOG_EXIT_CODE`` (wedged,
  state on disk is good), a ``SimulatedPreemption``/checkpoint failure
  (rc 1), or a signal death (rc < 0) — triggers a bounded-retry restart
  with the jittered exponential backoff of ``resilience.retry``;
* restarted children get ``--load`` appended (when absent) so they
  resume from the ``LAST_GOOD`` lineage pointer, and
  ``SAT_SUPERVISOR_RESTARTS`` in their environment so the run can gauge
  ``supervisor/restarts`` into ``heartbeat.json``;
* ``SAT_FI_*`` fault-injection variables are disarmed for restarted
  children: an injected deterministic fault would otherwise re-fire at
  the same step on every incarnation and live-lock the supervisor —
  exactly like the resilience tests delenv before resuming;
* SIGTERM/SIGINT to the supervisor forwards to the child and stops the
  restart loop — preemption of the *pair* stays graceful.

The supervisor exits 0 when a child finally succeeds, else with the last
child's exit code once the restart budget is spent.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from .faultinject import ENV_PREFIX as _FI_PREFIX
from .quarantine import DATA_CORRUPTION_EXIT_CODE
from .retry import backoff_delay
from .watchdog import WATCHDOG_EXIT_CODE

RESTARTS_ENV = "SAT_SUPERVISOR_RESTARTS"

# Supervisor-side PRNG mirrors retry._jitter_rng: fixed seed for
# deterministic tests, PID decorrelation on a real fleet.
_rng = random.Random(0x5A7D)


def _strip_supervise(argv: List[str]) -> List[str]:
    out = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == "--supervise":
            continue
        if a == "--max_restarts":
            skip = True
            continue
        if a.startswith("--max_restarts="):
            continue
        out.append(a)
    return out


def _describe(rc: int) -> str:
    # The child writes postmortem_<run_id>/ under its telemetry dir on
    # both abnormal codes when launched with --blackbox; the supervisor
    # is a jax-free parent that never resolves that path, so it names
    # the analyzer instead of the bundle.
    if rc == WATCHDOG_EXIT_CODE:
        return (
            "watchdog abort (wedged run; LAST_GOOD landed); with "
            "--blackbox a postmortem_<run_id>/ bundle is in the telemetry "
            "dir — summarize with scripts/analyze_postmortem.py"
        )
    if rc == DATA_CORRUPTION_EXIT_CODE:
        return (
            "systemic data corruption (quarantine ceiling); with "
            "--blackbox see postmortem_<run_id>/ via "
            "scripts/analyze_postmortem.py"
        )
    if rc < 0:
        try:
            return f"killed by {signal.Signals(-rc).name}"
        except ValueError:
            return f"killed by signal {-rc}"
    return f"exit code {rc}"


def supervise(
    argv: List[str],
    *,
    max_restarts: int = 3,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    runner: Optional[Callable[[List[str], Dict[str, str]], int]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> int:
    """Run ``python -m sat_tpu.cli <argv minus --supervise>`` under the
    crash-only restart policy.  ``runner`` overrides the child launch for
    tests (receives the full command + environment, returns an rc)."""
    child_argv = _strip_supervise(list(argv))
    restarts = 0
    stop = {"signaled": None}

    child_proc: Dict[str, Optional[subprocess.Popen]] = {"p": None}

    def _forward(signum, frame):
        stop["signaled"] = signum
        p = child_proc["p"]
        if p is not None and p.poll() is None:
            try:
                p.send_signal(signum)
            except OSError:
                pass

    installed = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            installed[sig] = signal.signal(sig, _forward)
        except ValueError:  # not the main thread (tests)
            pass

    def _launch(cmd: List[str], env: Dict[str, str]) -> int:
        if runner is not None:
            return runner(cmd, env)
        p = subprocess.Popen(cmd, env=env)
        child_proc["p"] = p
        try:
            return p.wait()
        finally:
            child_proc["p"] = None

    try:
        while True:
            this_argv = list(child_argv)
            env = dict(os.environ)
            env[RESTARTS_ENV] = str(restarts)
            if restarts:
                if "--load" not in this_argv:
                    this_argv.append("--load")
                for k in [k for k in env if k.startswith(_FI_PREFIX)]:
                    del env[k]
            cmd = [sys.executable, "-m", "sat_tpu.cli"] + this_argv
            print(
                f"[supervise] launching attempt {restarts + 1} "
                f"(restarts so far: {restarts}): {' '.join(this_argv)}",
                file=sys.stderr,
                flush=True,
            )
            rc = _launch(cmd, env)
            if rc == 0:
                if restarts:
                    print(
                        f"[supervise] run completed after {restarts} "
                        "restart(s)",
                        file=sys.stderr,
                        flush=True,
                    )
                return 0
            if stop["signaled"] is not None:
                print(
                    f"[supervise] child died ({_describe(rc)}) after the "
                    "supervisor was signaled — not restarting",
                    file=sys.stderr,
                    flush=True,
                )
                return rc
            if rc == DATA_CORRUPTION_EXIT_CODE:
                # fatal, never restarted: the rot is in the INPUT data,
                # so a relaunch deterministically re-reads it and trips
                # the same ceiling — crash-only restarts only help when
                # the failure is in the process plane
                print(
                    f"[supervise] child failed ({_describe(rc)}) — not "
                    "restarting; repair the data (--repair_shards) or "
                    "inspect the quarantine ledger",
                    file=sys.stderr,
                    flush=True,
                )
                return rc
            if restarts >= max_restarts:
                print(
                    f"[supervise] child failed ({_describe(rc)}) and the "
                    f"restart budget ({max_restarts}) is spent — giving up",
                    file=sys.stderr,
                    flush=True,
                )
                return rc
            delay = backoff_delay(
                restarts,
                base_delay_s=backoff_base_s,
                max_delay_s=backoff_max_s,
                rng=_rng,
            )
            restarts += 1
            print(
                f"[supervise] child failed ({_describe(rc)}); restarting "
                f"from LAST_GOOD in {delay:.2f}s "
                f"(restart {restarts}/{max_restarts})",
                file=sys.stderr,
                flush=True,
            )
            sleep(delay)
    finally:
        for sig, prev in installed.items():
            signal.signal(sig, prev)
