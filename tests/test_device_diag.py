"""Device-side diagnostics tests: in-graph model-health taps
(telemetry/device.py), the off-is-bitwise-identical guarantee, the
doubly-stochastic identity, the no-hidden-sync lint, the bench
provenance stamp, the regression gate (scripts/check_regression.py), and
the end-to-end ``--diag_level full`` artifact chain
(docs/OBSERVABILITY.md)."""

import glob
import json
import os
import re
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sat_tpu import telemetry
from sat_tpu.telemetry import device as tdev

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_global_telemetry():
    yield
    telemetry.disable()


# ---------------------------------------------------------------------------
# tap math vs numpy references
# ---------------------------------------------------------------------------


def test_global_l2_matches_numpy():
    rng = np.random.default_rng(0)
    tree = {
        "a": rng.normal(size=(3, 4)).astype(np.float32),
        "b": {"c": rng.normal(size=(7,)).astype(np.float32)},
    }
    want = np.sqrt(
        np.sum(tree["a"] ** 2) + np.sum(tree["b"]["c"] ** 2)
    )
    got = tdev._l2(jax.tree.map(jnp.asarray, tree))
    assert float(got) == pytest.approx(float(want), rel=1e-6)
    assert float(tdev._l2({})) == 0.0


def test_l2_accumulates_in_fp32_for_low_precision_leaves():
    # 4096 bf16 ones: naive bf16 accumulation saturates badly; the fp32
    # upcast keeps the norm exact (= 64)
    tree = {"w": jnp.ones((4096,), jnp.bfloat16)}
    assert float(tdev._l2(tree)) == pytest.approx(64.0, rel=1e-6)


def test_nonfinite_count_matches_numpy():
    tree = {
        "a": jnp.asarray([1.0, np.nan, np.inf, -np.inf]),
        "b": jnp.asarray([[0.0, 2.0], [np.nan, 3.0]]),
    }
    assert float(tdev._nonfinite_count(tree)) == 4.0
    assert float(tdev._nonfinite_count({})) == 0.0


def test_attention_entropy_uniform_and_onehot():
    B, T, N = 2, 3, 8
    uniform = jnp.full((B, T, N), 1.0 / N)
    masks = jnp.ones((B, T))
    assert float(tdev.attention_entropy(uniform, masks)) == pytest.approx(
        np.log(N), rel=1e-5
    )
    onehot = jnp.zeros((B, T, N)).at[..., 0].set(1.0)
    assert float(tdev.attention_entropy(onehot, masks)) == pytest.approx(
        0.0, abs=1e-6
    )


def test_attention_entropy_respects_masks():
    # row 0: uniform (entropy ln N); row 1: one-hot (entropy 0) but
    # masked OUT — the masked mean must see only row 0
    N = 4
    alphas = jnp.stack(
        [jnp.full((N,), 1.0 / N), jnp.zeros((N,)).at[0].set(1.0)]
    )[None]                                     # [1,2,N]
    masks = jnp.asarray([[1.0, 0.0]])
    assert float(tdev.attention_entropy(alphas, masks)) == pytest.approx(
        np.log(N), rel=1e-5
    )


def test_alpha_coverage_deviation_hand_computed():
    # B=1, T=2, N=2; masks all-on.  coverage_i = sum_t alpha_ti:
    # ctx0 -> 0.7+0.2 = 0.9, ctx1 -> 0.3+0.8 = 1.1
    # dev = mean((1-0.9)^2, (1-1.1)^2) = mean(0.01, 0.01) = 0.01
    alphas = jnp.asarray([[[0.7, 0.3], [0.2, 0.8]]])
    masks = jnp.ones((1, 2))
    assert float(
        tdev.alpha_coverage_deviation(alphas, masks)
    ) == pytest.approx(0.01, rel=1e-5)
    # masking out word 1 changes coverage to (0.7, 0.3):
    # dev = mean(0.09, 0.49) = 0.29
    masks = jnp.asarray([[1.0, 0.0]])
    assert float(
        tdev.alpha_coverage_deviation(alphas, masks)
    ) == pytest.approx(0.29, rel=1e-5)


def test_loss_taps_levels_and_values():
    B, T, N, V = 2, 3, 4, 7
    rng = np.random.default_rng(1)
    alphas = jax.nn.softmax(
        jnp.asarray(rng.normal(size=(B, T, N)).astype(np.float32))
    )
    masks = jnp.ones((B, T))
    logits = jnp.asarray(rng.normal(size=(B, T, V)).astype(np.float32))
    assert tdev.loss_taps("off", alphas=alphas, masks=masks, logits=logits) == {}
    taps = tdev.loss_taps("basic", alphas=alphas, masks=masks, logits=logits)
    assert set(taps) == {
        "diag/attn_entropy",
        "diag/attn_entropy_frac",
        "diag/alpha_coverage_dev",
        "diag/logit_max",
    }
    assert float(taps["diag/logit_max"]) == pytest.approx(
        float(np.max(np.abs(np.asarray(logits)))), rel=1e-6
    )
    # entropy_frac normalizes by the uniform bound ln N
    assert float(taps["diag/attn_entropy_frac"]) == pytest.approx(
        float(taps["diag/attn_entropy"]) / np.log(N), rel=1e-5
    )
    assert 0.0 < float(taps["diag/attn_entropy_frac"]) <= 1.0


def test_grad_taps_levels_groups_and_ratio():
    rng = np.random.default_rng(2)

    def tree():
        return {
            "decoder": {
                "lstm": {"w": jnp.asarray(rng.normal(size=(4, 4)).astype(np.float32))},
                "attend": {"w": jnp.asarray(rng.normal(size=(3,)).astype(np.float32))},
            }
        }

    grads, updates, params = tree(), tree(), tree()
    assert tdev.grad_taps("off", grads=grads, updates=updates, params=params) == {}
    basic = tdev.grad_taps("basic", grads=grads, updates=updates, params=params)
    assert set(basic) == {
        "diag/param_norm",
        "diag/update_norm",
        "diag/update_ratio",
    }
    assert float(basic["diag/update_ratio"]) == pytest.approx(
        float(basic["diag/update_norm"]) / float(basic["diag/param_norm"]),
        rel=1e-5,
    )
    full = tdev.grad_taps("full", grads=grads, updates=updates, params=params)
    assert set(basic) < set(full)
    assert full.keys() >= {
        "diag/grad_nonfinite",
        "diag/grad_norm/decoder.lstm",
        "diag/update_norm/decoder.attend",
        "diag/param_norm/decoder.lstm",
    }
    # per-group norm is the norm of just that subtree
    assert float(full["diag/grad_norm/decoder.lstm"]) == pytest.approx(
        float(np.sqrt(np.sum(np.asarray(grads["decoder"]["lstm"]["w"]) ** 2))),
        rel=1e-5,
    )
    assert float(full["diag/grad_nonfinite"]) == 0.0


# ---------------------------------------------------------------------------
# in-step semantics: off is bitwise-identical, coverage tap matches the
# doubly-stochastic loss term
# ---------------------------------------------------------------------------


def _tiny_config(**kw):
    from sat_tpu.config import Config

    return Config(
        phase="train",
        batch_size=4,
        image_size=32,
        dim_embedding=16,
        num_lstm_units=16,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        vocabulary_size=50,
        compute_dtype="float32",
        **kw,
    )


def _tiny_batch(config, seed=0):
    rng = np.random.default_rng(seed)
    B, T = config.batch_size, config.max_caption_length
    return {
        "images": jnp.asarray(
            rng.integers(0, 255, (B, config.image_size, config.image_size, 3),
                         np.uint8)
        ),
        "word_idxs": jnp.asarray(
            rng.integers(0, config.vocabulary_size, (B, T), np.int32)
        ),
        "masks": jnp.asarray(
            (np.arange(T)[None, :] < rng.integers(3, T, (B, 1))).astype(
                np.float32
            )
        ),
    }


@pytest.fixture(scope="module")
def diag_steps():
    """Two steps of the tiny model under every diag level, same seeds."""
    from sat_tpu.train.step import create_train_state, make_jit_train_step

    out = {}
    for level in ("off", "basic", "full"):
        config = _tiny_config(diag_level=level)
        step_fn = make_jit_train_step(config)
        state = create_train_state(jax.random.PRNGKey(0), config)
        metrics = None
        for i in range(2):
            state, metrics = step_fn(
                state, _tiny_batch(config, seed=i),
                jax.random.key(7, impl=config.rng_impl),
            )
        out[level] = (config, state, jax.device_get(metrics))
    return out


def test_diag_off_params_bitwise_identical_to_full(diag_steps):
    """The taps must be observation-only: enabling them cannot perturb
    training, down to the last bit."""
    _, state_off, _ = diag_steps["off"]
    _, state_full, _ = diag_steps["full"]
    off_leaves = jax.tree_util.tree_leaves(jax.device_get(state_off.params))
    full_leaves = jax.tree_util.tree_leaves(jax.device_get(state_full.params))
    assert len(off_leaves) == len(full_leaves)
    for a, b in zip(off_leaves, full_leaves):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_diag_metric_sets_nest_by_level(diag_steps):
    _, _, m_off = diag_steps["off"]
    _, _, m_basic = diag_steps["basic"]
    _, _, m_full = diag_steps["full"]
    assert not any(k.startswith("diag/") for k in m_off)
    basic_diag = {k for k in m_basic if k.startswith("diag/")}
    full_diag = {k for k in m_full if k.startswith("diag/")}
    assert basic_diag == {
        "diag/attn_entropy",
        "diag/attn_entropy_frac",
        "diag/alpha_coverage_dev",
        "diag/logit_max",
        "diag/param_norm",
        "diag/update_norm",
        "diag/update_ratio",
    }
    assert basic_diag < full_diag
    # full adds the per-layer-group split over the decoder blocks
    groups = {"word_embedding", "lstm", "initialize", "attend", "decode"}
    for g in groups:
        assert f"diag/grad_norm/decoder.{g}" in full_diag
    # non-diag metrics are level-invariant
    assert {k for k in m_off} == {
        k for k in m_full if not k.startswith("diag/")
    }
    for k, v in m_full.items():
        assert np.isfinite(v), f"{k} not finite"


def test_alpha_coverage_tap_matches_doubly_stochastic_loss(diag_steps):
    """attention_loss = factor * 0.5 * mean((1-Σα)²) — the tap is the
    unscaled penalty, so the identity ties it to the paper's eq. 14."""
    config, _, m = diag_steps["basic"]
    want = config.attention_loss_factor * 0.5 * m["diag/alpha_coverage_dev"]
    assert float(m["attention_loss"]) == pytest.approx(float(want), rel=1e-4)


# ---------------------------------------------------------------------------
# no-hidden-sync lint (static)
# ---------------------------------------------------------------------------

_SYNC_RE = re.compile(
    r"block_until_ready|\.item\(|(?<![\w.])float\(|np\.asarray\("
)


def _sync_lint_targets():
    """runtime.py plus every module of the serving AND resilience
    subsystems — the serve hot path (batcher dispatch chain, engine
    drain) carries the same zero-hidden-syncs contract as the
    train/decode loops, and the resilience observers (watchdog thread,
    sentinel, fault plan) run INSIDE those loops so a hidden sync there
    is a hidden sync in the loop.  ``data`` rides the same contract: the
    prefetch producers and the integrity verifier run host-side work
    that must never touch a device value."""
    targets = [os.path.join(REPO, "sat_tpu", "runtime.py")]
    # bulk rides the serve drain discipline: its decode loop drains the
    # slot-pool done flags whole-array, so it lints like serve does;
    # lifecycle's loader syncs once at candidate-staging time (declared)
    # and its controller/reloader threads run beside the serve loop
    for sub in ("serve", "resilience", "data", "bulk", "lifecycle"):
        sub_dir = os.path.join(REPO, "sat_tpu", sub)
        targets.extend(
            os.path.join(sub_dir, f)
            for f in sorted(os.listdir(sub_dir))
            if f.endswith(".py")
        )
    # the observability modules added by ISSUE 9 run on the serve request
    # path (tracectx, promtext) or inside loop-adjacent threads (slo,
    # profwin), so they carry the same contract — joined by ISSUE 10's
    # fleet plane and black box, which tick at the train-loop log
    # boundary; the rest of telemetry/ is exempt (exporters' attention
    # dump is an offline boundary)
    # quality.py and exemplar.py (ISSUE 19) run on the serve detok
    # thread per request — the quality plane's zero-new-syncs claim is
    # exactly this lint
    for mod in ("tracectx.py", "promtext.py", "slo.py", "profwin.py",
                "fleet.py", "blackbox.py", "quality.py", "exemplar.py"):
        targets.append(os.path.join(REPO, "sat_tpu", "telemetry", mod))
    # the encoder-quantization pass runs at serve load time inside the
    # engine boot path: its one-time calibration host syncs must be
    # declared, and nothing else in it may sync (the quantized encode is
    # AOT-compiled onto the same async dispatch chain as the fp32 one)
    targets.append(os.path.join(REPO, "sat_tpu", "nn", "quant.py"))
    return targets


def test_runtime_sync_sites_are_annotated():
    """Every host-sync construct in runtime.py and sat_tpu/serve/ must
    carry a `# sync-ok` marker naming its boundary — new unmarked syncs
    fail this lint, which is the guard behind the zero-extra-syncs claim
    of the diag taps and the serve path's one-drain-per-batch design."""
    bad = []
    for path in _sync_lint_targets():
        rel = os.path.relpath(path, REPO)
        for i, line in enumerate(open(path), 1):
            code = line.split("#", 1)[0]
            if _SYNC_RE.search(code) and "sync-ok" not in line:
                bad.append(f"{rel}:{i}: {line.strip()}")
    assert not bad, "unannotated host syncs:\n" + "\n".join(bad)


def test_device_tap_modules_never_sync():
    """device.py/xla.py build graph values and host reports; neither may
    force a transfer of its own."""
    for mod in ("device.py", "xla.py"):
        src = open(os.path.join(REPO, "sat_tpu", "telemetry", mod)).read()
        for needle in ("block_until_ready", ".item(", "device_get("):
            assert needle not in src, f"telemetry/{mod} contains {needle}"


def test_telemetry_core_is_jax_free():
    """The host-side telemetry core must import (and run) without jax —
    bench_telemetry.py and the lint above both rely on this split."""
    code = (
        "import sys\n"
        "assert 'jax' not in sys.modules\n"
        "from sat_tpu import telemetry\n"
        "from sat_tpu.telemetry import exporters, heartbeat, spans\n"
        "from sat_tpu.telemetry import blackbox, fleet, profwin, promtext, slo, tracectx\n"
        "from sat_tpu.telemetry import exemplar, quality\n"
        "stamp = telemetry.bench_stamp()\n"
        "assert 'jax' not in sys.modules, 'telemetry core pulled in jax'\n"
        "assert 'platform' not in stamp['device']\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


def test_fleet_router_is_jax_free():
    """The serving fleet's control plane (router + replica manager, and
    the lazy serve package itself) must import without jax: like the
    --supervise parent, the router exists to outlive replicas whose
    device runtime wedges, so it may never load the device stack.  Only
    touching an engine-side symbol pulls jax (PEP 562 laziness)."""
    code = (
        "import sys\n"
        "assert 'jax' not in sys.modules\n"
        "import sat_tpu.serve\n"
        "from sat_tpu.serve import replica, router, scheduler, tenants\n"
        "router.replica_weight(True, False, 0.25)\n"
        "replica.parse_endpoints('127.0.0.1:8710,127.0.0.1:8711')\n"
        # the multi-tenant plane (registry + DRR scheduler) rides the
        # router process too — parse, admit, and schedule without jax
        "reg = tenants.TenantRegistry.parse('a:4:10,b:1')\n"
        "assert reg.multi and reg.try_admit('a')\n"
        "drr = scheduler.DeficitRoundRobin(maxsize=2, weights=reg.weights())\n"
        "class _I:\n"
        "    tenant = 'b'\n"
        "drr.put_nowait(_I())\n"
        "assert drr.get_nowait().tenant == 'b'\n"
        "assert 'jax' not in sys.modules, 'router/replica/tenants pulled in jax'\n"
        "sat_tpu.serve.Rejected\n"
        "assert 'jax' in sys.modules, 'lazy engine-side export broken'\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr


def test_lifecycle_control_plane_is_jax_free():
    """The model-lifecycle control plane (canary hash, reloader poll,
    controller state machine) must import and run without jax: the
    router forwards /reload-/promote-/rollback without owning a device
    stack, and the reloader/ledger logic unit-tests on jax-free hosts.
    Only the loader touches jax, and only inside load_candidate."""
    code = (
        "import sys\n"
        "assert 'jax' not in sys.modules\n"
        "import sat_tpu.lifecycle\n"
        "from sat_tpu.lifecycle import canary, controller, loader, reloader\n"
        "assert canary.assign_slot('req-1', 0.5) in ('incumbent', 'canary')\n"
        "assert canary.caption_divergence('a b', 'a b') == 0.0\n"
        "controller.STATE_CODES['CANARY']\n"
        "assert 'jax' not in sys.modules, 'lifecycle control plane pulled in jax'\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr


def test_bulk_control_plane_is_jax_free():
    """The bulk subsystem's control plane (corpus walk, shard plan,
    manifest, output writer — everything resume touches before deciding
    there is work) must import and run without jax: a resume that finds
    all shards complete exits without ever booting the device runtime,
    and the --supervise parent may import the package for diagnostics."""
    code = (
        "import sys\n"
        "assert 'jax' not in sys.modules\n"
        "import sat_tpu.bulk\n"
        "from sat_tpu.bulk import corpus, manifest, runner, writer\n"
        "manifest.corpus_fingerprint(['a.jpg'], 4, 32)\n"
        "corpus.plan_shards(['a.jpg', 'b.jpg'], 1)\n"
        "writer.shard_filename(3)\n"
        "assert 'jax' not in sys.modules, 'bulk control plane pulled in jax'\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# bench provenance stamp
# ---------------------------------------------------------------------------


def test_bench_stamp_schema_and_git_sha():
    stamp = telemetry.bench_stamp()
    assert stamp["schema_version"] == telemetry.SCHEMA_VERSION == 1
    assert stamp["run_id"] == telemetry.run_id()
    assert stamp["stamp_unix"] > 0
    # this test runs inside the repo, so the sha must resolve
    assert re.fullmatch(r"[0-9a-f]{12}", stamp["git_sha"])
    dev = stamp["device"]
    assert dev["host"] and dev["machine"] and dev["python"]
    # jax is imported in this process, so the device facts are present
    assert dev["platform"] == "cpu"
    assert dev["device_count"] >= 1


def test_all_bench_scripts_emit_the_stamp():
    """Satellite: every scripts/bench_*.py must merge bench_stamp() into
    its JSON output so check_regression can verify provenance."""
    for path in sorted(glob.glob(os.path.join(REPO, "scripts", "bench_*.py"))):
        src = open(path).read()
        assert "bench_stamp" in src, f"{os.path.basename(path)} is unstamped"


# ---------------------------------------------------------------------------
# regression gate (scripts/check_regression.py)
# ---------------------------------------------------------------------------

GATE = os.path.join(REPO, "scripts", "check_regression.py")


def _gate(*argv, timeout=60):
    return subprocess.run(
        [sys.executable, GATE, *argv], capture_output=True, text=True,
        cwd=REPO, timeout=timeout,
    )


def _bench_row(**kw):
    row = {
        "metric": "train_captions_per_sec",
        "value": 1000.0,
        "unit": "captions/s",
        "vs_baseline": 1.0,
        "schema_version": telemetry.SCHEMA_VERSION,
    }
    row.update(kw)
    return row


def test_gate_infra_skips_repo_bench_trajectory():
    """The committed BENCH_r0*.json files are the real acceptance input.
    Their newest artifact records the r05 ``device_unreachable`` outage,
    so the gate must report an infra-skip (exit 3) — an outage is not a
    measurement and must be distinguishable from both a pass (0) and a
    regression (2) without a human reading stderr."""
    proc = _gate(os.path.join(REPO, "BENCH_r0*.json"))
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "infra-skip (device_unreachable)" in proc.stderr


def test_gate_flags_degraded_throughput(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(_bench_row()))
    cur.write_text(json.dumps(_bench_row(value=700.0)))   # -30%
    proc = _gate(str(base), str(cur))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "train_captions_per_sec" in proc.stdout
    # same file as candidate of itself: clean
    assert _gate(str(base), str(base)).returncode == 0
    # improvement is never a regression
    cur.write_text(json.dumps(_bench_row(value=1400.0)))
    assert _gate(str(base), str(cur)).returncode == 0


def test_gate_direction_lower_is_better_for_times(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(_bench_row(metric="step_time_ms", value=30.0,
                                          unit="ms")))
    cur.write_text(json.dumps(_bench_row(metric="step_time_ms", value=40.0,
                                         unit="ms")))
    assert _gate(str(base), str(cur)).returncode == 2
    cur.write_text(json.dumps(_bench_row(metric="step_time_ms", value=25.0,
                                         unit="ms")))
    assert _gate(str(base), str(cur)).returncode == 0


def test_gate_respects_margin_override(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(_bench_row()))
    cur.write_text(json.dumps(_bench_row(value=960.0)))   # -4%
    assert _gate(str(base), str(cur)).returncode == 0     # default 5%
    assert _gate(str(base), str(cur), "--margin",
                 "train_captions_per_sec=2").returncode == 2


def test_gate_refuses_schema_mismatch(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(_bench_row()))
    cur.write_text(json.dumps(_bench_row(schema_version=99)))
    proc = _gate(str(base), str(cur))
    assert proc.returncode == 3
    assert "schema" in (proc.stdout + proc.stderr).lower()


def test_gate_compile_report_mode(tmp_path):
    def report(flops, temp):
        return {
            "schema_version": telemetry.SCHEMA_VERSION,
            "run_id": "r",
            "time_unix": 1.0,
            "backend": "cpu",
            "device_kind": "cpu",
            "functions": {
                "train_step": {
                    "lower_seconds": 0.1,
                    "compile_seconds": 1.0,
                    "cost": {"flops": flops},
                    "memory": {"temp_bytes": temp},
                }
            },
        }

    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(report(1e9, 1 << 20)))
    cur.write_text(json.dumps(report(1e9, 1 << 20)))
    assert _gate("--compile-baseline", str(base),
                 "--compile-current", str(cur)).returncode == 0
    # +10% flops over the 1% margin: regression
    cur.write_text(json.dumps(report(1.1e9, 1 << 20)))
    assert _gate("--compile-baseline", str(base),
                 "--compile-current", str(cur)).returncode == 2


# ---------------------------------------------------------------------------
# end-to-end: full-diag train + attention-introspection eval
# ---------------------------------------------------------------------------

SMALL_MODEL = dict(
    image_size=32,
    dim_embedding=16,
    num_lstm_units=16,
    dim_initialize_layer=16,
    dim_attend_layer=16,
    dim_decode_layer=32,
    compute_dtype="float32",
    save_period=3,
    log_every=2,
    num_epochs=1,
    num_data_workers=2,
)


@pytest.fixture(scope="module")
def diag_run(coco_fixture, tmp_path_factory):
    """One full-diag telemetry train run + attention-mapped eval, shared
    by the artifact assertions below."""
    from sat_tpu import runtime

    tmp = tmp_path_factory.mktemp("diag_run")
    config = coco_fixture["config"].replace(
        **SMALL_MODEL,
        save_dir=str(tmp / "models"),
        summary_dir=str(tmp / "summary"),
        telemetry=True,
        heartbeat_interval=0.1,
        diag_level="full",
    )
    state = runtime.train(config)
    telemetry.disable()
    cfg_eval = config.replace(phase="eval", save_attention_maps=True)
    runtime.evaluate(cfg_eval, state=state)
    telemetry.disable()
    return config, cfg_eval, state


def test_e2e_diag_gauges_ride_log_boundaries(diag_run):
    config, _, _ = diag_run
    path = os.path.join(config.summary_dir, "telemetry", "telemetry.jsonl")
    rows = [json.loads(l) for l in open(path)]
    assert [r["step"] for r in rows] == [2, 4, 6]    # log_every=2, 6 steps
    for r in rows:
        diag = {k: v for k, v in r["gauges"].items() if k.startswith("diag/")}
        assert {
            "diag/attn_entropy",
            "diag/alpha_coverage_dev",
            "diag/param_norm",
            "diag/grad_nonfinite",
            "diag/grad_norm/decoder.lstm",
        } <= set(diag)
        for k, v in diag.items():
            assert np.isfinite(v), f"step {r['step']}: {k} not finite"
        assert r["gauges"]["diag/grad_nonfinite"] == 0


def test_e2e_metrics_jsonl_carries_diag_columns(diag_run):
    config, _, _ = diag_run
    rows = [
        json.loads(l)
        for l in open(os.path.join(config.summary_dir, "metrics.jsonl"))
    ]
    # log_every=2 over 6 steps -> rows at the 3 log boundaries
    assert [r["step"] for r in rows] == [2, 4, 6]
    for r in rows:
        assert 0.0 < r["diag/attn_entropy_frac"] <= 1.0
        assert r["diag/alpha_coverage_dev"] >= 0.0


def test_e2e_compile_report_schema(diag_run):
    config, _, _ = diag_run
    path = os.path.join(config.summary_dir, "telemetry", "compile_report.json")
    report = json.load(open(path))
    assert report["schema_version"] == telemetry.SCHEMA_VERSION
    assert report["backend"] == "cpu"
    fn = report["functions"]["train_step"]
    assert fn["compile_seconds"] > 0 and fn["lower_seconds"] > 0
    assert fn["cost"]["flops"] > 0
    assert fn["memory"]["temp_bytes"] > 0
    assert fn["memory"]["output_bytes"] > 0
    # donation facts: the step donates its state arguments
    assert 0 < fn["donation"]["donated_args"] <= fn["donation"]["total_args"]
    assert fn["argument_bytes_host_estimate"] > 0


def test_e2e_eval_compile_report_covers_decode_fns(diag_run):
    config, cfg_eval, _ = diag_run
    path = os.path.join(
        config.summary_dir, "telemetry", "compile_report-decode.json"
    )
    report = json.load(open(path))
    assert {"decode/encode", "decode/beam_search"} <= set(report["functions"])
    for fn in report["functions"].values():
        assert fn["compile_seconds"] > 0


def test_e2e_heartbeat_carries_diag_and_device_facts(diag_run):
    config, _, _ = diag_run
    hb = json.load(
        open(os.path.join(config.summary_dir, "telemetry", "heartbeat.json"))
    )
    assert hb["device_platform"] == "cpu"
    assert "device_kind" in hb
    # last diag snapshot, gauge prefix stripped
    assert hb["diag"]["attn_entropy"] > 0
    assert hb["diag"]["alpha_coverage_dev"] >= 0
    # xla accounting summary rides along
    assert hb["xla"]["train_step/compile_s"] > 0


def test_e2e_attention_artifacts_schema(diag_run):
    _, cfg_eval, _ = diag_run
    out_dir = cfg_eval.eval_result_dir
    rows = [json.loads(l) for l in open(os.path.join(out_dir, "attn.jsonl"))]
    assert rows, "no attention records exported"
    for r in rows:
        assert r["run_id"]
        assert len(r["words"]) == len(r["entropy"]) == len(r["alphas"])
        assert r["grid"] ** 2 == r["num_ctx"] == len(r["alphas"][0])
        for h, grid_row in zip(r["entropy"], r["alphas"]):
            assert 0.0 <= h <= np.log(r["num_ctx"]) + 1e-3
            assert sum(grid_row) == pytest.approx(1.0, abs=0.01)
        assert 0.0 <= r["entropy_frac_mean"] <= 1.0
        assert r["coverage_dev"] >= 0.0
        assert 0.0 < r["alpha_max"] <= 1.0
    html = open(os.path.join(out_dir, "attn.html")).read()
    assert "<table" in html and "rgba(" in html
    for r in rows:
        assert r["caption"] in html and str(r["image_id"]) in html


def test_diag_off_run_leaves_no_diag_columns(coco_fixture, tmp_path):
    """Default off: metrics.jsonl must not grow diag columns (the
    bitwise-unchanged guarantee's observable face)."""
    from sat_tpu import runtime

    config = coco_fixture["config"].replace(
        **SMALL_MODEL,
        save_dir=str(tmp_path / "models"),
        summary_dir=str(tmp_path / "summary"),
        max_steps=2,
    )
    runtime.train(config)
    rows = [
        json.loads(l)
        for l in open(os.path.join(config.summary_dir, "metrics.jsonl"))
    ]
    assert rows
    for r in rows:
        assert not any(k.startswith("diag/") for k in r)


def test_cli_rejects_bad_diag_level():
    from sat_tpu.config import Config

    with pytest.raises(ValueError, match="diag_level"):
        Config(diag_level="verbose")
