"""Quality-plane overhead gate: what the drift observability costs.

docs/OBSERVABILITY.md claims the caption-quality plane (signal
extraction at the detok boundary + streaming sketch/PSI updates,
sat_tpu/telemetry/quality.py) is cheap enough to leave on for every
serving request.  This bench puts a number on it the same way the
metering bench does:

* **live arm** — a real in-process serving stack booted with
  ``--serve_quality on`` (tiny procedural model, AOT-warmed), one
  closed-loop client; measures request p50 WITH the plane enabled,
  asserts ZERO steady-state recompiles (the alphas harvest must ride
  the existing drained transfer, never add a jitted gather), and that
  /stats carries a live ``quality`` block with a frozen reference.
* **quality-path microbench** — the per-request host work in isolation
  (``extract_signals`` over a real drained beam result, alphas
  included, then ``QualityMonitor.observe`` with a frozen reference —
  the sketch updates + outlier screen every request pays; periodic
  PSI publication rides its rate limiter exactly as in production),
  priced against the live arm's p50.

Prints one BENCH-contract JSON line (scripts/check_regression.py):

* ``quality_overhead_pct`` (pct, lower is better, noise-floored at
  0.05) — extraction+sketch cost as % of serve p50.  **Hard gate:**
  raw overhead <= 0.5% and zero steady-state recompiles, exit 1
  otherwise.

Usage: python scripts/bench_quality.py [--requests 80] [--microbench 4000]
       [--workdir DIR]
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import shutil
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_quality +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


SENTENCES = [
    "a man riding a horse on the beach.",
    "a group of people standing around a kitchen.",
    "two dogs playing with a red ball in the grass.",
    "a plate of food with rice and vegetables.",
    "a bus driving down a city street.",
    "a cat sitting on top of a wooden table.",
]


def _make_jpegs(n: int, size: int) -> list:
    import cv2

    out = []
    for i in range(n):
        rng = np.random.default_rng(100 + i)
        img = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
        c = i % 3
        extent = size // 4 + (3 * i) % (3 * size // 4)
        img[:extent, :, c] = 30 * (i + 1) % 255
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        out.append(bytes(buf))
    return out


def _make_ckpt(workdir, quality_window):
    """Tiny fresh model saved through checkpoint+lineage, quality ON."""
    import jax

    from sat_tpu import runtime, telemetry
    from sat_tpu.config import Config
    from sat_tpu.data.vocabulary import Vocabulary
    from sat_tpu.resilience import lineage
    from sat_tpu.train.checkpoint import save_checkpoint
    from sat_tpu.train.step import create_train_state

    vocab_file = os.path.join(workdir, "vocabulary.csv")
    vocabulary = Vocabulary(size=50)
    vocabulary.build(SENTENCES)
    vocabulary.save(vocab_file)

    config = Config(
        phase="serve",
        image_size=32,
        dim_embedding=16,
        num_lstm_units=16,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        compute_dtype="float32",
        vocabulary_size=vocabulary.size,
        vocabulary_file=vocab_file,
        beam_size=2,
        save_dir=os.path.join(workdir, "models"),
        summary_dir=os.path.join(workdir, "summary"),
        serve_buckets=(1, 4),
        serve_max_batch=4,
        serve_max_wait_ms=2,
        heartbeat_interval=0.0,
        serve_quality="on",
        serve_quality_window=quality_window,
        serve_quality_exemplar_dir=os.path.join(workdir, "exemplars"),
    )
    os.makedirs(config.save_dir, exist_ok=True)
    tel = telemetry.enable(capacity=1 << 18)
    runtime._install_compile_listener()
    state = create_train_state(jax.random.PRNGKey(0), config)
    save_checkpoint(state, config)
    lineage.mark_last_good(config.save_dir, int(np.asarray(state.step)))
    return config, vocabulary, tel


def _post(port, data, timeout=60.0):
    t0 = time.perf_counter()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/caption", body=data,
                     headers={"Content-Type": "image/jpeg"})
        resp = conn.getresponse()
        resp.read()
        return resp.status, time.perf_counter() - t0
    finally:
        conn.close()


def _get_json(port, path, timeout=10.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=80,
                    help="closed-loop requests on the live arm")
    ap.add_argument("--microbench", type=int, default=4000,
                    help="quality-path iterations in the microbench")
    ap.add_argument("--quality-window", type=int, default=32,
                    help="reference window (small, so it freezes mid-run)")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_quality_")
    made_workdir = args.workdir is None
    try:
        from sat_tpu import telemetry
        from sat_tpu.serve.engine import ServeEngine, load_serving_state
        from sat_tpu.serve.server import CaptionServer
        from sat_tpu.telemetry.quality import (
            QualityMonitor,
            extract_signals,
        )

        config, vocabulary, tel = _make_ckpt(workdir, args.quality_window)
        state, _ = load_serving_state(config)
        engine = ServeEngine(config, state, vocabulary, tel=tel)
        engine.warmup()
        server = CaptionServer(config, engine, port=0).start()
        log(f"server up on port {server.port} (quality on, "
            f"window {args.quality_window})")

        jpegs = _make_jpegs(16, config.image_size)
        try:
            # --- live arm: closed loop, zero-recompile assert ---------
            status, _ = _post(server.port, jpegs[0])  # warm pass
            assert status == 200, f"warm request failed: {status}"
            compiles0 = tel.counters().get("jax/compiles", 0)
            lats = []
            for i in range(args.requests):
                status, lat = _post(server.port, jpegs[i % len(jpegs)])
                if status == 200:
                    lats.append(lat)
            recompiles = tel.counters().get("jax/compiles", 0) - compiles0
            data = np.sort(np.asarray(lats, np.float64)) * 1e3
            p50 = round(float(data[int(0.5 * len(data))]), 3)
            stats = _get_json(server.port, "/stats")
            quality = stats.get("quality") or {}
            frozen = bool(quality.get("reference"))
            log(f"live arm: {len(lats)}/{args.requests} ok, p50 {p50}ms, "
                f"steady-state compiles {recompiles}, quality block "
                f"requests={quality.get('requests')} psi_max="
                f"{quality.get('psi_max')} reference_frozen={frozen}")

            # --- microbench: the per-request quality path -------------
            out = engine.dispatch(engine.pad_batch(
                [engine.preprocess(jpegs[0])])[0])
            words, lengths, scores, alphas = engine.drain_output(out, 1)
            assert alphas is not None, "quality-on drain must carry alphas"
            monitor = QualityMonitor(window=64, tel=tel)
            vocab_size = len(vocabulary.words)
            # fill + freeze the reference first, so the timed loop pays
            # the steady-state path (sketch update + PSI screen), not the
            # one-time freeze
            for _ in range(80):
                sig = extract_signals(
                    words[0], lengths[0], scores[0],
                    vocab_size=vocab_size, eos_id=engine.eos_id,
                    alphas=alphas[0])
                monitor.observe(sig)
            t0 = time.perf_counter()
            for _ in range(args.microbench):
                sig = extract_signals(
                    words[0], lengths[0], scores[0],
                    vocab_size=vocab_size, eos_id=engine.eos_id,
                    alphas=alphas[0])
                monitor.observe(sig)
                monitor.maybe_publish()
            quality_us = (time.perf_counter() - t0) / args.microbench * 1e6
            log(f"quality path: {quality_us:.2f}us/request over "
                f"{args.microbench} iterations (signals + sketch + "
                f"rate-limited publish)")

            raw_overhead = quality_us / 1e3 / p50 * 100.0 if p50 else 0.0
            # noise-floored like the metering row: the raw number is tiny
            # and a percent-delta gate over it would page on scheduler
            # jitter; the HARD gate below judges the raw value
            overhead = round(max(raw_overhead, 0.05), 4)

            print(json.dumps({
                "metric": "quality_overhead_pct",
                "value": overhead,
                "unit": "pct",
                "raw_overhead_pct": round(raw_overhead, 5),
                "noise_floor": 0.05,
                "gate_pct": 0.5,
                "quality_path_us": round(quality_us, 3),
                "microbench_iters": args.microbench,
                "request_p50_ms": p50,
                "requests_ok": len(lats),
                "steady_state_compiles": recompiles,
                "quality_requests": quality.get("requests"),
                "quality_psi_max": quality.get("psi_max"),
                "reference_frozen": frozen,
                **telemetry.bench_stamp(),
            }), flush=True)

            ok = (
                raw_overhead <= 0.5
                and recompiles == 0
                and len(lats) == args.requests
                and quality.get("requests", 0) > 0
                and frozen
            )
            if not ok:
                log("GATE FAILED: overhead > 0.5%, a steady-state "
                    "recompile, failed requests, or no live quality block")
            return 0 if ok else 1
        finally:
            server.shutdown()
    finally:
        if made_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
