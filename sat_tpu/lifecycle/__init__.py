"""Zero-downtime model lifecycle: hot-swap reloader, canary routing,
SLO-gated promote/rollback.

The package is jax-free at import time (the loader defers its jax
imports) so the control plane — reloader, canary hash, controller state
machine — runs on jax-free hosts: the router, admin tooling, unit tests.
"""

from .canary import (
    CANARY,
    INCUMBENT,
    DivergenceGauge,
    assign_slot,
    caption_divergence,
    request_weight,
)
from .controller import STATE_CODES, STATES, LifecycleController
from .loader import load_candidate
from .reloader import Reloader

__all__ = [
    "CANARY",
    "INCUMBENT",
    "DivergenceGauge",
    "LifecycleController",
    "Reloader",
    "STATES",
    "STATE_CODES",
    "assign_slot",
    "caption_divergence",
    "load_candidate",
    "request_weight",
]
