"""Layout-exact synthetic reference checkpoints.

Generators that replicate — name for name, shape for shape — the three
external weight formats the reference consumes, so import paths are tested
against the *real* layouts rather than fixtures derived from our own
naming:

* ``make_vgg16_no_fc``    — the nested ``{op: {param: arr}}`` caffe-export
  layout of ``vgg16_no_fc.npy`` (all 13 convs, ``weights``/``biases``
  param names, HWIO shapes; reference scopes model.py:24-60, loader
  base_model.py:280-297);
* ``make_resnet50_no_fc`` — ``resnet50_no_fc.npy``: conv1 + 16 bottleneck
  blocks' convs (bias-free) + per-conv BN entries with the caffe-style
  ``mean/variance/scale/offset`` param names (reference scopes
  model.py:62-188);
* ``make_reference_train_checkpoint`` — the flat ``{var.name: value}``
  dict that the reference's own ``save()`` writes (base_model.py:242-249):
  TF1 variable names with ``:0`` suffixes, ``lstm/lstm_cell/kernel`` as
  the single concatenated [(D+E+H), 4H] matrix in (i, j, f, o) gate order.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

# (name, out_channels) in reference build order, model.py:32-52
VGG16_CONVS = [
    ("conv1_1", 64), ("conv1_2", 64),
    ("conv2_1", 128), ("conv2_2", 128),
    ("conv3_1", 256), ("conv3_2", 256), ("conv3_3", 256),
    ("conv4_1", 512), ("conv4_2", 512), ("conv4_3", 512),
    ("conv5_1", 512), ("conv5_2", 512), ("conv5_3", 512),
]

# (stage prefix, bottleneck width, #identity blocks) — model.py:83-100
RESNET_STAGES = [("2", 64, 2), ("3", 128, 3), ("4", 256, 5), ("5", 512, 2)]


def make_vgg16_no_fc(path: str, seed: int = 0) -> Dict[str, Dict[str, np.ndarray]]:
    rng = np.random.default_rng(seed)
    nested: Dict[str, Dict[str, np.ndarray]] = {}
    c_in = 3
    for name, c_out in VGG16_CONVS:
        nested[name] = {
            "weights": rng.normal(0, 0.05, (3, 3, c_in, c_out)).astype(np.float32),
            "biases": rng.normal(0, 0.01, (c_out,)).astype(np.float32),
        }
        c_in = c_out
    np.save(path, np.array(nested, dtype=object), allow_pickle=True)
    return nested


def _bn_entry(rng, c: int) -> Dict[str, np.ndarray]:
    return {
        "mean": rng.normal(0, 0.1, (c,)).astype(np.float32),
        "variance": rng.uniform(0.5, 1.5, (c,)).astype(np.float32),
        "scale": rng.uniform(0.9, 1.1, (c,)).astype(np.float32),
        "offset": rng.normal(0, 0.01, (c,)).astype(np.float32),
    }


def make_resnet50_no_fc(path: str, seed: int = 0) -> Dict[str, Dict[str, np.ndarray]]:
    rng = np.random.default_rng(seed)
    nested: Dict[str, Dict[str, np.ndarray]] = {}

    def conv(name: str, k: int, c_in: int, c_out: int) -> None:
        nested[name] = {
            "weights": rng.normal(0, 0.05, (k, k, c_in, c_out)).astype(np.float32)
        }

    conv("conv1", 7, 3, 64)
    nested["bn_conv1"] = _bn_entry(rng, 64)

    c_in = 64
    for prefix, width, n_identity in RESNET_STAGES:
        # projection block: branch1 + branch2{a,b,c}
        st = f"{prefix}a"
        conv(f"res{st}_branch1", 1, c_in, 4 * width)
        nested[f"bn{st}_branch1"] = _bn_entry(rng, 4 * width)
        conv(f"res{st}_branch2a", 1, c_in, width)
        nested[f"bn{st}_branch2a"] = _bn_entry(rng, width)
        conv(f"res{st}_branch2b", 3, width, width)
        nested[f"bn{st}_branch2b"] = _bn_entry(rng, width)
        conv(f"res{st}_branch2c", 1, width, 4 * width)
        nested[f"bn{st}_branch2c"] = _bn_entry(rng, 4 * width)
        c_in = 4 * width
        for i in range(n_identity):
            st = f"{prefix}{chr(ord('b') + i)}"
            conv(f"res{st}_branch2a", 1, c_in, width)
            nested[f"bn{st}_branch2a"] = _bn_entry(rng, width)
            conv(f"res{st}_branch2b", 3, width, width)
            nested[f"bn{st}_branch2b"] = _bn_entry(rng, width)
            conv(f"res{st}_branch2c", 1, width, 4 * width)
            nested[f"bn{st}_branch2c"] = _bn_entry(rng, 4 * width)

    np.save(path, np.array(nested, dtype=object), allow_pickle=True)
    return nested


def make_reference_train_checkpoint(
    path: str, config, seed: int = 0, include_cnn: bool = True
) -> Dict[str, np.ndarray]:
    """Flat ``{var.name: value}`` dict as the reference's save() emits
    (base_model.py:242-249) for the *train* graph with the 2-layer
    initialize/attend/decode variants; returns the dict after np.save."""
    rng = np.random.default_rng(seed)
    E, H = config.dim_embedding, config.num_lstm_units
    D, N, V = config.dim_ctx, config.num_ctx, config.vocabulary_size

    def w(shape) -> np.ndarray:
        return rng.normal(0, 0.08, shape).astype(np.float32)

    flat: Dict[str, np.ndarray] = {}
    if include_cnn and config.cnn == "vgg16":
        c_in = 3
        for name, c_out in VGG16_CONVS:
            flat[f"{name}/kernel:0"] = w((3, 3, c_in, c_out))
            flat[f"{name}/bias:0"] = w((c_out,))
            c_in = c_out

    flat["word_embedding/weights:0"] = w((V, E))

    di = config.dim_initialize_layer
    for fc, d_out in (("fc_a1", di), ("fc_b1", di)):
        flat[f"initialize/{fc}/kernel:0"] = w((D, d_out))
        flat[f"initialize/{fc}/bias:0"] = w((d_out,))
    for fc in ("fc_a2", "fc_b2"):
        flat[f"initialize/{fc}/kernel:0"] = w((di, H))
        flat[f"initialize/{fc}/bias:0"] = w((H,))

    da = config.dim_attend_layer
    flat["attend/fc_1a/kernel:0"] = w((D, da))
    flat["attend/fc_1a/bias:0"] = w((da,))
    flat["attend/fc_1b/kernel:0"] = w((H, da))
    flat["attend/fc_1b/bias:0"] = w((da,))
    flat["attend/fc_2/kernel:0"] = w((da, 1))  # use_bias=False (model.py:436)

    # TF1 LSTMCell under scope "lstm": one concatenated kernel
    # [(input_depth + H), 4H], input = concat(context, word_embed)
    # (model.py:277), gates ordered (i, j, f, o); +1.0 forget bias is
    # applied at runtime, NOT stored.
    flat["lstm/lstm_cell/kernel:0"] = w((D + E + H, 4 * H))
    flat["lstm/lstm_cell/bias:0"] = w((4 * H,))

    dd = config.dim_decode_layer
    flat["decode/fc_1/kernel:0"] = w((H + D + E, dd))
    flat["decode/fc_1/bias:0"] = w((dd,))
    flat["decode/fc_2/kernel:0"] = w((dd, V))
    flat["decode/fc_2/bias:0"] = w((V,))

    flat["global_step:0"] = np.asarray(1234, np.int64)
    # optimizer slots ride along in real checkpoints; must be skipped
    flat["OptimizeLoss/word_embedding/weights/Adam:0"] = w((V, E))
    flat["OptimizeLoss/beta1_power:0"] = np.asarray(0.9, np.float32)

    np.save(path, np.array(flat, dtype=object), allow_pickle=True)
    return flat
