// C ABI for the sat_tpu native components, consumed via ctypes
// (sat_tpu/native/__init__.py).  Strings are UTF-8; returned buffers are
// malloc'd and must be released with sat_free.

#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace sat_native {
std::vector<std::string> ptb_tokenize(const std::string&, bool);
std::vector<std::string> ptb_tokenize_no_punct(const std::string&, bool);
std::string porter_stem(const std::string&);
double meteor_segment(const std::string&, const std::string&);
void meteor_set_data(const std::string&, const std::string&,
                     const std::string&);
}  // namespace sat_native

namespace {

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (out != nullptr) std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

char* join_tokens(const std::vector<std::string>& tokens) {
  std::string joined;
  for (size_t i = 0; i < tokens.size(); i++) {
    if (i) joined += ' ';
    joined += tokens[i];
  }
  return dup_string(joined);
}

}  // namespace

extern "C" {

// Tokenize one sentence; returns space-joined tokens (malloc'd).
char* sat_tokenize(const char* text, int lowercase, int strip_punct) {
  if (text == nullptr) return nullptr;
  auto tokens = strip_punct
                    ? sat_native::ptb_tokenize_no_punct(text, lowercase != 0)
                    : sat_native::ptb_tokenize(text, lowercase != 0);
  return join_tokens(tokens);
}

// Porter-stem one word (malloc'd).
char* sat_stem(const char* word) {
  if (word == nullptr) return nullptr;
  return dup_string(sat_native::porter_stem(word));
}

// Install the METEOR 1.5 language data (pushed from Python's
// meteor_data.py so both backends share one source of truth):
// function_words = space-joined words; synsets = newline-separated
// groups of space-joined synonymous words; paraphrases =
// newline-separated groups of '|'-separated multi-word phrases.  Call
// before scoring; not thread-safe against concurrent scoring (the
// ctypes layer holds a lock during load).
void sat_meteor_set_data(const char* function_words, const char* synsets,
                         const char* paraphrases) {
  sat_native::meteor_set_data(function_words ? function_words : "",
                              synsets ? synsets : "",
                              paraphrases ? paraphrases : "");
}

// METEOR score of one hypothesis against one reference, both given as
// space-joined token strings.  Returns -1.0 if the reference exceeds
// the aligner's 128-word coverage-mask capacity (scores live in [0,1]);
// callers must treat negative as "unscorable here", not as a score.
double sat_meteor_segment(const char* hyp, const char* ref) {
  if (hyp == nullptr || ref == nullptr) return 0.0;
  return sat_native::meteor_segment(hyp, ref);
}

// METEOR with multiple references: max over refs (jar behavior).
// refs: array of n space-joined token strings.  Returns -1.0 when any
// reference is over the per-segment cap — skipping it would silently
// change the max-over-refs semantics.
double sat_meteor_multi(const char* hyp, const char** refs, int n) {
  if (hyp == nullptr || refs == nullptr) return 0.0;
  double best = 0.0;
  for (int i = 0; i < n; i++) {
    if (refs[i] == nullptr) continue;
    double s = sat_native::meteor_segment(hyp, refs[i]);
    if (s < 0.0) return -1.0;
    if (s > best) best = s;
  }
  return best;
}

void sat_free(char* p) { std::free(p); }

int sat_native_abi_version() { return 5; }

}  // extern "C"
