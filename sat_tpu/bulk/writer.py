"""Sharded caption output: ``captions_<shard>.jsonl`` + crc32c sidecar.

Crash-only discipline, mirroring the shard cache's append-only build
(``data.shards``): every output shard is written in full to
``<name>.jsonl.tmp`` and ``os.replace``d into place only once complete,
so the final filename existing IS the commit record.  A kill -9
mid-shard leaves only a ``.tmp`` orphan, which resume deletes and
re-decodes from the shard's first row — rows are never appended to a
surviving file, which is how the no-duplicate/no-missing-row guarantee
holds without any intra-shard bookkeeping.

Each committed shard gets a ``<name>.jsonl.crc32c`` sidecar (rows,
whole-file crc, per-row crcs — ``utils.summary.crc32c``, the same
polynomial as the input shard cache's row sidecars) written through
``retry_io`` + ``atomic_write``; :func:`verify_shard` re-checks a file
against it (and optionally the manifest's recorded row count/crc)
before resume skips the shard.

Rows are serialized with ``json.dumps(obj, sort_keys=True)`` and no
timestamps or host identity — an interrupted-and-resumed run must
produce bitwise-identical files to an uninterrupted one.

Jax-free by design (see the package docstring).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

from ..resilience.retry import retry_io
from ..utils.fileio import atomic_write
from ..utils.summary import crc32c


def shard_filename(shard_idx: int) -> str:
    return f"captions_{shard_idx:05d}.jsonl"


def sidecar_path(shard_path: str) -> str:
    return shard_path + ".crc32c"


def encode_row(obj: dict) -> bytes:
    """The one serialization used by writer and verifier alike: sorted
    keys, newline-terminated, UTF-8.  Determinism lives here."""
    return (json.dumps(obj, sort_keys=True) + "\n").encode("utf-8")


class ShardWriter:
    """Writes one output shard (see module docstring).  Rows are buffered
    in memory as well as streamed to the tmp file — shards are small by
    construction (``--bulk_shard_rows``, default 256) and the buffer is
    what makes the whole-file crc and the sidecar exact without a
    re-read."""

    def __init__(self, out_dir: str, shard_idx: int):
        os.makedirs(out_dir, exist_ok=True)
        self.shard_idx = shard_idx
        self.path = os.path.join(out_dir, shard_filename(shard_idx))
        self.tmp = self.path + ".tmp"
        self._blobs: List[bytes] = []
        self._row_crcs: List[int] = []
        self._f = open(self.tmp, "wb")

    @property
    def rows(self) -> int:
        return len(self._blobs)

    def write_row(self, obj: dict) -> None:
        blob = encode_row(obj)
        self._f.write(blob)
        self._blobs.append(blob)
        self._row_crcs.append(crc32c(blob))

    def finish(self) -> Tuple[str, int, int]:
        """fsync + commit the shard; returns ``(filename, rows, crc)``
        for the caller's manifest entry.  The sidecar lands after the
        rename — a crash between the two leaves a file that fails
        :func:`verify_shard` and gets re-decoded (identically)."""
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        file_crc = crc32c(b"".join(self._blobs))
        retry_io(
            lambda: os.replace(self.tmp, self.path),
            desc=f"commit {os.path.basename(self.path)}",
        )
        payload = json.dumps(
            {"rows": self.rows, "crc32c": file_crc, "row_crc32c": self._row_crcs},
            sort_keys=True,
        )
        retry_io(
            lambda: atomic_write(
                sidecar_path(self.path), "w", lambda f: f.write(payload + "\n")
            ),
            desc=f"write {os.path.basename(sidecar_path(self.path))}",
        )
        return os.path.basename(self.path), self.rows, file_crc

    def abort(self) -> None:
        try:
            self._f.close()
        finally:
            if os.path.exists(self.tmp):
                os.unlink(self.tmp)


def verify_shard(
    shard_path: str,
    expect_rows: Optional[int] = None,
    expect_crc: Optional[int] = None,
) -> bool:
    """True iff the committed shard matches its sidecar (whole-file and
    per-row crcs) and, when given, the manifest's recorded row count and
    crc.  Any failure — missing file, missing/torn sidecar, mismatch —
    is False: the caller re-decodes the shard, trading time for
    certainty."""
    try:
        with open(shard_path, "rb") as f:
            data = f.read()
        with open(sidecar_path(shard_path)) as f:
            side = json.load(f)
    except (OSError, ValueError):
        return False
    lines = data.splitlines(keepends=True)
    if not isinstance(side, dict):
        return False
    row_crcs = side.get("row_crc32c")
    if side.get("rows") != len(lines) or not isinstance(row_crcs, list):
        return False
    if len(row_crcs) != len(lines):
        return False
    if side.get("crc32c") != crc32c(data):
        return False
    if any(crc32c(line) != c for line, c in zip(lines, row_crcs)):
        return False
    if expect_rows is not None and expect_rows != len(lines):
        return False
    if expect_crc is not None and expect_crc != side.get("crc32c"):
        return False
    return True
