"""Zero-sync progress watchdog: detect a wedged run, dump, abort.

The one failure mode PR 2's resilience layer cannot touch is the backend
wedging *silently* — BENCH_r05 died with four consecutive probe timeouts
and zero metrics because a hung dispatch makes no progress and raises
nothing.  This module watches the run from a side thread and escalates
when a tracked phase stops completing:

1. **gauges** — ``watchdog/state`` flips to ``STALLED`` and
   ``watchdog/stalled_s`` starts counting, so ``heartbeat.json`` (and
   ``/healthz``) show the stall while it is still recoverable;
2. **dump** — ``faulthandler`` writes an all-thread stack dump to the
   ``dump_path`` artifact and the telemetry ring flushes a Chrome trace
   next to it, preserving *where* every thread was parked;
3. **abort** — after ``grace_s`` more seconds the ``pre_abort`` hook runs
   (bounded — the train loop passes the async checkpoint writer's flush
   so ``LAST_GOOD`` lands) and the process exits with
   ``WATCHDOG_EXIT_CODE`` so a supervisor (``resilience.supervisor``)
   can tell "wedged, restart me" from every other failure.

Observation is **zero-sync by design**: the watchdog thread reads host
clocks and host dicts only — never a device value, never jax (the
no-hidden-sync lint in tests/test_device_diag.py covers this package).
The observed signal is phase *guards*: the instrumented thread brackets
each potentially-wedging region with ``with wd.phase("dispatch"):`` —
entry records a host timestamp, exit clears it.  A phase's deadline is
enforced only after that phase has completed at least once, so a cold
first step (XLA compiling for minutes) never false-trips a steady-state
deadline.

``SAT_FI_SLOW_STEP_MS`` (a degraded-but-alive device) keeps completing
phases and must never fire; ``SAT_FI_WEDGE_AT_STEP`` parks the loop
inside its step guard and must always fire.  Both are pinned by
tests/test_supervisor.py.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional

from .. import telemetry

# Distinct from every exit code already in the fleet's vocabulary:
# 0 clean, 1 checkpoint-write/preemption failure, 2 pytest/argparse,
# 3 bench-child watchdog + check_regression infra-skip, 4 bench
# orchestrator gave up, 87 systemic data corruption (the quarantine
# ceiling — resilience/quarantine.py; the supervisor must NOT restart it).  The supervisor treats this one as "wedged,
# state on disk is good, restart me".
WATCHDOG_EXIT_CODE = 86

# watchdog/state gauge values (heartbeat.json renders the raw number)
OK, STALLED, DUMPED, ABORTING = 0, 1, 2, 3
STATE_NAMES = {OK: "ok", STALLED: "stalled", DUMPED: "dumped", ABORTING: "aborting"}


class _PhaseGuard:
    """Context manager bracketing one instrumented region."""

    __slots__ = ("_wd", "_name")

    def __init__(self, wd: "Watchdog", name: str):
        self._wd = wd
        self._name = name

    def __enter__(self):
        self._wd._enter(self._name)
        return self

    def __exit__(self, *exc):
        self._wd._exit(self._name)
        return False


class Watchdog:
    """Observer thread enforcing per-phase progress deadlines.

    Parameters
    ----------
    deadlines: phase name -> seconds the phase may stay open once it has
        completed at least once.  Phases without an entry are tracked
        (visible in the stack dump) but never enforced.
    poll_s: observer wake-up cadence; detection latency is one poll.
    dump_path: where the faulthandler all-thread stack dump lands; the
        telemetry trace flushes next to it as ``<stem>_trace.json``.
    pre_abort: best-effort callable run (bounded by ``grace_s``) before
        the abort — the train loop passes the async checkpoint writer's
        ``flush`` so LAST_GOOD lands before the process dies.
    abort: the final rung.  Defaults to ``os._exit(WATCHDOG_EXIT_CODE)``;
        tests inject a recorder.
    """

    def __init__(
        self,
        deadlines: Dict[str, float],
        *,
        poll_s: float = 1.0,
        grace_s: float = 2.0,
        dump_path: Optional[str] = None,
        pre_abort: Optional[Callable[[], None]] = None,
        abort: Optional[Callable[[int], None]] = None,
        tel=None,
    ) -> None:
        self.deadlines = {k: v for k, v in deadlines.items() if v and v > 0}
        self.poll_s = max(0.05, poll_s)
        self.grace_s = max(0.0, grace_s)
        self.dump_path = dump_path
        self.pre_abort = pre_abort
        self._abort = abort if abort is not None else self._default_abort
        self._tel = tel if tel is not None else telemetry.get()
        # phase name -> monotonic entry time; written by instrumented
        # threads, read by the observer.  Plain dict ops are atomic under
        # the GIL and a torn read here costs one poll of latency, not
        # correctness, so no lock on the hot path.
        self._active: Dict[str, float] = {}
        self._completed: Dict[str, bool] = {}
        self.state = OK
        self.stalled_phase: Optional[str] = None
        self.aborted_rc: Optional[int] = None  # set when abort is injected
        self._dumped_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # every deadline comparison reads this clock; tests swap in a
        # fake (use_clock) so stall/no-stall scenarios are exact instead
        # of racing wall time under suite load (the TenantRegistry
        # injectable-clock pattern)
        self._clock = time.monotonic
        self._tel.gauge("watchdog/state", OK)

    def use_clock(self, clock: Callable[[], float]) -> "Watchdog":
        """Swap the monotonic time source (tests only)."""
        self._clock = clock
        return self

    # -- instrumentation (called from watched threads) ---------------------

    def phase(self, name: str) -> _PhaseGuard:
        return _PhaseGuard(self, name)

    def _enter(self, name: str) -> None:
        self._active[name] = self._clock()

    def _exit(self, name: str) -> None:
        self._active.pop(name, None)
        self._completed[name] = True
        if self.state != OK and self.stalled_phase == name:
            # the phase the ladder was climbing on just completed after
            # all — stand down (a dump may already have landed; that is
            # evidence, not damage)
            self.state = OK
            self.stalled_phase = None
            self._dumped_at = None
            self._tel.gauge("watchdog/state", OK)
            self._tel.gauge("watchdog/stalled_s", 0.0)

    # -- observer ----------------------------------------------------------

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sat-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _overdue(self) -> Optional[tuple]:
        """(phase, seconds overdue) of the worst enforced open phase."""
        now = self._clock()
        worst = None
        for name, t0 in list(self._active.items()):
            deadline = self.deadlines.get(name)
            if deadline is None or not self._completed.get(name):
                continue
            over = (now - t0) - deadline
            if over > 0 and (worst is None or over > worst[1]):
                worst = (name, over)
        return worst

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def check(self) -> None:
        """One observer tick (public so tests and the bench can drive the
        ladder without waiting on the poll clock)."""
        worst = self._overdue()
        if worst is None:
            if self.state != OK:
                self.state = OK
                self.stalled_phase = None
                self._dumped_at = None
                self._tel.gauge("watchdog/state", OK)
                self._tel.gauge("watchdog/stalled_s", 0.0)
            return
        name, over = worst
        self._tel.gauge("watchdog/stalled_s", over)
        if self.state == OK:
            self.state = STALLED
            self.stalled_phase = name
            self._tel.gauge("watchdog/state", STALLED)
            self._tel.count("watchdog/stalls")
            print(
                f"sat_tpu watchdog: phase {name!r} exceeded its "
                f"{self.deadlines[name]:g}s deadline by {over:.1f}s — "
                "escalating (stack dump next tick, then abort)",
                file=sys.stderr,
                flush=True,
            )
            return
        if self.state == STALLED:
            self.state = DUMPED
            self._dumped_at = self._clock()
            self._tel.gauge("watchdog/state", DUMPED)
            self._dump(name, over)
            return
        if self.state == DUMPED and (
            self._clock() - (self._dumped_at or 0.0) >= self.grace_s
        ):
            self.state = ABORTING
            self._tel.gauge("watchdog/state", ABORTING)
            print(
                f"sat_tpu watchdog: phase {name!r} still wedged "
                f"{over:.1f}s past deadline — landing LAST_GOOD and "
                f"aborting with exit code {WATCHDOG_EXIT_CODE}",
                file=sys.stderr,
                flush=True,
            )
            self._run_pre_abort()
            self._postmortem(name, over)
            self._abort(WATCHDOG_EXIT_CODE)

    # -- escalation rungs --------------------------------------------------

    def _dump(self, name: str, over: float) -> None:
        """Rung 2: all-thread stacks + telemetry trace, best-effort."""
        if not self.dump_path:
            return
        try:
            os.makedirs(os.path.dirname(self.dump_path) or ".", exist_ok=True)
            with open(self.dump_path, "w") as f:
                f.write(
                    f"sat_tpu watchdog stack dump: phase={name} "
                    f"overdue={over:.1f}s deadline={self.deadlines[name]:g}s "
                    f"pid={os.getpid()}\n"
                )
                f.flush()
                faulthandler.dump_traceback(file=f, all_threads=True)
            print(
                f"sat_tpu watchdog: stack dump written to {self.dump_path}",
                file=sys.stderr,
                flush=True,
            )
        except Exception as e:
            print(f"sat_tpu watchdog: stack dump failed: {e!r}", file=sys.stderr)
        try:
            if telemetry.enabled():
                from ..telemetry import exporters

                stem, _ = os.path.splitext(self.dump_path)
                exporters.export_chrome_trace(telemetry.get(), stem + "_trace.json")
        except Exception as e:
            print(f"sat_tpu watchdog: trace flush failed: {e!r}", file=sys.stderr)

    def _run_pre_abort(self) -> None:
        """Rung 3 prologue: run ``pre_abort`` in a helper thread bounded
        by ``grace_s`` — the hook itself may be wedged (a checkpoint
        flush stuck on the same dead device), and the abort must not be."""
        if self.pre_abort is None:
            return
        done = threading.Event()

        def _run():
            try:
                self.pre_abort()
            except Exception as e:
                print(
                    f"sat_tpu watchdog: pre-abort hook failed: {e!r}",
                    file=sys.stderr,
                    flush=True,
                )
            finally:
                done.set()

        t = threading.Thread(target=_run, name="sat-watchdog-preabort", daemon=True)
        t.start()
        if not done.wait(timeout=max(self.grace_s, 2.0)):
            print(
                "sat_tpu watchdog: pre-abort hook wedged too — aborting anyway",
                file=sys.stderr,
                flush=True,
            )

    def _postmortem(self, name: str, over: float) -> None:
        """Rung 3 epilogue: black-box bundle BEFORE ``os._exit`` (atexit
        never runs on the abort path, so this is the only window).  Same
        bounded-helper-thread discipline as the pre-abort hook — the dump
        is pure host file IO, but a dead network mount must not turn the
        abort into a second wedge.  No-op unless the run installed a
        recorder (``--blackbox``)."""
        done = threading.Event()

        def _run():
            try:
                from ..telemetry import blackbox

                bb = blackbox.installed()
                if bb is not None:
                    bb.event(
                        "watchdog_abort", phase=name, overdue_s=round(over, 1)
                    )
                blackbox.dump(
                    "watchdog_wedge",
                    exit_code=WATCHDOG_EXIT_CODE,
                    phase=name,
                    overdue_s=round(over, 1),
                    deadline_s=self.deadlines.get(name),
                )
            except Exception as e:
                print(
                    f"sat_tpu watchdog: postmortem dump failed: {e!r}",
                    file=sys.stderr,
                    flush=True,
                )
            finally:
                done.set()

        t = threading.Thread(
            target=_run, name="sat-watchdog-postmortem", daemon=True
        )
        t.start()
        if not done.wait(timeout=max(self.grace_s, 2.0)):
            print(
                "sat_tpu watchdog: postmortem dump wedged — aborting anyway",
                file=sys.stderr,
                flush=True,
            )

    def _default_abort(self, code: int) -> None:
        self.aborted_rc = code
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(code)


def deadlines_from_config(config) -> Dict[str, float]:
    """The per-phase deadline map the train loop arms (seconds; a value
    of 0 disables that phase).  ``step`` brackets the whole loop body —
    the net that catches a wedge landing *between* finer-grained phases."""
    return {
        "step": config.watchdog_step_s,
        "data_wait": config.watchdog_data_wait_s,
        "dispatch": config.watchdog_dispatch_s,
        "checkpoint": config.watchdog_checkpoint_s,
    }
