"""Corpus resolution and output-shard planning for ``--phase bulk``.

A bulk corpus is whatever the operator points ``--bulk_input`` at:

* a **directory** — recursively walked for image files
  (``data.images.walk_images``; non-image files are counted and
  skipped, not fatal);
* a **file list** — a regular text file, one image path per line
  (blank lines and ``#`` comments ignored), resolved relative to the
  list's own directory so a list ships alongside its corpus.

Both forms resolve to the same thing: an ordered list of absolute
paths.  The ORDER is the contract — the manifest fingerprint, the shard
plan, the quarantine substitution and therefore the bitwise-resume
guarantee all key off it — so both paths normalize and sort
deterministically, independent of filesystem iteration order.

Jax-free by design (see the package docstring).
"""

from __future__ import annotations

import os
from typing import List

from ..data.images import walk_images


class CorpusError(ValueError):
    """``--bulk_input`` does not resolve to a usable corpus (missing
    path, empty directory, empty list).  Configuration, not data: raised
    before any decode work starts, never quarantined."""


def resolve_corpus(bulk_input: str) -> List[str]:
    """Resolve ``--bulk_input`` to the ordered list of absolute image
    paths (see module docstring for the two accepted forms)."""
    if not bulk_input:
        raise CorpusError("--bulk_input is required for --phase bulk")
    path = os.path.abspath(bulk_input)
    if os.path.isdir(path):
        files = walk_images(path)
        if not files:
            raise CorpusError(f"no image files under directory {path!r}")
        return files
    if os.path.isfile(path):
        files = _read_file_list(path)
        if not files:
            raise CorpusError(f"file list {path!r} names no images")
        return files
    raise CorpusError(f"--bulk_input {path!r} is neither a directory nor a file")


def _read_file_list(list_path: str) -> List[str]:
    # retrying read (utils.fileio): the list often lives on the same
    # flaky shared mount as the corpus itself
    from ..utils.fileio import read_text

    base = os.path.dirname(list_path)
    files = []
    for line in read_text(list_path, desc=f"read corpus list {list_path}").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if not os.path.isabs(line):
            line = os.path.join(base, line)
        files.append(os.path.abspath(line))
    # de-dup preserving nothing subtle: sort is the corpus-order contract
    return sorted(set(files))


def plan_shards(files: List[str], rows_per_shard: int) -> List[List[str]]:
    """Split the ordered corpus into output-shard file lists: every shard
    holds ``rows_per_shard`` rows except the final remainder.

    The plan is a pure function of (corpus order, rows_per_shard) —
    never of chip count, pool geometry or restart history — which is
    what makes resume elastic: a job killed on 8 chips and resumed on 1
    re-derives the identical plan and only re-decodes shards without a
    completed, crc-verified output file.
    """
    if rows_per_shard < 1:
        raise ValueError(f"rows_per_shard must be >= 1, got {rows_per_shard}")
    return [files[i : i + rows_per_shard] for i in range(0, len(files), rows_per_shard)]
