"""Host-side span/counter/gauge recording on preallocated ring buffers.

``jax.profiler`` answers "what did the device do for these 3 steps"; this
module answers "where did the HOST milliseconds of the whole run go" —
cheaply enough to leave on for every step of every run.  Three primitives:

* **span** — a named wall-clock interval (``time.perf_counter_ns``)
  recorded into preallocated numpy ring buffers.  The hot path takes no
  lock: a slot index comes from ``itertools.count`` (``next()`` on it is
  a single C-level operation, atomic under the GIL, so producer threads
  — prefetch, checkpoint writer — never tear each other's slots) and the
  per-name aggregates are monotonic accumulators where a lost race costs
  one sample of statistics, never a crash or a corrupt trace.
* **counter** — a monotonically increasing named count (retry attempts,
  decode fallbacks, sentinel verdicts).
* **gauge** — a last-value-wins named measurement (current step, prefetch
  queue depth, last-checkpoint timestamp).

Counters and gauges take a small lock — they are called per *event*
(a retry, a log boundary), not per microsecond, so contention is nil.

The module-level API (``span``/``count``/``gauge``/``record``) dispatches
through a process-global implementation that defaults to
:data:`NULL_TELEMETRY` — a no-op object whose methods cost one attribute
lookup and one call (~0.1 µs), so instrumented library code (shards,
retry, checkpoint) pays nothing measurable when telemetry is off and the
off-path behavior is bit-for-bit what it was before instrumentation.

Deliberately jax-free (like ``resilience/``): host-only tools —
``scripts/bench_telemetry.py`` — must import this without dragging in an
accelerator backend, and recording must never add a device sync.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

# Span names are interned to small integer ids; aggregate arrays are sized
# in blocks of this many names (a run uses a few dozen distinct names).
_NAME_BLOCK = 256


class _NullSpan:
    """Context manager that does nothing — the telemetry-off span."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


NULL_SPAN = _NullSpan()


class NullTelemetry:
    """The telemetry-off implementation: every method is a no-op returning
    an inert value, so call sites never branch on enablement."""

    enabled = False

    def span(self, name: str) -> _NullSpan:
        return NULL_SPAN

    def record(self, name: str, t0_ns: int, dur_ns: int) -> None:
        pass

    def count(self, name: str, n: int = 1) -> None:
        pass

    def gauge(self, name: str, value: float) -> None:
        pass

    def counters(self) -> Dict[str, float]:
        return {}

    def gauges(self) -> Dict[str, float]:
        return {}

    def aggregates(self) -> Dict[str, Tuple[int, int, int]]:
        return {}

    def durations_ns(self, name: str) -> np.ndarray:
        return np.empty(0, np.int64)

    def spans_snapshot(self):
        return [], *(np.empty(0, d) for d in (np.int32, np.int64, np.int64, np.int64))


NULL_TELEMETRY = NullTelemetry()


class _Span(object):
    """One timed interval; created per use (re-entrant and thread-safe by
    construction — no shared mutable timing state)."""

    __slots__ = ("_tel", "_sid", "_t0")

    def __init__(self, tel: "Telemetry", sid: int) -> None:
        self._tel = tel
        self._sid = sid

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        t0 = self._t0
        self._tel._record(self._sid, t0, time.perf_counter_ns() - t0)
        return False


class Telemetry:
    """Ring-buffered span recorder + counter/gauge registry.

    ``capacity`` (rounded up to a power of two) bounds the sample window:
    older spans are overwritten, but the per-name aggregates (count /
    total / max) accumulate for the whole run, so end-of-run totals are
    exact even when the ring wrapped; only the percentile window is
    bounded.
    """

    enabled = True

    def __init__(self, capacity: int = 65536) -> None:
        cap = 1 << max(int(capacity) - 1, 255).bit_length()  # pow2, >= 256
        self._capacity = cap
        self._mask = cap - 1
        self._ids = np.zeros(cap, np.int32)
        self._t0s = np.zeros(cap, np.int64)
        self._durs = np.zeros(cap, np.int64)
        self._tids = np.zeros(cap, np.int64)
        self._slot = itertools.count()
        self._written = 0  # approximate under racing writers; exact enough
        self._names: Dict[str, int] = {}
        self._name_list: List[str] = []
        self._name_lock = threading.Lock()
        self._agg_count = np.zeros(_NAME_BLOCK, np.int64)
        self._agg_total = np.zeros(_NAME_BLOCK, np.int64)
        self._agg_max = np.zeros(_NAME_BLOCK, np.int64)
        self._meta_lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # Anchors pairing the monotonic span clock with wall time, so
        # exporters can place trace events on an absolute timeline.
        self.anchor_ns = time.perf_counter_ns()
        self.anchor_unix = time.time()

    # -- hot path ----------------------------------------------------------

    def span(self, name: str) -> _Span:
        sid = self._names.get(name)
        if sid is None:
            sid = self._intern(name)
        return _Span(self, sid)

    def record(self, name: str, t0_ns: int, dur_ns: int) -> None:
        """Record a manually timed interval (loop bodies that can't wrap a
        ``with`` around their own ``for``-statement fetch)."""
        sid = self._names.get(name)
        if sid is None:
            sid = self._intern(name)
        self._record(sid, t0_ns, dur_ns)

    def _record(self, sid: int, t0_ns: int, dur_ns: int) -> None:
        i = next(self._slot)          # lock-free slot reservation
        j = i & self._mask
        self._ids[j] = sid
        self._t0s[j] = t0_ns
        self._durs[j] = dur_ns
        self._tids[j] = threading.get_ident()
        self._written = i + 1
        # racing writers may drop one aggregate update; the ring row above
        # is slot-exclusive and never torn
        self._agg_count[sid] += 1
        self._agg_total[sid] += dur_ns
        if dur_ns > self._agg_max[sid]:
            self._agg_max[sid] = dur_ns

    def _intern(self, name: str) -> int:
        with self._name_lock:
            sid = self._names.get(name)
            if sid is None:
                sid = len(self._name_list)
                if sid >= len(self._agg_count):
                    grow = len(self._agg_count) + _NAME_BLOCK
                    for attr in ("_agg_count", "_agg_total", "_agg_max"):
                        old = getattr(self, attr)
                        new = np.zeros(grow, np.int64)
                        new[: len(old)] = old
                        setattr(self, attr, new)
                self._name_list.append(name)
                self._names[name] = sid
            return sid

    # -- events ------------------------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        with self._meta_lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._meta_lock:
            self._gauges[name] = value

    # -- read side (exporters; never on the hot path) ----------------------

    def counters(self) -> Dict[str, float]:
        with self._meta_lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._meta_lock:
            return dict(self._gauges)

    def aggregates(self) -> Dict[str, Tuple[int, int, int]]:
        """{name: (count, total_ns, max_ns)} over the whole run."""
        out = {}
        for name, sid in list(self._names.items()):
            c = int(self._agg_count[sid])
            if c:
                out[name] = (c, int(self._agg_total[sid]), int(self._agg_max[sid]))
        return out

    def _window(self) -> np.ndarray:
        """Ring indices of the retained sample window, oldest first."""
        n = self._written
        if n <= self._capacity:
            return np.arange(n)
        start = n & self._mask
        return (np.arange(self._capacity) + start) & self._mask

    def durations_ns(self, name: str) -> np.ndarray:
        """Sampled durations for ``name`` within the ring window (the
        percentile source; totals come from :meth:`aggregates`)."""
        sid = self._names.get(name)
        if sid is None:
            return np.empty(0, np.int64)
        idx = self._window()
        return self._durs[idx][self._ids[idx] == sid]

    def spans_snapshot(self):
        """(names, ids, t0s, durs, tids) — the retained window in
        chronological order; ``names[ids[k]]`` is span k's name."""
        idx = self._window()
        with self._name_lock:
            names = list(self._name_list)
        return (
            names,
            self._ids[idx].copy(),
            self._t0s[idx].copy(),
            self._durs[idx].copy(),
            self._tids[idx].copy(),
        )


# ---------------------------------------------------------------------------
# process-global dispatch
# ---------------------------------------------------------------------------

_impl = NULL_TELEMETRY


def get():
    """The active implementation (hot loops grab this once per loop)."""
    return _impl


def enabled() -> bool:
    return _impl.enabled


def enable(capacity: int = 65536) -> Telemetry:
    """Install a FRESH enabled implementation (one per run: buffers and
    counters start empty) and return it."""
    global _impl
    _impl = Telemetry(capacity)
    return _impl


def disable() -> NullTelemetry:
    global _impl
    _impl = NULL_TELEMETRY
    return _impl


def span(name: str):
    return _impl.span(name)


def record(name: str, t0_ns: int, dur_ns: int) -> None:
    _impl.record(name, t0_ns, dur_ns)


def count(name: str, n: int = 1) -> None:
    _impl.count(name, n)


def gauge(name: str, value: float) -> None:
    _impl.gauge(name, value)
