"""Reference-surface entry point: ``python main.py --phase=train|eval|test``.

The reference is driven as ``python main.py`` with the flags defined at
/root/reference/main.py:15-36; this shim gives the identical invocation
surface on top of the package CLI (``python -m sat_tpu.cli``), which also
accepts ``--set key=value`` overrides for every Config field.
"""

from sat_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
