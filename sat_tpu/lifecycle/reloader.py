"""LAST_GOOD pointer watcher — the lifecycle plane's trigger.

A background thread polls the lineage ``LAST_GOOD`` pointer (mtime is
not trusted alone — the pointer is an atomic rename, so content is
re-read every poll; both are one tiny file read) with a jittered
interval so a fleet of replicas sharing one save_dir doesn't thundering-
herd the filesystem.  When the pointer names a NEW step that is neither
the currently served one nor in the rejection ledger, ``on_new(step,
path)`` fires — at most once per distinct step, however long the load
it triggers takes.

Jax-free: polling and firing are host IO; the loading it triggers
happens in the controller's cycle thread.
"""

from __future__ import annotations

import os
import random
import sys
import threading
from typing import Callable, Optional

from ..resilience import lineage


class Reloader:
    """Watch ``save_dir``'s LAST_GOOD pointer; fire ``on_new`` on change.

    ``current_step`` is a callable returning the step being served (the
    engine moves it on promote, so the reloader never re-fires for the
    checkpoint that just won).  ``poll_once`` is the unit-testable core;
    the thread is just poll_once on a jittered timer.
    """

    def __init__(
        self,
        save_dir: str,
        interval_s: float,
        on_new: Callable[[int, str], None],
        current_step: Optional[Callable[[], int]] = None,
        tel=None,
        jitter: float = 0.2,
    ) -> None:
        from .. import telemetry

        self.save_dir = save_dir
        self.interval_s = float(interval_s)  # sync-ok: host config scalar
        self.on_new = on_new
        self.current_step = current_step
        self.jitter = float(jitter)  # sync-ok: host config scalar
        self._tel = tel if tel is not None else telemetry.get()
        self._seen: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the poll (unit-tested directly) -----------------------------------

    def poll_once(self) -> Optional[int]:
        """One pointer read.  Returns the step fired, or None when the
        pointer is absent, unchanged, rejected, or already serving."""
        step = lineage.last_good_step(self.save_dir)
        if step is None or step == self._seen:
            return None
        # mark seen BEFORE any skip decision: a rejected or already-
        # serving step must not be re-examined every poll
        self._seen = step
        if self.current_step is not None and step == self.current_step():
            return None
        if lineage.is_rejected(self.save_dir, step):
            self._tel.count("lifecycle/skipped_rejected")
            print(
                f"sat_tpu: lifecycle reloader skipping step {step} — in "
                "the rejection ledger",
                file=sys.stderr,
                flush=True,
            )
            return None
        path = os.path.join(self.save_dir, f"{step}.npz")
        self._tel.count("lifecycle/reloads_triggered")
        self.on_new(step, path)
        return step

    def mark_seen(self, step: int) -> None:
        """Startup bookkeeping: the checkpoint loaded at boot must not
        immediately re-trigger a canary of itself."""
        self._seen = int(step)

    # -- the thread --------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            # jittered sleep FIRST: the server just loaded this pointer's
            # target at startup, so an immediate poll is always a no-op
            delay = self.interval_s * random.uniform(
                1 - self.jitter, 1 + self.jitter
            )
            if self._stop.wait(timeout=max(0.01, delay)):
                return
            try:
                self.poll_once()
            except Exception as e:  # polling must never die
                print(
                    f"sat_tpu: lifecycle reloader poll failed: {e}",
                    file=sys.stderr,
                    flush=True,
                )

    def start(self) -> "Reloader":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="sat-lifecycle-reloader", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
