"""Measure the fused Pallas attention kernel against XLA on the real chip.

Decides the fate of ``use_pallas_attention`` (VERDICT r1 item 6): flagship
decode shapes, both implementations timed over identical inputs, plus the
end-to-end beam-search step impact.  Run on TPU (no JAX_PLATFORMS override).

Usage: python scripts/bench_pallas.py [--batch 48] [--iters 200]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, args, iters: int, warmup: int = 5) -> float:
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=48, help="B (images × beams)")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--block-b", type=int, default=0, help="0 = sweep")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from sat_tpu.ops.pallas_attention import fused_attend, fused_attend_reference

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})", flush=True)

    # flagship decode shapes: VGG16 grid N=196, da=D=512
    B, N, da, D = args.batch, 196, 512, 512
    rng = np.random.default_rng(0)
    t1 = jnp.asarray(rng.normal(size=(B, N, da)).astype(np.float32))
    t2 = jnp.asarray(rng.normal(size=(B, da)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(da, 1)).astype(np.float32))
    ctx = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))

    xla = jax.jit(fused_attend_reference, static_argnames=("compute_dtype",))
    t_xla = timeit(xla, (t1, t2, w2, ctx), args.iters)
    traffic_mb = (t1.nbytes + ctx.nbytes) / 1e6
    print(
        f"XLA fused:    {t_xla*1e6:8.1f} us   "
        f"(~{traffic_mb / t_xla / 1e3:.0f} GB/s effective)", flush=True,
    )

    blocks = [args.block_b] if args.block_b else [4, 8, 16]
    best = (None, float("inf"))
    for bb in blocks:
        try:
            t_pal = timeit(
                lambda *a: fused_attend(*a, block_b=bb),
                (t1, t2, w2, ctx), args.iters,
            )
        except Exception as e:  # mosaic lowering failure at this tiling
            print(f"pallas bb={bb}: FAILED ({type(e).__name__}: {e})", flush=True)
            continue
        print(
            f"pallas bb={bb:2d}: {t_pal*1e6:8.1f} us   "
            f"(~{traffic_mb / t_pal / 1e3:.0f} GB/s effective)", flush=True,
        )
        if t_pal < best[1]:
            best = (bb, t_pal)

    if best[0] is None:
        print("verdict: pallas kernel failed to run — keep XLA path")
        return 1
    speedup = t_xla / best[1]
    print(f"best pallas: block_b={best[0]}  speedup vs XLA: {speedup:.2f}x")
    # correctness BEFORE the verdict: a fast-but-wrong kernel must never
    # emit the ENABLE line
    want = fused_attend_reference(t1, t2, w2, ctx)
    got = fused_attend(t1, t2, w2, ctx, block_b=best[0])
    np.testing.assert_allclose(
        np.asarray(got[1]), np.asarray(want[1]), rtol=2e-5, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(got[0]), np.asarray(want[0]), rtol=2e-4, atol=2e-4
    )
    print("on-device correctness: OK")
    print(
        "verdict: ENABLE use_pallas_attention"
        if speedup > 1.05
        else "verdict: keep XLA path (no win)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
