from .bleu import Bleu
from .cider import Cider
from .eval import CocoEvalCap
from .meteor import Meteor
from .rouge import Rouge

__all__ = ["Bleu", "Cider", "CocoEvalCap", "Meteor", "Rouge"]
