"""bench.py orchestrator contract tests.

The driver's only window into performance is bench.py's stdout; r01/r02
produced no parsed artifact because the tunneled backend hung before any
JSON landed.  These tests pin the resilience contract: the orchestrator
never imports jax itself, emits a machine-readable error line when the
backend is unreachable within budget, and the probe child really
round-trips a computation.
"""

import json
import os
import subprocess
import sys

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def test_orchestrator_emits_error_json_when_budget_exhausted():
    # A 1-second budget is below the minimum run reserve, so the probe
    # loop never starts: the orchestrator must still print a parseable
    # JSON line naming the failure (VERDICT r02 §next-round #1c) and exit
    # with a distinct code.
    env = dict(os.environ, BENCH_WATCHDOG_S="1")
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=env,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 4
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    assert len(lines) == 1
    parsed = json.loads(lines[0])
    assert parsed["error"] == "device_unreachable"
    assert parsed["metric"] == "train_captions_per_sec"
    assert parsed["value"] is None


def test_probe_round_trips_a_computation_on_cpu():
    env = dict(os.environ, BENCH_CPU="1", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, BENCH, "--probe"],
        env=env,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "probe ok" in proc.stderr


def test_orchestrator_reports_deterministic_child_failure_as_bench_failed():
    # A healthy probe followed by a bench child that crashes fast (bogus
    # BENCH_CNN -> Config validation error) must NOT be retried until the
    # budget burns and then mislabeled device_unreachable: after two fast
    # failures the orchestrator emits bench_failed with the child's rc.
    env = dict(
        os.environ,
        BENCH_CPU="1",
        JAX_PLATFORMS="cpu",
        BENCH_CNN="bogus_cnn",
        BENCH_WATCHDOG_S="300",
    )
    proc = subprocess.run(
        [sys.executable, BENCH],
        env=env,
        capture_output=True,
        text=True,
        timeout=280,
    )
    assert proc.returncode == 4, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")]
    parsed = json.loads(lines[-1])
    assert parsed["error"] == "bench_failed"
    assert parsed["child_rc"] not in (None, 0)
