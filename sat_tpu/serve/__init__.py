"""Online captioning service (docs/SERVING.md).

The first request-driven workload in the codebase: frozen params loaded
through the resilience lineage, ``encode + beam_search`` AOT-compiled at
a fixed ladder of batch buckets so steady state never recompiles, a
dynamic micro-batcher with admission control, and a stdlib HTTP frontend
with graceful SIGTERM drain.  ``serve_mode="continuous"`` swaps the
whole-batch dispatch for step-level continuous batching over a paged
slot pool (same zero-recompile guarantee, bitwise-identical results).

Layering:

* :mod:`engine`    — lineage param load, AOT bucket warmup, pad-to-bucket
  dispatch through compiled executables, detokenize drain;
* :mod:`slot_pool` — fixed-capacity paged slot pool for the stepped
  decode: AOT-warmed seed/step/harvest programs + host slot bookkeeping;
* :mod:`batcher`   — bounded queue and admission control; MicroBatcher
  gathers whole padded batches, ContinuousBatcher admits into free slots
  between decode steps and detokenizes asynchronously;
* :mod:`server`    — ThreadingHTTPServer frontend (POST /caption,
  GET /healthz, GET /stats), drain sequencing, the ``serve()`` CLI entry.
"""

from .batcher import ContinuousBatcher, MicroBatcher, Rejected, Request
from .engine import BucketOverflow, ServeEngine, load_serving_state
from .server import CaptionServer, serve
from .slot_pool import PagedSlotPool

__all__ = [
    "BucketOverflow",
    "CaptionServer",
    "ContinuousBatcher",
    "MicroBatcher",
    "PagedSlotPool",
    "Rejected",
    "Request",
    "ServeEngine",
    "load_serving_state",
    "serve",
]
