"""Watchdog cost accounting: guarded-vs-bare step-loop overhead.

docs/RESILIENCE.md claims the hang/wedge watchdog observes the train
loop for < 0.5% of a production step — the guards are two GIL-atomic
dict writes per phase and the observer thread reads host clocks on its
own schedule, never the loop's.  This bench puts a number on the claim
without jax: the instrumented cost is pure host work, so a synthetic
loop performing exactly the per-step guard sequence runtime.train
performs (one ``data_wait`` guard, one ``step`` guard wrapping a
``dispatch`` guard — the checkpoint guard only runs every save_period
steps and is excluded as conservative) measures the same cost the real
loop pays.

* ``off``: the loop body with no watchdog constructed — the bare
  baseline.
* ``on``: the same body bracketed by a live, **started** watchdog's
  phase guards while its observer thread polls — the armed cost.

Prints one BENCH-contract JSON line ({"metric", "value", "unit",
"vs_baseline", ...extras}).  ``value`` is the armed overhead in percent
of a ``--step-ms`` device step (0.5 is the acceptance bar, gated by
scripts/check_regression.py like every "overhead" metric).  No jax
import anywhere.

Usage: python scripts/bench_watchdog.py [--step-ms 30] [--iters 200000]
       [--workdir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sat_tpu import telemetry
from sat_tpu.resilience.watchdog import Watchdog

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_watchdog +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _bare_loop(iters: int) -> float:
    """The guard-free skeleton; seconds per step."""
    t_start = time.perf_counter()
    sink = 0
    for step in range(iters):
        sink += step  # same trivial body both loops carry
    assert sink >= 0
    return (time.perf_counter() - t_start) / iters


def _guarded_loop(wd: Watchdog, iters: int) -> float:
    """runtime.train's per-step guard sequence; seconds per step."""
    t_start = time.perf_counter()
    sink = 0
    for step in range(iters):
        with wd.phase("data_wait"):
            pass
        with wd.phase("step"):
            sink += step
            with wd.phase("dispatch"):
                pass
    assert sink >= 0
    return (time.perf_counter() - t_start) / iters


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--step-ms", type=float, default=30.0,
                    help="device step time the overhead is judged against")
    ap.add_argument("--iters", type=int, default=200000,
                    help="synthetic steps per measurement")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_watchdog_")
    made_workdir = args.workdir is None
    try:
        telemetry.disable()
        # warm both paths (interning, allocator) before timing
        _bare_loop(1000)
        off_s = _bare_loop(args.iters)

        wd = Watchdog(
            {"step": 3600.0, "data_wait": 3600.0, "dispatch": 3600.0},
            poll_s=0.25,
            dump_path=os.path.join(workdir, "watchdog_stacks.txt"),
        )
        wd.start()  # armed: the observer thread polls while we measure
        try:
            _guarded_loop(wd, 1000)
            on_s = _guarded_loop(wd, args.iters)
        finally:
            wd.stop()
        assert wd.state == 0 and wd.aborted_rc is None  # never tripped

        off_us, on_us = off_s * 1e6, on_s * 1e6
        overhead_us = max(0.0, on_us - off_us)
        overhead_pct = 100.0 * (overhead_us / 1e3) / args.step_ms
        log(f"per-step: bare {off_us:.3f} us, guarded {on_us:.3f} us -> "
            f"{overhead_pct:.4f}% of a {args.step_ms:.0f} ms step")

        result = {
            "metric": "watchdog_hot_path_overhead",
            "value": round(overhead_pct, 4),
            "unit": "%_of_step",
            "vs_baseline": 0.5,  # the acceptance bar (ISSUE: < 0.5%)
            "watchdog_on_us_per_step": round(on_us, 3),
            "watchdog_off_us_per_step": round(off_us, 3),
            "step_ms_assumed": args.step_ms,
            "poll_s": wd.poll_s,
            **telemetry.bench_stamp(),
        }
        print(json.dumps(result), flush=True)
        return 0 if overhead_pct <= 0.5 else 1
    finally:
        if made_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
