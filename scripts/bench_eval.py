"""Eval-side decode throughput: images/sec at beam_size=3.

BASELINE.md declares this a to-be-measured metric (the reference publishes
none; its host-side beam loop does ~beam×20 sess.run round-trips per image,
/root/reference/base_model.py:184-212).  Measures the full on-device
pipeline per batch: VGG16 encode + batched beam-search scan, one dispatch.

Usage: python scripts/bench_eval.py [--batch 32] [--beam 3] [--iters 20]
       (add --cpu --image-size 64 for a smoke run off-TPU)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--beam", type=int, default=3)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        # both mechanisms: the env's sitecustomize imports jax itself and
        # re-pins the platform (see tests/conftest.py)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")

    import jax

    from sat_tpu.config import Config
    from sat_tpu.models.captioner import encode, init_variables
    from sat_tpu.ops.beam_search import beam_search_jit

    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)}", file=sys.stderr, flush=True)

    config = Config(
        batch_size=args.batch, beam_size=args.beam, image_size=args.image_size
    )
    B = args.batch
    rng = np.random.default_rng(0)
    images = jax.device_put(
        rng.normal(size=(B, args.image_size, args.image_size, 3)).astype(np.float32)
    )
    variables = init_variables(jax.random.PRNGKey(0), config)
    eos = 1  # any fixed vocab index; cost is termination-independent worst case

    @jax.jit
    def decode(variables, images):
        contexts, _ = encode(variables, config, images, train=False)
        out = beam_search_jit(
            variables["params"]["decoder"], config, contexts, eos,
            beam_size=args.beam,
        )
        # serializing dependency for chained timing: a score-derived term
        # too small to perturb fp32 image pixels (block_until_ready on
        # independent dispatches is not trustworthy on the tunneled
        # platform — see PERF.md methodology note)
        chained = images + 1e-30 * out.log_scores.sum()
        return out, chained

    t0 = time.perf_counter()
    out, images_c = decode(variables, images)
    jax.device_get(out.log_scores[0, 0])
    compile_s = time.perf_counter() - t0
    print(f"compile+first: {compile_s:.1f}s", file=sys.stderr, flush=True)

    t0 = time.perf_counter()
    for _ in range(args.iters):
        out, images_c = decode(variables, images_c)
    jax.device_get(out.log_scores[0, 0])
    elapsed = time.perf_counter() - t0

    images_per_sec = args.iters * B / elapsed
    print(
        json.dumps(
            {
                "metric": "eval_images_per_sec",
                "value": round(images_per_sec, 2),
                "unit": f"images/sec @ beam={args.beam}",
                "batch_size": B,
                "batch_ms": round(1e3 * elapsed / args.iters, 1),
                "device_kind": getattr(dev, "device_kind", dev.platform),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
