"""Beam search tests: greedy oracle, numpy step-wise oracle, reference-style
host-heap oracle (the algorithm of reference base_model.py:163-240
re-implemented as a correctness baseline), and the no-completion fallback."""

import heapq

import jax
import jax.numpy as jnp
import numpy as np

from sat_tpu.config import Config
from sat_tpu.models.decoder import decoder_step, init_decoder_params, init_state
from sat_tpu.ops import beam_search, greedy_decode


def tiny_config(**kw) -> Config:
    base = dict(
        cnn="vgg16",
        vocabulary_size=30,
        dim_embedding=12,
        num_lstm_units=16,
        dim_initialize_layer=12,
        dim_attend_layer=12,
        dim_decode_layer=24,
        max_caption_length=6,
        batch_size=3,
        beam_size=3,
        compute_dtype="float32",
    )
    base.update(kw)
    return Config(**base)


EOS = 2  # pretend '.' lives at index 2


def setup(seed=0, B=3, **kw):
    cfg = tiny_config(**kw)
    params = init_decoder_params(jax.random.PRNGKey(seed), cfg)
    contexts = jnp.asarray(
        np.random.default_rng(seed).normal(size=(B, cfg.num_ctx, cfg.dim_ctx)),
        jnp.float32,
    )
    return cfg, params, contexts


def host_step(params, cfg, contexts, state, words):
    """One decoder step on host, returning (state, log-probs)."""
    state, logits, _ = decoder_step(
        params, cfg, contexts, state, jnp.asarray(words, jnp.int32), train=False
    )
    return state, np.asarray(jax.nn.log_softmax(logits, axis=-1))


class TestGreedy:
    def test_greedy_matches_argmax_rollout(self):
        cfg, params, contexts = setup()
        res = greedy_decode(params, cfg, contexts, eos_id=EOS)
        B, T = contexts.shape[0], cfg.max_caption_length

        state = init_state(params, cfg, contexts)
        words = np.zeros((B,), np.int32)
        done = np.zeros((B,), bool)
        out = np.zeros((B, T), np.int32)
        logp_total = np.zeros((B,), np.float64)
        for t in range(T):
            state, logp = host_step(params, cfg, contexts, state, words)
            # greedy == beam 1: continuation excludes eos; eos closes the beam
            for b in range(B):
                if done[b]:
                    continue
                best = int(np.argmax(logp[b]))
                if best == EOS:
                    out[b, t] = EOS
                    logp_total[b] += logp[b, EOS]
                    done[b] = True
                else:
                    cont = logp[b].copy()
                    cont[EOS] = -np.inf
                    w = int(np.argmax(cont))
                    out[b, t] = w
                    logp_total[b] += cont[w]
                    words[b] = w

        got = np.asarray(res.words[:, 0])
        for b in range(B):
            L = int(res.lengths[b, 0])
            finished = EOS in out[b]
            if finished:
                exp_len = int(np.argmax(out[b] == EOS)) + 1
                assert L == exp_len
                np.testing.assert_array_equal(got[b, :L], out[b, :L])


class TestBeamOracle:
    def _numpy_beam(self, cfg, params, contexts, K, T):
        """Step-wise numpy implementation of OUR semantics (global top-K,
        log-space, eos completes)."""
        B = contexts.shape[0]
        V = cfg.vocabulary_size
        state0 = init_state(params, cfg, contexts)
        # replicate per beam via flat batch
        ctx_rep = jnp.repeat(contexts, K, axis=0)
        state = type(state0)(*(jnp.repeat(s, K, axis=0) for s in state0))
        live_logp = np.full((B, K), -1e30)
        live_logp[:, 0] = 0.0
        live_words = np.zeros((B, K, T), np.int32)
        live_len = np.zeros((B, K), np.int32)
        last = np.zeros((B, K), np.int32)
        fin = [[] for _ in range(B)]  # list of (logp, words, len)

        for t in range(T):
            state, step_logp = host_step(
                params, cfg, ctx_rep, state, last.reshape(-1)
            )
            step_logp = step_logp.reshape(B, K, V)
            logp = step_logp + live_logp[..., None]
            for b in range(B):
                # completions — gated on eos being in the beam's top-(K+1)
                for k in range(K):
                    kth = np.sort(step_logp[b, k])[-min(K + 1, V)]
                    if step_logp[b, k, EOS] < kth:
                        continue
                    w = live_words[b, k].copy()
                    w[t] = EOS
                    fin[b].append((logp[b, k, EOS], w, live_len[b, k] + 1))
                fin[b] = sorted(fin[b], key=lambda x: -x[0])[:K]
            cont = logp.copy()
            cont[:, :, EOS] = -np.inf
            flat = cont.reshape(B, K * V)
            sel = np.argsort(-flat, axis=1)[:, :K]
            parent, word = sel // V, sel % V
            new_words = np.zeros_like(live_words)
            new_len = np.zeros_like(live_len)
            ns = [np.asarray(s).reshape(B, K, -1) for s in state]
            picked = [np.zeros_like(s) for s in ns]
            for b in range(B):
                for k in range(K):
                    p = parent[b, k]
                    new_words[b, k] = live_words[b, p]
                    new_words[b, k, t] = word[b, k]
                    new_len[b, k] = live_len[b, p] + 1
                    for i in range(3):
                        picked[i][b, k] = ns[i][b, p]
                live_logp[b] = flat[b, sel[b]]
            live_words, live_len, last = new_words, new_len, word.astype(np.int32)
            state = type(state0)(
                *(jnp.asarray(p.reshape(B * K, -1), jnp.float32) for p in picked)
            )
        return fin

    def test_matches_numpy_oracle(self):
        cfg, params, contexts = setup(seed=3)
        # nudge eos into contention so completions actually happen
        bias = np.asarray(params["decode"]["fc_2"]["bias"]).copy()
        bias[EOS] += 1.5
        params["decode"]["fc_2"]["bias"] = jnp.asarray(bias)
        K, T = cfg.beam_size, cfg.max_caption_length
        res = beam_search(params, cfg, contexts, eos_id=EOS)
        fin = self._numpy_beam(cfg, params, contexts, K, T)
        for b in range(contexts.shape[0]):
            assert fin[b], "oracle found no completions; reseed the test"
            n = len(fin[b])
            exp_scores = [s for s, _, _ in fin[b]]
            np.testing.assert_allclose(
                np.asarray(res.log_scores[b, :n]), exp_scores, rtol=1e-4, atol=1e-4
            )
            best_words = fin[b][0][1]
            L = fin[b][0][2]
            np.testing.assert_array_equal(
                np.asarray(res.words[b, 0, :L]), best_words[:L]
            )

    def test_at_least_as_good_as_reference_heap_semantics(self):
        """Reference algorithm (per-beam top-(K+1), prob products, TopN
        heaps) re-implemented on host; our global-top-K search must find a
        best caption with score >= the reference's."""
        cfg, params, contexts = setup(seed=11)
        K, T = cfg.beam_size, cfg.max_caption_length
        B = contexts.shape[0]
        state0 = init_state(params, cfg, contexts)

        # ---- reference-style host search (one image at a time) ----
        ref_best = []
        for b in range(B):
            ctx_b = contexts[b : b + 1]
            partial = [([], np.asarray(state0.memory[b]),
                        np.asarray(state0.output[b]), 1.0)]
            complete = []
            for t in range(T):
                expansions = []
                for sent, mem, out, score in partial:
                    st = type(state0)(
                        memory=jnp.asarray(mem[None]),
                        output=jnp.asarray(out[None]),
                        recurrent=jnp.asarray(out[None]),
                    )
                    word_in = sent[-1] if sent else 0
                    st2, logp = host_step(params, cfg, ctx_b, st, [word_in])
                    probs = np.exp(logp[0])
                    top = np.argsort(-probs)[: K + 1]
                    for w in top:
                        cand = (sent + [int(w)], np.asarray(st2.memory[0]),
                                np.asarray(st2.output[0]), score * probs[w])
                        if w == EOS:
                            complete.append(cand)
                        else:
                            expansions.append(cand)
                complete = sorted(complete, key=lambda x: -x[3])[:K]
                partial = sorted(expansions, key=lambda x: -x[3])[:K]
            pool = complete if complete else partial
            ref_best.append(max(c[3] for c in pool))

        res = beam_search(params, cfg, contexts, eos_id=EOS)
        ours = np.exp(np.asarray(res.log_scores[:, 0], np.float64))
        for b in range(B):
            assert ours[b] >= ref_best[b] * (1 - 1e-4), (b, ours[b], ref_best[b])


class TestFallback:
    def test_no_completion_returns_partials(self):
        """Suppress eos by giving it a huge negative embedding-path logit:
        easier — just use an eos_id the model can't prefer and tiny T with
        a vocab where eos never tops; verify lengths == T when nothing
        finished."""
        cfg, params, contexts = setup(seed=5)
        # make eos catastrophically unlikely by biasing the decode layer
        p2 = jax.tree_util.tree_map(lambda x: x, params)
        bias = np.asarray(p2["decode"]["fc_2"]["bias"]).copy()
        bias[EOS] = -1e9
        p2["decode"]["fc_2"]["bias"] = jnp.asarray(bias)
        res = beam_search(p2, cfg, contexts, eos_id=EOS)
        T = cfg.max_caption_length
        assert (np.asarray(res.lengths) == T).all()
        assert (np.asarray(res.words) != EOS).all()
        # scores sorted descending
        s = np.asarray(res.log_scores)
        assert (np.diff(s, axis=1) <= 1e-6).all()

    def test_partial_slots_backfilled_with_live_beams(self):
        """Images with 1..K-1 completions must not surface -inf junk rows:
        unfilled slots come from the live partial beams."""
        cfg, params, contexts = setup(seed=3)
        bias = np.asarray(params["decode"]["fc_2"]["bias"]).copy()
        bias[EOS] += 1.5  # some but rarely K completions per image
        params["decode"]["fc_2"]["bias"] = jnp.asarray(bias)
        res = beam_search(params, cfg, contexts, eos_id=EOS)
        s = np.asarray(res.log_scores)
        assert (s > -1e15).all(), "junk sentinel rows leaked into results"
        words = np.asarray(res.words)
        lengths = np.asarray(res.lengths)
        T = cfg.max_caption_length
        for b in range(words.shape[0]):
            for k in range(cfg.beam_size):
                finished = EOS in words[b, k]
                # a backfilled partial is a full-length eos-free rollout
                assert finished or lengths[b, k] == T

    def test_beam1_equals_greedy(self):
        cfg, params, contexts = setup(seed=7)
        r1 = beam_search(params, cfg, contexts, eos_id=EOS, beam_size=1)
        r2 = greedy_decode(params, cfg, contexts, eos_id=EOS)
        np.testing.assert_array_equal(np.asarray(r1.words), np.asarray(r2.words))


def test_returned_alphas_match_teacher_forced_replay():
    """The winning caption's attention maps must equal the alphas obtained
    by replaying that exact word sequence through decoder_step — pins the
    per-step parent-gather bookkeeping of the alpha carry."""
    cfg, params, contexts = setup(seed=5, B=3)
    out = beam_search(params, cfg, contexts, EOS, return_alphas=True)
    B, K, T, N = out.alphas.shape
    assert (B, K, T, N) == (3, 3, cfg.max_caption_length, cfg.num_ctx)

    for b in range(B):
        for k in range(K):
            words = np.asarray(out.words[b, k])
            length = int(out.lengths[b, k])
            state = init_state(params, cfg, contexts[b : b + 1], train=False)
            for t in range(length):
                last = 0 if t == 0 else int(words[t - 1])
                state, _, alpha = decoder_step(
                    params, cfg, contexts[b : b + 1], state,
                    jnp.asarray([last], jnp.int32), train=False,
                )
                np.testing.assert_allclose(
                    np.asarray(out.alphas[b, k, t]),
                    np.asarray(alpha[0]),
                    rtol=1e-5, atol=1e-6,
                    err_msg=f"b={b} k={k} t={t}",
                )
            # rows sum to 1 inside the caption, stay zero past its end
            sums = np.asarray(out.alphas[b, k]).sum(-1)
            np.testing.assert_allclose(sums[:length], 1.0, rtol=1e-5)
            np.testing.assert_allclose(sums[length:], 0.0, atol=1e-7)


def test_alphas_off_by_default_and_costless():
    cfg, params, contexts = setup(seed=3, B=2)
    out = beam_search(params, cfg, contexts, EOS)
    assert out.alphas is None


def test_valid_size_masks_phantom_vocab_columns():
    """A vocabulary smaller than config.vocabulary_size leaves trailing
    logit columns with no word (reference vocabulary.py:25-26 shrinks the
    vocab; its word list would be indexed past the end).  With valid_size
    set, no emitted token id may reach the phantom range."""
    from sat_tpu.config import Config
    from sat_tpu.models import init_decoder_params
    from sat_tpu.ops.beam_search import beam_search_jit

    config = Config(
        vocabulary_size=50,
        dim_embedding=16,
        num_lstm_units=16,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        max_caption_length=6,
        compute_dtype="float32",
    )
    params = init_decoder_params(jax.random.PRNGKey(0), config)
    rng = np.random.default_rng(0)
    contexts = jnp.asarray(rng.normal(size=(3, 8, 512)).astype(np.float32))

    valid = 17
    out = beam_search_jit(
        params, config, contexts, eos_id=3, beam_size=3, valid_size=valid
    )
    words = np.asarray(out.words)
    lengths = np.asarray(out.lengths)
    for b in range(words.shape[0]):
        for k in range(words.shape[1]):
            emitted = words[b, k, : lengths[b, k]]
            assert (emitted < valid).all(), (b, k, emitted)


def test_early_exit_is_exact():
    """The while_loop early exit (stop once every image's finished set can
    no longer change) must return bit-identical results to the full
    T-step control, across seeds and beam widths — including models whose
    beams complete at different steps per image."""
    for seed in range(6):
        for K in (1, 2, 3):
            cfg, params, contexts = setup(seed=seed, B=4, beam_size=K,
                                          max_caption_length=8)
            full = beam_search(
                params, cfg, contexts, EOS, early_exit=False,
                return_alphas=True,
            )
            fast = beam_search(
                params, cfg, contexts, EOS, early_exit=True,
                return_alphas=True,
            )
            np.testing.assert_array_equal(
                np.asarray(fast.words), np.asarray(full.words),
                err_msg=f"seed={seed} K={K}",
            )
            np.testing.assert_array_equal(
                np.asarray(fast.lengths), np.asarray(full.lengths)
            )
            np.testing.assert_array_equal(
                np.asarray(fast.log_scores), np.asarray(full.log_scores)
            )
            np.testing.assert_array_equal(
                np.asarray(fast.alphas), np.asarray(full.alphas)
            )


def test_early_exit_actually_exits():
    """With the decode bias rigged so eos dominates every step, all beams
    finish immediately; the early-exit search must (a) still equal the
    full-length control and (b) demonstrably stop.  The stop is asserted
    on the deterministic steps_run probe (the while_loop's final t), not
    wall-clock — timing on a loaded CI box is advisory only (ADVICE r3)."""
    import time

    cfg, params, contexts = setup(seed=1, B=4, beam_size=3,
                                  max_caption_length=40)
    # rig the vocab-logit bias: eos wins by a mile at every step
    p = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy via rebuild
    fc = "fc" if "fc" in p["decode"] else list(p["decode"].keys())[-1]
    bias = np.asarray(p["decode"][fc]["bias"]).copy()
    bias[EOS] += 50.0
    p["decode"][fc]["bias"] = jnp.asarray(bias)

    full = jax.jit(
        lambda c: beam_search(p, cfg, c, EOS, early_exit=False,
                              return_steps=True)
    )
    fast = jax.jit(
        lambda c: beam_search(p, cfg, c, EOS, early_exit=True,
                              return_steps=True)
    )
    rf = full(contexts)
    rx = fast(contexts)
    np.testing.assert_array_equal(np.asarray(rx.words), np.asarray(rf.words))
    # beam 0 completes at step 0; the other fin slots fill at step 1 —
    # nothing survives past two tokens when eos dominates
    assert int(np.asarray(rx.lengths).max()) <= 2

    # the deterministic signal: the control runs all 40 iterations, the
    # exited program stops as soon as every image is sealed (~2 steps;
    # ≤4 leaves margin for the one extra cond evaluation per fill step)
    assert int(np.asarray(rf.steps_run)) == 40
    assert int(np.asarray(rx.steps_run)) <= 4, int(np.asarray(rx.steps_run))

    def steady(fn):
        jax.block_until_ready(fn(contexts))
        t0 = time.perf_counter()
        for _ in range(20):
            out = fn(contexts)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    t_full, t_fast = steady(full), steady(fast)
    if t_fast >= t_full / 2:  # advisory: report, don't flake
        import warnings

        warnings.warn(
            f"early-exit wall-clock advisory: fast={t_fast:.3f}s "
            f"full={t_full:.3f}s (deterministic steps_run check passed)"
        )
