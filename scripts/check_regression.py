"""Automated perf/quality regression gate over BENCH + compile_report artifacts.

The repo accumulates a measurement trajectory — ``BENCH_r0*.json`` driver
wrappers, ``runs/*/bench_*.json`` BENCH-contract rows, ``BASELINE.json``
published numbers, and (since the telemetry PRs) ``compile_report.json``
FLOP/HBM accounting.  Until now a PR that regressed any of it relied on a
human noticing.  This script is the contract: feed it the prior artifacts
and a fresh one, and it exits nonzero when the fresh numbers are worse
than the best prior beyond a per-metric noise margin.

Usage
-----
Trajectory mode (chronological; the LAST file is the candidate)::

    python scripts/check_regression.py BENCH_r01.json BENCH_r02.json fresh.json

Explicit pair mode::

    python scripts/check_regression.py --baseline prior.json --current fresh.json

Compile-report mode (may be combined with either of the above)::

    python scripts/check_regression.py \
        --compile-baseline runs/prior/compile_report.json \
        --compile-current  out/telemetry/compile_report.json

Inputs accepted per file: a BENCH-contract JSONL stream
(``{"metric","value","unit","vs_baseline",...}`` per line), one JSON
object/array of such rows, or a bench-driver wrapper
(``{"n","cmd","rc","tail","parsed"}`` — only ``parsed`` is read).
Wrappers whose run never produced numbers (``parsed: null``, the
device-unreachable sessions) contribute nothing; when NO comparable pair
exists the gate exits 0 with a warning — an unreachable device must not
fail CI, only a measured regression may.

Schema compatibility: rows/reports stamped with a ``schema_version``
different from the current ``sat_tpu.telemetry.SCHEMA_VERSION`` are
REFUSED (exit 3) — a changed contract must bump the version and reset
the trajectory.  Unstamped rows are legacy and compared best-effort.

Direction + margins: each metric has a better-direction (throughput up,
time/FLOPs/bytes down — see ``_lower_better``) and a noise margin in
percent (defaults below, override with ``--margin name=pct``).  The
candidate is compared against the BEST prior value so a noisy low prior
can't mask a real regression.

Infra-skip: a CANDIDATE artifact carrying ``error: device_unreachable``
rows (bench.py's fallback line when every probe/run attempt died inside
the device-watchdog budget) means measurement never happened — that is
an infrastructure outage, not a metric regression.  The gate exits 3
with a named reason so CI can mark the job skipped instead of failed;
measured regressions in the same artifact still win (exit 2 takes
precedence).

Exit codes: 0 = no regression (or nothing comparable), 2 = regression,
3 = incompatible schema or infra-skip (candidate is an unmeasured
device-unreachable artifact), 1 = usage/IO error.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sat_tpu.telemetry import SCHEMA_VERSION


class SchemaMismatch(Exception):
    pass


# per-metric noise margins in percent of the best prior value
DEFAULT_MARGINS = {
    "flops": 1.0,              # compile-time FLOPs are exact; 1% = real change
    "temp_bytes": 2.0,         # HBM temp footprint: layout jitter only
    "output_bytes": 2.0,
    "argument_bytes": 2.0,
    "step_time_ms": 5.0,       # wall-clock: CI noise
    "train_captions_per_sec": 5.0,
    "eval_images_per_sec": 5.0,
    "Bleu_4": 1.0,             # quality: a point of BLEU is never noise
    "CIDEr": 1.0,
    "serve_encode_ms": 10.0,   # encode-lane timing: shared-host jitter
    "serve_encode_ms_int8": 10.0,
    "serve_encode_ms_bf16": 10.0,
    # quantization parity deltas are bounded-zero: the fixture harness
    # already holds them under their gate, so any measured GROWTH is a
    # quantizer regression (wrong scale axis, dropped dequant), not noise
    "quant_ctx_rel_err": 1.0,
    "quant_logit_drift": 1.0,
    # fleet rows ride N subprocess replicas on a shared CPU host — the
    # noisiest bench family we gate, so the margins are wide; a real
    # scaling regression moves goodput far more than this
    "fleet_goodput_rps": 10.0,
    "fleet_open_loop_p99_latency_ms": 15.0,
    "fleet_router_overhead_ms": 25.0,
    # bulk rows time whole CLI subprocesses (jax boot + checkpoint load +
    # decode) on a shared CPU host — wide margins like the fleet family
    "bulk_throughput_captions_s": 10.0,
    "bulk_resume_overhead_s": 25.0,
    # fused-decode rows (docs/SERVING.md "Fused decode window"): the
    # single-stream row is one closed-loop client on a shared CPU host —
    # per-request wall clock, so moderately noisy; admission p95 rides
    # the near-capacity open loop and inherits its burst jitter
    "serve_single_stream_latency_ms": 15.0,
    "serve_admission_latency_ms": 20.0,
    # lifecycle rows: the swap blackout is a continuous-mode pool drain
    # timed on a shared CPU host, and canary overhead is a ratio of two
    # open-loop p50s — both wall-clock-noisy families, wide margins
    "swap_blackout_ms": 25.0,
    "canary_overhead_pct": 25.0,
    # multi-tenant rows (docs/SERVING.md "Multi-tenant serving"): the
    # isolation ratio divides two open-loop p99s on a shared CPU host
    # (tail-over-tail — the noisiest shape we gate); fair-share error is
    # a completion-count fraction over a fixed window, much steadier
    "tenant_isolation_p99_ratio": 30.0,
    "tenant_fair_share_error": 25.0,
    # metering rows (docs/OBSERVABILITY.md "Cost attribution and tenant
    # metering"): overhead is a noise-floored microbench-over-p50 ratio
    # (bench_serve exit-gates the raw value at 0.5% separately); the
    # would-hit probe is a seeded-Zipf hit fraction, nearly deterministic
    "metering_overhead_pct": 25.0,
    "encode_cache_would_hit_ratio": 10.0,
    # encode-cache rows (docs/SERVING.md "Encode cache & tiered
    # fleets"): the ACTUAL hit ratio under seeded Zipf traffic is nearly
    # deterministic (bench_serve exit-gates the 0.6 floor separately);
    # the goodput row is an open loop on a shared CPU host — wide like
    # the fleet family, as is the two-hop disaggregated arm
    "encode_cache_hit_ratio": 10.0,
    "cache_serve_goodput_rps": 10.0,
    "fleet_disagg_goodput_rps": 10.0,
    # quality-plane row (docs/OBSERVABILITY.md "Caption quality"): the
    # same noise-floored microbench-over-p50 shape as metering_overhead
    # (bench_quality exit-gates the raw value at 0.5% separately)
    "quality_overhead_pct": 25.0,
}
FALLBACK_MARGIN = 5.0

# metrics where SMALLER is better; everything else is throughput/quality
_LOWER_BETTER_EXACT = {
    "step_time_ms",
    "compile_s",
    "telemetry_hot_path_overhead",
    "diag_tap_overhead",
    "ckpt_step_overhead",
    "flops",
    "transcendentals",
    "bytes_accessed",
    "temp_bytes",
    "output_bytes",
    "argument_bytes",
    "serve_encode_ms",
    "serve_single_stream_latency_ms",
    "serve_admission_latency_ms",
    "quant_ctx_rel_err",
    "quant_logit_drift",
    "tenant_isolation_p99_ratio",
    "tenant_fair_share_error",
}
# explicitly HIGHER-better (checked first — "per_sec" would otherwise
# trip the "_s" suffix heuristic below)
_HIGHER_BETTER_EXACT = {
    "train_captions_per_sec",
    "eval_images_per_sec",
    "shard_feed_speedup",
    "min_speedup",
    "fleet_goodput_rps",
    "fleet_disagg_goodput_rps",
    # a HIGHER would-be hit ratio means caching would pay off more —
    # the probe regressing toward 0 under the same seeded Zipf traffic
    # means the sketch (or its crc32c feed) broke
    "encode_cache_would_hit_ratio",
    # ...and the ACTUAL ratio regressing under the same traffic means
    # the device ring broke (keys drifting, over-eager flush/eviction)
    "encode_cache_hit_ratio",
    "Bleu_4",
    "CIDEr",
    "METEOR",
    "ROUGE_L",
}
_LOWER_BETTER_TOKENS = ("overhead", "seconds", "bytes", "latency")
_LOWER_BETTER_SUFFIXES = ("_ms", "_s", "_us", "_mb", "_time")
# quant-arm rows suffix the base metric with their mode
# (serve_encode_ms_int8, serve_closed_loop_throughput_bf16, ...) so the
# A/B pair gates independently; the variant inherits the base direction
_VARIANT_TAGS = ("_int8", "_bf16")


def _lower_better(metric: str) -> bool:
    for tag in _VARIANT_TAGS:
        if metric.endswith(tag):
            metric = metric[: -len(tag)]
            break
    if metric in _HIGHER_BETTER_EXACT:
        return False
    if metric in _LOWER_BETTER_EXACT:
        return True
    m = metric.lower()
    if "per_sec" in m or "speedup" in m or "throughput" in m:
        return False
    return any(tok in m for tok in _LOWER_BETTER_TOKENS) or m.endswith(
        _LOWER_BETTER_SUFFIXES
    )


def _check_schema(obj: Dict, path: str) -> None:
    v = obj.get("schema_version")
    if v is not None and v != SCHEMA_VERSION:
        raise SchemaMismatch(
            f"{path}: schema_version={v} is incompatible with this repo's "
            f"SCHEMA_VERSION={SCHEMA_VERSION} — refusing to compare"
        )


def _rows_from_obj(obj: Any, path: str) -> List[Dict]:
    """Normalize one parsed JSON value into BENCH rows."""
    if obj is None:
        return []
    if isinstance(obj, list):
        rows: List[Dict] = []
        for item in obj:
            rows.extend(_rows_from_obj(item, path))
        return rows
    if not isinstance(obj, dict):
        return []
    if "parsed" in obj and "rc" in obj:      # bench-driver wrapper
        return _rows_from_obj(obj.get("parsed"), path)
    if "metric" in obj:
        _check_schema(obj, path)
        value = obj.get("value")
        if isinstance(value, (int, float)):
            return [obj]
        return []                            # degraded row (value null)
    return []


# error strings that mean "the run never measured anything for
# infrastructure reasons" — candidate artifacts carrying them are an
# infra-skip (exit 3), never a regression
INFRA_SKIP_ERRORS = ("device_unreachable",)


def _errors_from_obj(obj: Any) -> List[str]:
    """Error strings carried by BENCH rows (``value`` null, ``error``
    set — the bench orchestrator's fallback line)."""
    if obj is None:
        return []
    if isinstance(obj, list):
        errors: List[str] = []
        for item in obj:
            errors.extend(_errors_from_obj(item))
        return errors
    if not isinstance(obj, dict):
        return []
    if "parsed" in obj and "rc" in obj:      # bench-driver wrapper
        return _errors_from_obj(obj.get("parsed"))
    err = obj.get("error")
    return [str(err)] if err else []


def load_errors(path: str) -> List[str]:
    """Error strings from one artifact file (same formats as load_rows)."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return []
    try:
        return _errors_from_obj(json.loads(text))
    except json.JSONDecodeError:
        errors: List[str] = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                errors.extend(_errors_from_obj(json.loads(line)))
            except json.JSONDecodeError:
                continue
        return errors


def load_rows(path: str) -> List[Dict]:
    """BENCH rows from one artifact file (JSON, JSON array, JSONL, or
    driver wrapper).  IO/parse failures raise — a missing candidate file
    is a usage error, not a pass."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return []
    try:
        return _rows_from_obj(json.loads(text), path)
    except json.JSONDecodeError:
        rows: List[Dict] = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            rows.extend(_rows_from_obj(json.loads(line), path))
        return rows


def best_prior(
    values: List[float], lower_better: bool
) -> float:
    return min(values) if lower_better else max(values)


def compare_metric(
    metric: str,
    prior: List[float],
    current: float,
    margins: Dict[str, float],
) -> Tuple[bool, str]:
    """(is_regression, human line) for one metric."""
    lower = _lower_better(metric)
    best = best_prior(prior, lower)
    margin = margins.get(metric, FALLBACK_MARGIN)
    if best == 0:
        delta_pct = 0.0 if current == 0 else float("inf")
    else:
        delta_pct = 100.0 * (current - best) / abs(best)
    worse = delta_pct > margin if lower else delta_pct < -margin
    arrow = "↓ better" if lower else "↑ better"
    verdict = "REGRESSION" if worse else "ok"
    return worse, (
        f"{metric:<32} best-prior {best:g}  current {current:g}  "
        f"delta {delta_pct:+.2f}% (margin {margin:g}%, {arrow}): {verdict}"
    )


def check_bench(
    prior_files: List[str],
    current_file: str,
    margins: Dict[str, float],
) -> Tuple[int, List[str]]:
    """Compare the candidate file's rows against every prior file.
    Returns (regression_count, report_lines); raises SchemaMismatch."""
    prior_by_metric: Dict[str, List[float]] = {}
    prior_step_ms: Dict[str, List[float]] = {}
    for path in prior_files:
        for row in load_rows(path):
            prior_by_metric.setdefault(row["metric"], []).append(
                float(row["value"])
            )
            if isinstance(row.get("step_time_ms"), (int, float)):
                prior_step_ms.setdefault(row["metric"], []).append(
                    float(row["step_time_ms"])
                )

    current_rows = load_rows(current_file)
    lines: List[str] = []
    regressions = 0
    compared = 0
    for row in current_rows:
        metric = row["metric"]
        if metric in prior_by_metric:
            compared += 1
            worse, line = compare_metric(
                metric, prior_by_metric[metric], float(row["value"]), margins
            )
            regressions += worse
            lines.append(line)
        # step_time_ms rides many throughput rows as an extra field and
        # regresses independently of the headline metric
        if metric in prior_step_ms and isinstance(
            row.get("step_time_ms"), (int, float)
        ):
            compared += 1
            worse, line = compare_metric(
                "step_time_ms",
                prior_step_ms[metric],
                float(row["step_time_ms"]),
                margins,
            )
            regressions += worse
            lines.append(f"[{metric}] {line}")
    if not compared:
        lines.append(
            "warning: no comparable metric rows between candidate and "
            "priors (unparsed/degraded artifacts?) — nothing to gate"
        )
    return regressions, lines


def check_compile_reports(
    baseline_path: str, current_path: str, margins: Dict[str, float]
) -> Tuple[int, List[str]]:
    """Gate per-function FLOPs and HBM footprints between two
    compile_report.json files; compile time is reported, never gated
    (cache hits make it meaningless across runs)."""
    with open(baseline_path) as f:
        base = json.load(f)
    with open(current_path) as f:
        cur = json.load(f)
    _check_schema(base, baseline_path)
    _check_schema(cur, current_path)
    lines: List[str] = []
    regressions = 0
    compared = 0
    for name, cur_fn in (cur.get("functions") or {}).items():
        base_fn = (base.get("functions") or {}).get(name)
        if not base_fn:
            continue
        pairs: List[Tuple[str, Optional[float], Optional[float]]] = [
            (
                "flops",
                (base_fn.get("cost") or {}).get("flops"),
                (cur_fn.get("cost") or {}).get("flops"),
            )
        ]
        for key in ("temp_bytes", "output_bytes", "argument_bytes"):
            pairs.append(
                (
                    key,
                    (base_fn.get("memory") or {}).get(key),
                    (cur_fn.get("memory") or {}).get(key),
                )
            )
        for key, b, c in pairs:
            if b is None or c is None:
                continue
            compared += 1
            worse, line = compare_metric(key, [float(b)], float(c), margins)
            regressions += worse
            lines.append(f"[{name}] {line}")
        b_s, c_s = base_fn.get("compile_seconds"), cur_fn.get("compile_seconds")
        if b_s is not None and c_s is not None:
            lines.append(
                f"[{name}] compile_seconds {b_s:g} -> {c_s:g} (informational)"
            )
    if not compared:
        lines.append(
            "warning: compile reports share no comparable functions/fields"
        )
    return regressions, lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="BENCH/compile_report regression gate "
        "(exit 0 ok, 2 regression, 3 schema mismatch)"
    )
    ap.add_argument(
        "trajectory",
        nargs="*",
        help="bench artifacts in chronological order; the LAST is the candidate",
    )
    ap.add_argument("--baseline", help="explicit prior bench artifact")
    ap.add_argument("--current", help="explicit candidate bench artifact")
    ap.add_argument("--compile-baseline", help="prior compile_report.json")
    ap.add_argument("--compile-current", help="candidate compile_report.json")
    ap.add_argument(
        "--margin",
        action="append",
        default=[],
        metavar="METRIC=PCT",
        help="override a per-metric noise margin (repeatable)",
    )
    args = ap.parse_args(argv)

    margins = dict(DEFAULT_MARGINS)
    for spec in args.margin:
        name, _, pct = spec.partition("=")
        try:
            margins[name] = float(pct)
        except ValueError:
            ap.error(f"--margin {spec!r}: expected METRIC=PCT")

    # shells without glob expansion (CI yaml) pass the pattern literally
    files: List[str] = []
    for pattern in args.trajectory:
        matched = sorted(_glob.glob(pattern)) if any(
            ch in pattern for ch in "*?["
        ) else [pattern]
        files.extend(matched)

    jobs = 0
    regressions = 0
    candidate_errors: List[str] = []
    try:
        if args.baseline or args.current:
            if not (args.baseline and args.current):
                ap.error("--baseline and --current must be given together")
            jobs += 1
            n, lines = check_bench([args.baseline], args.current, margins)
            regressions += n
            candidate_errors.extend(load_errors(args.current))
            print("\n".join(lines))
        if len(files) >= 2:
            jobs += 1
            n, lines = check_bench(files[:-1], files[-1], margins)
            regressions += n
            candidate_errors.extend(load_errors(files[-1]))
            print("\n".join(lines))
        elif files:
            # a single artifact has nothing to regress against: validate
            # it (schema + parse) and pass
            jobs += 1
            rows = load_rows(files[0])
            candidate_errors.extend(load_errors(files[0]))
            print(
                f"{files[0]}: {len(rows)} row(s), no prior artifacts — "
                "nothing to gate"
            )
        if args.compile_baseline or args.compile_current:
            if not (args.compile_baseline and args.compile_current):
                ap.error(
                    "--compile-baseline and --compile-current must be "
                    "given together"
                )
            jobs += 1
            n, lines = check_compile_reports(
                args.compile_baseline, args.compile_current, margins
            )
            regressions += n
            print("\n".join(lines))
    except SchemaMismatch as e:
        print(f"check_regression: {e}", file=sys.stderr)
        return 3
    except (OSError, json.JSONDecodeError, KeyError, ValueError) as e:
        print(f"check_regression: bad artifact: {e}", file=sys.stderr)
        return 1

    if jobs == 0:
        ap.error("nothing to do: pass a trajectory, --baseline/--current, "
                 "or --compile-baseline/--compile-current")
    if regressions:
        # measured regressions outrank an infra-skip: numbers that DID
        # land and got worse must fail the gate even if a later attempt
        # in the same artifact hit the outage
        print(f"check_regression: {regressions} regression(s)", file=sys.stderr)
        return 2
    skips = sorted({e for e in candidate_errors if e in INFRA_SKIP_ERRORS})
    if skips:
        print(
            f"check_regression: infra-skip ({', '.join(skips)}) — the "
            "candidate artifact records an infrastructure outage, not a "
            "measurement; nothing was gated",
            file=sys.stderr,
        )
        return 3
    for e in sorted({e for e in candidate_errors if e not in INFRA_SKIP_ERRORS}):
        print(
            f"check_regression: warning: candidate carries error rows "
            f"({e}) — not a recognized infra-skip reason",
            file=sys.stderr,
        )
    print("check_regression: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
