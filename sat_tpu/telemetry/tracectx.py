"""Request-scoped tracing: one id per request, phase timings, Perfetto lanes.

The span layer (``spans.py``) sees the *process* — aggregate p50/p95/p99
over every request that ever ran.  This module sees one *request*: a
trace id minted at the HTTP boundary (honoring an inbound ``X-Request-Id``
so a client or an upstream proxy can correlate), carried on the batcher's
``Request`` object through admission → batch forming → dispatch → drain →
detok, with each phase stamped as a ``(t0_ns, dur_ns)`` interval on the
same ``perf_counter_ns`` clock the telemetry ring uses.  Three outputs:

* ``access.jsonl`` — one structured line per terminal reply (success AND
  sheds), size-capped through :func:`exporters.rotating_append`, holding
  the trace id, status, bucket, tenant, total latency, the attributed
  device cost (telemetry/metering.py), and all five phase timings.  ``queue_wait + batch_form + dispatch + drain + detok`` are
  disjoint sub-intervals of the request's life, so their sum is ≤ the
  total — the residual is host preprocessing and scheduling gaps.
* Chrome-trace child spans — :meth:`RequestTracer.trace_events` renders
  each retained request as its own named lane (synthetic tid + a
  ``thread_name`` metadata event), so one slow request is one clickable
  lane in Perfetto next to the process-level tracks.
* the completed-trace ring itself (bounded, ``keep`` most recent) for
  tests and ad-hoc introspection.

Deliberately jax-free and sync-free: every timestamp is host wall/mono
time already being taken by the serve path.  All writers degrade on
failure (the SummaryWriter rule) — tracing must never fail a request.
"""

from __future__ import annotations

import re
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import run_id

# the correlation header, honored inbound and echoed on EVERY reply
# (including 400/429/503/504 sheds — clients correlate rejects too)
TRACE_HEADER = "X-Request-Id"

# the five per-request phases, in causal order (docs/OBSERVABILITY.md):
# queue_wait   submit -> popped from the admission queue
# batch_form   popped -> the batch's dispatch boundary (held open for
#              riders up to serve_max_wait_ms)
# dispatch     pad-to-bucket + AOT executable launch (async)
# drain        host<->device sync waiting on the batch's device results
# detok        host detokenize of the drained arrays
PHASES = ("queue_wait", "batch_form", "dispatch", "drain", "detok")

# inbound ids are sanitized, not trusted: header-safe charset, bounded
_ID_RE = re.compile(r"[^A-Za-z0-9_.:\-]")
_MAX_ID_LEN = 128

# synthetic Perfetto lane ids for request tracks, far above any real
# thread ident's low bits so lanes never collide with host-thread tracks
_LANE_BASE = 1 << 20


def mint_trace_id() -> str:
    """A fresh 16-hex-char request id (uuid4 entropy, log-friendly)."""
    return uuid.uuid4().hex[:16]


def ensure_id(raw: Optional[str]) -> str:
    """The id a reply must echo: the inbound header value when one came
    (sanitized to a header-safe charset, length-bounded), minted fresh
    otherwise."""
    if raw is None:
        return mint_trace_id()
    cleaned = _ID_RE.sub("", raw.strip())[:_MAX_ID_LEN]
    return cleaned if cleaned else mint_trace_id()


class RequestTrace:
    """One request's id + phase intervals.

    Phases are marked from the batcher thread (strictly ordered), and
    :meth:`RequestTracer.finish` reads them from the HTTP thread only
    after the request's ``done`` event fired — the Event is the
    happens-before edge, so no lock is needed."""

    __slots__ = ("trace_id", "t_start_ns", "phases")

    def __init__(self, trace_id: str, t_start_ns: Optional[int] = None) -> None:
        self.trace_id = trace_id
        self.t_start_ns = (
            t_start_ns if t_start_ns is not None else time.perf_counter_ns()
        )
        self.phases: Dict[str, Tuple[int, int]] = {}

    def mark(self, phase: str, t0_ns: int, dur_ns: int) -> None:
        """Stamp one phase interval (last write wins; phases fire once
        per request on the happy path)."""
        self.phases[phase] = (t0_ns, max(0, dur_ns))

    def phase_ms(self) -> Dict[str, float]:
        """All five phase durations in ms, absent phases as 0.0 — the
        access-log contract is that every record carries every phase."""
        return {
            f"{name}_ms": round(self.phases.get(name, (0, 0))[1] / 1e6, 3)
            for name in PHASES
        }


class RequestTracer:
    """Mints traces, writes ``access.jsonl``, retains finished traces.

    ``path`` empty disables the access log (traces still retain for the
    Perfetto export); ``cap_bytes`` 0 disables rotation."""

    def __init__(self, path: str = "", cap_bytes: int = 0, keep: int = 256) -> None:
        self.path = path
        self.cap_bytes = int(cap_bytes)
        self._lock = threading.Lock()
        self._finished: "deque" = deque(maxlen=max(1, int(keep)))
        self._seq = 0

    # -- request lifecycle -------------------------------------------------

    def begin(self, raw_header: Optional[str] = None) -> RequestTrace:
        return RequestTrace(ensure_id(raw_header))

    def finish(
        self,
        trace: RequestTrace,
        status: int,
        total_ns: int,
        bucket: Optional[int] = None,
        error: Optional[str] = None,
        tenant: Optional[str] = None,
        cost: Optional[Any] = None,
    ) -> Dict[str, Any]:
        """Record the terminal reply: one access.jsonl line + retention.
        ``tenant`` stamps the submitting tenant (per-tenant log filtering
        and Perfetto lane args); ``cost`` is the request's attributed
        device cost (a ``metering.RequestCost`` — its ms view lands as a
        ``cost`` sub-object).  Returns the record (tests and callers read
        it back); never raises — a failed append degrades inside
        ``rotating_append``."""
        record: Dict[str, Any] = {
            "run_id": run_id(),
            "trace_id": trace.trace_id,
            "wall_time": round(time.time(), 6),
            "status": int(status),
            "total_ms": round(max(0, total_ns) / 1e6, 3),
            "phases": trace.phase_ms(),
        }
        if bucket is not None:
            record["bucket"] = int(bucket)
        if tenant is not None:
            record["tenant"] = str(tenant)
        if cost is not None:
            record["cost"] = cost.as_dict()
        if error:
            record["error"] = error
        with self._lock:
            self._seq += 1
            self._finished.append((self._seq, trace, record))
        if self.path:
            import json

            from .exporters import rotating_append

            rotating_append(self.path, json.dumps(record), self.cap_bytes)
        return record

    # -- read side ---------------------------------------------------------

    def finished(self) -> List[Dict[str, Any]]:
        """The retained access records, oldest first."""
        with self._lock:
            return [rec for _, _, rec in self._finished]

    def trace_events(self, anchor_ns: int, pid: int = 0) -> List[Dict]:
        """Chrome trace events for the retained requests: one lane per
        request (synthetic tid + thread_name metadata), a whole-request
        parent span, and one child span per recorded phase — merged into
        the process trace via ``exporters.chrome_trace(extra_events=…)``.
        """
        events: List[Dict] = []
        with self._lock:
            entries = list(self._finished)
        for seq, trace, record in entries:
            tid = _LANE_BASE + seq
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": f"request {trace.trace_id}"},
                }
            )
            events.append(
                {
                    "name": f"request {trace.trace_id}",
                    "cat": "request",
                    "ph": "X",
                    "pid": pid,
                    "tid": tid,
                    "ts": (trace.t_start_ns - anchor_ns) / 1e3,
                    "dur": record["total_ms"] * 1e3,
                    "args": {
                        "trace_id": trace.trace_id,
                        "status": record["status"],
                        "bucket": record.get("bucket"),
                        # tenant + attributed cost ride the lane args so
                        # Perfetto queries can filter/aggregate by tenant
                        "tenant": record.get("tenant"),
                        "cost": record.get("cost"),
                    },
                }
            )
            for phase, (t0, dur) in trace.phases.items():
                events.append(
                    {
                        "name": phase,
                        "cat": "request",
                        "ph": "X",
                        "pid": pid,
                        "tid": tid,
                        "ts": (t0 - anchor_ns) / 1e3,
                        "dur": dur / 1e3,
                        "args": {"trace_id": trace.trace_id},
                    }
                )
        return events
