"""ROUGE-L (longest-common-subsequence F-measure).

Own implementation of Lin (2004) with the reference wrapper's conventions
(/root/reference/utils/coco/pycocoevalcap/rouge/rouge.py:13-102): β=1.2,
per-image score = F(max precision over refs, max recall over refs), corpus
score = mean over images.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

BETA = 1.2


def lcs_length(a: Sequence[str], b: Sequence[str]) -> int:
    """Classic O(len(a)·len(b)) LCS dynamic program, O(min) memory."""
    if len(a) < len(b):
        a, b = b, a
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[-1]))
        prev = cur
    return prev[-1]


def rouge_l_single(hypothesis: str, references: List[str]) -> float:
    hyp = hypothesis.split()
    precisions, recalls = [], []
    for ref in references:
        r = ref.split()
        lcs = lcs_length(r, hyp)
        precisions.append(lcs / len(hyp) if hyp else 0.0)
        recalls.append(lcs / len(r) if r else 0.0)
    p, r = max(precisions), max(recalls)
    if p != 0 and r != 0:
        return ((1 + BETA**2) * p * r) / (r + BETA**2 * p)
    return 0.0


class Rouge:
    def compute_score(self, gts: Dict, res: Dict) -> Tuple[float, np.ndarray]:
        assert sorted(gts.keys()) == sorted(res.keys())
        scores = [
            rouge_l_single(res[i][0], gts[i]) for i in sorted(gts.keys())
        ]
        return float(np.mean(scores)), np.array(scores)

    def method(self) -> str:
        return "Rouge"
