from .layers import (
    Conv,
    conv_kernel_init,
    dropout,
    fc_kernel_init,
    max_pool2d,
    regularization_loss,
)

__all__ = [
    "Conv",
    "conv_kernel_init",
    "dropout",
    "fc_kernel_init",
    "max_pool2d",
    "regularization_loss",
]
