from .beam_search import (
    BeamResult,
    SlotCarry,
    beam_search,
    beam_search_jit,
    decode_multi_step,
    decode_step,
    greedy_decode,
    harvest_slots,
    init_slot_pool,
    init_slots,
    retire_slots,
)

__all__ = [
    "BeamResult",
    "SlotCarry",
    "beam_search",
    "beam_search_jit",
    "decode_multi_step",
    "decode_step",
    "greedy_decode",
    "harvest_slots",
    "init_slot_pool",
    "init_slots",
    "retire_slots",
]
