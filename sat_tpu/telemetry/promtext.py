"""Prometheus text exposition (format 0.0.4) over the telemetry recorder.

Everything rendered here is already host-side — counters, gauges, and
span aggregates live in the recorder's rings, and the train-side extras
come from the heartbeat payload that is computed anyway.  Exposition is
therefore a pure read: zero device syncs, zero jax imports, no new
state.  Three metric families cover the whole recorder without a
registration step (new counters/gauges appear in the scrape the moment
the code counts them):

* ``sat_counter_total{name="serve/completed"}`` — monotonic counters;
* ``sat_gauge{name="serve/queue_depth"}`` — last-set gauges, plus any
  numeric scalars from an ``extra`` mapping (heartbeat payload fields
  like ``steps_per_s`` ride in through here);
* ``sat_span_seconds_count`` / ``sat_span_seconds_sum`` — per-span
  summary pairs from :meth:`Telemetry.aggregates`, so Prometheus can
  rate() a phase's time share the standard way.

Callers may additionally request true histogram families (cumulative
``_bucket{le=…}`` / ``_sum`` / ``_count`` exposition) for chosen spans
via ``render(histograms=…)`` — bucket counts come from the recorder's
retained sample window (the same source as the /stats percentiles), so
``histogram_quantile()`` works server-side without the service choosing
quantiles for you.

:class:`MetricsListener` is the training-side carrier: a stdlib
threading HTTP server exposing ``GET /metrics`` (this format) and
``GET /healthz`` (the heartbeat JSON) read-only — the caption server
serves the same render from its own handler instead.
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

CONTENT_TYPE = "text/plain; version=0.0.4"

# A histogram request: span name to sample, upper bounds (in OUTPUT
# units, ascending; +Inf is implicit), and the factor converting the
# recorder's raw int64 slot values into output units (1e-9 for ns→s;
# 1.0 for spans that store raw counts, e.g. steps-per-dispatch).
HistogramSpec = Tuple[str, Sequence[float], float]

_ESCAPES = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _escape_label(value: str) -> str:
    return "".join(_ESCAPES.get(ch, ch) for ch in value)


def _fmt(value) -> str:
    # Prometheus wants plain decimal or scientific notation; repr of a
    # python int/float satisfies that, but bools must narrow to 0/1.
    if isinstance(value, bool):
        return "1" if value else "0"
    return repr(value) if isinstance(value, float) else str(int(value))


def _histogram_lines(
    tel, family: str, spec: HistogramSpec, lines: List[str]
) -> None:
    """Append one cumulative-bucket histogram family computed from the
    span's retained sample window.  Bucket counts are le-cumulative per
    the exposition format; ``_sum``/``_count`` cover the same window so
    ``histogram_quantile()`` is internally consistent."""
    span, bounds, scale = spec
    values = tel.durations_ns(span).astype(np.float64) * scale
    lines.append(f"# HELP {family} sampled window of span {span}")
    lines.append(f"# TYPE {family} histogram")
    sorted_values = np.sort(values)
    for le in bounds:
        n = int(np.searchsorted(sorted_values, float(le), side="right"))  # sync-ok: host telemetry ring
        lines.append(f'{family}_bucket{{le="{_fmt(float(le))}"}} {n}')  # sync-ok: host scalar
    lines.append(f'{family}_bucket{{le="+Inf"}} {values.size}')
    lines.append(f"{family}_sum {_fmt(round(float(values.sum()), 9))}")  # sync-ok: host telemetry ring
    lines.append(f"{family}_count {values.size}")


def render(
    tel,
    extra: Optional[Mapping[str, object]] = None,
    histograms: Optional[Mapping[str, HistogramSpec]] = None,
) -> str:
    """The exposition document for ``tel``'s current state.

    ``extra`` merges additional numeric scalars into the gauge family
    (non-numeric values are skipped, not errors — callers hand whole
    heartbeat payloads over without filtering).  ``histograms`` maps
    family names to :data:`HistogramSpec` requests; each renders a true
    cumulative-bucket histogram alongside the three standing families."""
    lines: List[str] = []

    counters = tel.counters()
    lines.append("# HELP sat_counter_total sat_tpu telemetry counters")
    lines.append("# TYPE sat_counter_total counter")
    for name in sorted(counters):
        lines.append(
            f'sat_counter_total{{name="{_escape_label(name)}"}} '
            f"{_fmt(counters[name])}"
        )

    gauges: Dict[str, object] = dict(tel.gauges())
    if extra:
        for key, value in extra.items():
            if isinstance(value, (int, float)) and key not in gauges:
                gauges[key] = value
    lines.append("# HELP sat_gauge sat_tpu telemetry gauges")
    lines.append("# TYPE sat_gauge gauge")
    for name in sorted(gauges):
        value = gauges[name]
        if isinstance(value, (int, float)):
            lines.append(
                f'sat_gauge{{name="{_escape_label(name)}"}} {_fmt(value)}'
            )

    aggregates = tel.aggregates()
    lines.append(
        "# HELP sat_span_seconds host span durations (summary: count+sum)"
    )
    lines.append("# TYPE sat_span_seconds summary")
    for name in sorted(aggregates):
        count, total_ns, _ = aggregates[name]
        label = _escape_label(name)
        lines.append(f'sat_span_seconds_count{{span="{label}"}} {_fmt(count)}')
        lines.append(
            f'sat_span_seconds_sum{{span="{label}"}} '
            f"{_fmt(round(total_ns / 1e9, 9))}"
        )

    if histograms:
        for family in sorted(histograms):
            _histogram_lines(tel, family, histograms[family], lines)

    lines.append("# HELP sat_up exposition endpoint liveness")
    lines.append("# TYPE sat_up gauge")
    lines.append("sat_up 1")
    return "\n".join(lines) + "\n"


class MetricsListener:
    """Read-only train-side scrape endpoint riding the heartbeat payload.

    Binds ``host:port`` (port 0 picks an ephemeral one, read it back from
    :attr:`port`), serves ``GET /metrics`` and ``GET /healthz``, and
    degrades to a warning when the bind fails — an occupied port must
    never kill a training run."""

    def __init__(
        self,
        host: str,
        port: int,
        tel,
        payload_fn: Optional[Callable[[], Dict]] = None,
    ) -> None:
        self._tel = tel
        self._payload_fn = payload_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.host = host
        self.port = int(port)

    def start(self) -> bool:
        listener = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # quiet by design
                pass

            def do_GET(self) -> None:
                try:
                    if self.path.split("?", 1)[0] == "/metrics":
                        extra = None
                        if listener._payload_fn is not None:
                            extra = listener._payload_fn()
                        body = render(listener._tel, extra=extra).encode()
                        ctype = CONTENT_TYPE
                    elif self.path.split("?", 1)[0] == "/healthz":
                        payload = (
                            listener._payload_fn()
                            if listener._payload_fn is not None
                            else {}
                        )
                        body = json.dumps(payload).encode()
                        ctype = "application/json"
                    else:
                        body = b'{"error": "not found"}'
                        ctype = "application/json"
                        self.send_response(404)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

        try:
            self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        except OSError as e:
            print(
                f"sat_tpu: metrics listener bind failed "
                f"({self.host}:{self.port}): {e} — scrape endpoint disabled",
                file=sys.stderr,
                flush=True,
            )
            self._httpd = None
            return False
        self.port = self._httpd.server_address[1]
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="sat-metrics",
            daemon=True,
        )
        self._thread.start()
        return True

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
