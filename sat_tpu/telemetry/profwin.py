"""On-demand profiler windows: bounded live captures without a restart.

`ProfilerWindow` (runtime.py) captures a *preconfigured* step range —
you must know before launch what you want to see.  This module adds the
other half: a capture you can trigger against a *running* process —
``POST /profile?duration_ms=`` on the caption server, ``SIGUSR2`` on the
train loop — when the thing you want to profile is happening right now.

Safety contract, enforced here so every trigger path inherits it:

* **single capture at a time** — ``jax.profiler`` keeps global state and
  a second ``start_trace`` corrupts the first; the latch refuses
  (serve maps the refusal to HTTP 409) instead of corrupting;
* **hard duration cap** (:data:`HARD_CAP_MS`) — a fat-fingered
  ``duration_ms=9999999`` must not profile-tax a production server for
  hours; requests clamp, silently;
* **degrade-don't-raise** — a failed ``start_trace`` (no profiler build,
  bad dir) releases the latch and reports the reason; triggering a
  capture can never take the serving process down.

Captures land in ``<base_dir>/profiles/<stamp>/`` (TensorBoard- and
``scripts/profile_trace.sh``-loadable).  The module imports no jax at
module scope — jax loads lazily inside :meth:`start`, keeping the
telemetry package importable in jax-free tools.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Optional, Tuple

# no live window may exceed one minute — long captures belong to the
# preconfigured ProfilerWindow path where the operator planned for them
HARD_CAP_MS = 60_000.0
MIN_MS = 1.0

DEFAULT_WINDOW_MS = 2000.0


class ProfileLatch:
    """Single-capture-at-a-time gate over ``jax.profiler`` live traces."""

    def __init__(self, base_dir: str) -> None:
        self.base_dir = base_dir
        self._lock = threading.Lock()
        self._active_dir: Optional[str] = None
        self._timer: Optional[threading.Timer] = None
        self.captures = 0  # completed-or-started count, for stats

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._active_dir is not None

    def start(self, duration_ms: Optional[float] = None) -> Tuple[bool, str]:
        """Begin a bounded capture; returns ``(ok, path_or_reason)``.

        ``(False, reason)`` when a capture is already running (the 409
        path) or the profiler failed to start (degraded, latch released).
        The capture stops itself after the (clamped) duration."""
        if duration_ms is None:
            duration_ms = DEFAULT_WINDOW_MS
        duration_ms = min(max(duration_ms, MIN_MS), HARD_CAP_MS)
        stamp = time.strftime("%Y%m%d-%H%M%S") + f"-{int(time.time() * 1e3) % 1000:03d}"
        out_dir = os.path.join(self.base_dir, "profiles", stamp)
        with self._lock:
            if self._active_dir is not None:
                return False, "capture already in progress"
            self._active_dir = out_dir  # reserve before the slow open
        try:
            os.makedirs(out_dir, exist_ok=True)
            import jax

            jax.profiler.start_trace(out_dir)
        except Exception as e:  # degrade: release the latch, report why
            with self._lock:
                self._active_dir = None
            reason = f"profiler start failed: {e}"
            print(f"sat_tpu: {reason}", file=sys.stderr, flush=True)
            return False, reason
        self.captures += 1
        timer = threading.Timer(duration_ms / 1e3, self._finish)
        timer.daemon = True
        with self._lock:
            self._timer = timer
        timer.start()
        return True, out_dir

    def _finish(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            print(
                f"sat_tpu: profiler stop failed: {e}",
                file=sys.stderr,
                flush=True,
            )
        finally:
            with self._lock:
                self._active_dir = None
                self._timer = None

    def stop_now(self) -> None:
        """End an active capture early (shutdown path); no-op when idle."""
        with self._lock:
            timer = self._timer
            active = self._active_dir is not None
        if timer is not None:
            timer.cancel()
        if active:
            self._finish()


class SignalTrigger:
    """A latched flag set by a POSIX signal, drained at a safe boundary.

    The train loop installs this on ``SIGUSR2`` and polls :meth:`pop` at
    the ``log_every`` boundary — signals are async, profiler starts are
    not, so the handler only sets a flag.  Installation degrades (warns,
    stays un-installed) off the main thread or on platforms without the
    signal, matching the rest of the observability stack."""

    def __init__(self) -> None:
        self._flag = threading.Event()
        self.installed = False

    def install(self, signum: int) -> bool:
        import signal as _signal

        try:
            _signal.signal(signum, lambda *_args: self._flag.set())
            self.installed = True
        except (ValueError, OSError, AttributeError) as e:
            # ValueError: not the main thread; others: platform quirks
            print(
                f"sat_tpu: profiler signal trigger unavailable: {e}",
                file=sys.stderr,
                flush=True,
            )
        return self.installed

    def fire(self) -> None:
        """Set the flag directly (tests; same path the handler takes)."""
        self._flag.set()

    def pop(self) -> bool:
        """True once per firing: clears and returns the latched flag."""
        if self._flag.is_set():
            self._flag.clear()
            return True
        return False
