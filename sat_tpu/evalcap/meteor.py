"""METEOR 1.5 — native reimplementation (no JVM).

The reference wraps the external ``meteor-1.5.jar`` as a persistent Java
subprocess speaking a line protocol
(/root/reference/utils/coco/pycocoevalcap/meteor/meteor.py:15-58); the jar
itself is not even shipped (.MISSING_LARGE_BLOBS).  This module implements
METEOR 1.5 semantics (Denkowski & Lavie 2014, "Meteor Universal") directly
in Python with a C++-accelerated twin (see native/):

* stage-wise alignment with the full 1.5 English matcher stages and
  weights — exact 1.0, Porter-stem 0.6, synonym 0.8, paraphrase phrase
  spans 0.6 — each word stage pairing each unmatched hypothesis word
  with its nearest unmatched reference occurrence, and the paraphrase
  stage aligning table phrase spans longest-first (a chunk-minimizing
  greedy stand-in for the jar's beam aligner);
* the 1.5 scoring with the English rank-tuned parameters α=0.85, β=0.2,
  γ=0.6, δ=0.75: content/function-word-discounted weighted precision and
  recall, Fmean = P·R/(α·P+(1−α)·R), fragmentation penalty
  γ·(chunks/matches)^β applied only when the alignment has more than one
  chunk (so an exact hypothesis scores exactly 1.0, matching the jar's
  behavior on identical inputs);
* multi-reference: score against every reference, keep the max (jar
  behavior).

Known divergences from the jar, quantified in tests/test_evalcap.py:
* the synonym and paraphrase stages use the compact bundled tables in
  meteor_data.py instead of full WordNet / the ~80MB pivoting-derived
  paraphrase table (both unavailable offline; the reference never
  shipped them either — its jar is a missing large blob), and the
  function-word list is curated rather than frequency-derived.  Pairs
  outside those tables fall back to exact/stem matching, which biases
  those segments LOW relative to the jar; but curated entries the jar's
  pivot-derived table happens to lack (e.g. 'lake'~'pond') award credit
  the jar would not, so individual segments can also bias HIGH — the
  divergence is bounded, not one-sided.  Measured bound
  (tests/test_evalcap.py::TestMeteorGoldenFixtures): the tables move a
  single segment by at most ≈0.69 (a short all-synonym-linked segment),
  and the mean of a deliberately stage-exercising 12-pair corpus by
  ≈0.29; real caption corpora sit far below both since most matches are
  exact/stem.  The scoring formula itself is pinned to the published
  METEOR 1.5 equations by hand-derived golden fixtures in that same
  test class, on both backends.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .meteor_data import (
    FUNCTION_WORDS,
    MAX_PARAPHRASE_LEN,
    build_paraphrase_index,
    build_synonym_index,
)

# METEOR 1.5 English (rank-tuned) parameters — Denkowski & Lavie 2014,
# Table 1 (the jar's `-l en` defaults, reference meteor.py:18-19).
ALPHA = 0.85
BETA = 0.2
GAMMA = 0.6
DELTA = 0.75

EXACT_WEIGHT = 1.0
STEM_WEIGHT = 0.6
SYNONYM_WEIGHT = 0.8
PARAPHRASE_WEIGHT = 0.6

_stemmer = None
_syn_index: Optional[Dict[str, Set[int]]] = None
_para_index: Optional[Dict[str, Set[int]]] = None


from functools import lru_cache


@lru_cache(maxsize=65536)  # corpora re-stem the same caption vocabulary
def _stem(word: str) -> str:
    global _stemmer
    if _stemmer is None:
        try:
            from nltk.stem.porter import PorterStemmer

            # ORIGINAL_ALGORITHM: bit-for-bit the published Porter (1980)
            # steps, which is what the C++ aligner implements — keeps the
            # native and Python scorers in exact agreement.
            _stemmer = PorterStemmer(mode="ORIGINAL_ALGORITHM")
        except Exception:  # pragma: no cover - nltk is baked into the image
            _stemmer = False
    if _stemmer:
        return _stemmer.stem(word)
    return word


def _synonyms() -> Dict[str, Set[int]]:
    global _syn_index
    if _syn_index is None:
        _syn_index = build_synonym_index()
    return _syn_index


def _paraphrases() -> Dict[str, Set[int]]:
    global _para_index
    if _para_index is None:
        _para_index = build_paraphrase_index()
    return _para_index


def align(
    hyp: Sequence[str], ref: Sequence[str]
) -> Tuple[List[Tuple[int, int, float]], Dict[int, float], Dict[int, float]]:
    """Stage-wise greedy alignment.

    Returns ``(pairs, hyp_matched, ref_matched)``: ``pairs`` are
    (hyp_idx, ref_idx, weight) word pairings used for chunk counting;
    the two dicts map matched word index → match weight per side (they
    diverge from the pair list only for paraphrase span matches, whose
    sides may cover different word counts).

    Within each stage, candidate pairs are matched in an order that favors
    monotone (chunk-minimizing) pairings: for each hypothesis word the
    nearest unmatched reference occurrence is taken.
    """
    matches: List[Tuple[int, int, float]] = []
    hyp_matched: Dict[int, float] = {}
    ref_matched: Dict[int, float] = {}
    hyp_used = [False] * len(hyp)
    ref_used = [False] * len(ref)

    def run_key_stage(key_fn, weight):
        ref_slots: Dict[str, List[int]] = {}
        for j, w in enumerate(ref):
            if not ref_used[j]:
                ref_slots.setdefault(key_fn(w), []).append(j)
        for i, w in enumerate(hyp):
            if hyp_used[i]:
                continue
            slots = ref_slots.get(key_fn(w))
            if not slots:
                continue
            # nearest remaining occurrence to position i
            j = min(slots, key=lambda j: abs(j - i))
            slots.remove(j)
            hyp_used[i], ref_used[j] = True, True
            matches.append((i, j, weight))
            hyp_matched[i] = weight
            ref_matched[j] = weight

    run_key_stage(lambda w: w, EXACT_WEIGHT)
    run_key_stage(_stem, STEM_WEIGHT)

    # synonym stage: pairwise group-intersection test (not a single key)
    syn = _synonyms()
    for i, w in enumerate(hyp):
        if hyp_used[i]:
            continue
        gids = syn.get(w)
        if not gids:
            continue
        best_j = -1
        for j, r in enumerate(ref):
            if ref_used[j]:
                continue
            rgids = syn.get(r)
            if rgids and (gids & rgids):
                if best_j < 0 or abs(j - i) < abs(best_j - i):
                    best_j = j
        if best_j >= 0:
            hyp_used[i], ref_used[best_j] = True, True
            matches.append((i, best_j, SYNONYM_WEIGHT))
            hyp_matched[i] = SYNONYM_WEIGHT
            ref_matched[best_j] = SYNONYM_WEIGHT

    # paraphrase stage (the jar's final match stage, weight 0.6): phrase
    # spans from the table are aligned span-to-span.  Longest hypothesis
    # span first (maximal matches), leftmost first within a length; the
    # reference candidate is the nearest unmatched span sharing a group,
    # longer spans preferred on distance ties.
    para = _paraphrases()
    for L in range(MAX_PARAPHRASE_LEN, 0, -1):
        for i in range(0, len(hyp) - L + 1):
            if any(hyp_used[i:i + L]):
                continue
            gids = para.get(" ".join(hyp[i:i + L]))
            if not gids:
                continue
            best = None  # (distance, start, length)
            for M in range(MAX_PARAPHRASE_LEN, 0, -1):
                for j in range(0, len(ref) - M + 1):
                    if any(ref_used[j:j + M]):
                        continue
                    rgids = para.get(" ".join(ref[j:j + M]))
                    if rgids and (gids & rgids):
                        d = abs(j - i)
                        if best is None or d < best[0]:
                            best = (d, j, M)
            if best is None:
                continue
            _, j, M = best
            for k in range(L):
                hyp_used[i + k] = True
                hyp_matched[i + k] = PARAPHRASE_WEIGHT
            for k in range(M):
                ref_used[j + k] = True
                ref_matched[j + k] = PARAPHRASE_WEIGHT
            # chunk accounting: the span pair is internally monotone, so
            # it contributes one run of zipped word pairs
            for k in range(min(L, M)):
                matches.append((i + k, j + k, PARAPHRASE_WEIGHT))

    return sorted(matches), hyp_matched, ref_matched


def _chunks(matches: List[Tuple[int, int, float]]) -> int:
    """Number of maximal runs adjacent in both hyp and ref order."""
    if not matches:
        return 0
    chunks = 1
    for (i0, j0, _), (i1, j1, _) in zip(matches, matches[1:]):
        if not (i1 == i0 + 1 and j1 == j0 + 1):
            chunks += 1
    return chunks


def _weighted_split(
    words: Sequence[str], matched: Dict[int, float]
) -> Tuple[float, float]:
    """(Σ w over matched content words, Σ w over matched function words)."""
    wc = wf = 0.0
    for idx, w in matched.items():
        if words[idx] in FUNCTION_WORDS:
            wf += w
        else:
            wc += w
    return wc, wf


def _side_score(words: Sequence[str], matched: Dict[int, float]) -> float:
    """δ-discounted weighted match fraction for one side (P or R)."""
    n_f = sum(1 for w in words if w in FUNCTION_WORDS)
    n_c = len(words) - n_f
    denom = DELTA * n_c + (1.0 - DELTA) * n_f
    if denom == 0:
        return 0.0
    wc, wf = _weighted_split(words, matched)
    return (DELTA * wc + (1.0 - DELTA) * wf) / denom


def segment_stats(hypothesis: str, reference: str) -> Dict[str, float]:
    hyp, ref = hypothesis.split(), reference.split()
    pairs, hyp_matched, ref_matched = align(hyp, ref)
    # m for the fragmentation penalty: average matched-word count over the
    # two sides (METEOR 1.5; equals len(pairs) for word-level stages, and
    # generalizes to paraphrase spans covering unequal word counts)
    m = (len(hyp_matched) + len(ref_matched)) / 2.0
    return {
        "matches": m,
        "chunks": float(_chunks(pairs)),
        "p": _side_score(hyp, hyp_matched),
        "r": _side_score(ref, ref_matched),
        "len_h": float(len(hyp)),
        "len_r": float(len(ref)),
    }


def score_from_stats(s: Dict[str, float]) -> float:
    if s["matches"] == 0 or s["len_h"] == 0 or s["len_r"] == 0:
        return 0.0
    p, r = s["p"], s["r"]
    if p == 0 or r == 0:
        return 0.0
    fmean = (p * r) / (ALPHA * p + (1 - ALPHA) * r)
    # single-chunk alignments carry no fragmentation penalty (jar
    # behavior: identical sentences score exactly 1.0)
    if s["chunks"] <= 1:
        return fmean
    penalty = GAMMA * ((s["chunks"] / s["matches"]) ** BETA)
    return fmean * (1.0 - penalty)


def meteor_single(hypothesis: str, references: List[str]) -> float:
    from .. import native

    # The C++ scorer is ASCII/lowercase (like its Porter stage); anything
    # else goes through the Python twin so backends always agree.
    ascii_ok = hypothesis.isascii() and all(r.isascii() for r in references)
    if ascii_ok and native.available():
        return native.meteor_multi(hypothesis, list(references))
    return max(score_from_stats(segment_stats(hypothesis, r)) for r in references)


class Meteor:
    def compute_score(self, gts: Dict, res: Dict) -> Tuple[float, np.ndarray]:
        assert sorted(gts.keys()) == sorted(res.keys())
        scores = [meteor_single(res[i][0], gts[i]) for i in sorted(gts.keys())]
        return float(np.mean(scores)), np.array(scores)

    def method(self) -> str:
        return "METEOR"
