#!/bin/bash
# Re-run the measurement stages whose artifacts are missing or contain an
# "error" line, polling the tunneled device between attempts.  The tunnel
# has been observed dropping for minutes-to-hours mid-session
# (VERDICT r02 §missing #1, PERF.md dispatch caveat); tpu_session.sh
# bounds each stage with a timeout so a dead tunnel costs one budget per
# stage — this script is the complement: it waits for the device to come
# BACK and then re-runs only what is still unmeasured, cheapest first.
#
# Usage: bash scripts/tpu_retry.sh [outdir] [poll_seconds] [max_wait_s]
set -u
OUT=${1:-/root/repo/runs/tpu_session_r3}
POLL=${2:-120}
MAX_WAIT=${3:-14400}
# Deterministic failures (an OOM, a compile crash) must not be re-run
# until the deadline — they are indistinguishable from tunnel outages
# only if nobody counts.  A stage that fails this many times WITH the
# probe succeeding around it is dropped as given-up.
MAX_ATTEMPTS=${MAX_ATTEMPTS:-3}
cd "$(dirname "$0")/.."
mkdir -p "$OUT"
declare -A ATTEMPTS
GAVE_UP=""

# RETRY_STAGES / RETRY_STAGE_CMD / RETRY_PROBE_CMD exist so the
# give-up/artifact bookkeeping is testable without a device
# (tests/test_bench.py); production runs never set them.
ORDER=${RETRY_STAGES:-"bench_rng_threefry bench_remat_decoder bench_remat_cnn_joint bench_resnet50 bench_B256 bench_ce_bf16 bench_eval_ab fused_decode bench_quant fleet_serve bench_bulk lifecycle_serve tenant_serve metering_serve quality_serve cache_serve pallas pallas_serve profile bench_early_exit"}

stage_cmd() {
  if [ -n "${RETRY_STAGE_CMD:-}" ]; then echo "$RETRY_STAGE_CMD"; return; fi
  case "$1" in
    bench_rng_threefry)   echo "env BENCH_RNG_IMPL=threefry2x32 BENCH_EVAL=0 BENCH_SWEEP=0 BENCH_WATCHDOG_S=420 timeout 440 python bench.py" ;;
    bench_remat_decoder)  echo "env BENCH_REMAT=1 BENCH_EVAL=0 BENCH_SWEEP=0 BENCH_WATCHDOG_S=420 timeout 440 python bench.py" ;;
    bench_remat_cnn_joint) echo "env BENCH_TRAIN_CNN=1 BENCH_REMAT_CNN=1 BENCH_EVAL=0 BENCH_SWEEP=0 BENCH_WATCHDOG_S=420 timeout 440 python bench.py" ;;
    bench_resnet50)       echo "env BENCH_CNN=resnet50 BENCH_EVAL=0 BENCH_SWEEP=0 BENCH_WATCHDOG_S=420 timeout 440 python bench.py" ;;
    bench_B256)           echo "env BENCH_BATCH=256 BENCH_EVAL=0 BENCH_SWEEP=0 BENCH_WATCHDOG_S=420 timeout 440 python bench.py" ;;
    bench_ce_bf16)        echo "env BENCH_CE_DTYPE=bfloat16 BENCH_BATCH=128 BENCH_EVAL=0 BENCH_SWEEP=0 BENCH_WATCHDOG_S=420 timeout 440 python bench.py" ;;
    # outer timeout > sum of internal budgets: 6 arms (3 repeats x 2) x 420
    bench_eval_ab)        echo "timeout 2600 python scripts/bench_eval_ab.py --budget-s 420" ;;
    # int8 encoder A/B on both decode paths: eval throughput then the
    # serve closed loop (which boots a second engine — hence ~2x the
    # bench_serve budget); both write JSONL rows to the one artifact
    bench_quant)          echo "timeout 2000 bash -c 'python scripts/bench_eval.py --batch 32 --encoder-quant int8 && python scripts/bench_serve.py --quant-ab int8'" ;;
    # fused-decode K lanes on the real chip: bitwise parity vs stepped
    # K=1, on-device early exit, ladder AOT warmup with zero recompiles
    fused_decode)         echo "timeout 600 python -m pytest tests/test_continuous.py -q -k 'fused or multi_step or adaptive'" ;;
    # replica subprocess boots + 3 open-loop arms through the router,
    # then a second 2-replica encode/decode tiered fleet (disagg arm)
    fleet_serve)          echo "timeout 1500 python scripts/bench_serve.py --fleet" ;;
    # three CLI child runs (seed checkpoint, decode, resume)
    bench_bulk)           echo "timeout 900 python scripts/bench_bulk.py" ;;
    # full reload -> canary -> promote cycle under open-loop load
    lifecycle_serve)      echo "timeout 900 python scripts/bench_serve.py --lifecycle" ;;
    # victim/peer/flood registry on one continuous server: isolation
    # ratio + DRR fair-share window
    tenant_serve)         echo "timeout 900 python scripts/bench_serve.py --tenants" ;;
    # charge-path microbench + unique/Zipf probe arms: attribution
    # overhead gate, accounting identity, would-be encode-cache ratio
    metering_serve)       echo "timeout 900 python scripts/bench_serve.py --metering" ;;
    # quality-on live arm + signal/sketch microbench: drift-plane
    # overhead gate (0.5% of serve p50), zero steady-state recompiles
    quality_serve)        echo "timeout 900 python scripts/bench_quality.py" ;;
    # content-addressed encode cache: bitwise cold/hot parity, then
    # unique vs Zipf open-loop arms (ratio floor 0.6, zero recompiles)
    cache_serve)          echo "timeout 900 python scripts/bench_serve.py --encode-cache" ;;
    # batch sweep (4 sizes x up-to-4 loop compiles each) needs more than
    # the single-B budget
    pallas)               echo "timeout 1800 python scripts/bench_pallas.py" ;;
    # fused attention on slot-pool geometries (masked rows, odd batches)
    # compiled on the real chip — the CPU container can only
    # interpret-mode these kernels, so parity there proves nothing about
    # the Mosaic lowering
    pallas_serve)         echo "timeout 600 python -m pytest tests/test_continuous.py tests/test_pallas.py -q -k pallas" ;;
    profile)              echo "timeout 900 bash scripts/profile_trace.sh $OUT" ;;
    # outer timeout > sum of the script's internal budgets (300+700+2*400)
    bench_early_exit)     echo "timeout 1900 bash scripts/bench_early_exit.sh $OUT" ;;
    # subshell so the exit fails the STAGE, not the retry loop itself
    *) echo "( echo \"unknown stage: $1\" >&2; exit 64 )" ;;
  esac
}

artifact() {
  case "$1" in
    pallas)  echo "$OUT/pallas.txt" ;;
    pallas_serve) echo "$OUT/pallas_serve.txt" ;;
    fused_decode) echo "$OUT/fused_decode.txt" ;;
    profile) echo "$OUT/profile_done.txt" ;;
    *)       echo "$OUT/$1.json" ;;
  esac
}

needed() {  # artifact missing, empty, or an error line at the tail
  local f; f=$(artifact "$1")
  [ -s "$f" ] || return 0
  tail -1 "$f" | grep -q '"error"' && return 0
  return 1
}

probe_ok() {
  eval "${RETRY_PROBE_CMD:-timeout 150 python bench.py --probe}" >/dev/null 2>&1
}

deadline=$(( $(date +%s) + MAX_WAIT ))
while :; do
  pending=""
  for s in $ORDER; do
    needed "$s" || continue
    if [ "${ATTEMPTS[$s]:-0}" -ge "$MAX_ATTEMPTS" ]; then
      case " $GAVE_UP " in *" $s "*) ;; *)
        echo "stage $s failed $MAX_ATTEMPTS times with the device up — giving up on it"
        GAVE_UP="$GAVE_UP $s";;
      esac
      continue
    fi
    pending="$pending $s"
  done
  if [ -z "$pending" ]; then
    if [ -n "$GAVE_UP" ]; then
      echo "done; gave up on:$GAVE_UP — see their logs in $OUT"; exit 1
    fi
    echo "all stages measured; nothing to do"; exit 0
  fi
  [ "$(date +%s)" -ge "$deadline" ] && { echo "deadline reached; still pending:$pending"; exit 1; }

  if probe_ok; then
    for s in $pending; do
      ATTEMPTS[$s]=$(( ${ATTEMPTS[$s]:-0} + 1 ))
      echo "=== retrying $s (attempt ${ATTEMPTS[$s]}/$MAX_ATTEMPTS) ==="
      # stdout goes to a temp file first: a failed stage's error text must
      # not land in the artifact slot, where needed() would mistake it for
      # a measurement on the next pass.  Logs append, one header per
      # attempt — earlier failures are evidence, not scratch space.
      f=$(artifact "$s")
      echo "--- attempt ${ATTEMPTS[$s]} $(date -u +%FT%TZ) ---" >>"$OUT/$s.log"
      eval "$(stage_cmd "$s")" >"$f.tmp" 2>>"$OUT/$s.log"
      rc=$?
      if [ "$rc" -eq 0 ]; then
        mv "$f.tmp" "$f"
      else
        cat "$f.tmp" >>"$OUT/$s.log"; rm -f "$f.tmp"
      fi
      if [ "$rc" -ne 0 ] || needed "$s"; then
        echo "stage $s still failing (rc=$rc); re-probing before next stage"
        # an outage mid-stage shouldn't count against the attempt cap
        probe_ok && : || { ATTEMPTS[$s]=$(( ${ATTEMPTS[$s]} - 1 )); break; }
      else
        echo "stage $s landed: $(tail -1 "$f")"
      fi
    done
  else
    echo "$(date -u +%H:%M:%S) device unreachable; sleeping ${POLL}s"
  fi
  sleep "$POLL"
done
