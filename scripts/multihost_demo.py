"""Two-process distributed train + eval demo/proof on CPU.

Launches N real OS processes that bootstrap a jax.distributed cluster over
a loopback coordinator (the TPU-native replacement for the reference's
tf.train.Server/ClusterSpec plumbing, /root/reference/clusterone_config.py:
106-124), build a (N,1) device mesh spanning the processes, train the
captioner with per-host data sharding + XLA-inserted gradient all-reduce,
checkpoint from the sharded state, and run multi-host mesh-parallel
beam-search eval with cross-host result gather.

Run: python scripts/multihost_demo.py [--procs 2]
Exit 0 = multi-host train + eval completed and all hosts agreed.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
repo, pid, nprocs, port, root = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]), sys.argv[5]
)
sys.path.insert(0, repo)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
# share the repo's persistent compile cache across workers/reruns
from sat_tpu.utils.compile_cache import enable as _enable_cache
_enable_cache(jax, name=".jax_cache", root=repo, min_compile_time_secs=0.5)

from sat_tpu.parallel import initialize_distributed
initialize_distributed(
    coordinator_address="127.0.0.1:%d" % port, num_processes=nprocs, process_id=pid
)
assert jax.process_count() == nprocs, jax.process_count()

from sat_tpu.config import Config
config = Config.load(os.path.join(root, "config.json")).replace(
    summary_dir=os.path.join(root, "summary_p%d" % pid),
)

from sat_tpu import runtime
state = runtime.train(config)
print("[p%d] trained to step %d" % (pid, int(jax.device_get(state.step))), flush=True)

if config.fleet_telemetry:
    # every process's FleetPlane.finish() (train teardown) wrote its
    # terminal sidecar; barrier so ALL of them are on disk, then process
    # 0 runs the authoritative file-based merge the demo asserts on.
    # The barrier is file-based like the fleet plane itself: XLA's CPU
    # backend cannot run the multiprocess collective sync_processes uses.
    import time as _time
    open(os.path.join(config.fleet_dir, "done_p%d" % pid), "w").close()
    if pid == 0:
        deadline = _time.time() + 120
        while _time.time() < deadline:
            done = [
                os.path.exists(os.path.join(config.fleet_dir, "done_p%d" % p))
                for p in range(nprocs)
            ]
            if all(done):
                break
            _time.sleep(0.2)
        else:
            raise SystemExit("fleet barrier timed out: %s" % done)
        from sat_tpu.telemetry import fleet as fleet_mod
        doc = fleet_mod.aggregate_directory(
            config.fleet_dir, config.straggler_factor
        )
        s = (doc or {}).get("straggler", {})
        print(
            "[p0] fleet final: hosts=%s straggler=%s p%s skew=%s" % (
                (doc or {}).get("hosts_reporting"),
                s.get("verdict"), s.get("process_index"), s.get("skew"),
            ),
            flush=True,
        )

if tuple(config.mesh_shape)[1] > 1 and config.context_parallel == 1:
    # vocab-TP mode: the banner must not be earnable with silently
    # replicated params (the placement rule no-ops when vocabulary_size
    # isn't divisible by the model axis) — demand a leaf actually sharded
    # over 'model'
    import jax.tree_util as jtu
    on_model = any(
        "model" in str(getattr(l.sharding, "spec", ""))
        for l in jtu.tree_leaves(state.params)
    )
    assert on_model, "TP mode but no param leaf is sharded over 'model'"
    print("[p%d] TP verified: params sharded over 'model'" % pid, flush=True)

scores = runtime.evaluate(config, state=state)
with open(os.path.join(root, "scores_p%d.json" % pid), "w") as f:
    json.dump(scores, f)
print("[p%d] eval done" % pid, flush=True)
"""

# single-process control for the loss-parity check: same config/seed on a
# (1,1) mesh.  The shard views feed the identical global batch stream
# (parallel/data.py _ProcessShardView), so the multi-process trajectory
# must track this one.
CONTROL = r"""
import os, sys
repo, root = sys.argv[1], sys.argv[2]
sys.path.insert(0, repo)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from sat_tpu.utils.compile_cache import enable as _enable_cache
_enable_cache(jax, name=".jax_cache", root=repo, min_compile_time_secs=0.5)

from sat_tpu.config import Config
config = Config.load(os.path.join(root, "config.json")).replace(
    mesh_shape=(1, 1), context_parallel=1,
    summary_dir=os.path.join(root, "summary_control"),
    save_dir=os.path.join(root, "save_control"),
)
from sat_tpu import runtime
runtime.train(config)
print("[control] trained", flush=True)
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--port", type=int, default=12765)
    ap.add_argument("--root", default="/tmp/sat_tpu_multihost_demo")
    ap.add_argument(
        "--join-timeout", type=float, default=900.0,
        help="seconds to wait for the workers before declaring failure",
    )
    ap.add_argument(
        "--cp", action="store_true",
        help="context-parallel mode: mesh (1, procs) with the attention "
        "grid sharded ACROSS the processes (distributed-softmax psums over "
        "the loopback DCN) for both training and beam-search decode; every "
        "host feeds identical full batches (mesh_data_shard)",
    )
    ap.add_argument(
        "--tp", action="store_true",
        help="vocab tensor-parallel mode: mesh (1, procs) with the "
        "embedding table and softmax projection sharded ACROSS the "
        "processes (GSPMD inserts the cross-host collectives); every host "
        "feeds identical full batches",
    )
    ap.add_argument(
        "--mesh", default=None, metavar="D,M",
        help="explicit (data, model) mesh over D*M single-device "
        "processes — e.g. --mesh 2,2 --cp runs dp×CP combined: each data "
        "row spans TWO model-axis processes feeding identical row blocks "
        "while TWO data shards feed different ones (the first layout "
        "where both mesh_data_shard axes are nontrivial)",
    )
    ap.add_argument(
        "--fleet", action="store_true",
        help="fleet telemetry mode: enable the cross-host fleet plane "
        "with a shared fleet_dir, inject SAT_FI_SLOW_STEP_MS into worker "
        "0 only, and assert the merged fleet.json reports every host and "
        "names worker 0 as the straggler",
    )
    ap.add_argument(
        "--slow-ms", type=int, default=75,
        help="host-side stall injected per step into worker 0 under "
        "--fleet",
    )
    ap.add_argument(
        "--check-loss-parity", action="store_true",
        help="also train a single-process (1,1) control on the same "
        "config/seed and assert the multi-process loss trajectory matches "
        "it (the shard views feed the identical global batch stream)",
    )
    args = ap.parse_args()
    if args.cp and args.tp:
        ap.error("--cp and --tp are mutually exclusive (one model axis)")
    if args.mesh:
        dp, mp = (int(x) for x in args.mesh.split(","))
        if (args.cp or args.tp) and mp < 2:
            ap.error("--cp/--tp need a model axis >= 2")
        if mp > 1 and not (args.cp or args.tp):
            # a bare model axis would silently run implicit vocab-TP
            # while the banner (and the TP-verified aggregation check,
            # keyed on --tp) reported data-parallel — make the placement
            # explicit instead
            ap.error("--mesh with a model axis > 1 requires --cp or --tp")
        args.procs = dp * mp
        mesh_shape = (dp, mp)
    else:
        mesh_shape = (
            (1, args.procs) if (args.cp or args.tp) else (args.procs, 1)
        )

    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "tests"))
    os.makedirs(args.root, exist_ok=True)

    from fixtures import make_coco_fixture

    fx = make_coco_fixture(args.root)
    config = fx["config"].replace(
        image_size=32, dim_embedding=16, num_lstm_units=16,
        dim_initialize_layer=16, dim_attend_layer=16, dim_decode_layer=32,
        compute_dtype="float32", num_epochs=1, save_period=0, log_every=1,
        mesh_shape=mesh_shape,
        context_parallel=mesh_shape[1] if args.cp else 1,
        batch_size=4, beam_size=2,
        num_data_workers=2, max_eval_ann_num=8,
        # beam-0 alphas ride the cross-host gather; every host renders its
        # interleaved slice of the panels (runtime._local_render_rows)
        save_attention_maps=True,
    )
    if args.fleet:
        # Straggler visibility needs the hosts DESYNCHRONIZED between log
        # boundaries: with log_every=1 the boundary's device_get makes
        # every host wait out the slow one's all-reduce each step and the
        # host-side step times equalize (lockstep).  A sparse boundary
        # lets the fast workers' async dispatch run ahead, so only ~2 of
        # their 40 step spans absorb the collective wait — below the p95
        # cut — while worker 0 carries the injected stall in EVERY span.
        config = config.replace(
            telemetry=True,
            fleet_telemetry=True,
            fleet_dir=os.path.join(args.root, "fleet"),
            straggler_factor=1.5,
            num_epochs=40, max_steps=40, log_every=20,
        )
    config.save(os.path.join(args.root, "config.json"))
    # a reused --root must not inflate the final panel-coverage check
    import glob as _glob

    for f in _glob.glob(os.path.join(config.eval_result_dir, "*_attention.jpg")):
        os.remove(f)

    import re
    import threading

    # each worker must see exactly ONE local CPU device: an inherited
    # --xla_force_host_platform_device_count (e.g. from the test harness)
    # would give every process N devices and break the device↔process map
    env = dict(os.environ)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    ).strip()
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=1"
    ).strip()

    def run_workers(port):
        # fresh metric streams per attempt: SummaryWriter appends to
        # metrics.jsonl, so a retried cluster (or reused --root) would
        # otherwise stack trajectories and break the loss-parity check
        import shutil

        for name in [f"summary_p{p}" for p in range(args.procs)] + [
            "summary_control", "fleet",
        ]:
            shutil.rmtree(os.path.join(args.root, name), ignore_errors=True)

        def worker_env(p):
            # the straggler injection goes to worker 0 ONLY — a shared
            # env dict would slow the whole fleet and hide the skew
            e = dict(env)
            if args.fleet and p == 0:
                e["SAT_FI_SLOW_STEP_MS"] = str(args.slow_ms)
            return e

        procs = [
            subprocess.Popen(
                [sys.executable, "-u", "-c", WORKER,
                 REPO, str(p), str(args.procs), str(port), args.root],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=worker_env(p),
            )
            for p in range(args.procs)
        ]
        # drain every pipe concurrently: a worker blocked on a full
        # stdout pipe inside a collective would deadlock the cluster
        outputs = [""] * args.procs

        def drain(p, proc):
            out, _ = proc.communicate()
            outputs[p] = out or ""

        threads = [
            threading.Thread(target=drain, args=(p, proc), daemon=True)
            for p, proc in enumerate(procs)
        ]
        ok = True
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=args.join_timeout)
            for p, proc in enumerate(procs):
                rc = proc.returncode
                # full output to disk (postmortem), tail to the console
                with open(os.path.join(args.root, f"worker_p{p}.log"), "w") as f:
                    f.write(outputs[p])
                tail = "\n".join(outputs[p].strip().splitlines()[-6:])
                print(f"--- process {p} (rc={rc}) ---\n{tail}", flush=True)
                ok &= rc == 0
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
                    ok = False
            # the drain threads flush `outputs` only after communicate()
            # returns — join them (the kills above unblock them) so the
            # caller's failure-signature check reads complete logs
            for t in threads:
                t.join(timeout=30)
        return ok, outputs

    # Gloo (the CPU-emulation collectives backend — real TPU multi-host
    # rides ICI/DCN instead) forms each communicator inside a fixed ~30s
    # peer-connect window.  A 2D mesh's execution opens several pairwise
    # communicators concurrently, and on an oversubscribed CI host (one
    # core, N worker processes) their rendezvous interleaving sporadically
    # starves past the window.  That failure is an infrastructure flake
    # with an unmistakable signature, so the demo retries a fresh cluster
    # for it — and ONLY it; any other worker error fails immediately.
    gloo_flake = "Gloo context initialization failed"
    port = args.port
    for attempt in range(3):
        ok, outputs = run_workers(port)
        if ok:
            break
        failed_logs = "\n".join(outputs)
        if gloo_flake not in failed_logs:
            print("FAIL: a worker exited nonzero")
            return 1
        port += 1  # the old coordinator port may linger in TIME_WAIT
        print(f"gloo rendezvous flake (attempt {attempt + 1}/3); "
              f"relaunching cluster on port {port}", flush=True)
    else:
        print("FAIL: gloo rendezvous failed on every attempt")
        return 1

    if args.tp and any(
        "TP verified" not in outputs[p] for p in range(args.procs)
    ):
        print("FAIL: a worker did not verify TP sharding over 'model'")
        return 1

    scores = [
        json.load(open(os.path.join(args.root, f"scores_p{p}.json")))
        for p in range(args.procs)
    ]
    if any(s != scores[0] for s in scores[1:]):
        print("FAIL: hosts disagree on eval scores")
        return 1

    # the attention panels must cover every decoded image — each host
    # rendered only its slice (runtime._local_render_rows), so full
    # coverage proves the cross-host alpha gather AND the per-process
    # render partition worked
    import glob

    results = json.load(open(config.eval_result_file))
    panels = glob.glob(os.path.join(config.eval_result_dir, "*_attention.jpg"))
    if len(panels) != len(results):
        print(f"FAIL: {len(panels)} attention panels for {len(results)} "
              "decoded images")
        return 1
    if args.check_loss_parity:
        # control trains on ONE local device in its own process (clean
        # XLA_FLAGS), then the trajectories must agree: same global batch
        # stream + same init/dropout keys, differing only in collective
        # reduction order (which Adam amplifies over steps — hence the
        # loose trajectory band but a tight first step)
        ctl = subprocess.run(
            [sys.executable, "-u", "-c", CONTROL, REPO, args.root],
            capture_output=True, text=True, env=env, timeout=600,
        )
        if ctl.returncode != 0:
            print(f"FAIL: loss-parity control: {ctl.stdout[-1500:]}\n"
                  f"{ctl.stderr[-1000:]}")
            return 1

        def losses(summary_dir):
            rows = [
                json.loads(line)
                for line in open(os.path.join(summary_dir, "metrics.jsonl"))
            ]
            return [r["total_loss"] for r in rows]

        got = losses(os.path.join(args.root, "summary_p0"))
        want = losses(os.path.join(args.root, "summary_control"))
        if len(got) != len(want):
            print(f"FAIL: loss parity: {len(got)} vs {len(want)} steps")
            return 1
        first_rel = abs(got[0] - want[0]) / max(abs(want[0]), 1e-9)
        max_rel = max(
            abs(a - b) / max(abs(b), 1e-9) for a, b in zip(got, want)
        )
        if first_rel > 1e-3 or max_rel > 5e-2:
            print(f"FAIL: loss parity: first-step rel {first_rel:.2e} "
                  f"(>1e-3) or trajectory rel {max_rel:.2e} (>5e-2)\n"
                  f"mesh: {got}\ncontrol: {want}")
            return 1
        print(f"loss parity vs single-process control: first step rel "
              f"{first_rel:.2e}, trajectory max rel {max_rel:.2e} "
              f"over {len(got)} steps")

    if args.fleet:
        fleet_path = os.path.join(args.root, "fleet", "fleet.json")
        try:
            fleet_doc = json.load(open(fleet_path))
        except (OSError, ValueError) as e:
            print(f"FAIL: fleet.json missing/unreadable ({e})")
            return 1
        if fleet_doc.get("hosts_reporting") != args.procs:
            print(f"FAIL: fleet.json reports "
                  f"{fleet_doc.get('hosts_reporting')} hosts, expected "
                  f"{args.procs}")
            return 1
        verdict = fleet_doc.get("straggler", {})
        if not verdict.get("verdict") or verdict.get("process_index") != 0:
            print(f"FAIL: expected worker 0 named as straggler, got "
                  f"{verdict}")
            return 1
        print(f"fleet verdict: p{verdict['process_index']} "
              f"({verdict.get('host')}) is the straggler at "
              f"{verdict.get('skew')}x the fleet median "
              f"(factor {verdict.get('factor')}); "
              f"{fleet_doc['hosts_reporting']} hosts merged")

    mode = (
        "context-parallel" if args.cp
        else "tensor-parallel" if args.tp
        else "data-parallel"
    )
    if args.mesh:
        mode = f"mesh {mesh_shape[0]}x{mesh_shape[1]} {mode}"
    print(f"MULTIHOST OK ({mode}): {args.procs} processes, scores agree: "
          f"Bleu_4={scores[0]['Bleu_4']:.3f}; "
          f"{len(panels)} attention panels rendered across hosts")
    return 0


if __name__ == "__main__":
    sys.exit(main())
