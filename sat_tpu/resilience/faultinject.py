"""Deterministic fault injection for the resilience test harness.

Real TPU fleets fail in a handful of stereotyped ways — preemption mid
step, a NaN gradient poisoning the state, a torn or bit-rotted checkpoint,
a flaky network filesystem — and every one of this framework's recovery
paths (docs/RESILIENCE.md) must be provable without waiting for the fleet
to misbehave.  This module is the switchboard: each failure mode has ONE
injection point, armed by an ``SAT_FI_*`` environment variable, firing
deterministically at a configured step (or call count) and exactly once.

All knobs are **inert by default**: with no ``SAT_FI_*`` variables set,
every hook is a handful of host-side compares and the production hot loop
is untouched (``tests/conftest.py`` asserts this).  No jax is imported at
module level so the harness (and ``scripts/bench_ckpt.py``) stays usable
on hosts with no accelerator backend at all.

Knobs::

    SAT_FI_DIE_AT_STEP=k       raise SimulatedPreemption before step k is
                               dispatched (abrupt preemption; periodic
                               checkpoints written so far are the only
                               survivors)
    SAT_FI_SIGTERM_AT_STEP=k   deliver a real SIGTERM to this process
                               before step k (drives the *graceful*
                               preemption path end-to-end)
    SAT_FI_NAN_AT_STEP=k       poison the k-th completed step: params and
                               metrics become NaN, as a diverged gradient
                               would leave them
    SAT_FI_CORRUPT_CKPT_STEP=k flip a byte in ``<k>.npz`` right after it
                               is written (bit-rot between write and
                               verify; the post-write verify must catch
                               it and LAST_GOOD must not advance)
    SAT_FI_IO_FAILURES=n[:sub] the first n ``retry_io`` attempts whose
                               description contains ``sub`` (all, when no
                               ``sub``) raise a retryable InjectedIOError
    SAT_FI_WEDGE_AT_STEP=k     wedge the train loop before step k is
                               dispatched: the thread parks in a sleep
                               loop, making no progress, exactly like a
                               silently hung device dispatch (the
                               watchdog is expected to detect and abort)
    SAT_FI_SLOW_STEP_MS=m      add m milliseconds of host-side stall to
                               every step (a degraded-but-alive device;
                               the watchdog must NOT fire)
    SAT_FI_WEDGE_SERVE_BATCH=n wedge the n-th (1-based) dispatched serve
                               batch at the result drain: its requests
                               must fail 500, /healthz must degrade to
                               503, and the engine re-warms
    SAT_FI_SLOW_SERVE_MS=m     add m milliseconds of host-side stall to
                               every serve batch's result drain (a
                               degraded-but-alive serving device; the
                               latency SLO must start burning while the
                               wedge watchdog stays quiet)
    SAT_FI_CANARY_SLOW_MS=m    like SLOW_SERVE_MS but only for batches
                               dispatched against the CANARY param slot
                               (a bad candidate checkpoint whose decode
                               path stalls; the canary SLO must burn and
                               the lifecycle controller must roll back
                               while the incumbent stays fast)
    SAT_FI_CORRUPT_SHARD_ROW=k overwrite the first bytes of row k of
                               shard-00000.npy when the shard cache is
                               resolved (bit rot in a data shard; the
                               crc sidecar must detect it and the
                               live-decode fallback must recover).
                               Idempotent constant write, so re-firing
                               across loaders/restarts is harmless
    SAT_FI_BAD_IMAGE_EVERY=n   the live decode of any image whose
                               basename hashes into bucket 0 of n
                               raises (a truncated/rotted JPEG
                               population; quarantine must contain it).
                               Keyed on the file NAME, not call order,
                               so firing is deterministic under the
                               decode thread pool
    SAT_FI_BAD_CAPTION_AT=k    poison the k-th tokenized caption row
                               (its word_idxs/masks zeroed) so the
                               caption-anomaly detector must quarantine
                               it
    SAT_FI_QUALITY_SKEW=c      depress every drained top-beam log score
                               by c/100 at the serve-path detok boundary
                               (harvest-side scoring only — caption
                               TOKENS are untouched, so replay stays
                               bitwise).  Beam margins and normalized
                               log-probs shift together, exactly like a
                               quietly degraded checkpoint: the quality
                               drift lane must burn while /healthz stays
                               ok.  Re-read from the environment per
                               drain so a chaos scenario can arm it
                               mid-run
"""

from __future__ import annotations

import errno
import os
import signal
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

ENV_PREFIX = "SAT_FI_"


class SimulatedPreemption(RuntimeError):
    """Injected die-at-step-k: the run is 'preempted' mid-loop.  Callers
    treat it like the process vanishing — resume must come from the
    checkpoints already on disk."""


class InjectedIOError(OSError):
    """Injected transient IO failure (classified retryable by
    ``resilience.retry``: errno EIO)."""

    def __init__(self, desc: str, remaining: int):
        super().__init__(errno.EIO, f"injected transient IO error ({desc}; {remaining} more armed)")


def _env_int(env: Dict[str, str], key: str) -> Optional[int]:
    raw = env.get(ENV_PREFIX + key)
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError as e:
        raise ValueError(f"{ENV_PREFIX}{key}={raw!r}: expected an integer") from e


@dataclass
class FaultPlan:
    """One training run's armed faults.  Step-keyed faults fire at most
    once; a plan with nothing armed is ``inert`` and every hook is a
    no-op compare."""

    die_at_step: Optional[int] = None
    sigterm_at_step: Optional[int] = None
    nan_at_step: Optional[int] = None
    corrupt_ckpt_step: Optional[int] = None
    wedge_at_step: Optional[int] = None
    slow_step_ms: Optional[int] = None
    wedge_serve_batch: Optional[int] = None
    slow_serve_ms: Optional[int] = None
    canary_slow_ms: Optional[int] = None
    corrupt_shard_row: Optional[int] = None
    bad_image_every: Optional[int] = None
    bad_caption_at: Optional[int] = None
    _fired: Dict[str, bool] = field(default_factory=dict)

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "FaultPlan":
        env = os.environ if env is None else env
        return cls(
            die_at_step=_env_int(env, "DIE_AT_STEP"),
            sigterm_at_step=_env_int(env, "SIGTERM_AT_STEP"),
            nan_at_step=_env_int(env, "NAN_AT_STEP"),
            corrupt_ckpt_step=_env_int(env, "CORRUPT_CKPT_STEP"),
            wedge_at_step=_env_int(env, "WEDGE_AT_STEP"),
            slow_step_ms=_env_int(env, "SLOW_STEP_MS"),
            wedge_serve_batch=_env_int(env, "WEDGE_SERVE_BATCH"),
            slow_serve_ms=_env_int(env, "SLOW_SERVE_MS"),
            canary_slow_ms=_env_int(env, "CANARY_SLOW_MS"),
            corrupt_shard_row=_env_int(env, "CORRUPT_SHARD_ROW"),
            bad_image_every=_env_int(env, "BAD_IMAGE_EVERY"),
            bad_caption_at=_env_int(env, "BAD_CAPTION_AT"),
        )

    @property
    def inert(self) -> bool:
        return (
            self.die_at_step is None
            and self.sigterm_at_step is None
            and self.nan_at_step is None
            and self.corrupt_ckpt_step is None
            and self.wedge_at_step is None
            and self.slow_step_ms is None
            and self.wedge_serve_batch is None
            and self.slow_serve_ms is None
            and self.canary_slow_ms is None
            and self.corrupt_shard_row is None
            and self.bad_image_every is None
            and self.bad_caption_at is None
        )

    def _once(self, key: str) -> bool:
        if self._fired.get(key):
            return False
        self._fired[key] = True
        return True

    # -- hooks consumed by runtime.train ----------------------------------

    def maybe_kill(self, step: int) -> None:
        """Before dispatching ``step``: simulated preemption (abrupt raise)
        or a real self-SIGTERM (exercises the graceful-stop handler)."""
        if self.die_at_step is not None and step >= self.die_at_step and self._once("die"):
            raise SimulatedPreemption(f"injected preemption before step {step}")
        if (
            self.sigterm_at_step is not None
            and step >= self.sigterm_at_step
            and self._once("sigterm")
        ):
            os.kill(os.getpid(), signal.SIGTERM)

    def maybe_poison(self, step_done: int, state: Any, metrics: Any) -> Tuple[Any, Any]:
        """After the step that made the counter read ``step_done``: poison
        params and metrics with NaN, as a diverged gradient update would.
        Costs nothing unless armed AND firing (one tree_map on fire)."""
        if self.nan_at_step is None or step_done != self.nan_at_step or not self._once("nan"):
            return state, metrics
        import jax  # deferred: inert plans must not need jax
        import numpy as np

        nan = float("nan")  # sync-ok: host constant, no device value
        poisoned_params = jax.tree_util.tree_map(lambda x: x * nan, state.params)
        poisoned_metrics = {k: np.asarray(nan, np.float32) for k in metrics}  # sync-ok: host scalars
        return state._replace(params=poisoned_params), poisoned_metrics

    def maybe_wedge(self, step: int) -> None:
        """Before dispatching ``step``: park the calling thread forever
        (well, for an hour — long past any watchdog deadline), exactly
        like a silently hung device dispatch.  The process makes no
        progress until the watchdog aborts it."""
        if self.wedge_at_step is None or step < self.wedge_at_step or not self._once("wedge"):
            return
        deadline = time.monotonic() + 3600.0
        while time.monotonic() < deadline:  # interruptible only by abort
            time.sleep(0.05)

    def maybe_slow(self, step: int) -> None:
        """Before dispatching ``step``: stall ``slow_step_ms`` of host
        time.  Degraded-but-alive; per-phase progress keeps ticking and
        the watchdog must stay quiet."""
        if self.slow_step_ms is None:
            return
        time.sleep(self.slow_step_ms / 1e3)

    def maybe_slow_serve(self) -> None:
        """At every serve result drain: stall ``slow_serve_ms`` of host
        time.  Degraded-but-alive serving — request latency inflates (the
        latency SLO's test signal) but batches still complete."""
        if self.slow_serve_ms is None:
            return
        time.sleep(self.slow_serve_ms / 1e3)

    def maybe_slow_canary(self, slot: str) -> None:
        """At the serve result drain, when the drained batch ran against
        the canary param slot: stall ``canary_slow_ms`` of host time.
        The incumbent slot is untouched, so the canary SLO burns while
        the serve SLO stays green — the rollback trigger."""
        if self.canary_slow_ms is None or slot != "canary":
            return
        time.sleep(self.canary_slow_ms / 1e3)

    def maybe_wedge_serve(self, batch_index: int) -> bool:
        """At the serve result drain, for the ``batch_index``-th (1-based)
        dispatched batch: report True exactly once so the batcher can
        simulate a wedged in-flight batch without real device state."""
        return (
            self.wedge_serve_batch is not None
            and batch_index == self.wedge_serve_batch
            and self._once("wedge_serve")
        )

    def maybe_corrupt_checkpoint(self, path: str, step: int) -> None:
        """After ``<step>.npz`` landed: flip one byte mid-file (bit rot /
        torn replication).  The post-write verify is expected to catch it."""
        if (
            self.corrupt_ckpt_step is None
            or step != self.corrupt_ckpt_step
            or not self._once("corrupt")
        ):
            return
        corrupt_byte(path)

    def maybe_corrupt_shard_row(self, cache_dir: str) -> None:
        """When the shard cache is resolved: overwrite the first bytes
        of row ``corrupt_shard_row`` of the first shard with a constant
        (NOT a flip — a toggle would self-heal on the second loader's
        resolve).  The crc sidecar, written at build time, goes stale
        against exactly that row."""
        if self.corrupt_shard_row is None:
            return
        path = os.path.join(cache_dir, "shard-00000.npy")
        if not os.path.exists(path):
            return
        import numpy as np

        mm = np.load(path, mmap_mode="r+")
        row = min(self.corrupt_shard_row, len(mm) - 1)
        flat = mm.reshape(len(mm), -1)
        flat[row, :4] = 0xA5
        mm.flush()
        del mm


def corrupt_byte(path: str, offset: Optional[int] = None) -> None:
    """Flip one byte of ``path`` in place (test helper + injection body).
    Defaults to the middle of the file — inside some array's compressed
    payload, past the zip local headers."""
    size = os.path.getsize(path)
    if size == 0:
        return
    pos = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))


# -- transient-IO injection (consumed by resilience.retry) -----------------

# Keyed on the raw env value so re-arming with a new spec resets the
# budget; cleared the moment the variable disappears.
_io_state: Dict[str, Any] = {"spec": None, "remaining": 0, "match": ""}


def consume_io_fault(desc: str) -> None:
    """Called by ``retry_io`` before every attempt.  Inert (one dict get)
    unless ``SAT_FI_IO_FAILURES`` is set."""
    spec = os.environ.get(ENV_PREFIX + "IO_FAILURES")
    if not spec:
        _io_state["spec"] = None
        return
    if _io_state["spec"] != spec:
        count, _, match = spec.partition(":")
        _io_state.update(spec=spec, remaining=int(count), match=match)
    if _io_state["remaining"] > 0 and _io_state["match"] in desc:
        _io_state["remaining"] -= 1
        raise InjectedIOError(desc, _io_state["remaining"])


# -- bad-record injection (consumed by the data plane) ----------------------

# Caption faults are counted in the (single) tokenizing producer thread,
# so a plain counter is deterministic; keyed on the raw spec like
# _io_state so re-arming resets it.
_caption_state: Dict[str, Any] = {"spec": None, "count": 0}


def consume_decode_fault(image_file: str) -> None:
    """Called by ``ImageLoader.load_raw`` per image.  Inert (one env get)
    unless ``SAT_FI_BAD_IMAGE_EVERY`` is set; then raises for the stable
    1/n of images whose *basename* hashes into bucket 0 — call-order
    independent (the decode pool is unordered) and identical across
    runs/tmpdirs over the same file names."""
    spec = os.environ.get(ENV_PREFIX + "BAD_IMAGE_EVERY")
    if not spec:
        return
    import zlib

    n = max(1, int(spec))
    if zlib.crc32(os.path.basename(image_file).encode("utf-8")) % n == 0:
        raise ValueError(
            f"injected decode failure (SAT_FI_BAD_IMAGE_EVERY={n}): "
            f"{image_file}"
        )


def consume_quality_skew() -> float:
    """Called by the serve batchers at every detok boundary.  Inert (one
    env get) unless ``SAT_FI_QUALITY_SKEW`` is set; then returns the log
    score depression (``c / 100``) the drained top beam must absorb.
    Env-read per call — NOT captured into the batcher's FaultPlan — so
    the chaos campaign can flip drift on under live load."""
    spec = os.environ.get(ENV_PREFIX + "QUALITY_SKEW")
    if not spec:
        return 0.0
    return int(spec) / 100.0


def consume_caption_fault() -> bool:
    """Called per tokenized caption row.  True exactly once, when the
    running row count passes ``SAT_FI_BAD_CAPTION_AT`` — the caller
    zeroes that row so the anomaly detector has something to catch."""
    spec = os.environ.get(ENV_PREFIX + "BAD_CAPTION_AT")
    if not spec:
        _caption_state["spec"] = None
        return False
    if _caption_state["spec"] != spec:
        _caption_state.update(spec=spec, count=0)
    _caption_state["count"] += 1
    return _caption_state["count"] == int(spec)


def reset_io_faults() -> None:
    """Forget injection bookkeeping (test isolation)."""
    _io_state.update(spec=None, remaining=0, match="")
    _caption_state.update(spec=None, count=0)
