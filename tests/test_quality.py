"""Caption-quality observability plane tests (ISSUE 19).

Pins the contracts of sat_tpu/telemetry/quality.py + exemplar.py and
their serve/bulk wiring:

* signal extraction units — margin, normalized log-prob, distinct /
  repeat / unk rates, eos truncation, and the host attention
  diagnostics' IDENTITY with the PR 4 device taps (same formulas,
  B=1 masked);
* streaming sketches + PSI edges, reference freeze / JSON round-trip,
  outlier verdicts and the per-tenant cut;
* the exemplar flight recorder — rotation, image size cap, disk
  budget, torn-tail-tolerant reads, rate limiting;
* serve integration on a real warmed engine: /stats quality block,
  GET /quality_reference export, scripts/replay_exemplar.py replaying
  a captured request BITWISE through a fresh subprocess engine;
* the off-knob: ``--serve_quality off`` captions bit-identically to
  quality-on (alphas are passive passengers of beam selection) and the
  quality path never compiles anything new in steady state;
* bulk stamping: quality-on shard rows carry deterministic ``quality``
  fields and stay byte-identical across reruns; quality-off rows carry
  none.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from sat_tpu import telemetry
from sat_tpu.config import Config
from sat_tpu.telemetry import quality as Q
from sat_tpu.telemetry import exemplar as E
from sat_tpu.telemetry.quality import (
    FixedBinSketch,
    QualityMonitor,
    QualityReference,
    caption_divergence,
    extract_signals,
    host_attention_entropy,
    host_coverage_deviation,
    psi,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# signal extraction units
# ---------------------------------------------------------------------------


def _beam_arrays(rows, scores):
    """rows: list of id-lists (beams, padded to longest); → (words [K,T],
    lengths [K], scores [K])."""
    T = max(len(r) for r in rows)
    words = np.zeros((len(rows), T), np.int32)
    lengths = np.zeros((len(rows),), np.int32)
    for k, r in enumerate(rows):
        words[k, : len(r)] = r
        lengths[k] = len(r)
    return words, lengths, np.asarray(scores, np.float32)


class TestSignals:
    def test_margin_and_norm_logprob(self):
        words, lengths, scores = _beam_arrays(
            [[3, 4, 5, 9], [3, 4, 9, 0]], [-2.0, -3.5]
        )
        sig = extract_signals(
            words, lengths, scores, vocab_size=20, eos_id=9
        )
        assert sig["margin"] == pytest.approx(1.5)
        assert sig["norm_logprob"] == pytest.approx(-2.0 / 4)
        assert sig["caption_len"] == 4.0
        assert sig["eos_trunc"] == 0.0
        assert "coverage_dev" not in sig  # no alphas drained

    def test_single_beam_margin_zero(self):
        words, lengths, scores = _beam_arrays([[3, 9]], [-1.0])
        sig = extract_signals(
            words, lengths, scores, vocab_size=20, eos_id=9
        )
        assert sig["margin"] == 0.0

    def test_unk_rate_counts_pad_and_oov(self):
        # 0 (pad), vocab_size, vocab_size+5 are all OOV; 3 is real
        words, lengths, scores = _beam_arrays(
            [[0, 20, 25, 3], [3, 3, 3, 3]], [-1.0, -2.0]
        )
        sig = extract_signals(
            words, lengths, scores, vocab_size=20, eos_id=9
        )
        assert sig["unk_rate"] == pytest.approx(3 / 4)
        assert sig["eos_trunc"] == 1.0  # no eos id anywhere

    def test_distinct_and_repeat_bigram(self):
        words, lengths, scores = _beam_arrays(
            [[3, 4, 3, 4, 3, 4], [3, 3, 3, 3, 3, 3]], [-1.0, -2.0]
        )
        sig = extract_signals(
            words, lengths, scores, vocab_size=20, eos_id=9
        )
        assert sig["distinct_ratio"] == pytest.approx(2 / 6)
        # bigrams: (3,4)x3 + (4,3)x2 -> 2 distinct of 5
        assert sig["repeat_bigram"] == pytest.approx(1.0 - 2 / 5)

    def test_degenerate_length_clamped(self):
        words, lengths, scores = _beam_arrays([[9], [9]], [-1.0, -1.5])
        lengths[:] = 0  # all-eos-first rows harvest as length 0
        sig = extract_signals(
            words, lengths, scores, vocab_size=20, eos_id=9
        )
        assert sig["caption_len"] == 1.0
        assert sig["repeat_bigram"] == 0.0

    def test_coverage_deviation_matches_device_tap(self):
        """host_coverage_deviation == telemetry/device.py's training tap
        for B=1 with a first-``steps`` mask — one definition of the
        paper's doubly-stochastic deviation, device and host."""
        import jax.numpy as jnp

        from sat_tpu.telemetry.device import (
            alpha_coverage_deviation,
            attention_entropy,
        )

        rng = np.random.default_rng(7)
        T, N, steps = 12, 9, 8
        raw = rng.uniform(0.1, 1.0, (T, N)).astype(np.float32)
        alphas = raw / raw.sum(-1, keepdims=True)
        mask = np.zeros((1, T), np.float32)
        mask[0, :steps] = 1.0
        dev_cov = float(
            alpha_coverage_deviation(jnp.asarray(alphas[None]), jnp.asarray(mask))
        )
        dev_ent = float(
            attention_entropy(jnp.asarray(alphas[None]), jnp.asarray(mask))
        )
        assert host_coverage_deviation(alphas, steps) == pytest.approx(
            dev_cov, rel=1e-5
        )
        assert host_attention_entropy(alphas, steps) == pytest.approx(
            dev_ent, rel=1e-5
        )

    def test_attention_diag_edges(self):
        alphas = np.full((4, 8), 1.0 / 8, np.float32)
        # uniform rows: entropy ln(8), coverage sums to steps/8 per cell
        assert host_attention_entropy(alphas, 4) == pytest.approx(
            np.log(8), rel=1e-5
        )
        assert host_attention_entropy(alphas, 0) == 0.0
        one_hot = np.zeros((4, 8), np.float32)
        one_hot[:, 2] = 1.0
        assert host_attention_entropy(one_hot, 4) == pytest.approx(0.0, abs=1e-6)
        # steps clamped to T
        assert host_coverage_deviation(alphas, 99) == host_coverage_deviation(
            alphas, 4
        )


# ---------------------------------------------------------------------------
# sketches, PSI, reference round-trip
# ---------------------------------------------------------------------------


class TestSketchPsi:
    def test_window_rotation_is_bounded(self):
        s = FixedBinSketch(0.0, 1.0, bins=4, window=8)
        for i in range(50):
            s.update(i % 10 / 10.0)
        assert s.total == 8
        assert sum(s.counts) == 8
        assert abs(sum(s.probs()) - 1.0) < 1e-9

    def test_tails_clamp_into_terminal_bins(self):
        s = FixedBinSketch(0.0, 1.0, bins=4, window=8)
        s.update(-99.0)
        s.update(99.0)
        assert s.counts[0] == 1 and s.counts[-1] == 1

    def test_mean_tracks_window(self):
        s = FixedBinSketch(0.0, 10.0, bins=4, window=4)
        for v in (1.0, 2.0, 3.0, 4.0, 5.0):
            s.update(v)
        assert s.mean() == pytest.approx((2 + 3 + 4 + 5) / 4)

    def test_psi_edges(self):
        assert psi([0.5, 0.5], [0.5, 0.5]) == 0.0
        assert psi([], []) == 0.0
        assert psi([0.0, 0.0], [0.5, 0.5]) == 0.0  # empty side: no evidence
        shifted = psi([1.0, 0.0], [0.0, 1.0])
        assert shifted > 0.25  # fully moved mass is far past "investigate"
        assert psi([0.6, 0.4], [0.5, 0.5]) < shifted

    def test_reference_round_trip_through_json(self, tmp_path):
        sketches = {
            name: FixedBinSketch(lo, hi, bins=8, window=32)
            for name, lo, hi in Q.SIGNALS
        }
        rng = np.random.default_rng(3)
        for _ in range(32):
            for name, lo, hi in Q.SIGNALS:
                sketches[name].update(rng.uniform(lo, hi))
        ref = QualityReference.from_sketches(
            sketches, fingerprint={"model_step": 7}
        )
        path = str(tmp_path / "quality_reference.json")
        ref.save(path)
        back = QualityReference.load(path)
        assert back.fingerprint == {"model_step": 7}
        for name, _lo, _hi in Q.SIGNALS:
            # PSI of a distribution against its own round-trip is ~0
            assert psi(sketches[name].probs(), back.probs[name]) < 1e-6
            assert back.counts[name] == 32

    def test_reference_schema_mismatch_raises(self):
        with pytest.raises(ValueError):
            QualityReference.from_payload({"schema_version": 99})


# ---------------------------------------------------------------------------
# streaming monitor: freeze, outliers, drift, per-tenant cut
# ---------------------------------------------------------------------------


def _sig(margin=2.0, unk=0.0, eos_trunc=0.0, norm=-1.0, cov=0.1):
    return {
        "margin": margin,
        "norm_logprob": norm,
        "caption_len": 8.0,
        "distinct_ratio": 0.9,
        "repeat_bigram": 0.0,
        "unk_rate": unk,
        "eos_trunc": eos_trunc,
        "coverage_dev": cov,
        "attn_entropy": 3.0,
    }


class TestMonitor:
    def test_warmup_freeze_and_drift(self):
        tel = telemetry.enable(capacity=4096)
        try:
            m = QualityMonitor(window=16, tel=tel)
            for _ in range(16):
                assert m.observe(_sig()) == []
            assert m.reference is not None
            assert m.reference_source == "warmup"
            assert m.drift_scores()  # same traffic → all ~0
            assert max(m.drift_scores().values()) < 1e-6
            # hard shift: margin collapses, norm_logprob drops
            reasons = None
            for _ in range(16):
                reasons = m.observe(_sig(margin=9.5, norm=-9.0))
            assert "drift_margin" in reasons
            assert "drift_norm_logprob" in reasons
            scores = m.drift_scores()
            assert scores["margin"] > 0.25
            m.maybe_publish(force=True)
            gauges = tel.gauges()
            assert gauges["quality/psi_max"] > 0.25
            assert gauges["quality/reference_frozen"] == 1
            snap = m.snapshot()
            assert snap["requests"] == 32
            assert snap["outliers"] >= 16
            assert snap["psi_max"] == gauges["quality/psi_max"]
        finally:
            telemetry.disable()

    def test_threshold_outliers(self):
        m = QualityMonitor(window=16, margin_min=0.5, unk_max=0.2)
        assert "low_margin" in m.observe(_sig(margin=0.1))
        assert "high_unk" in m.observe(_sig(unk=0.9))
        assert "eos_trunc" in m.observe(_sig(eos_trunc=1.0))
        assert m.observe(_sig()) == []
        assert m.outliers == 3

    def test_file_reference_skips_warmup_freeze(self):
        sketches = {
            name: FixedBinSketch(lo, hi, bins=16, window=8)
            for name, lo, hi in Q.SIGNALS
        }
        for _ in range(8):
            for name, value in _sig().items():
                sketches[name].update(value)
        ref = QualityReference.from_sketches(sketches)
        m = QualityMonitor(window=16, reference=ref)
        assert m.reference_source == "file"
        # drift scoring live from request one — no warmup window needed
        reasons = m.observe(_sig(margin=9.9))
        assert "drift_margin" in reasons

    def test_per_tenant_cut(self):
        tel = telemetry.enable(capacity=4096)
        try:
            m = QualityMonitor(window=8, tel=tel)
            for _ in range(8):
                m.observe(_sig(), tenant="steady")
            for _ in range(8):
                m.observe(_sig(margin=9.5, norm=-9.5), tenant="skewed")
            m.maybe_publish(force=True)
            snap = m.snapshot()
            assert set(snap["tenants"]) == {"steady", "skewed"}
            assert snap["tenants"]["skewed"]["psi_max"] > 0.25
            assert snap["tenants"]["steady"]["psi_max"] < 0.05
            gauges = tel.gauges()
            assert gauges["quality/tenant_skewed_psi_max"] > 0.25
        finally:
            telemetry.disable()

    def test_publish_rate_limited_by_injectable_clock(self):
        tel = telemetry.enable(capacity=4096)
        try:
            now = [0.0]
            m = QualityMonitor(
                window=8, publish_interval_s=1.0, tel=tel,
                clock=lambda: now[0],
            )
            for _ in range(8):
                m.observe(_sig())
            tel.gauge("quality/requests", -1)  # sentinel to detect refresh
            m.observe(_sig())  # same tick: publish suppressed
            assert tel.gauges()["quality/requests"] == -1
            now[0] += 1.5
            m.observe(_sig())  # interval elapsed: gauges refresh
            assert tel.gauges()["quality/requests"] == 10
        finally:
            telemetry.disable()


class TestDivergenceShared:
    def test_divergence_values(self):
        assert caption_divergence("a dog runs.", "a dog runs.") == 0.0
        assert caption_divergence("a dog", "two cats") == 1.0
        assert caption_divergence("", "") == 0.0
        assert 0.0 < caption_divergence("a dog runs", "a cat runs") < 1.0

    def test_canary_reexports_the_shared_definition(self):
        """One quality module serves both planes: the lifecycle canary's
        divergence IS telemetry.quality's (ISSUE 19 refactor)."""
        from sat_tpu.lifecycle import canary

        assert canary.caption_divergence is Q.caption_divergence
        assert canary.DivergenceGauge is Q.DivergenceGauge


# ---------------------------------------------------------------------------
# exemplar flight recorder
# ---------------------------------------------------------------------------


def _recorder(tmp_path, **kw):
    now = [0.0]

    def clock():
        now[0] += 1.0  # every record lands outside the rate-limit window
        return now[0]

    kw.setdefault("clock", clock)
    return E.ExemplarRecorder(str(tmp_path / "ex"), **kw)


class TestExemplarRecorder:
    def test_record_and_read_back(self, tmp_path):
        r = _recorder(tmp_path)
        r.write_meta({"config": {"beam_size": 2}, "model_step": 5})
        assert r.record(
            reasons=["low_margin"], request_id="r1", tenant="t",
            caption="a dog.", beams=[{"caption": "a dog."}],
            signals={"margin": 0.125}, image_bytes=b"JPEGDATA",
            alphas=np.ones((2, 3, 4), np.float32),
        )
        rows, torn = E.read_exemplars(r.dir)
        assert torn == 0 and len(rows) == 1
        row = rows[0]
        assert row["reasons"] == ["low_margin"]
        assert row["signals"]["margin"] == 0.125
        assert row["alphas_digest"] == E.alphas_digest(
            np.ones((2, 3, 4), np.float32)
        )
        assert E.load_image(r.dir, row) == b"JPEGDATA"
        assert E.read_meta(r.dir)["model_step"] == 5
        assert row["image"].startswith("img_") and row["image"].endswith(".bin")

    def test_rate_limit_drops_storms(self, tmp_path):
        r = E.ExemplarRecorder(
            str(tmp_path / "ex"), min_interval_s=10.0, clock=lambda: 100.0
        )
        assert r.record(reasons=["a"])
        assert not r.record(reasons=["b"])  # same instant: dropped
        assert r.stats() == {"recorded": 1, "dropped": 1}

    def test_segment_rotation_bounds_rows(self, tmp_path):
        r = _recorder(tmp_path, segment_rows=2, segments=3)
        for i in range(9):
            assert r.record(reasons=[f"r{i}"])
        segs = sorted(
            f for f in os.listdir(r.dir) if f.startswith("seg_")
        )
        assert len(segs) <= 3
        rows, _ = E.read_exemplars(r.dir)
        # ring of 3 segments x 2 rows: the oldest rows rotated away
        assert 0 < len(rows) <= 6
        reasons = {row["reasons"][0] for row in rows}
        assert "r8" in reasons  # newest survives
        assert "r0" not in reasons  # oldest rotated out

    def test_image_size_cap_keeps_metadata(self, tmp_path):
        r = _recorder(tmp_path, image_cap_kb=1.0)
        assert r.record(reasons=["big"], image_bytes=b"x" * 2048)
        rows, _ = E.read_exemplars(r.dir)
        assert rows[0]["image"] is None
        assert rows[0]["image_bytes"] == 2048
        assert E.load_image(r.dir, rows[0]) is None

    def test_disk_budget_evicts_oldest(self, tmp_path):
        r = _recorder(
            tmp_path, budget_mb=8 / 1024.0, segment_rows=4, segments=2
        )  # 8 KiB budget
        for i in range(6):
            r.record(
                reasons=["x"], image_bytes=bytes([i]) * 3000
            )  # distinct 3 KB images
        total = sum(
            os.path.getsize(os.path.join(r.dir, f))
            for f in os.listdir(r.dir)
        )
        assert total <= 8 * 1024 + 4096  # budget + one in-flight row
        assert os.path.exists(os.path.join(r.dir, "seg_%03d.jsonl" % r._idx))

    def test_torn_tail_tolerated(self, tmp_path):
        r = _recorder(tmp_path)
        r.record(reasons=["ok"])
        seg = os.path.join(r.dir, "seg_000.jsonl")
        with open(seg, "a") as f:
            f.write('{"t_unix": 99, "reasons": ["torn')  # killed mid-append
        rows, torn = E.read_exemplars(r.dir)
        assert torn == 1
        assert [row["reasons"] for row in rows] == [["ok"]]

    def test_recorder_survives_unwritable_dir(self, capsys):
        r = E.ExemplarRecorder("/proc/definitely/not/writable")
        assert not r.record(reasons=["x"])  # warns once, never raises
        assert r.stats()["recorded"] == 0


# ---------------------------------------------------------------------------
# serve integration: warmed engine, HTTP surface, bitwise replay
# ---------------------------------------------------------------------------


SENTENCES = [
    "a man riding a horse on the beach.",
    "a group of people standing around a kitchen.",
    "two dogs playing with a red ball in the grass.",
]


def _jpeg(i, size=32):
    import cv2

    rng = np.random.default_rng(100 + i)
    img = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
    img[: 8 + i, :, i % 3] = 40 * (i + 1) % 255
    ok, buf = cv2.imencode(".jpg", img)
    assert ok
    return bytes(buf)


@pytest.fixture(scope="module")
def qserve(tmp_path_factory):
    """Fresh tiny checkpoint + quality-ON warmed engine + HTTP server.

    Procedural params (no training) — quality plumbing is orthogonal to
    caption merit, and the fixture stays fast."""
    import jax

    from sat_tpu import runtime
    from sat_tpu.data.vocabulary import Vocabulary
    from sat_tpu.resilience import lineage
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.serve.server import CaptionServer
    from sat_tpu.train.checkpoint import save_checkpoint
    from sat_tpu.train.step import create_train_state

    root = str(tmp_path_factory.mktemp("quality_serve"))
    vocab_file = os.path.join(root, "vocabulary.csv")
    vocabulary = Vocabulary(size=50)
    vocabulary.build(SENTENCES)
    vocabulary.save(vocab_file)
    config = Config(
        phase="serve",
        image_size=32,
        dim_embedding=16,
        num_lstm_units=16,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        compute_dtype="float32",
        vocabulary_size=vocabulary.size,
        vocabulary_file=vocab_file,
        beam_size=2,
        save_dir=os.path.join(root, "models"),
        summary_dir=os.path.join(root, "summary"),
        serve_buckets=(1, 4),
        serve_max_batch=4,
        serve_max_wait_ms=5.0,
        heartbeat_interval=0.0,
        serve_quality="on",
        serve_quality_window=8,
        serve_quality_exemplar_dir=os.path.join(root, "exemplars"),
    )
    os.makedirs(config.save_dir, exist_ok=True)
    tel = telemetry.enable(capacity=1 << 16)
    runtime._install_compile_listener()
    state = create_train_state(jax.random.PRNGKey(0), config)
    save_checkpoint(state, config)
    lineage.mark_last_good(config.save_dir, int(np.asarray(state.step)))
    state, _ = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    server = CaptionServer(config, engine, port=0).start()
    yield {
        "config": config,
        "engine": engine,
        "server": server,
        "tel": tel,
        "root": root,
        "vocabulary": vocabulary,
    }
    server.shutdown()
    telemetry.disable()


def _post(port, data):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/caption",
        data=data,
        method="POST",
        headers={"Content-Type": "image/jpeg"},
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def _get(port, route):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{route}", timeout=10
    ) as r:
        return r.status, json.loads(r.read())


def test_serve_quality_block_and_reference_export(qserve):
    port = qserve["server"].port
    # /quality_reference 409s until a full window froze one
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        _get(port, "/quality_reference")
    assert exc_info.value.code == 409
    for i in range(10):
        status, body = _post(port, _jpeg(i % 3))
        assert status == 200 and body["captions"]
    status, stats = _get(port, "/stats")
    q = stats["quality"]
    assert q["requests"] >= 10
    assert q["reference"] == "warmup"
    assert "exemplars" in q
    status, payload = _get(port, "/quality_reference")
    assert status == 200
    ref = QualityReference.from_payload(payload)  # round-trips
    assert ref.counts["margin"] == qserve["config"].serve_quality_window
    # the heartbeat/scrape carriers see the same gauges
    gauges = qserve["tel"].gauges()
    assert gauges.get("quality/reference_frozen") == 1
    assert "quality/psi_max" in gauges


def test_quality_requests_never_recompile(qserve):
    tel = qserve["tel"]
    port = qserve["server"].port
    compiles0 = tel.counters().get("jax/compiles", 0)
    for i in range(4):
        status, _ = _post(port, _jpeg(i % 3))
        assert status == 200
    assert tel.counters().get("jax/compiles", 0) == compiles0


def test_off_knob_captions_bit_identical(qserve):
    """serve_quality=off must caption bit-identically: alphas are
    passengers of the drained result, never inputs to beam selection."""
    from sat_tpu.serve.engine import ServeEngine, load_serving_state

    engine_on = qserve["engine"]
    config_off = qserve["config"].replace(serve_quality="off")
    state, _ = load_serving_state(config_off)
    engine_off = ServeEngine(
        config_off, state, qserve["vocabulary"], tel=qserve["tel"]
    )
    engine_off.warmup()
    imgs = [engine_on.preprocess(_jpeg(i)) for i in range(3)]
    out_on = engine_on.dispatch(engine_on.pad_batch(imgs)[0])
    out_off = engine_off.dispatch(engine_off.pad_batch(imgs)[0])
    won, lon, son, aon = engine_on.drain_output(out_on, 3)
    woff, loff, soff, aoff = engine_off.drain_output(out_off, 3)
    assert aon is not None and aoff is None  # the only difference
    assert np.array_equal(won, woff)
    assert np.array_equal(lon, loff)
    assert np.array_equal(son, soff)
    assert engine_on.detok_rows((won, lon, son, aon), 3) == (
        engine_off.detok_rows((woff, loff, soff), 3)
    )


def test_exemplar_replay_bitwise_subprocess(qserve):
    """The full flight-recorder loop: capture an exemplar off the live
    server, then scripts/replay_exemplar.py boots a FRESH engine from
    meta.json in a subprocess and must reproduce the caption bitwise."""
    server = qserve["server"]
    jpeg = _jpeg(1)
    status, body = _post(server.port, jpeg)
    assert status == 200
    caption = body["captions"][0]["caption"]
    # the recorder rate-limits (outliers from live traffic may have just
    # landed one); retry past the window rather than flake
    for _ in range(10):
        if server.exemplars.record(
            reasons=["test_capture"],
            request_id="replay-e2e",
            caption=caption,
            beams=body["captions"],
            image_bytes=jpeg,
        ):
            break
        time.sleep(0.3)
    else:
        pytest.fail("exemplar record kept hitting the rate limiter")
    exdir = server.exemplars.dir
    assert E.read_meta(exdir)["model_step"] == qserve["engine"].step
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "replay_exemplar.py"),
            "--dir", exdir, "--request-id", "replay-e2e",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"
    verdicts = [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]
    assert verdicts and verdicts[-1]["verdict"] == "BITWISE MATCH"
    assert verdicts[-1]["replayed"] == caption


def test_terminal_exemplar_on_shed(qserve):
    """Queue-full sheds record terminal exemplars (no caption, the raw
    request preserved) — the 'what were we shedding' flight record."""
    server = qserve["server"]
    time.sleep(0.3)  # clear the recorder's rate-limit window
    recorded0 = server.exemplars.stats()["recorded"]
    server._record_terminal_exemplar(
        type("T", (), {"trace_id": "shed-1"})(), 429, "shed", "default",
        b"rawbytes",
    )
    assert server.exemplars.stats()["recorded"] == recorded0 + 1
    rows, _ = E.read_exemplars(server.exemplars.dir)
    mine = [r for r in rows if r["request_id"] == "shed-1"]
    assert mine and mine[0]["reasons"] == ["shed"]
    assert mine[0]["status"] == 429
    assert E.load_image(server.exemplars.dir, mine[0]) == b"rawbytes"


# ---------------------------------------------------------------------------
# SLO lanes + healthz posture
# ---------------------------------------------------------------------------


def test_quality_slo_lanes_from_config():
    from sat_tpu.telemetry.slo import objectives_from_config

    config = Config(
        phase="serve", slo_quality_psi=0.25, slo_quality_unk=0.1
    )
    lanes = {o.name: o for o in objectives_from_config(config, "serve")}
    assert lanes["quality_drift"].kind == "gauge_ceiling"
    assert lanes["quality_drift"].source == "quality/psi_max"
    assert lanes["quality_unk"].source == "quality/unk_rate"
    # 0 disables (the config default)
    off = objectives_from_config(Config(phase="serve"), "serve")
    assert not any(o.name.startswith("quality_") for o in off)


def test_quality_burn_is_diagnostic_not_degrading(qserve):
    """A quality_* lane burning must not flip /healthz: drift is a model
    problem — routing traffic away fixes nothing (same posture as the
    tenant lanes).  A service lane burning still degrades."""
    server = qserve["server"]
    orig = server.slo.burning
    try:
        server.slo.burning = lambda: ["quality_drift", "tenant_a_latency"]
        health, status = server.healthz()
        assert health["status"] == "ok" and status == 200
        server.slo.burning = lambda: ["quality_drift", "p95_latency"]
        health, status = server.healthz()
        assert health["status"] == "degraded"
    finally:
        server.slo.burning = orig
    health, status = server.healthz()
    assert status == 200


# ---------------------------------------------------------------------------
# bulk stamping: deterministic quality fields in shard rows
# ---------------------------------------------------------------------------


def test_bulk_rows_stamp_quality_deterministically(qserve):
    from sat_tpu.bulk.runner import run_bulk

    root = qserve["root"]
    img_dir = os.path.join(root, "bulk_imgs")
    os.makedirs(img_dir, exist_ok=True)
    for i in range(4):
        with open(os.path.join(img_dir, f"img_{i}.jpg"), "wb") as f:
            f.write(_jpeg(i))

    def run(name, quality):
        cfg = qserve["config"].replace(
            phase="bulk",
            serve_quality=quality,
            serve_slot_pages=2,
            serve_page_width=2,
            shard_cache="off",
            bulk_input=img_dir,
            bulk_output=os.path.join(root, name),
            bulk_shard_rows=2,
            serve_quality_exemplar_dir="",
        )
        assert run_bulk(cfg) == 0
        return {
            f: open(os.path.join(cfg.bulk_output, f), "rb").read()
            for f in sorted(os.listdir(cfg.bulk_output))
            if f.startswith("captions_") and f.endswith(".jsonl")
        }

    on_a = run("bulk_on_a", "on")
    on_b = run("bulk_on_b", "on")
    off = run("bulk_off", "off")
    assert on_a == on_b  # byte-identical rerun: stamping is deterministic
    rows_on = [
        json.loads(l)
        for blob in on_a.values()
        for l in blob.decode().splitlines()
    ]
    rows_off = [
        json.loads(l)
        for blob in off.values()
        for l in blob.decode().splitlines()
    ]
    assert all("quality" in r for r in rows_on)
    for r in rows_on:
        assert set(r["quality"]) == {
            "margin", "norm_logprob", "unk_rate", "coverage_dev"
        }
    assert all("quality" not in r for r in rows_off)
    # quality is a pure addition: captions match the off run exactly
    strip = lambda rows: [
        {k: v for k, v in r.items() if k != "quality"} for r in rows
    ]
    assert strip(rows_on) == strip(rows_off)


# ---------------------------------------------------------------------------
# router fan-in (jax-free dict arithmetic)
# ---------------------------------------------------------------------------


def test_fleet_quality_fan_in():
    from sat_tpu.serve.router import fleet_quality

    replicas = {
        "r0": {"quality": {"requests": 10, "outliers": 1,
                           "psi_max": 0.02, "reference": "warmup"}},
        "r1": {"quality": {"requests": 30, "outliers": 6,
                           "psi_max": 0.41, "reference": "file"}},
        "r2": {},  # replica without a quality plane: skipped, not summed
    }
    fq = fleet_quality(replicas)
    assert fq["requests"] == 40
    assert fq["outliers"] == 7
    assert fq["psi_max"] == 0.41  # WORST replica, never the average
    assert fq["worst_replica"] == "r1"
    assert set(fq["replicas"]) == {"r0", "r1"}
    assert fleet_quality({"r0": {}}) == {}
