"""Fleet-telemetry-plane + black-box flight-recorder tests (ISSUE 10).

Pins the contracts of ``telemetry/fleet.py`` and ``telemetry/blackbox.py``
without ``jax.distributed`` — the file-based aggregation path is the one
multi-host correctness rests on, so everything here drives it with
simulated N-process sidecar fixtures:

* sidecar write/read roundtrip; torn/partial/garbage sidecars skipped and
  counted, never raised on;
* straggler verdict edge cases: named straggler, strictly-greater-than
  threshold (equality is "keeping up"), single host, zero median;
* the gather path: a collective matrix merged with sidecar identity
  metadata, and graceful fallback when the gather raises;
* the black-box ring: flattened record format, bounded rotation,
  torn-line tolerance, resume-on-newest-segment across restarts;
* the ONE finalizer chain: registration order, failure containment,
  same-name replacement, reentrancy guard;
* postmortem bundles: completeness against pre-made artifacts, the
  flush-before-read ordering, no-op when nothing is installed;
* ``scripts/analyze_postmortem.py`` heuristics and
  ``scripts/merge_traces.py`` timestamp alignment (imported directly);
* heartbeat / bench-stamp multi-host identity and ``fleet/*`` nesting,
  and the ``gauge_ceiling`` SLO kind the skew objective uses.

The e2e half runs a real ``runtime.train`` with ``--fleet_telemetry
--blackbox`` and a fault-injected SIGTERM, asserting the shutdown
ordering leaves a complete bundle (the ISSUE's satellite-3 regression).
"""

import glob
import importlib.util
import json
import os
import shutil
import sys
import time

import numpy as np
import pytest

from sat_tpu import runtime, telemetry
from sat_tpu.telemetry import SCHEMA_VERSION, blackbox, fleet, heartbeat, slo
from sat_tpu.telemetry.spans import NULL_TELEMETRY, Telemetry

from tests.test_runtime import SMALL_MODEL

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    path = os.path.join(REPO, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_sidecar(fleet_dir, p, step_p95_ms, host=None, **extra):
    row = {
        "schema_version": SCHEMA_VERSION,
        "run_id": "fixture",
        "process_index": p,
        "host": host or f"host{p}",
        "pid": 1000 + p,
        "time_unix": round(time.time(), 3),
        "step": 42,
        "step_p50_ms": step_p95_ms * 0.9,
        "step_p95_ms": step_p95_ms,
        "data_wait_ms": 2.0,
        "dispatch_ms": 1.0,
        "rss_mb": 512.0,
        "quarantined": 0.0,
        **extra,
    }
    with open(fleet.sidecar_path(fleet_dir, p), "w") as f:
        json.dump(row, f)
    return row


def _stepped_tel(step_ms=10.0, steps=64):
    tel = Telemetry(capacity=256)
    for _ in range(steps):
        now = time.perf_counter_ns()
        tel.record("train/step", now, int(step_ms * 1e6))
        tel.record("train/data_wait", now, 2_000_000)
        tel.record("train/dispatch", now, 1_000_000)
    return tel


@pytest.fixture
def bb_reset():
    """Isolate the process-wide finalizer chain + installed recorder."""
    blackbox._reset_for_tests()
    yield
    blackbox._reset_for_tests()


# ---------------------------------------------------------------------------
# sidecars: write/read roundtrip + torn tolerance
# ---------------------------------------------------------------------------


class TestSidecars:
    def test_write_and_read_roundtrip(self, tmp_path):
        tel = _stepped_tel()
        plane = fleet.FleetPlane(str(tmp_path), 0, 2, tel)
        row = plane.write_sidecar(step=7)
        assert row is not None
        rows = fleet.read_sidecars(str(tmp_path))
        assert len(rows) == 1
        got = rows[0]
        assert got["process_index"] == 0 and got["process_count"] == 2
        assert got["step"] == 7 and got["pid"] == os.getpid()
        assert got["schema_version"] == SCHEMA_VERSION
        for key in fleet.FLEET_SCALARS:
            assert key in got
        assert got["step_p95_ms"] == pytest.approx(10.0, rel=0.05)

    def test_torn_sidecars_skipped_and_counted(self, tmp_path):
        _write_sidecar(str(tmp_path), 0, 10.0)
        _write_sidecar(str(tmp_path), 2, 12.0)
        with open(fleet.sidecar_path(str(tmp_path), 1), "w") as f:
            f.write('{"process_index": 1, "step_p95_ms":')  # torn mid-write
        with open(fleet.sidecar_path(str(tmp_path), 3), "w") as f:
            f.write("[1, 2, 3]")  # parseable but not an object
        tel = Telemetry()
        rows = fleet.read_sidecars(str(tmp_path), tel=tel)
        assert [r["process_index"] for r in rows] == [0, 2]
        assert tel.counters()["fleet/torn_sidecars"] == 2

    def test_filename_index_backfills_missing_payload_index(self, tmp_path):
        with open(fleet.sidecar_path(str(tmp_path), 3), "w") as f:
            json.dump({"step_p95_ms": 5.0}, f)
        rows = fleet.read_sidecars(str(tmp_path))
        assert rows[0]["process_index"] == 3

    def test_empty_dir_yields_no_rows(self, tmp_path):
        assert fleet.read_sidecars(str(tmp_path)) == []


# ---------------------------------------------------------------------------
# aggregation + straggler verdict edge cases (pure, no IO)
# ---------------------------------------------------------------------------


class TestAggregateRows:
    def test_summary_medians_max_and_per_host_skew(self):
        rows = [
            _row_dict(0, 10.0),
            _row_dict(1, 20.0),
            _row_dict(2, 40.0),
        ]
        doc = fleet.aggregate_rows(rows, straggler_factor=10.0)
        assert doc["hosts_reporting"] == 3 and doc["process_count"] == 3
        assert doc["fleet"]["step_p95_ms_median"] == 20.0
        assert doc["fleet"]["step_p95_ms_max"] == 40.0
        assert doc["fleet"]["step_p95_skew"] == 2.0
        assert [h["skew"] for h in doc["hosts"]] == [0.5, 1.0, 2.0]
        assert doc["straggler"] == {"verdict": False}

    def test_straggler_named_with_reason(self):
        rows = [_row_dict(0, 10.0), _row_dict(1, 10.0), _row_dict(2, 100.0)]
        doc = fleet.aggregate_rows(rows, straggler_factor=2.0)
        verdict = doc["straggler"]
        assert verdict["verdict"] is True
        assert verdict["process_index"] == 2 and verdict["host"] == "host2"
        assert verdict["step_p95_ms"] == 100.0
        assert verdict["fleet_median_ms"] == 10.0
        assert verdict["skew"] == 10.0 and verdict["factor"] == 2.0
        assert "host2" in verdict["reason"] and "p2" in verdict["reason"]

    def test_exactly_at_threshold_is_not_a_straggler(self):
        # median of [10, 30] = 20; worst 30 == 20 * 1.5 — equality is
        # "keeping up", the rule is strictly greater
        rows = [_row_dict(0, 30.0), _row_dict(1, 10.0)]
        doc = fleet.aggregate_rows(rows, straggler_factor=1.5)
        assert doc["straggler"] == {"verdict": False}
        doc = fleet.aggregate_rows(rows, straggler_factor=1.49)
        assert doc["straggler"]["verdict"] is True
        assert doc["straggler"]["process_index"] == 0

    def test_single_host_never_a_straggler(self):
        doc = fleet.aggregate_rows([_row_dict(0, 1000.0)], straggler_factor=1.1)
        assert doc["straggler"] == {"verdict": False}
        assert doc["fleet"]["step_p95_skew"] == 1.0

    def test_zero_median_no_verdict_no_division(self):
        rows = [_row_dict(0, 0.0), _row_dict(1, 0.0)]
        doc = fleet.aggregate_rows(rows, straggler_factor=1.5)
        assert doc["straggler"] == {"verdict": False}
        assert doc["fleet"]["step_p95_skew"] == 0.0
        assert all(h["skew"] == 0.0 for h in doc["hosts"])

    def test_garbage_scalars_coerce_to_zero(self):
        rows = [_row_dict(0, 10.0), _row_dict(1, 10.0)]
        rows[1]["data_wait_ms"] = "bogus"
        rows[1]["rss_mb"] = None
        doc = fleet.aggregate_rows(rows, straggler_factor=2.0)
        h1 = doc["hosts"][1]
        assert h1["data_wait_ms"] == 0.0 and h1["rss_mb"] == 0.0

    def test_process_count_override_tracks_absent_hosts(self):
        doc = fleet.aggregate_rows(
            [_row_dict(0, 10.0)], straggler_factor=2.0, process_count=4
        )
        assert doc["process_count"] == 4 and doc["hosts_reporting"] == 1

    def test_empty_rows(self):
        doc = fleet.aggregate_rows([], straggler_factor=2.0)
        assert doc["hosts_reporting"] == 0 and doc["fleet"] == {}


def _row_dict(p, p95):
    return {
        "process_index": p,
        "host": f"host{p}",
        "step_p50_ms": p95 * 0.9,
        "step_p95_ms": p95,
        "data_wait_ms": 2.0,
        "dispatch_ms": 1.0,
        "rss_mb": 512.0,
        "quarantined": 0.0,
    }


class TestAggregateDirectory:
    def test_merges_sidecars_and_writes_fleet_json(self, tmp_path):
        for p, p95 in enumerate((10.0, 12.0, 95.0)):
            _write_sidecar(str(tmp_path), p, p95)
        doc = fleet.aggregate_directory(str(tmp_path), straggler_factor=1.5)
        assert doc is not None and doc["hosts_reporting"] == 3
        assert doc["straggler"]["process_index"] == 2
        on_disk = json.load(open(tmp_path / "fleet.json"))
        assert on_disk["straggler"] == doc["straggler"]

    def test_empty_dir_returns_none(self, tmp_path):
        assert (
            fleet.aggregate_directory(str(tmp_path), straggler_factor=1.5)
            is None
        )
        assert not (tmp_path / "fleet.json").exists()


# ---------------------------------------------------------------------------
# FleetPlane.tick: roles, gather path, publication, degradation
# ---------------------------------------------------------------------------


class TestFleetPlane:
    def test_nonzero_process_writes_sidecar_but_never_aggregates(self, tmp_path):
        plane = fleet.FleetPlane(str(tmp_path), 1, 2, _stepped_tel())
        assert plane.tick(5) is None
        assert os.path.isfile(fleet.sidecar_path(str(tmp_path), 1))
        assert not (tmp_path / "fleet.json").exists()

    def test_p0_aggregates_publishes_gauges_and_history(self, tmp_path):
        tel = _stepped_tel(step_ms=10.0)
        _write_sidecar(str(tmp_path), 1, 100.0, host="slowhost")
        plane = fleet.FleetPlane(
            str(tmp_path), 0, 2, tel, straggler_factor=1.5
        )
        doc = plane.tick(5)
        assert doc["hosts_reporting"] == 2
        assert doc["straggler"]["verdict"] is True
        assert doc["straggler"]["process_index"] == 1
        assert doc["straggler"]["host"] == "slowhost"
        gauges = tel.gauges()
        assert gauges["fleet/hosts_reporting"] == 2
        assert gauges["fleet/straggler_index"] == 1
        assert gauges["fleet/step_p95_skew"] > 1.5
        assert gauges["fleet/step_p95_ms_max"] == 100.0
        assert (tmp_path / "fleet.json").is_file()
        history = [
            json.loads(line)
            for line in open(tmp_path / "fleet_history.jsonl")
        ]
        assert history and history[-1]["straggler"]["process_index"] == 1

    def test_no_straggler_gauges_minus_one(self, tmp_path):
        tel = _stepped_tel(step_ms=10.0)
        _write_sidecar(str(tmp_path), 1, 10.0)
        plane = fleet.FleetPlane(str(tmp_path), 0, 2, tel, straggler_factor=2.0)
        doc = plane.tick(1)
        assert doc["straggler"] == {"verdict": False}
        assert tel.gauges()["fleet/straggler_index"] == -1

    def test_gather_path_merges_matrix_with_sidecar_identity(self, tmp_path):
        tel = _stepped_tel(step_ms=10.0)
        # the peer's sidecar carries identity but STALE scalars — the
        # gathered matrix must win for FLEET_SCALARS
        _write_sidecar(str(tmp_path), 1, 1.0, host="peerhost")
        plane = fleet.FleetPlane(str(tmp_path), 0, 2, tel, straggler_factor=1.5)
        calls = []

        def gather_fn(vec):
            calls.append(vec)
            peer = np.array([90.0, 100.0, 5.0, 2.0, 1024.0, 3.0])
            return np.stack([np.asarray(vec, np.float64), peer])

        doc = plane.tick(9, gather_fn=gather_fn)
        assert len(calls) == 1 and calls[0].shape == (len(fleet.FLEET_SCALARS),)
        assert doc["hosts_reporting"] == 2
        h1 = doc["hosts"][1]
        assert h1["host"] == "peerhost"  # identity from the sidecar
        assert h1["step_p95_ms"] == 100.0  # scalars from the gather
        assert h1["quarantined"] == 3.0
        assert doc["straggler"]["process_index"] == 1

    def test_gather_failure_falls_back_to_sidecars(self, tmp_path, capsys):
        tel = _stepped_tel()
        _write_sidecar(str(tmp_path), 1, 100.0)
        plane = fleet.FleetPlane(str(tmp_path), 0, 2, tel, straggler_factor=1.5)

        def bad_gather(vec):
            raise RuntimeError("collective timed out")

        doc = plane.tick(3, gather_fn=bad_gather)
        assert doc is not None and doc["hosts_reporting"] == 2
        assert "falling back to sidecars" in capsys.readouterr().err

    def test_finish_is_file_based_and_never_raises(self, tmp_path):
        tel = _stepped_tel()
        plane = fleet.FleetPlane(str(tmp_path), 0, 1, tel)
        plane.tick(4)
        doc = plane.finish()
        assert doc is not None and doc["hosts"][0]["step"] == 4
        # a destroyed fleet dir degrades to a warning, not an exception
        shutil.rmtree(tmp_path)
        assert plane.finish() is None


# ---------------------------------------------------------------------------
# the black-box ring
# ---------------------------------------------------------------------------


class TestBlackBoxRing:
    def test_append_flattens_fields_into_records(self, tmp_path):
        tel = Telemetry()
        tel.count("data/batches", 5)
        tel.gauge("train/step", 9)
        bb = blackbox.BlackBox(str(tmp_path), tel)
        bb.event("sentinel_trip", step=3, reason="nan")
        bb.journal(9)
        records, torn = bb.read_all()
        assert torn == 0 and len(records) == 2
        ev, snap = records
        assert ev["kind"] == "event" and ev["event"] == "sentinel_trip"
        assert ev["step"] == 3 and ev["reason"] == "nan"
        assert "t" in ev and "mono_ns" in ev
        assert snap["kind"] == "snapshot" and snap["step"] == 9
        assert snap["counters"]["data/batches"] == 5
        assert snap["gauges"]["train/step"] == 9

    def test_rotation_bounds_disk_use(self, tmp_path):
        bb = blackbox.BlackBox(
            str(tmp_path), Telemetry(), segment_bytes=4096, segments=3
        )
        payload = "x" * 100
        for i in range(400):  # ~150 bytes/record * 400 >> 3 * 4096
            bb.append("noise", {"i": i, "pad": payload})
        segs = glob.glob(str(tmp_path / "seg_*.jsonl"))
        assert len(segs) <= 3
        total = sum(os.path.getsize(s) for s in segs)
        # cap + one record of slop per segment (rotation happens at >=)
        assert total <= 3 * (4096 + 200)
        # the newest records survived; the oldest rotated away
        records, _ = bb.read_all()
        ids = [r["i"] for r in records if "i" in r]
        assert max(ids) == 399 and min(ids) > 0

    def test_torn_lines_skipped_not_fatal(self, tmp_path):
        bb = blackbox.BlackBox(str(tmp_path), Telemetry())
        bb.event("ok", n=1)
        with open(tmp_path / "seg_000.jsonl", "a") as f:
            f.write('{"t": 99, "kind": "event", "ev')  # killed mid-append
        records, torn = bb.read_all()
        assert torn == 1
        assert [r["event"] for r in records if r["kind"] == "event"] == ["ok"]

    def test_restart_resumes_on_newest_segment(self, tmp_path):
        bb = blackbox.BlackBox(
            str(tmp_path), Telemetry(), segment_bytes=4096, segments=4
        )
        for i in range(120):
            bb.append("noise", {"i": i, "pad": "x" * 100})
        assert bb._idx > 0  # the ring rotated
        bb2 = blackbox.BlackBox(
            str(tmp_path), Telemetry(), segment_bytes=4096, segments=4
        )
        assert bb2._idx == bb._idx
        bb2.event("after_restart")
        records, _ = bb2.read_all()
        assert any(r.get("event") == "after_restart" for r in records)

    def test_unserializable_record_degrades(self, tmp_path, capsys):
        bb = blackbox.BlackBox(str(tmp_path), Telemetry())
        bb.append("bad", {"obj": object()})  # must not raise
        records, torn = bb.read_all()
        assert records == [] and torn == 0
        assert "black box degraded" in capsys.readouterr().err

    def test_span_tail_wall_clock_anchoring(self, tmp_path):
        tel = Telemetry()
        with tel.span("train/step"):
            time.sleep(0.01)
        bb = blackbox.BlackBox(str(tmp_path), tel)
        tail = bb.span_tail(30.0)
        assert len(tail) == 1
        span = tail[0]
        assert span["name"] == "train/step"
        assert span["dur_ms"] >= 10.0
        assert abs(span["t_unix"] - time.time()) < 5.0

    def test_span_tail_null_telemetry_is_empty(self, tmp_path):
        bb = blackbox.BlackBox(str(tmp_path), NULL_TELEMETRY)
        assert bb.span_tail() == []


# ---------------------------------------------------------------------------
# the finalizer chain (shutdown-ordering contract)
# ---------------------------------------------------------------------------


class TestFinalizerChain:
    def test_runs_in_registration_order_with_containment(self, bb_reset, capsys):
        calls = []
        blackbox.register_finalizer("a", lambda: calls.append("a"))
        blackbox.register_finalizer("boom", lambda: 1 / 0)
        blackbox.register_finalizer("b", lambda: calls.append("b"))
        blackbox.run_finalizers()  # must not raise
        assert calls == ["a", "b"]
        assert "finalizer 'boom' failed" in capsys.readouterr().err

    def test_same_name_replaces_not_stacks(self, bb_reset):
        calls = []
        blackbox.register_finalizer("ring", lambda: calls.append("stale"))
        blackbox.register_finalizer("ring", lambda: calls.append("fresh"))
        blackbox.run_finalizers()
        assert calls == ["fresh"]

    def test_reentrancy_guarded(self, bb_reset):
        calls = []

        def recursing():
            calls.append("outer")
            blackbox.run_finalizers()  # a finalizer crashing into dump()

        blackbox.register_finalizer("recurse", recursing)
        blackbox.register_finalizer("tail", lambda: calls.append("tail"))
        blackbox.run_finalizers()
        assert calls == ["outer", "tail"]  # inner call was a no-op

    def test_safe_to_run_twice(self, bb_reset):
        calls = []
        blackbox.register_finalizer("idem", lambda: calls.append(1))
        blackbox.run_finalizers()
        blackbox.run_finalizers()
        assert calls == [1, 1]


# ---------------------------------------------------------------------------
# install + postmortem bundles
# ---------------------------------------------------------------------------


def _seed_artifacts(tdir, fdir):
    os.makedirs(tdir, exist_ok=True)
    os.makedirs(fdir, exist_ok=True)
    json.dump({"seq": 7}, open(os.path.join(tdir, "heartbeat.json"), "w"))
    open(os.path.join(tdir, "watchdog_stacks.txt"), "w").write("Thread-1\n")
    json.dump({"compiles": 2}, open(os.path.join(tdir, "compile_report.json"), "w"))
    json.dump({"step_ms": 30}, open(os.path.join(tdir, "breakdown.json"), "w"))
    with open(os.path.join(tdir, "slo.jsonl"), "w") as f:
        for i in range(250):  # > the 200-line tail cap
            f.write(json.dumps({"i": i}) + "\n")
    open(os.path.join(tdir, "telemetry.jsonl"), "w").write('{"k": 1}\n')
    json.dump(
        {"hosts_reporting": 2, "straggler": {"verdict": False}},
        open(os.path.join(fdir, "fleet.json"), "w"),
    )
    _write_sidecar(fdir, 0, 10.0)
    _write_sidecar(fdir, 1, 11.0)
    open(os.path.join(fdir, "fleet_history.jsonl"), "w").write('{"h": 1}\n')


class TestPostmortemBundles:
    def test_dump_is_noop_when_not_installed(self, bb_reset):
        assert blackbox.installed() is None
        assert blackbox.dump("anything", exit_code=86) is None

    def test_install_threads_ring_onto_chain(self, bb_reset, tmp_path):
        bb = blackbox.BlackBox(str(tmp_path / "ring"), Telemetry())
        blackbox.install(bb, telemetry_dir=str(tmp_path))
        assert blackbox.installed() is bb
        assert any(name == "blackbox-ring" for name, _ in blackbox._FINALIZERS)
        blackbox.uninstall()
        assert blackbox.installed() is None

    def test_bundle_completeness(self, bb_reset, tmp_path):
        tdir = str(tmp_path / "telemetry")
        fdir = str(tmp_path / "fleet")
        ledger = str(tmp_path / "quarantine.jsonl")
        _seed_artifacts(tdir, fdir)
        open(ledger, "w").write('{"shard": "s3"}\n')

        tel = Telemetry()
        with tel.span("train/step"):
            time.sleep(0.001)
        tel.gauge("train/step", 5)
        bb = blackbox.BlackBox(os.path.join(tdir, "blackbox"), tel)
        bb.journal(5)
        bb.event("anomaly_rollback", step=5, reason="nan")
        # flush-before-read: a finalizer lands one LAST record; it must be
        # inside the copied ring, proving the chain ran before the copy
        blackbox.install(
            bb,
            telemetry_dir=tdir,
            fleet_dir=fdir,
            config_snapshot={"model_dims": 16},
            quarantine_ledger=ledger,
        )
        blackbox.register_finalizer(
            "marker", lambda: bb.event("flushed_by_chain")
        )

        bundle = blackbox.dump(
            "anomaly_rollback", exit_code=None, step=5, reason_detail="nan"
        )
        assert bundle is not None and os.path.isdir(bundle)
        assert os.path.dirname(bundle) == tdir
        assert os.path.basename(bundle) == f"postmortem_{telemetry.run_id()}"

        manifest = json.load(open(os.path.join(bundle, "manifest.json")))
        assert manifest["schema_version"] == SCHEMA_VERSION
        assert manifest["reason"] == "anomaly_rollback"
        assert manifest["exit_code"] is None
        assert manifest["pid"] == os.getpid()
        assert manifest["step"] == 5 and manifest["reason"] == "anomaly_rollback"
        assert manifest["last_phase"] == "train/step"

        for name in (
            "spans_tail.json",
            "state.json",
            "heartbeat.json",
            "watchdog_stacks.txt",
            "compile_report.json",
            "breakdown.json",
            "fleet.json",
            "heartbeat_p0.json",
            "heartbeat_p1.json",
            "slo.jsonl",
            "telemetry.jsonl",
            "fleet_history.jsonl",
            "quarantine.jsonl",
            "config.json",
        ):
            assert os.path.isfile(os.path.join(bundle, name)), name

        assert len(open(os.path.join(bundle, "slo.jsonl")).readlines()) == 200
        assert json.load(open(os.path.join(bundle, "config.json"))) == {
            "model_dims": 16
        }
        state = json.load(open(os.path.join(bundle, "state.json")))
        assert state["gauges"]["train/step"] == 5
        spans = json.load(open(os.path.join(bundle, "spans_tail.json")))
        assert spans and spans[-1]["name"] == "train/step"

        copied = glob.glob(os.path.join(bundle, "blackbox", "seg_*.jsonl"))
        assert copied
        ring = [
            json.loads(line) for seg in copied for line in open(seg)
        ]
        events = [r.get("event") for r in ring if r["kind"] == "event"]
        assert "anomaly_rollback" in events
        assert "flushed_by_chain" in events  # the chain ran BEFORE the copy

    def test_dump_with_missing_artifacts_still_bundles(self, bb_reset, tmp_path):
        tdir = str(tmp_path / "bare")
        bb = blackbox.BlackBox(os.path.join(tdir, "blackbox"), Telemetry())
        blackbox.install(bb, telemetry_dir=tdir)
        bundle = blackbox.dump("uncaught_exception", exit_code=1, error="boom")
        assert bundle is not None
        manifest = json.load(open(os.path.join(bundle, "manifest.json")))
        assert manifest["error"] == "boom" and manifest["last_phase"] is None


# ---------------------------------------------------------------------------
# scripts/analyze_postmortem.py heuristics
# ---------------------------------------------------------------------------


class TestAnalyzePostmortem:
    @pytest.fixture(scope="class")
    def mod(self):
        return _load_script("analyze_postmortem")

    def _bundle(self, tmp_path, manifest, **files):
        bundle = tmp_path / "postmortem_test"
        bundle.mkdir()
        json.dump(manifest, open(bundle / "manifest.json", "w"))
        for name, doc in files.items():
            with open(bundle / name.replace("__", "."), "w") as f:
                if name.endswith("jsonl"):
                    for row in doc:
                        f.write(json.dumps(row) + "\n")
                else:
                    json.dump(doc, f)
        return str(bundle)

    def test_watchdog_wedge_names_the_phase(self, mod, tmp_path):
        bundle = self._bundle(
            tmp_path,
            {"reason": "watchdog_wedge", "exit_code": 86, "phase": "step",
             "overdue_s": 7.5},
        )
        out = mod.summarize(bundle)
        assert out["wedged_phase"] == "step"
        assert "wedged" in out["probable_cause"]
        assert "exit 86" in out["probable_cause"]

    def test_corruption_cites_quarantine_evidence(self, mod, tmp_path):
        bundle = self._bundle(
            tmp_path,
            {"reason": "systemic_corruption", "exit_code": 87},
            quarantine__jsonl=[{"shard": "s1"}, {"shard": "s2"}],
        )
        out = mod.summarize(bundle)
        assert "corruption" in out["probable_cause"]
        assert "restarting will NOT help" in out["probable_cause"]
        assert any("quarantine" in ev for ev in out["evidence"])

    def test_straggler_verdict_surfaces_as_evidence(self, mod, tmp_path):
        bundle = self._bundle(
            tmp_path,
            {"reason": "watchdog_wedge", "exit_code": 86, "phase": "step"},
            fleet__json={
                "straggler": {
                    "verdict": True, "process_index": 3,
                    "host": "slowhost", "skew": 4.2,
                }
            },
        )
        out = mod.summarize(bundle)
        assert out["straggler"]["process_index"] == 3
        assert any("slowhost" in ev for ev in out["evidence"])

    def test_sigterm_reports_final_checkpoint(self, mod, tmp_path):
        bundle = self._bundle(
            tmp_path,
            {"reason": "sigterm_during_checkpoint", "exit_code": 0,
             "signal": "SIGTERM", "final_checkpoint": "/ckpt/6.npz"},
        )
        out = mod.summarize(bundle)
        assert "SIGTERM" in out["probable_cause"]
        assert "/ckpt/6.npz" in out["probable_cause"]

    def test_find_bundle_picks_newest(self, mod, tmp_path):
        old = tmp_path / "postmortem_old"
        new = tmp_path / "postmortem_new"
        for d, age in ((old, 100), (new, 0)):
            d.mkdir()
            json.dump({}, open(d / "manifest.json", "w"))
            t = time.time() - age
            os.utime(d, (t, t))
        assert mod._find_bundle(str(tmp_path)) == str(new)
        assert mod._find_bundle(str(old)) == str(old)
        assert mod._find_bundle(str(tmp_path / "nowhere")) is None


# ---------------------------------------------------------------------------
# scripts/merge_traces.py: one timeline, a lane per host
# ---------------------------------------------------------------------------


class TestMergeTraces:
    @pytest.fixture(scope="class")
    def mod(self):
        return _load_script("merge_traces")

    def test_anchors_align_timestamps(self, mod):
        doc0 = {
            "traceEvents": [{"name": "step", "ph": "X", "pid": 0, "ts": 100.0}],
            "otherData": {"anchor_unix": 1000.0, "process_index": 0},
        }
        doc1 = {
            "traceEvents": [{"name": "step", "ph": "X", "pid": 1, "ts": 100.0}],
            "otherData": {"anchor_unix": 1002.5, "process_index": 1},
        }
        merged = mod.merge([doc0, doc1])
        by_pid = {
            e["pid"]: e for e in merged["traceEvents"] if e.get("ph") == "X"
        }
        assert by_pid[0]["ts"] == 100.0  # the earliest anchor is the base
        assert by_pid[1]["ts"] == 100.0 + 2.5e6  # shifted by the skew, in us
        assert merged["otherData"]["anchor_unix"] == 1000.0
        assert merged["displayTimeUnit"] == "ms"
        shifts = {
            h["process_index"]: h["shift_us"]
            for h in merged["otherData"]["merged_from"]
        }
        assert shifts == {0: 0.0, 1: 2.5e6}

    def test_missing_anchor_merges_unshifted(self, mod, capsys):
        doc = {"traceEvents": [{"name": "e", "ph": "X", "pid": 4, "ts": 7.0}]}
        merged = mod.merge([doc])
        ev = [e for e in merged["traceEvents"] if e.get("ph") == "X"][0]
        assert ev["ts"] == 7.0
        assert "no anchor_unix" in capsys.readouterr().err

    def test_process_name_lanes_injected_once(self, mod):
        named = {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 0,
                 "args": {"name": "custom"}},
                {"name": "e", "ph": "X", "pid": 0, "ts": 1.0},
            ],
            "otherData": {"anchor_unix": 1.0, "process_index": 0},
        }
        anonymous = {
            "traceEvents": [{"name": "e", "ph": "X", "pid": 1, "ts": 1.0}],
            "otherData": {"anchor_unix": 1.0, "process_index": 1},
        }
        merged = mod.merge([named, anonymous])
        meta = [
            e for e in merged["traceEvents"] if e.get("name") == "process_name"
        ]
        assert {e["pid"] for e in meta} == {0, 1}
        assert len([e for e in meta if e["pid"] == 0]) == 1  # not duplicated
        injected = [e for e in meta if e["pid"] == 1][0]
        assert injected["args"]["name"] == "sat_tpu host p1"


# ---------------------------------------------------------------------------
# identity stamping + heartbeat nesting + the skew SLO
# ---------------------------------------------------------------------------


class TestFleetSurfaces:
    def test_bench_stamp_carries_process_identity(self):
        stamp = telemetry.bench_stamp()
        assert stamp["process_index"] == 0 and stamp["process_count"] >= 1

    def test_heartbeat_nests_fleet_gauges_and_identity(self, tmp_path):
        tel = Telemetry()
        tel.gauge("fleet/hosts_reporting", 2)
        tel.gauge("fleet/step_p95_skew", 3.2)
        tel.gauge("fleet/straggler_index", 1)
        hb = heartbeat.Heartbeat(str(tmp_path / "heartbeat.json"), 1.0, tel)
        payload = hb.payload()
        assert payload["process_index"] == 0
        assert payload["process_count"] >= 1
        assert payload["fleet"]["hosts_reporting"] == 2
        assert payload["fleet"]["step_p95_skew"] == 3.2
        assert payload["fleet"]["straggler_index"] == 1

    def test_gauge_ceiling_kind_burns_on_sustained_skew(self):
        tel = Telemetry()
        obj = slo.Objective(
            name="fleet_step_skew",
            kind="gauge_ceiling",
            target=1.5,
            source="fleet/step_p95_skew",
        )
        engine = slo.SLOEngine(tel, [obj])
        # absent gauge: no data, never burning
        result = engine.tick()["fleet_step_skew"]
        assert result["burning"] is False and result["measured_fast"] is None
        tel.gauge("fleet/step_p95_skew", 3.0)
        result = engine.tick()["fleet_step_skew"]
        assert result["burning"] is True
        assert result["measured_fast"] == 3.0 and result["burn_fast"] == 2.0
        tel.gauge("fleet/step_p95_skew", 1.2)
        assert engine.tick()["fleet_step_skew"]["burning"] is False

    def test_fleet_objective_gated_on_config(self, coco_fixture):
        base = coco_fixture["config"]
        on = base.replace(fleet_telemetry=True, straggler_factor=1.75)
        names = {o.name: o for o in slo.objectives_from_config(on, "train")}
        assert "fleet_step_skew" in names
        obj = names["fleet_step_skew"]
        assert obj.kind == "gauge_ceiling" and obj.target == 1.75
        assert obj.source == "fleet/step_p95_skew"
        off = base.replace(fleet_telemetry=False)
        assert "fleet_step_skew" not in {
            o.name for o in slo.objectives_from_config(off, "train")
        }


# ---------------------------------------------------------------------------
# e2e: runtime.train with the fleet plane + black box under fault injection
# (the satellite-3 shutdown-ordering regression)
# ---------------------------------------------------------------------------


def _cfg(coco_fixture, tmp_path, name, **kw):
    return coco_fixture["config"].replace(
        **{
            **SMALL_MODEL,
            "save_dir": str(tmp_path / name),
            "summary_dir": str(tmp_path / (name + "_s")),
            **kw,
        }
    )


class TestTrainIntegration:
    def test_sigterm_during_checkpoint_leaves_complete_bundle(
        self, coco_fixture, tmp_path, monkeypatch, bb_reset
    ):
        """A fault-injected SIGTERM at the checkpoint boundary must leave a
        postmortem bundle whose ring was flushed through the finalizer
        chain — the exit paths may not tear the ring down first."""
        cfg = _cfg(
            coco_fixture,
            tmp_path,
            "bbx",
            telemetry=True,
            blackbox=True,
            fleet_telemetry=True,
        )
        monkeypatch.setenv("SAT_FI_SIGTERM_AT_STEP", "4")
        state = runtime.train(cfg)
        assert int(state.step) == 4

        tdir = os.path.join(cfg.summary_dir, "telemetry")
        bundles = glob.glob(os.path.join(tdir, "postmortem_*"))
        assert len(bundles) == 1
        bundle = bundles[0]
        manifest = json.load(open(os.path.join(bundle, "manifest.json")))
        assert manifest["reason"] == "sigterm_during_checkpoint"
        assert manifest["signal"] == "SIGTERM"
        assert manifest["step"] == 4
        assert manifest["final_checkpoint"].endswith("4.npz")

        # the ring journaled the run and recorded the stop event
        segs = glob.glob(os.path.join(bundle, "blackbox", "seg_*.jsonl"))
        assert segs
        ring = [json.loads(line) for seg in segs for line in open(seg)]
        events = [r.get("event") for r in ring if r["kind"] == "event"]
        assert "train_start" in events and "sigterm_stop" in events
        snaps = [r for r in ring if r["kind"] == "snapshot"]
        assert snaps and snaps[-1]["step"] >= 1

        # the single-host fleet plane rode along: sidecar + merged view,
        # no straggler (one host), and the bundle copied both
        fleet_doc = json.load(open(os.path.join(bundle, "fleet.json")))
        assert fleet_doc["hosts_reporting"] == 1
        assert fleet_doc["straggler"] == {"verdict": False}
        assert os.path.isfile(os.path.join(bundle, "heartbeat_p0.json"))
        assert json.load(
            open(os.path.join(tdir, "fleet.json"))
        )["hosts"][0]["process_index"] == 0

        # the analyzer reads the bundle cold
        mod = _load_script("analyze_postmortem")
        summary = mod.summarize(bundle)
        assert "SIGTERM" in summary["probable_cause"]
        assert summary["run_id"] == manifest["run_id"]
