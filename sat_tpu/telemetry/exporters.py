"""Telemetry exporters: Chrome trace JSON, telemetry.jsonl, breakdown report.

Three output formats, one source (:class:`~sat_tpu.telemetry.spans.Telemetry`):

* :func:`export_chrome_trace` — trace-event JSON (``ph:"X"`` complete
  events, microsecond timestamps) loadable in Perfetto /
  ``chrome://tracing``, one track per recording thread;
* :func:`append_jsonl` — one JSON line per call (written at ``log_every``
  boundaries, alongside ``metrics.jsonl``) carrying the counters, gauges,
  and per-span running totals at that moment;
* :func:`step_breakdown` / :func:`format_breakdown` — the end-of-run
  per-phase step-time report (count, total, p50/p95/max) the CLI prints
  and saves as JSON.  Phases are the *disjoint* decomposition of a step;
  the residual between the step-total span and the phase sum is reported
  as the ``other`` phase, so the phase sum always reconstructs measured
  wall time (docs/OBSERVABILITY.md explains how to read it).

All writers degrade on failure (observability must never kill the run —
the SummaryWriter rule) and none of them touch jax.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Iterable, List, Optional

import numpy as np

from ..utils.fileio import atomic_write
from . import run_id


# ---------------------------------------------------------------------------
# Chrome trace-event JSON
# ---------------------------------------------------------------------------


def chrome_trace(tel, process_name: str = "sat_tpu host") -> Dict:
    """The trace-event document for ``tel``'s retained span window.

    Timestamps are microseconds since the recorder's anchor; the absolute
    anchor (unix seconds) rides in ``otherData`` for post-hoc alignment
    with ``metrics.jsonl``'s wall-clock stamps.
    """
    names, ids, t0s, durs, tids = tel.spans_snapshot()
    pid = os.getpid()
    events: List[Dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": process_name},
        }
    ]
    anchor = tel.anchor_ns
    for k in range(len(ids)):
        events.append(
            {
                "name": names[int(ids[k])],
                "cat": "host",
                "ph": "X",
                "pid": pid,
                "tid": int(tids[k]),
                "ts": (int(t0s[k]) - anchor) / 1e3,
                "dur": int(durs[k]) / 1e3,
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_id": run_id(),
            "anchor_unix": tel.anchor_unix,
            "counters": tel.counters(),
            "gauges": tel.gauges(),
        },
    }


def export_chrome_trace(tel, path: str) -> Optional[str]:
    """Write the Perfetto-loadable trace JSON atomically; returns the path
    (None when the write failed — reported, never raised)."""
    try:
        doc = chrome_trace(tel)
        atomic_write(path, "w", lambda f: json.dump(doc, f))
        return path
    except (OSError, ValueError) as e:
        print(
            f"sat_tpu: telemetry trace export failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return None


# ---------------------------------------------------------------------------
# periodic telemetry.jsonl
# ---------------------------------------------------------------------------


def snapshot_row(tel, step: Optional[int] = None) -> Dict:
    """One JSON-able snapshot of the recorder: counters, gauges, and
    per-span running (count, total ms, max ms) — same stamp fields as
    ``metrics.jsonl`` rows so the two join on (run_id, step/time)."""
    spans = {
        name: {
            "count": c,
            "total_ms": round(total / 1e6, 3),
            "max_ms": round(mx / 1e6, 3),
        }
        for name, (c, total, mx) in tel.aggregates().items()
    }
    row: Dict = {
        "run_id": run_id(),
        "wall_time": round(time.time(), 6),
        "mono_ns": time.perf_counter_ns(),
        "counters": tel.counters(),
        "gauges": tel.gauges(),
        "spans": spans,
    }
    if step is not None:
        row["step"] = int(step)
    return row


def append_jsonl(tel, path: str, step: Optional[int] = None) -> None:
    """Append one snapshot row; failures degrade to a one-line warning
    (tracked by the ``telemetry/export_errors`` counter)."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(snapshot_row(tel, step)) + "\n")
    except (OSError, ValueError) as e:
        tel.count("telemetry/export_errors")
        print(
            f"sat_tpu: telemetry.jsonl append failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )


# ---------------------------------------------------------------------------
# step-time breakdown
# ---------------------------------------------------------------------------


def _stats(count: int, total_ns: int, max_ns: int, samples_ns: np.ndarray) -> Dict:
    out = {
        "count": int(count),
        "total_s": round(total_ns / 1e9, 6),
        "mean_ms": round(total_ns / count / 1e6, 4) if count else 0.0,
        "max_ms": round(max_ns / 1e6, 4),
    }
    if samples_ns.size:
        p50, p95 = np.percentile(samples_ns, [50, 95])
        out["p50_ms"] = round(float(p50) / 1e6, 4)
        out["p95_ms"] = round(float(p95) / 1e6, 4)
    else:
        out["p50_ms"] = out["p95_ms"] = None
    return out


def step_breakdown(
    tel,
    step_span: str,
    phases: Iterable[str],
    nested: Iterable[str] = (),
) -> Optional[Dict]:
    """Per-phase step-time report.

    ``step_span`` is the whole-iteration span; ``phases`` are its disjoint
    sub-intervals (their durations never overlap, so their sum plus the
    computed ``other`` residual equals the step total).  ``nested`` names
    spans that occur INSIDE a phase (e.g. ``feed/device_put`` inside the
    data wait) — reported for visibility but excluded from the sum.
    Returns None when no steps were recorded.
    """
    agg = tel.aggregates()
    if step_span not in agg:
        return None
    steps, wall_ns, max_ns = agg[step_span]
    report: Dict = {
        "run_id": run_id(),
        "step_span": step_span,
        "steps": steps,
        "wall_s": round(wall_ns / 1e9, 6),
        "steps_per_s": round(steps / (wall_ns / 1e9), 3) if wall_ns else 0.0,
        "step": _stats(steps, wall_ns, max_ns, tel.durations_ns(step_span)),
    }
    accounted = 0
    out_phases: Dict[str, Dict] = {}
    for name in phases:
        if name not in agg:
            continue
        c, total, mx = agg[name]
        accounted += total
        out_phases[name] = _stats(c, total, mx, tel.durations_ns(name))
    other_ns = max(0, wall_ns - accounted)
    out_phases["other"] = {
        "count": steps,
        "total_s": round(other_ns / 1e9, 6),
        "mean_ms": round(other_ns / steps / 1e6, 4) if steps else 0.0,
        "max_ms": None,
        "p50_ms": None,
        "p95_ms": None,
    }
    report["phases"] = out_phases
    report["phase_total_s"] = round((accounted + other_ns) / 1e9, 6)
    report["nested"] = {
        name: _stats(*agg[name], tel.durations_ns(name))
        for name in nested
        if name in agg
    }
    report["counters"] = tel.counters()
    return report


def format_breakdown(report: Dict) -> str:
    """The human-readable report the CLI prints at end of run."""
    lines = [
        f"step-time breakdown ({report['step_span']}): "
        f"{report['steps']} steps in {report['wall_s']:.3f} s wall "
        f"({report['steps_per_s']:.2f} steps/s)",
        f"  {'phase':<24} {'total_s':>9} {'share':>7} "
        f"{'p50_ms':>9} {'p95_ms':>9} {'max_ms':>9}",
    ]
    wall = report["wall_s"] or 1.0

    def fmt(v):
        return f"{v:9.3f}" if isinstance(v, (int, float)) else f"{'-':>9}"

    for name, st in report["phases"].items():
        share = 100.0 * st["total_s"] / wall
        lines.append(
            f"  {name:<24} {st['total_s']:9.3f} {share:6.1f}% "
            f"{fmt(st['p50_ms'])} {fmt(st['p95_ms'])} {fmt(st['max_ms'])}"
        )
    for name, st in report.get("nested", {}).items():
        lines.append(
            f"  ({name}: nested)        {st['total_s']:9.3f}         "
            f"{fmt(st['p50_ms'])} {fmt(st['p95_ms'])} {fmt(st['max_ms'])}"
        )
    return "\n".join(lines)


def save_breakdown(report: Dict, path: str) -> Optional[str]:
    try:
        atomic_write(path, "w", lambda f: json.dump(report, f, indent=2))
        return path
    except (OSError, ValueError) as e:
        print(
            f"sat_tpu: breakdown export failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return None
