"""Mesh-path runtime: SPMD training through runtime.train on the 8-device
CPU mesh, per-host data sharding, distributed checkpoint gather."""

import numpy as np
import jax
import pytest

from sat_tpu import runtime
from sat_tpu.data.dataset import DataSet
from sat_tpu.parallel.data import process_local_dataset
from sat_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    state_to_flat,
)
from sat_tpu.train.step import create_train_state

from tests.test_runtime import SMALL_MODEL


def test_train_on_mesh_end_to_end(coco_fixture, tmp_path):
    """runtime.train with mesh_shape=(4,2): dp over batch, tp over the
    vocab dims, checkpoint written from the sharded state and restorable
    into a plain single-device state."""
    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "mesh_shape": (4, 2)}
    )
    state = runtime.train(config)
    assert int(np.asarray(state.step)) == 6

    ckpt = latest_checkpoint(config.save_dir)
    assert ckpt is not None and ckpt.endswith("6.npz")

    plain = config.replace(mesh_shape=(1, 1))
    fresh = create_train_state(jax.random.PRNGKey(9), plain)
    restored, count = restore_checkpoint(fresh, model_file=ckpt)
    assert count > 0

    want = state_to_flat(state)
    got = state_to_flat(restored)
    assert set(want) == set(got)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], err_msg=k, rtol=1e-6)

    # and the restored single-device state evaluates (full path reuse)
    scores = runtime.evaluate(config.replace(mesh_shape=(1, 1)), state=restored)
    assert "Bleu_4" in scores


def test_mesh_and_single_device_training_agree(coco_fixture, tmp_path):
    """Same data, same init, same dropout keys: the dp+tp mesh run's loss
    trajectory must track the single-device run.  (Bitwise param equality
    is NOT expected — psum/matmul reduction order differs and Adam
    amplifies that on near-zero params; single-step numeric parity is
    pinned separately in test_parallel.py.)"""
    import json
    import os

    base = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "num_epochs": 1,
           "summary_dir": str(tmp_path / "s1"),
           "save_dir": str(tmp_path / "m1"),
           "save_period": 0}
    )
    runtime.train(base.replace(mesh_shape=(1, 1)), seed=0)
    runtime.train(
        base.replace(
            mesh_shape=(2, 2),
            summary_dir=str(tmp_path / "s2"),
            save_dir=str(tmp_path / "m2"),
        ),
        seed=0,
    )

    def losses(d):
        rows = [json.loads(x) for x in open(os.path.join(d, "metrics.jsonl"))]
        return np.array([r["total_loss"] for r in rows])

    a, b = losses(str(tmp_path / "s1")), losses(str(tmp_path / "s2"))
    assert a.shape == b.shape and len(a) == 6
    np.testing.assert_allclose(b, a, rtol=5e-2)


def test_mesh_eval_matches_single_device(coco_fixture, tmp_path):
    """decode_dataset routes through make_parallel_beam_search on a mesh;
    parallel eval must produce the SAME captions and scores as the
    single-device path end-to-end (VERDICT r1 item 5)."""
    base = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "eval_result_file": str(tmp_path / "res1.json"),
           "beam_size": 2}
    )
    state = runtime.train(base.replace(mesh_shape=(1, 1)))

    single = runtime.evaluate(base.replace(mesh_shape=(1, 1)), state=state)
    mesh = runtime.evaluate(
        base.replace(mesh_shape=(2, 1), eval_result_file=str(tmp_path / "res2.json")),
        state=state,
    )
    assert single.keys() == mesh.keys()
    for k in single:
        np.testing.assert_allclose(mesh[k], single[k], rtol=1e-6, err_msg=k)

    import json
    r1 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res1.json"))}
    r2 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res2.json"))}
    assert r1 == r2 and len(r1) > 0


def test_process_local_dataset_slices_disjointly():
    ids = np.arange(24)
    files = np.array([f"f{i}.jpg" for i in ids])
    w = np.arange(24 * 5).reshape(24, 5)
    m = np.ones((24, 5), np.float32)
    global_ds = DataSet(ids, files, 8, w, m, is_train=True, shuffle=False)

    shards = [
        process_local_dataset(global_ds, process_index=p, process_count=4)
        for p in range(4)
    ]
    seen = np.concatenate([s.image_ids for s in shards])
    assert sorted(seen.tolist()) == ids.tolist()          # disjoint cover
    for s in shards:
        assert s.batch_size == 2                          # 8 global / 4 hosts
        assert s.num_batches == global_ds.num_batches     # same step count

    with pytest.raises(ValueError, match="not divisible"):
        process_local_dataset(global_ds, process_index=0, process_count=3)


def test_process_local_dataset_equalizes_uneven_shards():
    """25 samples / 4 hosts: shards truncate to a common length so every
    host runs the same number of synchronous steps."""
    ids = np.arange(25)
    files = np.array([f"f{i}.jpg" for i in ids])
    global_ds = DataSet(ids, files, 8)
    shards = [
        process_local_dataset(global_ds, process_index=p, process_count=4)
        for p in range(4)
    ]
    assert {s.count for s in shards} == {6}
    assert {s.num_batches for s in shards} == {3}
