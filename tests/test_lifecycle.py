"""Model-lifecycle subsystem tests (docs/SERVING.md, lifecycle section).

Pins the contracts the zero-downtime lifecycle ISSUE promises:

* canary routing is a deterministic sticky hash (same id → same slot,
  monotone in the fraction) and the divergence gauge is a bounded EWMA;
* the rejection ledger is exactly-once and a rejected step is never
  re-canaried (reloader skip + /reload 409);
* the reloader fires once per distinct LAST_GOOD step and ignores
  unchanged/current/rejected pointers;
* the controller state machine: auto-promote at window end, manual
  hold, operator promote/rollback, load-failure → ledger rejection,
  SLO burn → rollback — all driven with stub engines/batchers (jax-free);
* the loader fails fast on vocab-fingerprint mismatch and on partial
  (geometry-drifted) checkpoints;
* end-to-end over HTTP in BOTH serve modes: reload → canary → rollback
  leaves the incumbent's answers bitwise identical, reload → canary →
  promote switches captions — with ZERO recompiles and ZERO 5xx across
  the full cycle, and the swap blackout measured.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from sat_tpu import runtime, telemetry
from sat_tpu.data.vocabulary import Vocabulary, vocab_fingerprint
from sat_tpu.lifecycle import canary
from sat_tpu.lifecycle.controller import (
    STATE_CODES,
    STATES,
    LifecycleController,
)
from sat_tpu.lifecycle.reloader import Reloader
from sat_tpu.resilience import lineage

from tests.test_runtime import SMALL_MODEL


# ---------------------------------------------------------------------------
# canary routing hash + divergence (pure host math)
# ---------------------------------------------------------------------------


def test_assign_slot_deterministic_and_sticky():
    ids = [f"req-{i}" for i in range(300)]
    first = [canary.assign_slot(rid, 0.3) for rid in ids]
    # sticky: the same id maps to the same slot every time
    assert first == [canary.assign_slot(rid, 0.3) for rid in ids]
    # both slots are actually used at an interior fraction
    assert canary.CANARY in first and canary.INCUMBENT in first


def test_assign_slot_monotone_in_fraction():
    """A request canaried at fraction f stays canaried at any f' > f —
    raising the fraction only ADDS traffic to the candidate, it never
    flaps an already-canaried client back."""
    for i in range(300):
        rid = f"req-{i}"
        if canary.assign_slot(rid, 0.2) == canary.CANARY:
            assert canary.assign_slot(rid, 0.5) == canary.CANARY
            assert canary.assign_slot(rid, 0.9) == canary.CANARY


def test_assign_slot_edges():
    assert canary.assign_slot("", 0.5) == canary.INCUMBENT
    assert canary.assign_slot(None, 1.0) == canary.INCUMBENT
    assert canary.assign_slot("abc", 0.0) == canary.INCUMBENT
    assert canary.assign_slot("abc", -1.0) == canary.INCUMBENT
    assert canary.assign_slot("abc", 1.0) == canary.CANARY


def test_assign_slot_fraction_is_calibrated():
    """The hash is uniform enough that the observed canary share tracks
    the configured fraction."""
    n = 4000
    hits = sum(
        canary.assign_slot(f"cal-{i}", 0.25) == canary.CANARY
        for i in range(n)
    )
    assert abs(hits / n - 0.25) < 0.05


def test_caption_divergence_jaccard():
    assert canary.caption_divergence("a cat sat", "a cat sat") == 0.0
    assert canary.caption_divergence("a b", "c d") == 1.0
    assert canary.caption_divergence("", "") == 0.0
    d = canary.caption_divergence("a cat on mat", "a dog on mat")
    assert 0.0 < d < 1.0


def test_divergence_gauge_ewma_bounded():
    g = canary.DivergenceGauge(alpha=0.5)
    assert g.value is None and g.samples == 0
    assert g.update(1.0) == 1.0
    assert g.update(0.0) == 0.5
    assert g.samples == 2
    # out-of-range inputs clamp instead of poisoning the EWMA
    g.update(7.0)
    assert 0.0 <= g.value <= 1.0


# ---------------------------------------------------------------------------
# rejection ledger (resilience.lineage)
# ---------------------------------------------------------------------------


def test_rejection_ledger_exactly_once(tmp_path):
    d = str(tmp_path)
    assert lineage.rejected_steps(d) == set()
    assert not lineage.is_rejected(d, 5)
    assert lineage.mark_rejected(d, 5, "canary slo burning") is True
    # second mark of the same step writes nothing (exactly-once)
    assert lineage.mark_rejected(d, 5, "again") is False
    assert lineage.rejected_steps(d) == {5}
    assert lineage.is_rejected(d, 5)
    assert not lineage.is_rejected(d, 6)
    # the ledger file holds ONE line for step 5
    lines = open(os.path.join(d, lineage.REJECTED_NAME)).read().splitlines()
    assert len([l for l in lines if l.strip()]) == 1
    assert json.loads(lines[0])["reason"] == "canary slo burning"


def test_rejection_ledger_skips_torn_lines(tmp_path):
    d = str(tmp_path)
    lineage.mark_rejected(d, 3, "bad")
    with open(os.path.join(d, lineage.REJECTED_NAME), "a") as f:
        f.write('{"step": 9, "rea')  # torn tail from a crash mid-append
    assert lineage.rejected_steps(d) == {3}
    # a later full append still lands
    assert lineage.mark_rejected(d, 9, "bad too") is True
    assert lineage.rejected_steps(d) == {3, 9}


# ---------------------------------------------------------------------------
# reloader poll (unit: real lineage files, stub callback)
# ---------------------------------------------------------------------------


def _reloader(tmp_path, fired, current=None):
    return Reloader(
        str(tmp_path),
        interval_s=0.05,
        on_new=lambda step, path: fired.append((step, path)),
        current_step=current,
    )


def test_reloader_fires_once_per_step(tmp_path):
    fired = []
    r = _reloader(tmp_path, fired)
    assert r.poll_once() is None  # no pointer yet
    lineage.mark_last_good(str(tmp_path), 7)
    assert r.poll_once() == 7
    assert fired == [(7, os.path.join(str(tmp_path), "7.npz"))]
    # unchanged pointer: every later poll is a no-op
    assert r.poll_once() is None
    assert r.poll_once() is None
    assert len(fired) == 1
    # pointer moves → exactly one more fire
    lineage.mark_last_good(str(tmp_path), 9)
    assert r.poll_once() == 9
    assert len(fired) == 2


def test_reloader_skips_currently_served_step(tmp_path):
    fired = []
    r = _reloader(tmp_path, fired, current=lambda: 7)
    lineage.mark_last_good(str(tmp_path), 7)
    assert r.poll_once() is None
    assert fired == []
    # and it does not re-examine the same step forever
    assert r.poll_once() is None


def test_reloader_never_recanaries_rejected_step(tmp_path):
    fired = []
    r = _reloader(tmp_path, fired)
    lineage.mark_rejected(str(tmp_path), 11, "failed canary")
    lineage.mark_last_good(str(tmp_path), 11)
    assert r.poll_once() is None
    assert fired == []
    # a NEW (un-rejected) step still fires
    lineage.mark_last_good(str(tmp_path), 12)
    assert r.poll_once() == 12
    assert fired == [(12, os.path.join(str(tmp_path), "12.npz"))]


def test_reloader_thread_polls_on_interval(tmp_path):
    fired = []
    r = _reloader(tmp_path, fired)
    r.start()
    try:
        lineage.mark_last_good(str(tmp_path), 21)
        deadline = time.time() + 5.0
        while not fired and time.time() < deadline:
            time.sleep(0.02)
        assert fired == [(21, os.path.join(str(tmp_path), "21.npz"))]
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# controller state machine (stub engine/batcher — jax-free)
# ---------------------------------------------------------------------------


class _StubEngine:
    def __init__(self, step=10):
        self.step = step
        self._cand = None
        self.encoder_quant = "off"

    @property
    def candidate_step(self):
        return self._cand

    def install_candidate(self, variables, decoder_params, step, source):
        self._cand = int(step)

    def promote_candidate(self):
        assert self._cand is not None
        self.step, self._cand = self._cand, None
        return self.step

    def clear_candidate(self):
        self._cand = None


class _StubBatcher:
    """Mimics the batcher control plane: ``swap`` promotes the engine
    (the real ``_apply_control`` does) and reports a blackout."""

    def __init__(self, engine):
        self.engine = engine
        self.calls = []

    def lifecycle_control(self, action, timeout=120.0):
        self.calls.append(action)
        if action == "swap":
            return {
                "step": self.engine.promote_candidate(),
                "blackout_ms": 1.25,
            }
        return {"ok": True}

    def submit(self, image, **kw):
        raise RuntimeError("no shadow traffic in stub tests")


def _controller(tmp_path, monkeypatch, cand_step=11, **cfg_kw):
    from sat_tpu.config import Config
    from sat_tpu.lifecycle import controller as controller_mod

    base = dict(
        save_dir=str(tmp_path),
        canary_window_s=0.2,
        promote_policy="auto",
        canary_shadow_rate=0.0,
        model_reload=0.0,
    )
    base.update(cfg_kw)
    config = Config(**base)
    eng = _StubEngine()
    bat = _StubBatcher(eng)
    monkeypatch.setattr(
        controller_mod,
        "load_candidate",
        lambda engine, cfg, path: {
            "variables": {},
            "decoder_params": {},
            "step": cand_step,
            "source": path,
        },
    )
    return LifecycleController(config, eng, bat), eng, bat


def test_controller_auto_promotes_after_clean_window(tmp_path, monkeypatch):
    ctl, eng, bat = _controller(tmp_path, monkeypatch)
    assert ctl.state == "IDLE"
    assert ctl.begin_cycle(11, "/ckpt/11.npz") is True
    # a second cycle while one is in flight is refused, not queued
    assert ctl.begin_cycle(12, "/ckpt/12.npz") is False
    assert ctl._cycle_done.wait(timeout=30.0)
    assert ctl.state == "IDLE"
    assert eng.step == 11 and eng.candidate_step is None
    assert bat.calls == ["arm_canary", "swap"]
    last = ctl.snapshot()["last_cycle"]
    assert last["outcome"] == "promoted" and last["step"] == 11
    assert last["blackout_ms"] == 1.25
    assert lineage.rejected_steps(str(tmp_path)) == set()


def test_controller_manual_policy_holds_then_promotes(tmp_path, monkeypatch):
    ctl, eng, bat = _controller(
        tmp_path, monkeypatch, promote_policy="manual", canary_window_s=0.05
    )
    ctl.begin_cycle(11, "/ckpt/11.npz")
    time.sleep(0.5)  # window long elapsed; manual policy must HOLD
    assert ctl.state == "CANARY"
    assert eng.step == 10
    ok, detail = ctl.promote()
    assert ok, detail
    assert eng.step == 11 and ctl.state == "IDLE"
    # nothing left to promote
    ok, detail = ctl.promote()
    assert not ok and "state=IDLE" in detail


def test_controller_operator_rollback_rejects_exactly_once(
    tmp_path, monkeypatch
):
    ctl, eng, bat = _controller(
        tmp_path, monkeypatch, promote_policy="manual", canary_window_s=60.0
    )
    ctl.begin_cycle(11, "/ckpt/11.npz")
    deadline = time.time() + 10.0
    while ctl.state != "CANARY" and time.time() < deadline:
        time.sleep(0.01)
    ok, detail = ctl.rollback("operator said no")
    assert ok, detail
    assert ctl.state == "IDLE"
    assert eng.step == 10 and eng.candidate_step is None
    assert "disarm_canary" in bat.calls and "swap" not in bat.calls
    assert lineage.rejected_steps(str(tmp_path)) == {11}
    lines = open(
        os.path.join(str(tmp_path), lineage.REJECTED_NAME)
    ).read().splitlines()
    assert len([l for l in lines if l.strip()]) == 1


def test_controller_load_failure_lands_in_ledger(tmp_path, monkeypatch):
    from sat_tpu.lifecycle import controller as controller_mod
    from sat_tpu.train.checkpoint import VocabMismatchError

    ctl, eng, bat = _controller(tmp_path, monkeypatch)

    def boom(engine, cfg, path):
        raise VocabMismatchError("vocab mismatch (got 30 words ...)")

    monkeypatch.setattr(controller_mod, "load_candidate", boom)
    ctl.begin_cycle(11, "/ckpt/11.npz")
    assert ctl._cycle_done.wait(timeout=30.0)
    assert ctl.state == "IDLE"
    assert eng.step == 10
    # the candidate never touched traffic: no arm, and the step is
    # permanently rejected with the raising error recorded
    assert "arm_canary" not in bat.calls
    assert lineage.is_rejected(str(tmp_path), 11)
    ledger = open(
        os.path.join(str(tmp_path), lineage.REJECTED_NAME)
    ).read()
    assert "VocabMismatchError" in ledger


def test_controller_slo_burn_rolls_back(tmp_path, monkeypatch):
    tel = telemetry.enable(capacity=4096)
    try:
        ctl, eng, bat = _controller(
            tmp_path,
            monkeypatch,
            canary_window_s=30.0,
            canary_divergence_max=0.5,
        )
        ctl.begin_cycle(11, "/ckpt/11.npz")
        deadline = time.time() + 10.0
        while ctl.state != "CANARY" and time.time() < deadline:
            time.sleep(0.01)
        assert ctl.state == "CANARY"
        # shadow-pair divergence crosses the ceiling: the gauge_ceiling
        # objective burns instantly and the controller rolls back long
        # before the 30 s window would have promoted
        tel.gauge("lifecycle/caption_divergence", 0.9)
        assert ctl._cycle_done.wait(timeout=30.0)
        assert ctl.state == "IDLE"
        assert eng.step == 10 and eng.candidate_step is None
        assert lineage.is_rejected(str(tmp_path), 11)
        last = ctl.snapshot()["last_cycle"]
        assert last["outcome"] == "rolled_back"
        assert "canary_divergence" in last["why"]
    finally:
        telemetry.disable()


def test_controller_request_reload_guards(tmp_path, monkeypatch):
    ctl, eng, bat = _controller(tmp_path, monkeypatch)
    # no pointer at all
    ok, detail = ctl.request_reload()
    assert not ok and "LAST_GOOD" in detail
    # pointer at the serving step
    lineage.mark_last_good(str(tmp_path), 10)
    ok, detail = ctl.request_reload()
    assert not ok and "already serving" in detail
    # pointer at a rejected step
    lineage.mark_rejected(str(tmp_path), 15, "failed before")
    lineage.mark_last_good(str(tmp_path), 15)
    ok, detail = ctl.request_reload()
    assert not ok and "rejection ledger" in detail


def test_state_codes_cover_all_states():
    assert set(STATE_CODES) == set(STATES)
    assert STATE_CODES["IDLE"] == 0  # the gauge's resting value


# ---------------------------------------------------------------------------
# end-to-end: train a tiny model, run real reload→canary→verdict cycles
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def lifecycle_env(coco_fixture, tmp_path_factory):
    """Tiny trained model + warmed engine + lifecycle-enabled config.

    One engine serves every e2e test in this module (promotes mutate its
    step — tests read ``engine.step`` at entry, never assume the trained
    base)."""
    from sat_tpu.serve.engine import ServeEngine, load_serving_state

    root = tmp_path_factory.mktemp("lifecycle")
    train_config = coco_fixture["config"].replace(
        **SMALL_MODEL,
        save_dir=os.path.join(str(root), "models"),
        summary_dir=os.path.join(str(root), "summary"),
    )
    runtime.train(train_config)

    config = train_config.replace(
        phase="serve",
        beam_size=2,
        serve_buckets=(1, 4),
        serve_max_batch=4,
        serve_max_wait_ms=30.0,
        serve_queue_depth=8,
        heartbeat_interval=0.2,
        # lifecycle: manual policy so the tests drive every verdict
        # deterministically over HTTP; no background poller (POST /reload)
        model_reload=0.0,
        canary_fraction=0.5,
        canary_window_s=60.0,
        promote_policy="manual",
        canary_shadow_rate=0.0,
    )
    tel = telemetry.enable(capacity=16384)
    runtime._install_compile_listener()
    vocabulary = Vocabulary(config.vocabulary_size, config.vocabulary_file)
    state, source = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    yield {
        "config": config,
        "engine": engine,
        "tel": tel,
        "base_step": engine.step,
    }
    telemetry.disable()


def _stage_candidate(env, step, jitter=0.0, vocab=None):
    """Write a geometry-identical candidate checkpoint (the trained
    params, decoder floats nudged by ``jitter``) + sidecar, and point
    LAST_GOOD at it."""
    config = env["config"]
    src = os.path.join(config.save_dir, f"{env['base_step']}.npz")
    flat = dict(np.load(src))
    if jitter:
        for k in list(flat):
            if k.startswith("params/decoder/") and flat[k].dtype.kind == "f":
                flat[k] = (flat[k] + np.asarray(jitter, flat[k].dtype))
    flat["global_step"] = np.asarray(step, np.int64)
    path = os.path.join(config.save_dir, f"{step}.npz")
    with open(path, "wb") as f:
        np.savez(f, **flat)
    if vocab is None:
        vocab = vocab_fingerprint(
            config.vocabulary_file, config.vocabulary_size
        )
    lineage.write_sidecar(path, vocab=vocab)
    lineage.mark_last_good(config.save_dir, step)
    return path


def _jpeg(env):
    d = env["config"].eval_image_dir
    f = sorted(os.listdir(d))[0]
    return open(os.path.join(d, f), "rb").read()


def _http(port, method, path, body=None, headers=None, timeout=240):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=body,
        method=method,
        headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _caption(port, jpeg, rid):
    return _http(
        port, "POST", "/caption", body=jpeg,
        headers={"Content-Type": "image/jpeg", "X-Request-Id": rid},
    )


def _admin(port, action):
    return _http(port, "POST", f"/{action}", body=b"")


def _wait_lifecycle_state(port, want, timeout=60.0):
    deadline = time.time() + timeout
    stats = {}
    while time.time() < deadline:
        _, stats = _http(port, "GET", "/stats")
        if stats["lifecycle"]["state"] == want:
            return stats
        time.sleep(0.1)
    raise AssertionError(
        f"lifecycle never reached {want}: {stats.get('lifecycle')}"
    )


def _slot_ids(fraction, n_inc=2, n_can=1):
    inc, can = [], []
    i = 0
    while len(inc) < n_inc or len(can) < n_can:
        rid = f"lc-{i}"
        i += 1
        if canary.assign_slot(rid, fraction) == canary.CANARY:
            can.append(rid)
        else:
            inc.append(rid)
    return inc[:n_inc], can[:n_can]


def test_e2e_continuous_reject_then_promote(lifecycle_env):
    """The full invariant, continuous mode: reload → canary → rollback
    leaves incumbent answers bitwise identical and the step rejected
    exactly once (never re-canaried); reload → canary → promote switches
    the served model — zero recompiles and zero 5xx across both cycles,
    swap blackout measured."""
    from sat_tpu.serve.server import CaptionServer

    env = lifecycle_env
    engine, tel = env["engine"], env["tel"]
    config = env["config"].replace(
        serve_mode="continuous", serve_slot_pages=2, serve_page_width=2
    )
    server = CaptionServer(config, engine, port=0).start()
    try:
        port = server.port
        jpeg = _jpeg(env)
        inc_ids, can_ids = _slot_ids(config.canary_fraction)
        base_step = engine.step
        compiles0 = tel.counters().get("jax/compiles", 0)

        # baseline: everything incumbent while IDLE, canary-hash ids too
        baseline = {}
        for rid in inc_ids + can_ids:
            status, p = _caption(port, jpeg, rid)
            assert status == 200
            assert p["slot"] == "incumbent"
            assert p["model_step"] == base_step
            baseline[rid] = p["captions"]

        # ---- cycle 1: canary, then operator rollback --------------------
        s_bad = env["base_step"] + 1000
        _stage_candidate(env, s_bad, jitter=1e-3)
        status, body = _admin(port, "reload")
        assert status == 200, body
        stats = _wait_lifecycle_state(port, "CANARY")
        assert stats["lifecycle"]["candidate_step"] == s_bad
        # healthz reports the canary from the cheap poll
        _, hz = _http(port, "GET", "/healthz")
        assert hz["lifecycle_state"] == "CANARY"
        assert hz["candidate_step"] == s_bad

        status, p = _caption(port, jpeg, can_ids[0])
        assert status == 200
        assert p["slot"] == "canary" and p["model_step"] == s_bad
        status, p = _caption(port, jpeg, inc_ids[0])
        assert status == 200
        assert p["slot"] == "incumbent" and p["model_step"] == base_step

        status, body = _admin(port, "rollback")
        assert status == 200, body
        assert body["state"] == "IDLE"
        assert lineage.is_rejected(config.save_dir, s_bad)

        # bitwise parity: the incumbent answers EXACTLY as before the
        # rejected canary (same captions, same log probs)
        for rid in inc_ids + can_ids:
            status, p = _caption(port, jpeg, rid)
            assert status == 200
            assert p["model_step"] == base_step
            assert p["captions"] == baseline[rid]

        # the rejected step is never re-canaried
        status, body = _admin(port, "reload")
        assert status == 409, body
        assert "rejection ledger" in body["detail"]
        ledger = lineage.rejected_steps(config.save_dir)
        assert s_bad in ledger

        # ---- cycle 2: canary, then operator promote ---------------------
        s_good = env["base_step"] + 2000
        _stage_candidate(env, s_good, jitter=2e-3)
        status, body = _admin(port, "reload")
        assert status == 200, body
        _wait_lifecycle_state(port, "CANARY")
        status, body = _admin(port, "promote")
        assert status == 200, body
        assert body["model_step"] == s_good
        status, p = _caption(port, jpeg, inc_ids[0])
        assert status == 200 and p["model_step"] == s_good

        # ---- the invariant ----------------------------------------------
        _, stats = _http(port, "GET", "/stats")
        assert stats["compiles_since_ready"] == 0
        assert tel.counters().get("jax/compiles", 0) == compiles0
        assert tel.counters().get("serve/http_5xx", 0) == 0
        last = stats["lifecycle"]["last_cycle"]
        assert last["outcome"] == "promoted" and last["step"] == s_good
        assert last["blackout_ms"] >= 0.0
        assert tel.gauges().get("lifecycle/swap_blackout_ms") is not None
        assert stats["lifecycle"]["rejected_steps"] == [s_bad]
        # exactly-once in the ledger file too
        lines = open(
            os.path.join(config.save_dir, lineage.REJECTED_NAME)
        ).read().splitlines()
        assert len([l for l in lines if l.strip()]) == 1
    finally:
        server.shutdown()


def test_e2e_batch_mode_cycle_zero_recompiles(lifecycle_env):
    """Batch mode rides the same machine: reload → canary request hits
    the candidate through the SAME warmed executables (params are
    runtime args), promote flips between dispatches — zero recompiles."""
    from sat_tpu.serve.server import CaptionServer

    env = lifecycle_env
    engine, tel = env["engine"], env["tel"]
    server = CaptionServer(env["config"], engine, port=0).start()
    try:
        port = server.port
        jpeg = _jpeg(env)
        inc_ids, can_ids = _slot_ids(env["config"].canary_fraction)
        base_step = engine.step
        compiles0 = tel.counters().get("jax/compiles", 0)

        s_new = env["base_step"] + 3000
        _stage_candidate(env, s_new, jitter=3e-3)
        status, body = _admin(port, "reload")
        assert status == 200, body
        _wait_lifecycle_state(port, "CANARY")
        status, p = _caption(port, jpeg, can_ids[0])
        assert status == 200
        assert p["slot"] == "canary" and p["model_step"] == s_new
        status, p = _caption(port, jpeg, inc_ids[0])
        assert status == 200
        assert p["slot"] == "incumbent" and p["model_step"] == base_step

        status, body = _admin(port, "promote")
        assert status == 200, body
        status, p = _caption(port, jpeg, inc_ids[0])
        assert status == 200 and p["model_step"] == s_new

        assert tel.counters().get("jax/compiles", 0) == compiles0
        _, stats = _http(port, "GET", "/stats")
        assert stats["compiles_since_ready"] == 0
    finally:
        server.shutdown()


def test_loader_vocab_mismatch_fails_fast(lifecycle_env):
    """A candidate attested against a different vocabulary raises
    VocabMismatchError BEFORE any device memory is spent."""
    from sat_tpu.lifecycle.loader import load_candidate
    from sat_tpu.train.checkpoint import VocabMismatchError

    env = lifecycle_env
    step = env["base_step"] + 4000
    path = _stage_candidate(
        env, step, vocab={"sha256": "0" * 64, "size": 7}
    )
    with pytest.raises(VocabMismatchError):
        load_candidate(env["engine"], env["config"], path)


def test_loader_rejects_partial_checkpoint(lifecycle_env):
    """Full-coverage placement: a checkpoint missing decoder tensors
    (geometry drift / truncated write) is rejected, not half-loaded."""
    from sat_tpu.lifecycle.loader import load_candidate

    env = lifecycle_env
    config = env["config"]
    step = env["base_step"] + 5000
    src = os.path.join(config.save_dir, f"{env['base_step']}.npz")
    flat = dict(np.load(src))
    dropped = [k for k in flat if k.startswith("params/decoder/")][0]
    del flat[dropped]
    flat["global_step"] = np.asarray(step, np.int64)
    path = os.path.join(config.save_dir, f"{step}.npz")
    with open(path, "wb") as f:
        np.savez(f, **flat)
    lineage.write_sidecar(
        path,
        vocab=vocab_fingerprint(
            config.vocabulary_file, config.vocabulary_size
        ),
    )
    with pytest.raises(ValueError, match="covers"):
        load_candidate(env["engine"], env["config"], path)
