"""Model layer tests: encoders, decoder math, losses, train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sat_tpu.config import Config
from sat_tpu.models import (
    DecoderState,
    attend,
    decoder_step,
    init_decoder_params,
    init_state,
    lstm_step,
    teacher_forced_decode,
)
from sat_tpu.models.captioner import compute_loss, init_variables
from sat_tpu.nn.layers import regularization_loss
from sat_tpu.train import create_train_state, make_jit_train_step


def tiny_config(**kw) -> Config:
    base = dict(
        cnn="vgg16",
        vocabulary_size=50,
        dim_embedding=16,
        num_lstm_units=24,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        max_caption_length=8,
        batch_size=4,
        compute_dtype="float32",
    )
    base.update(kw)
    return Config(**base)


def tiny_contexts_batch(cfg, rng_seed=0):
    rng = np.random.default_rng(rng_seed)
    B, T = cfg.batch_size, cfg.max_caption_length
    contexts = jnp.asarray(rng.normal(size=(B, cfg.num_ctx, cfg.dim_ctx)), jnp.float32)
    sentences = jnp.asarray(rng.integers(1, cfg.vocabulary_size, (B, T)), jnp.int32)
    masks = np.ones((B, T), np.float32)
    masks[:, T - 2 :] = 0.0
    return {"contexts": contexts, "word_idxs": sentences, "masks": jnp.asarray(masks)}


class TestLSTM:
    def test_matches_manual_numpy(self):
        H, I = 4, 3
        rng = np.random.default_rng(0)
        kernel = rng.normal(size=(I + H, 4 * H)).astype(np.float32)
        bias = rng.normal(size=(4 * H,)).astype(np.float32)
        c = rng.normal(size=(2, H)).astype(np.float32)
        h = rng.normal(size=(2, H)).astype(np.float32)
        x = rng.normal(size=(2, I)).astype(np.float32)

        def sigmoid(v):
            return 1.0 / (1.0 + np.exp(-v))

        z = np.concatenate([x, h], -1) @ kernel + bias
        i, j, f, o = np.split(z, 4, -1)
        exp_c = sigmoid(f + 1.0) * c + sigmoid(i) * np.tanh(j)
        exp_h = sigmoid(o) * np.tanh(exp_c)

        new_c, new_h = lstm_step(
            {"kernel": jnp.asarray(kernel), "bias": jnp.asarray(bias)},
            jnp.asarray(c), jnp.asarray(h), jnp.asarray(x), dtype=jnp.float32,
        )
        np.testing.assert_allclose(new_c, exp_c, rtol=1e-5)
        np.testing.assert_allclose(new_h, exp_h, rtol=1e-5)


class TestDecoder:
    @pytest.mark.parametrize("n_layers", [1, 2])
    def test_attention_shapes_and_simplex(self, n_layers):
        cfg = tiny_config(num_attend_layers=n_layers)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        contexts = jnp.ones((4, cfg.num_ctx, cfg.dim_ctx))
        output = jnp.ones((4, cfg.num_lstm_units))
        alpha = attend(params, cfg, contexts, output)
        assert alpha.shape == (4, cfg.num_ctx)
        np.testing.assert_allclose(alpha.sum(-1), np.ones(4), rtol=1e-5)
        assert (np.asarray(alpha) >= 0).all()

    @pytest.mark.parametrize("n_layers", [1, 2])
    def test_init_state_shapes(self, n_layers):
        cfg = tiny_config(num_initialize_layers=n_layers)
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        contexts = jnp.ones((4, cfg.num_ctx, cfg.dim_ctx))
        state = init_state(params, cfg, contexts)
        assert state.memory.shape == (4, cfg.num_lstm_units)
        assert state.output.shape == (4, cfg.num_lstm_units)
        np.testing.assert_allclose(state.output, state.recurrent)

    def test_scan_matches_stepwise_unroll(self):
        """lax.scan teacher forcing == manual python unroll (eval mode)."""
        cfg = tiny_config()
        params = init_decoder_params(jax.random.PRNGKey(1), cfg)
        batch = tiny_contexts_batch(cfg)
        contexts, sentences = batch["contexts"], batch["word_idxs"]

        logits_scan, alphas_scan = teacher_forced_decode(
            params, cfg, contexts, sentences, train=False
        )

        state = init_state(params, cfg, contexts)
        B, T = sentences.shape
        words_in = jnp.concatenate(
            [jnp.zeros((B, 1), sentences.dtype), sentences[:, :-1]], 1
        )
        for t in range(T):
            state, logits_t, alpha_t = decoder_step(
                params, cfg, contexts, state, words_in[:, t]
            )
            np.testing.assert_allclose(
                logits_scan[:, t], logits_t, rtol=2e-4, atol=2e-4
            )
            np.testing.assert_allclose(alphas_scan[:, t], alpha_t, rtol=2e-4, atol=2e-4)

    def test_decode_layers_variants(self):
        for n in (1, 2):
            cfg = tiny_config(num_decode_layers=n)
            params = init_decoder_params(jax.random.PRNGKey(0), cfg)
            batch = tiny_contexts_batch(cfg)
            logits, alphas = teacher_forced_decode(
                params, cfg, batch["contexts"], batch["word_idxs"]
            )
            assert logits.shape == (4, cfg.max_caption_length, cfg.vocabulary_size)
            assert alphas.shape == (4, cfg.max_caption_length, cfg.num_ctx)

    def test_dropout_only_in_train(self):
        cfg = tiny_config()
        params = init_decoder_params(jax.random.PRNGKey(0), cfg)
        batch = tiny_contexts_batch(cfg)
        l1, _ = teacher_forced_decode(params, cfg, batch["contexts"], batch["word_idxs"])
        l2, _ = teacher_forced_decode(params, cfg, batch["contexts"], batch["word_idxs"])
        np.testing.assert_allclose(l1, l2)  # deterministic without train
        l3, _ = teacher_forced_decode(
            params, cfg, batch["contexts"], batch["word_idxs"],
            train=True, rng=jax.random.PRNGKey(7),
        )
        assert not np.allclose(l1, l3)


class TestLoss:
    def test_masking_excludes_padded_steps(self):
        cfg = tiny_config()
        variables = {"params": {"cnn": {}, "decoder": init_decoder_params(jax.random.PRNGKey(0), cfg)}}
        batch = tiny_contexts_batch(cfg)
        total, aux = compute_loss(variables, cfg, batch, train=False)
        m = aux["metrics"]
        assert np.isfinite(total)
        # change labels only in masked-out positions: loss identical
        w = np.asarray(batch["word_idxs"]).copy()
        w[:, -1] = (w[:, -1] + 1) % cfg.vocabulary_size
        batch2 = dict(batch, word_idxs=jnp.asarray(w))
        total2, _ = compute_loss(variables, cfg, batch2, train=False)
        np.testing.assert_allclose(total, total2, rtol=1e-6)
        assert 0.0 <= float(m["accuracy"]) <= 1.0

    def test_attention_loss_zero_when_alphas_sum_to_one_per_masked_steps(self):
        # with factor 0 the term vanishes
        cfg = tiny_config(attention_loss_factor=0.0)
        variables = {"params": {"cnn": {}, "decoder": init_decoder_params(jax.random.PRNGKey(0), cfg)}}
        batch = tiny_contexts_batch(cfg)
        _, aux = compute_loss(variables, cfg, batch, train=False)
        assert float(aux["metrics"]["attention_loss"]) == 0.0

    def test_reg_loss_accounting(self):
        cfg = tiny_config()
        dec = init_decoder_params(jax.random.PRNGKey(0), cfg)
        params = {"cnn": {"conv1_1": {"conv": {"kernel": jnp.ones((3, 3, 3, 4)), "bias": jnp.ones((4,))}}},
                  "decoder": dec}
        # frozen CNN: conv kernels excluded
        r_frozen = regularization_loss(params, fc_scale=1e-4, conv_scale=1e-4, train_cnn=False)
        r_train = regularization_loss(params, fc_scale=1e-4, conv_scale=1e-4, train_cnn=True)
        conv_term = 0.5 * 1e-4 * 3 * 3 * 3 * 4
        np.testing.assert_allclose(float(r_train) - float(r_frozen), conv_term, rtol=1e-5)
        # lstm kernel never regularized
        no_lstm = jax.tree_util.tree_map(lambda x: x, params)
        no_lstm["decoder"] = {k: v for k, v in dec.items() if k != "lstm"}
        np.testing.assert_allclose(
            float(regularization_loss(no_lstm, 1e-4, 1e-4, False)),
            float(r_frozen), rtol=1e-6,
        )


class TestEncoders:
    def test_vgg16_context_grid(self):
        from sat_tpu.models import VGG16

        m = VGG16(dtype=jnp.float32)
        variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
        out = m.apply(variables, jnp.ones((1, 224, 224, 3)))
        assert out.shape == (1, 196, 512)
        assert "conv1_1" in variables["params"] and "conv5_3" in variables["params"]

    def test_resnet50_context_grid(self):
        from sat_tpu.models import ResNet50

        m = ResNet50(dtype=jnp.float32)
        variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)))
        out = m.apply(variables, jnp.ones((1, 224, 224, 3)))
        assert out.shape == (1, 49, 2048)
        assert "batch_stats" in variables
        p = variables["params"]
        assert "conv1" in p and "res2a" in p and "res5c" in p
        assert "res5c_branch2c" in p["res5c"]


class TestTrainStep:
    def test_loss_decreases_decoder_only(self):
        cfg = tiny_config(initial_learning_rate=5e-3)
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        # bypass the CNN with precomputed contexts: frozen-CNN training mode
        step = make_jit_train_step(cfg)
        batch = tiny_contexts_batch(cfg)
        rngs = jax.random.split(jax.random.PRNGKey(42), 60)
        first = None
        for i in range(60):
            state, metrics = step(state, batch, rngs[i])
            if first is None:
                first = float(metrics["total_loss"])
        last = float(metrics["total_loss"])
        assert last < first * 0.7, (first, last)
        assert int(state.step) == 60

    def test_frozen_cnn_params_unchanged(self):
        cfg = tiny_config()
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        cnn_before = jax.tree_util.tree_map(np.asarray, state.params["cnn"])
        step = make_jit_train_step(cfg)
        batch = tiny_contexts_batch(cfg)
        state, _ = step(state, batch, jax.random.PRNGKey(1))
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
            cnn_before, state.params["cnn"],
        )

    def test_optimizer_variants_build(self):
        from sat_tpu.train import make_optimizer

        for name in ("Adam", "RMSProp", "Momentum", "SGD"):
            cfg = tiny_config(optimizer=name)
            opt = make_optimizer(cfg)
            params = {"w": jnp.ones((3,))}
            opt_state = opt.init(params)
            updates, _ = opt.update({"w": jnp.ones((3,))}, opt_state, params)
            assert updates["w"].shape == (3,)


class TestRngImpl:
    """config.rng_impl routes dropout-mask bits to XLA's RngBitGenerator
    ("rbg", the TPU hardware path; measured 1.4x train-step speedup at
    flagship shapes) while threefry2x32 remains available for bitwise
    cross-backend reproducibility."""

    @pytest.mark.parametrize("impl", ["threefry2x32", "rbg", "unsafe_rbg"])
    def test_train_step_runs_under_each_impl(self, impl):
        cfg = tiny_config(rng_impl=impl)
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        step = make_jit_train_step(cfg)
        batch = tiny_contexts_batch(cfg)
        key = jax.random.key(7, impl=impl)
        state, m1 = step(state, batch, jax.random.fold_in(key, 0))
        state, m2 = step(state, batch, jax.random.fold_in(key, 1))
        assert np.isfinite(float(m1["total_loss"]))
        assert np.isfinite(float(m2["total_loss"]))
        # fresh dropout masks per step: same batch, different key -> the
        # stochastic loss must differ (dropout rates are nonzero here)
        assert float(m1["total_loss"]) != float(m2["total_loss"])

    def test_invalid_impl_rejected(self):
        with pytest.raises(ValueError, match="rng_impl"):
            tiny_config(rng_impl="philox")


class TestRematDecoder:
    @pytest.mark.parametrize("act_scale", [0.0, 1e-4])
    def test_remat_grads_match_baseline(self, act_scale):
        """config.remat_decoder recomputes the scan step in backward from
        the same per-step keys — loss and grads must match the
        residual-stacking baseline to float tolerance.  Parametrized over
        L1 activity regularization since with_activity changes the scan's
        output structure under jax.checkpoint."""
        base = tiny_config(
            fc_drop_rate=0.3, lstm_drop_rate=0.2,
            fc_activity_regularizer_scale=act_scale,
        )
        remat = base.replace(remat_decoder=True)
        batch = tiny_contexts_batch(base)
        variables = init_variables(jax.random.PRNGKey(0), base)
        key = jax.random.key(5, impl=base.rng_impl)

        def loss_fn(cfg):
            def f(v):
                total, _ = compute_loss(v, cfg, batch, rng=key, train=True)
                return total
            return jax.jit(jax.value_and_grad(f))

        l0, g0 = loss_fn(base)(variables)
        l1, g1 = loss_fn(remat)(variables)
        assert float(l0) == pytest.approx(float(l1), rel=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
            ),
            g0, g1,
        )

    def test_remat_cnn_grads_match_baseline(self):
        """config.remat_cnn recomputes the encoder forward in backward —
        loss and CNN grads must match the baseline (both encoder
        families: vgg16 plain path, resnet50 mutable-BN path)."""
        for cnn in ("vgg16", "resnet50"):
            base = tiny_config(cnn=cnn, train_cnn=True, image_size=32)
            remat = base.replace(remat_cnn=True)
            variables = init_variables(jax.random.PRNGKey(0), base)
            rng = np.random.default_rng(3)
            B, T = 2, base.max_caption_length
            batch = {
                "images": jnp.asarray(
                    rng.normal(size=(B, 32, 32, 3)).astype(np.float32)
                ),
                "word_idxs": jnp.asarray(
                    rng.integers(0, base.vocabulary_size, size=(B, T)).astype(np.int32)
                ),
                "masks": jnp.ones((B, T), jnp.float32),
            }
            key = jax.random.key(9, impl=base.rng_impl)

            def grad_of(cfg):
                def f(v):
                    total, _ = compute_loss(v, cfg, batch, rng=key, train=True)
                    return total
                return jax.jit(jax.value_and_grad(f))(variables)

            l0, g0 = grad_of(base)
            l1, g1 = grad_of(remat)
            assert float(l0) == pytest.approx(float(l1), rel=1e-6), cnn
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
                ),
                g0["params"]["cnn"], g1["params"]["cnn"],
            )


class TestActivityRegularization:
    """L1 activity regularization (reference utils/nn.py:23-26,40-43):
    scale·Σ|output| over *activated* layer outputs — tanh fc layers when
    training, relu convs only when the CNN trains.  The loss is linear in
    each scale with the activity sum as slope, which the tests exploit to
    verify the term without duplicating the forward math."""

    def _loss(self, cfg, batch, key):
        variables = init_variables(jax.random.PRNGKey(0), cfg)
        total, _ = compute_loss(variables, cfg, batch, rng=key, train=True)
        return float(total)

    def test_fc_activity_linear_in_scale(self):
        key = jax.random.PRNGKey(7)
        losses = {}
        for s in (0.0, 1e-4, 2e-4):
            cfg = tiny_config(fc_activity_regularizer_scale=s)
            losses[s] = self._loss(cfg, tiny_contexts_batch(cfg), key)
        slope = (losses[1e-4] - losses[0.0]) / 1e-4
        assert slope > 0, "tanh activity sum must be positive"
        np.testing.assert_allclose(
            losses[2e-4] - losses[0.0], 2 * (losses[1e-4] - losses[0.0]), rtol=1e-4
        )

    def test_fc_activity_zero_without_activated_layers(self):
        # 1-layer init/attend/decode variants use activation=None everywhere
        # (reference model.py:362-371,402-415,442-446): nothing collects
        key = jax.random.PRNGKey(7)
        losses = []
        for s in (0.0, 1e-3):
            cfg = tiny_config(
                fc_activity_regularizer_scale=s,
                num_initialize_layers=1,
                num_attend_layers=1,
                num_decode_layers=1,
            )
            losses.append(self._loss(cfg, tiny_contexts_batch(cfg), key))
        np.testing.assert_allclose(losses[0], losses[1], rtol=1e-7)

    def test_conv_activity_vgg16_linear_resnet_zero_frozen_off(self):
        key = jax.random.PRNGKey(3)

        def loss(cnn, s, train_cnn=True):
            cfg = tiny_config(
                cnn=cnn, image_size=32, train_cnn=train_cnn,
                conv_activity_regularizer_scale=s,
            )
            B, T = cfg.batch_size, cfg.max_caption_length
            rng = np.random.default_rng(0)  # same batch for every scale
            batch = {
                "images": jnp.asarray(
                    rng.normal(size=(B, 32, 32, 3)), jnp.float32
                ),
                "word_idxs": jnp.asarray(
                    np.arange(B * T).reshape(B, T) % cfg.vocabulary_size, jnp.int32
                ),
                "masks": jnp.ones((B, T), jnp.float32),
            }
            variables = init_variables(jax.random.PRNGKey(0), cfg)
            total, _ = compute_loss(variables, cfg, batch, rng=key, train=True)
            return float(total)

        # VGG16: 13 relu convs collect; linear in the scale
        l0, l1, l2 = (loss("vgg16", s) for s in (0.0, 1e-6, 2e-6))
        assert l1 > l0
        np.testing.assert_allclose(l2 - l0, 2 * (l1 - l0), rtol=1e-3)
        # ResNet50: every conv passes activation=None (relu applied after
        # BN, reference model.py:70-81,111-188) — no activity anywhere
        r0, r1 = (loss("resnet50", s) for s in (0.0, 1e-3))
        np.testing.assert_allclose(r0, r1, rtol=1e-7)
        # frozen CNN: the conv activity gate is train_cnn (utils/nn.py:23)
        f0, f1 = (loss("vgg16", s, train_cnn=False) for s in (0.0, 1e-3))
        np.testing.assert_allclose(f0, f1, rtol=1e-7)


class TestCeDtype:
    """config.ce_dtype="bfloat16": CE computed without materializing a
    [B,T,V] fp32 log-softmax — bf16 max/shift/exp, fp32 normalizer
    accumulation (the MFU lever named in VERDICT r03 weak #2)."""

    def test_bf16_formulation_exact_in_fp32(self):
        """With fp32 logits the two CE paths are the same mathematics —
        the manual logsumexp formulation must match log_softmax
        essentially bitwise, grads included."""
        base = tiny_config(fc_drop_rate=0.3, lstm_drop_rate=0.2)
        bf = base.replace(ce_dtype="bfloat16")
        batch = tiny_contexts_batch(base)
        variables = init_variables(jax.random.PRNGKey(0), base)
        key = jax.random.key(5, impl=base.rng_impl)

        def loss_fn(cfg):
            def f(v):
                total, aux = compute_loss(v, cfg, batch, rng=key, train=True)
                return total, aux["metrics"]["cross_entropy_loss"]
            return jax.jit(jax.value_and_grad(f, has_aux=True))

        (l0, ce0), g0 = loss_fn(base)(variables)
        (l1, ce1), g1 = loss_fn(bf)(variables)
        assert float(ce0) == pytest.approx(float(ce1), rel=1e-6)
        assert float(l0) == pytest.approx(float(l1), rel=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
            ),
            g0, g1,
        )

    def test_bf16_ce_close_under_bf16_compute(self):
        """Under compute_dtype=bfloat16 (the TPU flagship), the bf16 CE
        tracks the fp32-materializing path within bf16 resolution and the
        gradients stay aligned."""
        base = tiny_config(compute_dtype="bfloat16")
        bf = base.replace(ce_dtype="bfloat16")
        batch = tiny_contexts_batch(base)
        variables = init_variables(jax.random.PRNGKey(0), base)
        key = jax.random.key(5, impl=base.rng_impl)

        def loss_fn(cfg):
            def f(v):
                total, _ = compute_loss(v, cfg, batch, rng=key, train=True)
                return total
            return jax.jit(jax.value_and_grad(f))

        l0, g0 = loss_fn(base)(variables)
        l1, g1 = loss_fn(bf)(variables)
        # bf16 exp/shift carry ~2^-8 relative error into the normalizer
        assert float(l0) == pytest.approx(float(l1), rel=1e-2)
        flat0 = jnp.concatenate([
            jnp.ravel(x).astype(jnp.float32)
            for x in jax.tree_util.tree_leaves(g0)
        ])
        flat1 = jnp.concatenate([
            jnp.ravel(x).astype(jnp.float32)
            for x in jax.tree_util.tree_leaves(g1)
        ])
        cos = jnp.dot(flat0, flat1) / (
            jnp.linalg.norm(flat0) * jnp.linalg.norm(flat1)
        )
        assert float(cos) > 0.999, float(cos)

    def test_eval_path_unaffected(self):
        """ce_dtype only touches training: eval CE is gated on train=True
        and stays the exact fp32 materialization."""
        base = tiny_config()
        bf = base.replace(ce_dtype="bfloat16")
        batch = tiny_contexts_batch(base)
        variables = init_variables(jax.random.PRNGKey(0), base)
        l0, _ = compute_loss(variables, base, batch, train=False)
        l1, _ = compute_loss(variables, bf, batch, train=False)
        assert float(l0) == float(l1)

    def test_config_rejects_bad_ce_dtype(self):
        with pytest.raises(ValueError, match="ce_dtype"):
            tiny_config(ce_dtype="float16")
