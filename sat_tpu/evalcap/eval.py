"""Caption evaluation orchestrator.

Equivalent of the reference COCOEvalCap
(/root/reference/utils/coco/pycocoevalcap/eval.py:8-76): gathers ground
truths and results per image id (optionally restricted to an eval subset),
PTB-tokenizes both sides (our native tokenizer replaces the CoreNLP jar),
runs BLEU-1..4 / METEOR / ROUGE-L / CIDEr, and records corpus plus
per-image scores.
"""

from __future__ import annotations

from typing import Dict, List

from ..data.coco import CocoCaptions
from ..data.tokenizer import tokenize_captions
from .bleu import Bleu
from .cider import Cider
from .meteor import Meteor
from .rouge import Rouge


class CocoEvalCap:
    def __init__(
        self,
        coco: CocoCaptions,
        coco_res: CocoCaptions,
        eval_data=None,
    ):
        """coco: ground-truth index; coco_res: result index from
        CocoCaptions.load_results; eval_data: optional DataSet whose
        image_ids restrict evaluation to the capped eval subset
        (reference eval.py:15-18)."""
        self.coco = coco
        self.coco_res = coco_res
        self.eval: Dict[str, float] = {}
        self.img_to_eval: Dict[int, Dict[str, float]] = {}
        if eval_data is not None:
            self.params = {"image_id": [int(i) for i in set(eval_data.image_ids)]}
        else:
            self.params = {"image_id": list(coco_res.imgs.keys())}

    def evaluate(self, verbose: bool = True) -> Dict[str, float]:
        img_ids = [i for i in self.params["image_id"] if i in self.coco_res.imgs]

        gts: Dict[int, List[str]] = {}
        res: Dict[int, List[str]] = {}
        for img_id in img_ids:
            gts[img_id] = [a["caption"] for a in self.coco.img_to_anns[img_id]]
            res[img_id] = [a["caption"] for a in self.coco_res.img_to_anns[img_id]]

        # PTB tokenization with punctuation stripping (reference
        # ptbtokenizer.py semantics) applied to both sides
        gts = {i: tokenize_captions(c) for i, c in gts.items()}
        res = {i: tokenize_captions(c) for i, c in res.items()}

        scorers = [
            (Bleu(4), ["Bleu_1", "Bleu_2", "Bleu_3", "Bleu_4"]),
            (Meteor(), "METEOR"),
            (Rouge(), "ROUGE_L"),
            (Cider(), "CIDEr"),
        ]
        for scorer, method in scorers:
            score, scores = scorer.compute_score(gts, res)
            if isinstance(method, list):
                for sc, scs, m in zip(score, scores, method):
                    self._set_eval(m, sc)
                    self._set_img_scores(m, img_ids, scs)
                    if verbose:
                        print(f"{m}: {sc:.3f}")
            else:
                self._set_eval(method, score)
                self._set_img_scores(method, img_ids, scores)
                if verbose:
                    print(f"{method}: {score:.3f}")
        return dict(self.eval)

    def _set_eval(self, method: str, score: float) -> None:
        self.eval[method] = float(score)

    def _set_img_scores(self, method: str, img_ids, scores) -> None:
        for img_id, score in zip(sorted(img_ids), scores):
            self.img_to_eval.setdefault(img_id, {"image_id": img_id})[
                method
            ] = float(score)
