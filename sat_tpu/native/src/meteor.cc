// METEOR 1.5 segment scorer — native replacement for the reference's
// persistent meteor-1.5.jar subprocess (/root/reference/utils/coco/
// pycocoevalcap/meteor/meteor.py:15-58).
//
// Mirror of the Python implementation in sat_tpu/evalcap/meteor.py
// (golden-tested against it): joint alignment resolution over all
// matcher candidates — exact (1.0), Porter-stem (0.6), synonym (0.8),
// paraphrase phrase spans (0.6) — beam-searched to select the
// non-overlapping subset that (1) maximizes covered words across both
// sentences, (2) minimizes chunks, (3) minimizes summed start-position
// distance (Denkowski & Lavie 2014 §3, the jar's Aligner.resolve; beam
// width 40 like the jar, exhaustive at caption lengths) — and METEOR
// 1.5 scoring with the English rank-tuned parameters α=0.85, β=0.2,
// γ=0.6, δ=0.75: content/function-word discounted P and R (per-side
// coverage, so paraphrase spans of unequal length score correctly),
// fragmentation penalty only when the alignment has more than one
// chunk.  The function-word, synonym, and paraphrase tables are pushed
// in from Python (meteor_data.py) via sat_meteor_set_data so both
// backends share one source of truth.

#include <algorithm>
#include <cmath>
#include <cstdlib>
#include <map>
#include <sstream>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace sat_native {

std::string porter_stem(const std::string& input);

namespace {

constexpr double kAlpha = 0.85;
constexpr double kBeta = 0.2;
constexpr double kGamma = 0.6;
constexpr double kDelta = 0.75;
constexpr double kExactWeight = 1.0;
constexpr double kStemWeight = 0.6;
constexpr double kSynonymWeight = 0.8;
constexpr double kParaphraseWeight = 0.6;

std::unordered_set<std::string> g_function_words;
// word -> group ids (two words are synonyms iff their id sets intersect)
std::unordered_map<std::string, std::vector<int>> g_synonyms;
// phrase (space-joined) -> group ids; same intersection semantics
std::unordered_map<std::string, std::vector<int>> g_paraphrases;
int g_max_paraphrase_len = 0;

std::vector<std::string> split_ws(const std::string& s) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < s.size()) {
    while (i < s.size() && s[i] == ' ') i++;
    size_t start = i;
    while (i < s.size() && s[i] != ' ') i++;
    if (i > start) out.push_back(s.substr(start, i - start));
  }
  return out;
}

struct Match {
  int hyp_idx;
  int ref_idx;
  double weight;
};

bool share_group(const std::vector<int>& a, const std::vector<int>& b) {
  for (int ga : a)
    for (int gb : b)
      if (ga == gb) return true;
  return false;
}

// Beam width of the alignment resolution — the jar's default; mirrors
// ALIGN_BEAM in sat_tpu/evalcap/meteor.py.
constexpr int kAlignBeam = 40;
// Reference-side coverage mask capacity.  PTB-tokenized captions run
// well under this; sat_tpu.evalcap.meteor.meteor_single routes longer
// segments to the Python twin (whose mask is an unbounded int), and
// meteor_segment returns the -1.0 sentinel for over-cap references so
// a direct C ABI caller can never get a silently truncated score.
constexpr int kMaxRefWords = 128;

struct Mask {
  uint64_t lo = 0, hi = 0;
  bool test(int j) const {
    return j < 64 ? (lo >> j) & 1u : (hi >> (j - 64)) & 1u;
  }
  void set(int j) {
    if (j < 64)
      lo |= (uint64_t{1} << j);
    else
      hi |= (uint64_t{1} << (j - 64));
  }
  bool overlaps(const Mask& o) const {
    return (lo & o.lo) != 0 || (hi & o.hi) != 0;
  }
  bool operator==(const Mask& o) const { return lo == o.lo && hi == o.hi; }
};

struct State {
  int covered = 0;
  int chunks = 0;
  int dist = 0;
  double weight = 0.0;
  Mask mask;
  int li = -2, lj = -2;  // last zipped pair (run tail for chunk counting)
  std::vector<Match> pairs;
  std::vector<std::pair<int, double>> hcov, rcov;  // (word idx, weight)
};

// "a strictly better than b" under the resolution's lexicographic
// objective — mirrors the Python key (-covered, chunks, dist, -weight,
// pairs, hcov, rcov).  The pairs/coverage comparisons are deterministic
// final tiebreaks: two optima can share identical pairs but differ in
// per-side coverage (a 2→1 vs 1→2 paraphrase span at the same anchor),
// which changes P/R — both backends must pick the same one.
int cmp_idx_weight(const std::vector<std::pair<int, double>>& a,
                   const std::vector<std::pair<int, double>>& b) {
  size_t n = std::min(a.size(), b.size());
  for (size_t k = 0; k < n; k++) {
    if (a[k].first != b[k].first) return a[k].first < b[k].first ? -1 : 1;
    if (a[k].second != b[k].second) return a[k].second < b[k].second ? -1 : 1;
  }
  if (a.size() != b.size()) return a.size() < b.size() ? -1 : 1;
  return 0;
}

bool state_better(const State& a, const State& b) {
  if (a.covered != b.covered) return a.covered > b.covered;
  if (a.chunks != b.chunks) return a.chunks < b.chunks;
  if (a.dist != b.dist) return a.dist < b.dist;
  if (a.weight != b.weight) return a.weight > b.weight;
  size_t n = std::min(a.pairs.size(), b.pairs.size());
  for (size_t k = 0; k < n; k++) {
    const Match &x = a.pairs[k], &y = b.pairs[k];
    if (x.hyp_idx != y.hyp_idx) return x.hyp_idx < y.hyp_idx;
    if (x.ref_idx != y.ref_idx) return x.ref_idx < y.ref_idx;
    if (x.weight != y.weight) return x.weight < y.weight;
  }
  if (a.pairs.size() != b.pairs.size())
    return a.pairs.size() < b.pairs.size();
  int c = cmp_idx_weight(a.hcov, b.hcov);
  if (c != 0) return c < 0;
  return cmp_idx_weight(a.rcov, b.rcov) < 0;
}

struct WordCand {
  int j;
  double weight;
};
struct SpanCand {
  int len_h;  // L
  int j;
  int len_r;  // M
};

std::string join_span(const std::vector<std::string>& words, int start,
                      int len);

// All matcher-generated candidates, jointly (mirror of Python
// _candidates): word candidates take the highest-PRECEDENCE applicable
// matcher's weight (exact > stem > synonym — module order, not weight
// order); span candidates come from the paraphrase table, minus 1×1
// duplicates of word candidates and identical phrases (both fully
// served by exact word matches — see the Python twin's rationale).
void build_candidates(const std::vector<std::string>& hyp,
                      const std::vector<std::string>& ref,
                      const std::vector<std::string>& hyp_stems,
                      const std::vector<std::string>& ref_stems,
                      std::vector<std::vector<WordCand>>* word_cands,
                      std::vector<std::vector<SpanCand>>* span_cands) {
  int nh = static_cast<int>(hyp.size());
  int nr = std::min(static_cast<int>(ref.size()), kMaxRefWords);
  word_cands->assign(nh, {});
  span_cands->assign(nh, {});
  for (int i = 0; i < nh; i++) {
    auto hsyn = g_synonyms.find(hyp[i]);
    for (int j = 0; j < nr; j++) {
      if (hyp[i] == ref[j]) {
        (*word_cands)[i].push_back({j, kExactWeight});
      } else if (hyp_stems[i] == ref_stems[j]) {
        (*word_cands)[i].push_back({j, kStemWeight});
      } else if (hsyn != g_synonyms.end()) {
        auto rsyn = g_synonyms.find(ref[j]);
        if (rsyn != g_synonyms.end() &&
            share_group(hsyn->second, rsyn->second)) {
          (*word_cands)[i].push_back({j, kSynonymWeight});
        }
      }
    }
  }
  // gid -> reference spans (j, M) carrying that group
  std::unordered_map<int, std::vector<std::pair<int, int>>> ref_spans;
  for (int M = 1; M <= g_max_paraphrase_len; M++) {
    for (int j = 0; j + M <= nr; j++) {
      auto it = g_paraphrases.find(join_span(ref, j, M));
      if (it == g_paraphrases.end()) continue;
      for (int gid : it->second) ref_spans[gid].push_back({j, M});
    }
  }
  for (int L = 1; L <= g_max_paraphrase_len; L++) {
    for (int i = 0; i + L <= nh; i++) {
      auto it = g_paraphrases.find(join_span(hyp, i, L));
      if (it == g_paraphrases.end()) continue;
      std::unordered_set<int> seen;  // key = j * (kMaxRefWords+1) + M
      for (int gid : it->second) {
        auto rit = ref_spans.find(gid);
        if (rit == ref_spans.end()) continue;
        for (auto [j, M] : rit->second) {
          int key = j * (kMaxRefWords + 1) + M;
          if (seen.count(key)) continue;
          if (L == 1 && M == 1) {
            bool dup = false;
            for (const auto& wc : (*word_cands)[i])
              if (wc.j == j) dup = true;
            if (dup) continue;
          }
          if (L == M) {
            bool identical = true;
            for (int k = 0; k < L && identical; k++)
              identical = hyp[i + k] == ref[j + k];
            if (identical) continue;
          }
          seen.insert(key);
          (*span_cands)[i].push_back({L, j, M});
        }
      }
    }
  }
}

// Resolve the alignment by beam search over hypothesis positions
// (mirror of the Python align(); see the module header for the
// objective).  Fills matches / per-side coverage weights.
void resolve_alignment(const std::vector<std::string>& hyp,
                       const std::vector<std::string>& ref,
                       const std::vector<std::string>& hyp_stems,
                       const std::vector<std::string>& ref_stems,
                       std::vector<Match>* matches,
                       std::vector<double>* hyp_w,
                       std::vector<double>* ref_w) {
  std::vector<std::vector<WordCand>> word_cands;
  std::vector<std::vector<SpanCand>> span_cands;
  build_candidates(hyp, ref, hyp_stems, ref_stems, &word_cands, &span_cands);

  int nh = static_cast<int>(hyp.size());
  std::vector<std::vector<State>> pools(nh + 1);
  pools[0].push_back(State{});

  for (int pos = 0; pos < nh; pos++) {
    auto pool = std::move(pools[pos]);
    pools[pos].clear();
    if (pool.empty()) continue;
    // dedup on (mask, run tail): states identical there extend
    // identically — keep the best-scored representative
    std::map<std::tuple<uint64_t, uint64_t, int, int>, size_t> best_by;
    std::vector<State> kept;
    for (auto& st : pool) {
      auto k = std::make_tuple(st.mask.lo, st.mask.hi, st.li, st.lj);
      auto it = best_by.find(k);
      if (it == best_by.end()) {
        best_by[k] = kept.size();
        kept.push_back(std::move(st));
      } else if (state_better(st, kept[it->second])) {
        kept[it->second] = std::move(st);
      }
    }
    std::sort(kept.begin(), kept.end(), state_better);
    if (static_cast<int>(kept.size()) > kAlignBeam) kept.resize(kAlignBeam);

    for (const auto& st : kept) {
      // option: leave hyp word `pos` uncovered
      pools[pos + 1].push_back(st);
      for (const auto& wc : word_cands[pos]) {
        if (st.mask.test(wc.j)) continue;
        State nx = st;
        bool adj = pos == st.li + 1 && wc.j == st.lj + 1;
        nx.covered += 2;
        nx.chunks += adj ? 0 : 1;
        nx.dist += std::abs(pos - wc.j);
        nx.weight += wc.weight;
        nx.mask.set(wc.j);
        nx.li = pos;
        nx.lj = wc.j;
        nx.pairs.push_back({pos, wc.j, wc.weight});
        nx.hcov.push_back({pos, wc.weight});
        nx.rcov.push_back({wc.j, wc.weight});
        pools[pos + 1].push_back(std::move(nx));
      }
      for (const auto& sc : span_cands[pos]) {
        Mask span_mask;
        for (int k = 0; k < sc.len_r; k++) span_mask.set(sc.j + k);
        if (st.mask.overlaps(span_mask)) continue;
        int z = std::min(sc.len_h, sc.len_r);
        State nx = st;
        bool adj = pos == st.li + 1 && sc.j == st.lj + 1;
        nx.covered += sc.len_h + sc.len_r;
        nx.chunks += adj ? 0 : 1;
        nx.dist += std::abs(pos - sc.j);
        nx.weight += z * kParaphraseWeight;
        nx.mask.lo |= span_mask.lo;
        nx.mask.hi |= span_mask.hi;
        nx.li = pos + z - 1;
        nx.lj = sc.j + z - 1;
        for (int k = 0; k < z; k++)
          nx.pairs.push_back({pos + k, sc.j + k, kParaphraseWeight});
        for (int k = 0; k < sc.len_h; k++)
          nx.hcov.push_back({pos + k, kParaphraseWeight});
        for (int k = 0; k < sc.len_r; k++)
          nx.rcov.push_back({sc.j + k, kParaphraseWeight});
        pools[pos + sc.len_h].push_back(std::move(nx));
      }
    }
  }

  const State* best = nullptr;
  for (const auto& st : pools[nh]) {
    if (best == nullptr || state_better(st, *best)) best = &st;
  }
  matches->clear();
  hyp_w->assign(hyp.size(), -1.0);
  ref_w->assign(ref.size(), -1.0);
  if (best == nullptr) return;
  *matches = best->pairs;
  for (const auto& [idx, w] : best->hcov) (*hyp_w)[idx] = w;
  for (const auto& [idx, w] : best->rcov) (*ref_w)[idx] = w;
}

std::string join_span(const std::vector<std::string>& words, int start,
                      int len) {
  std::string out;
  for (int k = 0; k < len; k++) {
    if (k) out += ' ';
    out += words[start + k];
  }
  return out;
}

// δ-discounted weighted match fraction for one side (P or R) from the
// per-side coverage weights (-1 = unmatched).
double side_score(const std::vector<std::string>& words,
                  const std::vector<double>& weights) {
  int n_f = 0;
  for (const auto& w : words)
    if (g_function_words.count(w)) n_f++;
  int n_c = static_cast<int>(words.size()) - n_f;
  double denom = kDelta * n_c + (1.0 - kDelta) * n_f;
  if (denom == 0.0) return 0.0;
  double wc = 0.0, wf = 0.0;
  for (size_t idx = 0; idx < words.size(); idx++) {
    if (weights[idx] < 0.0) continue;
    if (g_function_words.count(words[idx]))
      wf += weights[idx];
    else
      wc += weights[idx];
  }
  return (kDelta * wc + (1.0 - kDelta) * wf) / denom;
}

}  // namespace

void meteor_set_data(const std::string& function_words,
                     const std::string& synset_lines,
                     const std::string& paraphrase_lines) {
  g_function_words.clear();
  for (const auto& w : split_ws(function_words)) g_function_words.insert(w);
  g_synonyms.clear();
  std::istringstream in(synset_lines);
  std::string line;
  int gid = 0;
  while (std::getline(in, line)) {
    auto words = split_ws(line);
    if (words.empty()) continue;
    for (const auto& w : words) g_synonyms[w].push_back(gid);
    gid++;
  }
  // paraphrase groups: one group per line, phrases separated by '|'
  g_paraphrases.clear();
  g_max_paraphrase_len = 0;
  std::istringstream pin(paraphrase_lines);
  int pgid = 0;
  while (std::getline(pin, line)) {
    bool any = false;
    size_t pos = 0;
    while (pos <= line.size()) {
      size_t bar = line.find('|', pos);
      if (bar == std::string::npos) bar = line.size();
      std::string phrase = line.substr(pos, bar - pos);
      auto words = split_ws(phrase);
      if (!words.empty()) {
        g_paraphrases[join_span(words, 0, static_cast<int>(words.size()))]
            .push_back(pgid);
        g_max_paraphrase_len =
            std::max(g_max_paraphrase_len, static_cast<int>(words.size()));
        any = true;
      }
      pos = bar + 1;
    }
    if (any) pgid++;
  }
}

double meteor_segment(const std::string& hypothesis,
                      const std::string& reference) {
  std::vector<std::string> hyp = split_ws(hypothesis);
  std::vector<std::string> ref = split_ws(reference);
  if (hyp.empty() || ref.empty()) return 0.0;
  // Over-cap references cannot be represented in the coverage mask;
  // refuse with a sentinel (scores live in [0,1]) instead of silently
  // deflating recall by truncation (ADVICE r04) — the ctypes wrapper
  // refuses earlier with a message, this guards direct C ABI callers.
  if (static_cast<int>(ref.size()) > kMaxRefWords) return -1.0;

  std::vector<std::string> hyp_stems(hyp.size()), ref_stems(ref.size());
  // corpus scoring re-stems the same caption vocabulary across thousands
  // of segments; cache stems (safe: the ctypes layer serializes scoring)
  // bounded (the Python twin uses lru_cache(65536)): an open-ended
  // vocabulary in a long-lived process must not grow it without limit
  static std::unordered_map<std::string, std::string> stem_cache;
  auto cached_stem = [](const std::string& w) -> const std::string& {
    auto it = stem_cache.find(w);
    if (it == stem_cache.end()) {
      if (stem_cache.size() >= 65536) stem_cache.clear();
      it = stem_cache.emplace(w, porter_stem(w)).first;
    }
    return it->second;
  };
  for (size_t i = 0; i < hyp.size(); i++) hyp_stems[i] = cached_stem(hyp[i]);
  for (size_t j = 0; j < ref.size(); j++) ref_stems[j] = cached_stem(ref[j]);

  std::vector<double> hyp_w, ref_w;
  std::vector<Match> matches;
  resolve_alignment(hyp, ref, hyp_stems, ref_stems, &matches, &hyp_w, &ref_w);

  if (matches.empty()) return 0.0;
  std::sort(matches.begin(), matches.end(),
            [](const Match& a, const Match& b) {
              return a.hyp_idx != b.hyp_idx ? a.hyp_idx < b.hyp_idx
                                            : a.ref_idx < b.ref_idx;
            });

  int chunks = 1;
  for (size_t k = 1; k < matches.size(); k++) {
    if (!(matches[k].hyp_idx == matches[k - 1].hyp_idx + 1 &&
          matches[k].ref_idx == matches[k - 1].ref_idx + 1)) {
      chunks++;
    }
  }

  // m for the fragmentation penalty: average matched-word count over the
  // two sides (equals the pair count for word-level stages; generalizes
  // to paraphrase spans of unequal length)
  int hyp_covered = 0, ref_covered = 0;
  for (double w : hyp_w) hyp_covered += (w >= 0.0);
  for (double w : ref_w) ref_covered += (w >= 0.0);
  double m_avg = 0.5 * (hyp_covered + ref_covered);

  double p = side_score(hyp, hyp_w);
  double r = side_score(ref, ref_w);
  if (p == 0.0 || r == 0.0) return 0.0;
  double fmean = (p * r) / (kAlpha * p + (1.0 - kAlpha) * r);
  // single-chunk alignments carry no fragmentation penalty (jar
  // behavior: identical sentences score exactly 1.0)
  if (chunks <= 1) return fmean;
  double frag = static_cast<double>(chunks) / m_avg;
  double penalty = kGamma * std::pow(frag, kBeta);
  return fmean * (1.0 - penalty);
}

}  // namespace sat_native
