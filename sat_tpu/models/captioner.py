"""The full caption model: CNN encoder + attention-LSTM decoder + losses.

Equivalent of the reference CaptionGenerator (/root/reference/model.py:6-13)
plus its loss graph (model.py:293-334), reorganized functionally:

* ``init_variables`` builds the parameter pytree {'cnn': ..., 'decoder': ...}
  (+ 'batch_stats' for ResNet50's BN);
* ``encode`` maps images → context grid, with stop_gradient when the CNN is
  frozen (the reference freezes via trainable=False, utils/nn.py:66);
* ``compute_loss`` reproduces the three-part objective: masked
  cross-entropy normalized by total mask, the doubly-stochastic attention
  penalty 0.01 * l2(1-Σα_masked)/(B·N), and L2 weight regularization —
  plus teacher-forced token accuracy.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import Config
from ..nn.layers import regularization_loss
from .decoder import init_decoder_params, teacher_forced_decode
from .resnet50 import ResNet50
from .vgg16 import VGG16


def make_encoder(config: Config):
    dtype = jnp.dtype(config.compute_dtype)
    if config.cnn == "vgg16":
        return VGG16(dtype=dtype)
    if config.cnn == "resnet50":
        return ResNet50(dtype=dtype)
    raise ValueError(f"unknown cnn {config.cnn!r} (vgg16 or resnet50)")


def init_variables(rng: jax.Array, config: Config) -> Dict[str, Any]:
    """Initialize all model variables with dummy image input."""
    k_cnn, k_dec = jax.random.split(rng)
    encoder = make_encoder(config)
    dummy = jnp.zeros((1, config.image_size, config.image_size, 3), jnp.float32)
    cnn_vars = encoder.init(k_cnn, dummy, train=False)
    out = {
        "params": {
            "cnn": cnn_vars["params"],
            "decoder": init_decoder_params(k_dec, config),
        }
    }
    if "batch_stats" in cnn_vars:
        out["batch_stats"] = cnn_vars["batch_stats"]
    return out


def encode(
    variables: Dict[str, Any],
    config: Config,
    images: jnp.ndarray,
    train: bool = False,
    collect_activity: bool = False,
) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """images [B,224,224,3] → contexts [B,N,D].  Returns (contexts, new_model_state).

    train here means *the CNN is training* (train_cnn): enables BN batch
    statistics and gradient flow; otherwise contexts are stop-gradiented so
    the frozen CNN never enters the backward pass.

    collect_activity=True (static) additionally sums the 'activity'
    collection the Conv layers sow (Σ|relu output| per activated conv —
    VGG16 only; ResNet convs pass activation=None like the reference,
    utils/nn.py:55-57) into new_state['activity_l1']."""
    if images.dtype == jnp.uint8:
        # device-side preprocessing tail (ImageLoader raw=True feed): the
        # host already decoded/BGR→RGB/resized in uint8; the final
        # astype(float32) − ILSVRC mean runs here instead — bitwise equal
        # to the host path (reference utils/misc.py:22-27 order), 4× less
        # host→device traffic
        from ..data.images import ILSVRC_2012_MEAN

        images = images.astype(jnp.float32) - jnp.asarray(ILSVRC_2012_MEAN)
    if config.encoder_quant != "off" and "qcnn" in variables:
        # serve-path quantized encoder (nn/quant.py): the engine swaps the
        # fp32 cnn params for the 'qcnn' collection at load time, so this
        # branch is structurally unreachable from training (train variables
        # never carry qcnn) and config.encoder_quant="off" stays bitwise
        # the flax path below
        from ..nn import quant

        contexts = quant.quantized_encode(variables, config, images)
        return jax.lax.stop_gradient(contexts), {}
    encoder = make_encoder(config)
    cnn_vars: Dict[str, Any] = {"params": variables["params"]["cnn"]}
    if "batch_stats" in variables:
        cnn_vars["batch_stats"] = variables["batch_stats"]

    new_state: Dict[str, Any] = {}
    mutable = []
    if train and "batch_stats" in cnn_vars:
        mutable.append("batch_stats")
    if collect_activity:
        mutable.append("activity")
    if mutable:
        bn = "batch_stats" in mutable
        apply_mut = lambda v, im: encoder.apply(  # noqa: E731
            v, im, train=bn, mutable=list(mutable)
        )
        if train and config.remat_cnn:
            apply_mut = jax.checkpoint(apply_mut)
        contexts, mutated = apply_mut(cnn_vars, images)
        if bn:
            new_state["batch_stats"] = mutated["batch_stats"]
        if collect_activity:
            new_state["activity_l1"] = jax.tree_util.tree_reduce(
                lambda a, b: a + b, mutated.get("activity", {}), jnp.float32(0)
            )
    else:
        apply_fn = lambda v, im: encoder.apply(v, im, train=False)  # noqa: E731
        if train and config.remat_cnn:
            # full encoder remat: backward recomputes the CNN forward from
            # the images instead of storing every conv activation — the
            # memory lever that buys joint-training batch size (the conv1/2
            # stacks at 224^2 dominate live activation footprint)
            apply_fn = jax.checkpoint(apply_fn)
        contexts = apply_fn(cnn_vars, images)
    if not train:
        contexts = jax.lax.stop_gradient(contexts)
    return contexts, new_state


def token_ce(
    logits: jnp.ndarray,
    sentences: jnp.ndarray,
    config: Config,
    train: bool = True,
) -> jnp.ndarray:
    """Per-token cross-entropy [B, T] — the ONE implementation shared by
    the single-device loss and the context-parallel twin
    (parallel/context.py), so config.ce_dtype behaves identically on
    every path.

    ce_dtype="bfloat16" (train only): ce = logsumexp - target_logit
    computed WITHOUT materializing a [B,T,V] fp32 log-softmax —
    max/shift/exp stay in the logits' bf16 (halving that tensor's HBM
    traffic) and only the V-axis normalizer sum accumulates in fp32,
    where the precision actually matters.  Eval/metrics keep the exact
    fp32 path."""
    if config.ce_dtype == "bfloat16" and train:
        m = jax.lax.stop_gradient(logits.max(axis=-1, keepdims=True))
        s = jnp.sum(
            jnp.exp(logits - m), axis=-1, dtype=jnp.float32
        )  # [B,T] fp32 accumulation of bf16 exps
        lse = m[..., 0].astype(jnp.float32) + jnp.log(s)
        tgt = jnp.take_along_axis(logits, sentences[..., None], axis=-1)
        return lse - tgt[..., 0].astype(jnp.float32)           # [B,T]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, sentences[..., None], axis=-1)[..., 0]


def compute_loss(
    variables: Dict[str, Any],
    config: Config,
    batch: Dict[str, jnp.ndarray],
    rng: Optional[jax.Array] = None,
    train: bool = True,
) -> Tuple[jnp.ndarray, Dict[str, Any]]:
    """Forward pass + the reference's total loss (model.py:293-334).

    batch: images [B,224,224,3] (or precomputed 'contexts' [B,N,D]),
    word_idxs [B,T] int32, masks [B,T] float32.
    Returns (total_loss, aux) with aux carrying metrics, alphas, and any
    mutated model state (BN stats).
    """
    if train and rng is None:
        raise ValueError("compute_loss(train=True) requires an rng for dropout")
    # L1 activity regularization gates (reference utils/nn.py:23-26,40-43):
    # fc activity when training, conv activity only when the CNN trains.
    fc_act_scale = config.fc_activity_regularizer_scale if train else 0.0
    train_cnn = train and config.train_cnn
    conv_act_scale = config.conv_activity_regularizer_scale if train_cnn else 0.0
    if "contexts" in batch:
        contexts, new_state = batch["contexts"], {}
    else:
        contexts, new_state = encode(
            variables, config, batch["images"], train_cnn,
            collect_activity=conv_act_scale > 0,
        )
    conv_activity = new_state.pop("activity_l1", jnp.float32(0))

    sentences = batch["word_idxs"]
    masks = batch["masks"].astype(jnp.float32)
    B, T = sentences.shape
    N = contexts.shape[1]

    decoded = teacher_forced_decode(
        variables["params"]["decoder"], config, contexts, sentences, train, rng,
        with_activity=fc_act_scale > 0,
    )  # [B,T,V], [B,T,N] (+ activity L1)
    fc_activity = jnp.float32(0)
    if fc_act_scale > 0:
        logits, alphas, fc_activity = decoded
    else:
        logits, alphas = decoded

    # masked sparse softmax cross-entropy, summed / mask-sum (model.py:316-318)
    ce = token_ce(logits, sentences, config, train)            # [B,T]
    mask_sum = masks.sum()
    cross_entropy_loss = (ce * masks).sum() / mask_sum

    # doubly stochastic attention penalty (model.py:320-326):
    # alphas masked per-step, summed over time; penalize departure from 1
    masked_alphas = alphas * masks[..., None]          # [B,T,N]
    attentions = masked_alphas.sum(axis=1)             # [B,N]
    diffs = 1.0 - attentions
    attention_loss = (
        config.attention_loss_factor * 0.5 * jnp.sum(diffs * diffs) / (B * N)
    )

    reg_loss = regularization_loss(
        variables["params"],
        fc_scale=config.fc_kernel_regularizer_scale if train else 0.0,
        conv_scale=config.conv_kernel_regularizer_scale,
        train_cnn=train_cnn,
    )
    # activity terms join the same reg bucket the reference sums via
    # tf.losses.get_regularization_loss() (model.py:328)
    reg_loss = reg_loss + fc_act_scale * fc_activity + conv_act_scale * conv_activity

    total_loss = cross_entropy_loss + attention_loss + reg_loss

    predictions = jnp.argmax(logits, axis=-1)
    accuracy = ((predictions == sentences) * masks).sum() / mask_sum

    aux = {
        "metrics": {
            "cross_entropy_loss": cross_entropy_loss,
            "attention_loss": attention_loss,
            "reg_loss": reg_loss,
            "total_loss": total_loss,
            "accuracy": accuracy,
        },
        "attentions": attentions,
        "model_state": new_state,
    }
    if train and config.diag_level != "off":
        # forward-side diag taps (docs/OBSERVABILITY.md): computed here
        # where alphas/logits are live so nothing bulky rides through aux;
        # gated statically on diag_level, so the off-path XLA program is
        # bit-for-bit the pre-diagnostics program
        from ..telemetry.device import loss_taps

        aux["metrics"].update(
            loss_taps(config.diag_level, alphas=alphas, masks=masks, logits=logits)
        )
    return total_loss, aux
