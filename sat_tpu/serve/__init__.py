"""Online captioning service (docs/SERVING.md).

The first request-driven workload in the codebase: frozen params loaded
through the resilience lineage, ``encode + beam_search`` AOT-compiled at
a fixed ladder of batch buckets so steady state never recompiles, a
dynamic micro-batcher with admission control, and a stdlib HTTP frontend
with graceful SIGTERM drain.  ``serve_mode="continuous"`` swaps the
whole-batch dispatch for step-level continuous batching over a paged
slot pool (same zero-recompile guarantee, bitwise-identical results).

Layering:

* :mod:`engine`    — lineage param load, AOT bucket warmup, pad-to-bucket
  dispatch through compiled executables, detokenize drain;
* :mod:`slot_pool` — fixed-capacity paged slot pool for the stepped
  decode: AOT-warmed seed/step/harvest programs + host slot bookkeeping;
* :mod:`batcher`   — bounded queue and admission control; MicroBatcher
  gathers whole padded batches, ContinuousBatcher admits into free slots
  between decode steps and detokenizes asynchronously;
* :mod:`server`    — ThreadingHTTPServer frontend (POST /caption,
  GET /healthz, GET /stats), drain sequencing, the ``serve()`` CLI entry;
* :mod:`replica`   — jax-free replica manager: spawn/monitor N serve
  subprocesses over a port range, or front pre-started endpoints;
* :mod:`router`    — jax-free health-weighted HTTP router fronting N
  replicas: fleet view, hysteretic least-load picks, coherent edge
  shedding, single cross-replica retry, one-at-a-time drains.

Exports resolve lazily (PEP 562): importing :mod:`router`/:mod:`replica`
— or this package itself — must not pull jax, because the router process
is jax-free by contract (same rule as ``--supervise``); only touching an
engine-side symbol (ServeEngine, CaptionServer, ...) imports the jax
stack.
"""

from typing import TYPE_CHECKING

_LAZY = {
    "BucketOverflow": ("engine", "BucketOverflow"),
    "CaptionServer": ("server", "CaptionServer"),
    "ContinuousBatcher": ("batcher", "ContinuousBatcher"),
    "EncodeCache": ("encode_cache", "EncodeCache"),
    "GRID_CONTENT_TYPE": ("handoff", "GRID_CONTENT_TYPE"),
    "HandoffError": ("handoff", "HandoffError"),
    "MicroBatcher": ("batcher", "MicroBatcher"),
    "PagedSlotPool": ("slot_pool", "PagedSlotPool"),
    "Rejected": ("batcher", "Rejected"),
    "Request": ("batcher", "Request"),
    "ServeEngine": ("engine", "ServeEngine"),
    "load_serving_state": ("engine", "load_serving_state"),
    "serve": ("server", "serve"),
}

__all__ = sorted(_LAZY)

if TYPE_CHECKING:  # static analyzers see the eager imports
    from .batcher import ContinuousBatcher, MicroBatcher, Rejected, Request
    from .engine import BucketOverflow, ServeEngine, load_serving_state
    from .server import CaptionServer, serve
    from .slot_pool import PagedSlotPool


def __getattr__(name: str):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(f".{mod_name}", __name__), attr)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
