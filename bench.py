"""Benchmark: training throughput + MFU of the flagship caption model.

Measures steady-state captions/sec of the jitted train step — VGG16
encoder forward (frozen CNN, the reference's published configuration,
/root/reference/config.py:8-43 + README.md:85-89), 20-step scan decoder,
backward, global-norm clip 5.0, Adam — on whatever single device JAX
provides (the driver runs this on one real TPU chip).

Prints JSON lines on stdout of the shape
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
A driver reading either the FIRST or the LAST JSON line gets a valid
metric: the first line comes from a minimal timed window emitted as early
as possible, later lines re-emit the same schema with fuller numbers
(full window, then eval-decode extras).

Resilience (the r01/r02 artifacts died with zero parsed output because
the tunneled TPU backend hung during device init): the default entry
point is a lightweight ORCHESTRATOR that never imports jax.  It probes
the backend with a real compute round-trip in a short-timeout subprocess,
retrying in a loop while budget remains — observed tunnel outages are
transient — and only then runs the bench proper in a child with the
remaining budget as its watchdog.  If nothing lands, it prints a
machine-readable {"error": "device_unreachable", ...} line and exits 4.

The reference publishes no throughput numbers (SURVEY.md §6), so
``vs_baseline`` is computed against ``published.train_captions_per_sec``
in BASELINE.json when present (recorded from a prior round), else 1.0.

Env knobs: BENCH_BATCH (default 32), BENCH_STEPS (default 10),
BENCH_MIN_STEPS (minimal first-emit window, default 3),
BENCH_PROBE_MICRO (probe-side provisional micro-bench: "1" forces on,
"0" forces off, unset = on for accelerators / off for CPU; the probe
child emits a window="probe" contract line so a live probe alone lands a
non-null metric even if the full bench child later wedges — VERDICT r04
weak #1), BENCH_PROBE_MICRO_STEPS (its timed window, default 2),
BENCH_IMAGE_SIZE (override config.image_size for smoke/micro runs),
BENCH_WARMUP (default 2), BENCH_PEAK_TFLOPS (override chip bf16 peak for
MFU when the device kind is unknown), BENCH_TRAIN_CNN=1 (joint CNN+RNN
training instead of the default frozen-CNN reference configuration;
vs_baseline is pinned to 1.0 there since the recorded baseline is the
frozen config), BENCH_RNG_IMPL (override config.rng_impl, e.g.
threefry2x32 to reproduce the PERF.md dropout-PRNG A/B),
BENCH_WATCHDOG_S (total budget incl. probing, default 540),
BENCH_PROBE_TIMEOUT_S (per-probe-attempt timeout, default 120),
BENCH_CPU=1 (pin the CPU backend for dev/smoke runs),
BENCH_CNN=resnet50 (bench the second encoder family; vs_baseline pins
to 1.0 off the recorded vgg16 config), BENCH_REMAT=1 / BENCH_REMAT_CNN=1
(decoder / encoder rematerialization A/Bs),
BENCH_EVAL=0 (skip the additive eval-decode metric; BENCH_EVAL_ITERS
sizes its window), BENCH_SWEEP (comma list of extra batch sizes tried
after the primary windows land — default "64,128,256" for the
frozen-CNN config, "0" disables; the final line reports the best
measured config with the per-batch sweep results attached).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Orchestrator (default mode) — no jax import in this process, ever.
# ---------------------------------------------------------------------------


def _error_line(error: str, **extras) -> str:
    err = {
        "metric": "train_captions_per_sec",
        "value": None,
        "unit": "captions/sec/chip",
        "vs_baseline": None,
        "error": error,
    }
    # provenance stamp (schema_version / git_sha / run_id / host), same as
    # every measured row: an error row an infra-skip decision hangs off
    # (scripts/check_regression.py exit 3) must say which commit and
    # machine failed to measure.  telemetry.bench_stamp is jax-free, so
    # the orchestrator's no-jax rule holds; best-effort because the error
    # path must never be the thing that crashes.
    try:
        from sat_tpu import telemetry as _tel

        err.update(_tel.bench_stamp())
    except Exception:
        pass
    err.update(extras)
    return json.dumps(err)


def _emit_degraded(state: dict, child_rc) -> None:
    """Re-emit the last relayed (probe-provisional) metric line with
    ``degraded=true`` + the bench child's rc, so a driver reading the
    LAST JSON line sees BOTH a valid metric (value non-null, no ``error``
    key — the contract) and a machine-readable record that the full bench
    never completed (ADVICE r5 #2)."""
    if not state["last_metric"]:
        return  # a full-child line landed un-relayed; nothing to annotate
    final = dict(state["last_metric"])
    final.update(degraded=True, bench_child_rc=child_rc)
    print(json.dumps(final), flush=True)


def orchestrate() -> int:
    """Probe-retry-run loop inside the total BENCH_WATCHDOG_S budget.

    The tunneled backend wedges *uninterruptibly* when it is down (r02:
    `import jax` + device init hung 540s), so every touch of the backend
    happens in a subprocess the orchestrator can kill.  Outages observed
    so far were transient within a measurement day, hence the retry loop
    rather than one attempt (VERDICT r02 §next-round #1).
    """
    budget = float(os.environ.get("BENCH_WATCHDOG_S", "540"))
    deadline = _T0 + budget
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    min_run_budget = 45.0  # don't bother starting a bench child with less
    script = os.path.abspath(__file__)
    state = {"emitted": False, "attempts": 0, "probe_rc": None, "last_metric": None}

    # Last-resort self-deadline: a child stuck in uninterruptible kernel
    # sleep survives SIGKILL delivery until its syscall returns, which
    # would block both subprocess.run's post-kill wait() and the stdout
    # relay loop below past the budget — exactly the rc=124/zero-output
    # shape this orchestrator exists to prevent.  At deadline+20s, print
    # the error line (if no JSON landed) and exit hard.
    def last_resort():
        if not state["emitted"]:
            print(
                _error_line(
                    "orchestrator_deadline",
                    probe_attempts=state["attempts"],
                    last_probe_rc=state["probe_rc"],
                    budget_s=budget,
                ),
                flush=True,
            )
        log("ORCHESTRATOR DEADLINE: child unreapable; exiting hard")
        os._exit(4 if not state["emitted"] else 0)

    doom = threading.Timer(budget + 20.0, last_resort)
    doom.daemon = True
    doom.start()

    def remaining() -> float:
        return deadline - time.perf_counter()

    child_failures = 0
    last_child_rc = None
    while remaining() > min_run_budget:
        state["attempts"] += 1
        t = max(10.0, min(probe_timeout, remaining() - min_run_budget))
        log(
            f"probe attempt {state['attempts']} "
            f"(timeout {t:.0f}s, {remaining():.0f}s budget left)"
        )

        def relay(text: str | None) -> None:
            # Relay contract lines the probe child printed (its provisional
            # micro-bench metric) so a live probe alone lands a non-null
            # artifact even when the full bench child never finishes.
            # Parse-validate first: a probe killed mid-write can leave a
            # truncated line, which must neither enter the artifact nor
            # mark a metric as emitted.
            for pline in (text or "").splitlines():
                pline = pline.strip()
                if not pline.startswith("{"):
                    continue
                try:
                    parsed = json.loads(pline)
                except json.JSONDecodeError:
                    log("dropping truncated probe JSON fragment")
                    continue
                print(pline, flush=True)
                if parsed.get("value") is not None:
                    state["emitted"] = True
                    state["last_metric"] = parsed

        # The micro-bench needs import + init + a possibly-cold 20-40s
        # compile inside the probe's own timeout; with a short window
        # (late in the budget) that would convert a live-device probe
        # into a timeout.  Downgrade short-window probes to the pure
        # liveness check unless the caller pinned the knob explicitly.
        probe_env = dict(os.environ)
        if t < 90.0 and "BENCH_PROBE_MICRO" not in probe_env:
            probe_env["BENCH_PROBE_MICRO"] = "0"
        try:
            probe_proc = subprocess.run(
                [sys.executable, script, "--probe"],
                timeout=t,
                env=probe_env,
                stdout=subprocess.PIPE,
                text=True,
            )
            state["probe_rc"] = probe_proc.returncode
            relay(probe_proc.stdout)
        except subprocess.TimeoutExpired as e:
            state["probe_rc"] = -9
            # partial stdout may hold a metric emitted before the wedge
            out = e.stdout
            relay(out.decode(errors="replace") if isinstance(out, bytes) else out)
            log("probe timed out (backend unreachable or wedged)")
        if state["probe_rc"] != 0:
            log(f"probe failed rc={state['probe_rc']}; backing off before retry")
            time.sleep(min(10.0, max(0.0, remaining() - min_run_budget)))
            continue

        run_budget = remaining() - 5.0
        log(f"probe ok — launching bench child (budget {run_budget:.0f}s)")
        env = dict(os.environ, BENCH_WATCHDOG_S=str(max(30, int(run_budget))))
        t_child = time.perf_counter()
        proc = subprocess.Popen(
            [sys.executable, script, "--run"],
            env=env,
            stdout=subprocess.PIPE,
            text=True,
        )
        # Belt over the child's own watchdog braces: if the child wedges so
        # hard its watchdog thread can't fire, kill it from out here.
        killer = threading.Timer(run_budget + 10.0, proc.kill)
        killer.daemon = True
        killer.start()
        child_emitted = False
        try:
            assert proc.stdout is not None
            for line in proc.stdout:
                line = line.rstrip("\n")
                if not line:
                    continue
                print(line, flush=True)  # relay contract lines as they land
                if line.lstrip().startswith("{"):
                    state["emitted"] = True
                    child_emitted = True
            rc = proc.wait()
        finally:
            killer.cancel()
        if child_emitted:
            log(f"bench child exited rc={rc} after emitting JSON — done")
            return 0
        child_s = time.perf_counter() - t_child
        last_child_rc = rc
        log(f"bench child exited rc={rc} after {child_s:.0f}s with NO JSON")
        # A fast nonzero exit right after a healthy probe is a bug in the
        # bench itself (import error, compile crash), not a tunnel outage —
        # retrying forever would burn the budget and mislabel the failure.
        if rc != 0 and child_s < 60.0:
            child_failures += 1
            if child_failures >= 2:
                if state["emitted"]:
                    # the probe's provisional metric already landed; an
                    # error line here would become the LAST JSON line and
                    # break the "first or last line is a valid metric"
                    # contract.  Re-emit it annotated instead: extra keys
                    # keep the line a valid metric while recording
                    # machine-readably that the full bench child failed —
                    # a persistent bench bug must not masquerade as a
                    # healthy run (ADVICE r5 #2).
                    log(f"bench child keeps failing rc={rc}; keeping probe metric")
                    _emit_degraded(state, rc)
                    return 0
                print(
                    _error_line(
                        "bench_failed",
                        child_rc=rc,
                        probe_attempts=state["attempts"],
                        budget_s=budget,
                    ),
                    flush=True,
                )
                return 4
        log("re-probing if budget remains")

    # Budget exhausted.  A deterministic bench bug exits above via the
    # fast-failure path; reaching here means probes kept failing or a
    # child was killed mid-run (child_rc < 0) — a backend-availability
    # failure either way.  If a probe-side provisional metric landed, the
    # artifact is already valid — don't append an error as the last line.
    if state["emitted"]:
        # same degraded annotation as the fast-failure path: only the
        # probe's provisional window landed, so the artifact must say so
        log("budget exhausted after provisional metric — done")
        _emit_degraded(state, last_child_rc)
        return 0
    print(
        _error_line(
            "device_unreachable",
            probe_attempts=state["attempts"],
            last_probe_rc=state["probe_rc"],
            child_rc=last_child_rc,
            budget_s=budget,
        ),
        flush=True,
    )
    return 4


def probe() -> None:
    """Child: prove the backend actually computes, not just lists devices.

    The tunneled backend has been observed returning the device list while
    all computation hangs (scripts/tpu_session.sh stage 0) — require a
    matmul round-trip.

    After liveness is established, a provisional micro-bench runs the real
    jitted train step for a couple of timed steps and prints a
    window="probe" contract line (relayed by the orchestrator).  Four
    consecutive rounds produced value=null BENCH artifacts because the
    tunnel flapped between "probe ok" and the full child's first emit
    (r04: child wedged 464s in device init) — the provisional line makes
    a single live probe window sufficient for a non-null artifact.
    Default on for accelerators, off for CPU smoke probes
    (BENCH_PROBE_MICRO forces either way); best-effort — a micro-bench
    failure logs and leaves the probe's rc at 0.
    """
    log("probe: importing jax")
    import jax

    if os.environ.get("BENCH_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    x = jnp.ones((256, 256))
    val = float(jax.device_get((x @ x).sum()))
    d = jax.devices()[0]
    log(
        f"probe ok: {val} platform={d.platform} "
        f"kind={getattr(d, 'device_kind', '?')}"
    )

    micro = os.environ.get("BENCH_PROBE_MICRO", "")
    if micro == "0" or (micro != "1" and d.platform == "cpu"):
        return
    try:
        probe_micro(jax, d)
    except Exception as e:  # liveness already proven; metric is best-effort
        log(f"probe micro-bench failed (non-fatal): {e!r}")


def probe_micro(jax, device) -> None:
    """Timed micro-window of the real train step; prints one contract line.

    Uses the same config/batch construction as the full bench (so the
    provisional number is the same workload as the "minimal" window, just
    a shorter measurement) and the persistent compile cache (so a repeat
    probe in the same session compiles in ~0s).
    """
    import numpy as np

    _enable_compile_cache(jax)
    from sat_tpu.train.step import create_train_state, make_jit_train_step

    config = _config_from_env()
    B = config.batch_size
    n_steps = max(1, int(os.environ.get("BENCH_PROBE_MICRO_STEPS", "2")))
    log(f"probe micro: building batch B={B} T={config.max_caption_length}")
    host_batch = _host_batch(config, np.random.default_rng(0))
    log("probe micro: initializing model state")
    state = create_train_state(jax.random.PRNGKey(0), config)
    step_rng = jax.random.key(1, impl=config.rng_impl)
    batch = jax.device_put(host_batch, device)
    state = jax.device_put(state, device)
    jax.block_until_ready((batch, state))

    train_step = make_jit_train_step(config)
    log("probe micro: compiling train step (cached ~0s, cold ~20-40s)")
    t_c = time.perf_counter()
    compiled = train_step.lower(state, batch, step_rng).compile()
    compile_s = time.perf_counter() - t_c
    log(f"probe micro: compiled in {compile_s:.1f}s")

    state, metrics = compiled(state, batch, step_rng)  # warmup x1
    float(metrics["total_loss"])
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = compiled(state, batch, step_rng)
    float(metrics["total_loss"])  # sync
    elapsed = time.perf_counter() - t0

    captions_per_sec = n_steps * B / elapsed
    baseline = _load_baseline(config.train_cnn, config.cnn)
    result = {
        "metric": "train_captions_per_sec",
        "value": round(captions_per_sec, 2),
        "unit": "captions/sec/chip",
        "vs_baseline": round(captions_per_sec / baseline, 3) if baseline else 1.0,
        "step_time_ms": round(1e3 * elapsed / n_steps, 2),
        "batch_size": B,
        "train_cnn": config.train_cnn,
        "cnn": config.cnn,
        "compile_s": round(compile_s, 1),
        "device_kind": getattr(device, "device_kind", device.platform),
        "window": "probe",
        "steps_measured": n_steps,
    }
    flops = _program_flops(compiled)
    if flops is not None:
        achieved = flops * n_steps / elapsed
        result["tflops_per_sec"] = round(achieved / 1e12, 2)
        peak = _peak_flops(device)
        if peak:
            result["mfu"] = round(achieved / peak, 4)
    log(f"probe micro: {captions_per_sec:.2f} captions/sec (provisional)")
    print(json.dumps(result), flush=True)


# ---------------------------------------------------------------------------
# Bench proper (--run mode)
# ---------------------------------------------------------------------------

# bf16 peak FLOP/s per chip by accelerator generation (public spec sheets;
# used only to report MFU next to raw throughput).
_PEAK_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5lite": 197.0,   # JAX reports v5e as device_kind "TPU v5 lite"
    "v5p": 459.0,
    "v6e": 918.0,
    "v6lite": 918.0,
}


def _peak_flops(device) -> float | None:
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key, tf in _PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return None


def _program_flops(compiled) -> float | None:
    """FLOPs/step from XLA's cost analysis of the compiled program."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception as e:  # cost analysis is best-effort on some backends
        log(f"cost_analysis unavailable: {e!r}")
        return None


def _enable_compile_cache(jax) -> None:
    """Persistent XLA compilation cache: a re-run (or a driver retry, or
    the bench child right after a micro-probe) skips the 20-40s compile.
    Machine-keyed so caches never cross heterogeneous build boxes."""
    from sat_tpu.utils.compile_cache import enable

    enable(jax, root=os.path.dirname(os.path.abspath(__file__)))


def _config_from_env():
    """The benched Config, from the BENCH_* env knobs (shared between the
    probe micro-bench and the full bench child so both measure the same
    workload)."""
    from sat_tpu.config import Config

    config = Config(
        batch_size=int(os.environ.get("BENCH_BATCH", "32")),
        train_cnn=os.environ.get("BENCH_TRAIN_CNN", "0") == "1",
        cnn=os.environ.get("BENCH_CNN", "vgg16"),
    )
    if "BENCH_IMAGE_SIZE" in os.environ:  # smoke/micro runs off-reference
        config = config.replace(image_size=int(os.environ["BENCH_IMAGE_SIZE"]))
    if "BENCH_RNG_IMPL" in os.environ:  # e.g. threefry2x32, to rerun the
        config = config.replace(rng_impl=os.environ["BENCH_RNG_IMPL"])  # PERF.md A/B
    if os.environ.get("BENCH_REMAT") == "1":  # decoder-remat A/B
        config = config.replace(remat_decoder=True)
    if os.environ.get("BENCH_REMAT_CNN") == "1":  # encoder-remat A/B (joint)
        config = config.replace(remat_cnn=True)
    if "BENCH_CE_DTYPE" in os.environ:  # bf16-CE A/B (PERF.md MFU lever)
        config = config.replace(ce_dtype=os.environ["BENCH_CE_DTYPE"])
    return config


def _host_batch(config, rng, B=None):
    import numpy as np

    B = config.batch_size if B is None else B
    T = config.max_caption_length
    S = config.image_size
    return {
        "images": rng.normal(size=(B, S, S, 3)).astype(np.float32),
        "word_idxs": rng.integers(0, config.vocabulary_size, size=(B, T)).astype(
            np.int32
        ),
        "masks": (np.arange(T)[None, :] < rng.integers(8, T + 1, size=(B, 1))).astype(
            np.float32
        ),
    }


def _load_baseline(train_cnn: bool, cnn: str):
    """The recorded frozen-CNN vgg16 baseline, when that's the workload."""
    if train_cnn or cnn != "vgg16":
        # the recorded baseline is the frozen-CNN configuration; a joint
        # CNN+RNN run is a different workload, not a regression against it
        return None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            return json.load(f).get("published", {}).get("train_captions_per_sec")
    except (OSError, json.JSONDecodeError):
        return None


def _arm_watchdog() -> "callable":
    """Hard deadline for the bench child (BENCH_WATCHDOG_S, set by the
    orchestrator to its remaining budget).  Returns a disarm callback."""
    deadline = float(os.environ.get("BENCH_WATCHDOG_S", "540"))
    done = threading.Event()

    def monitor():
        if not done.wait(deadline):
            log(
                f"WATCHDOG: bench did not finish within {deadline:.0f}s — "
                "device backend unreachable or compile stuck; aborting"
            )
            os._exit(3)

    threading.Thread(target=monitor, daemon=True).start()
    return done.set


def run_bench() -> None:
    import numpy as np

    disarm = _arm_watchdog()
    log("importing jax")
    import jax

    if os.environ.get("BENCH_CPU") == "1":
        # dev/smoke runs off-TPU; config pin needed because the axon
        # sitecustomize re-registers the TPU plugin over JAX_PLATFORMS
        jax.config.update("jax_platforms", "cpu")

    _enable_compile_cache(jax)

    from sat_tpu.train.step import create_train_state, make_jit_train_step

    device = jax.devices()[0]
    log(f"platform={device.platform} device_kind={getattr(device, 'device_kind', '?')}")

    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    n_steps = int(os.environ.get("BENCH_STEPS", "10"))
    n_min = max(1, int(os.environ.get("BENCH_MIN_STEPS", "3")))
    config = _config_from_env()
    B = config.batch_size
    train_cnn = config.train_cnn
    cnn = config.cnn
    T = config.max_caption_length

    rng = np.random.default_rng(0)
    log(f"building host batch B={B} T={T}")
    host_batch = _host_batch(config, rng)

    log("initializing model state")
    state = create_train_state(jax.random.PRNGKey(0), config)
    step_rng = jax.random.key(1, impl=config.rng_impl)
    log("transferring batch + state to device")
    batch = jax.device_put(host_batch, device)
    state = jax.device_put(state, device)
    jax.block_until_ready((batch, state))

    train_step = make_jit_train_step(config)
    log("lowering + compiling train step (first compile ~20-40s uncached)")
    t_c = time.perf_counter()
    compiled = train_step.lower(state, batch, step_rng).compile()
    compile_s = time.perf_counter() - t_c
    log(f"compiled in {compile_s:.1f}s")
    flops_per_step = _program_flops(compiled)

    baseline = _load_baseline(train_cnn, cnn)
    peak = _peak_flops(device)

    def emit(elapsed: float, steps: int, window: str) -> dict:
        captions_per_sec = steps * B / elapsed
        step_ms = 1e3 * elapsed / steps
        log(f"[{window}] {captions_per_sec:.2f} captions/sec ({step_ms:.1f} ms/step)")
        result = {
            "metric": "train_captions_per_sec",
            "value": round(captions_per_sec, 2),
            "unit": "captions/sec/chip",
            "vs_baseline": round(captions_per_sec / baseline, 3) if baseline else 1.0,
            "step_time_ms": round(step_ms, 2),
            "batch_size": B,
            "train_cnn": train_cnn,
            "cnn": cnn,
            "compile_s": round(compile_s, 1),
            "device_kind": getattr(device, "device_kind", device.platform),
            "window": window,
            "steps_measured": steps,
        }
        if flops_per_step is not None:
            achieved = flops_per_step * steps / elapsed
            result["tflops_per_sec"] = round(achieved / 1e12, 2)
            if peak:
                result["mfu"] = round(achieved / peak, 4)
        print(json.dumps(result), flush=True)
        return result

    log(f"warmup x{warmup}")
    for _ in range(warmup):
        state, metrics = compiled(state, batch, step_rng)
        loss = float(metrics["total_loss"])  # hard host sync barrier
        log(f"warmup step done, loss={loss:.4f}")

    # Minimal window FIRST: a parsed contract line lands within seconds of
    # compile even if the tunnel dies mid-run (r02 lesson — nothing may
    # delay the first JSON print).
    log(f"minimal timing window x{n_min}")
    t0 = time.perf_counter()
    for _ in range(n_min):
        state, metrics = compiled(state, batch, step_rng)
    float(metrics["total_loss"])  # sync
    emit(time.perf_counter() - t0, n_min, "minimal")

    log(f"full timing window x{n_steps}")
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = compiled(state, batch, step_rng)
    float(metrics["total_loss"])  # sync
    result = emit(time.perf_counter() - t0, n_steps, "full")

    # Batch-size sweep: the chip's best operating point is usually a
    # bigger batch than the B=32 default (the MXU tiles 128 rows); with
    # the contract line already emitted, trying B∈{64,128,256} risks
    # nothing (per-size try/except; OOM just logs skipped) and the final
    # line reports the best measured config.  Skipped for the A/B
    # variants (joint CNN can OOM at B=128 without remat; BENCH_SWEEP=0
    # disables).
    sweep_env = os.environ.get(
        "BENCH_SWEEP", "64,128,256" if not train_cnn else "0"
    )
    sweep_batches = [
        int(x) for x in sweep_env.split(",") if x.strip() and x.strip() != "0"
    ]
    if sweep_batches:
        result["sweep"] = {str(B): result["value"]}
    for B2 in sweep_batches:
        if B2 == B:
            continue
        try:
            log(f"sweep: building + compiling B={B2}")
            batch2 = jax.device_put(_host_batch(config, rng, B2), device)
            state2 = jax.device_put(jax.device_get(state), device)
            cfg2 = config.replace(batch_size=B2)
            step2 = make_jit_train_step(cfg2)
            t_c = time.perf_counter()
            compiled2 = step2.lower(state2, batch2, step_rng).compile()
            log(f"sweep B={B2}: compiled in {time.perf_counter() - t_c:.1f}s")
            flops2 = _program_flops(compiled2)
            for _ in range(warmup):
                state2, m2 = compiled2(state2, batch2, step_rng)
                float(m2["total_loss"])
            t0 = time.perf_counter()
            for _ in range(n_steps):
                state2, m2 = compiled2(state2, batch2, step_rng)
            float(m2["total_loss"])
            el2 = time.perf_counter() - t0
            cps2 = n_steps * B2 / el2
            log(f"sweep B={B2}: {cps2:.2f} captions/sec ({1e3*el2/n_steps:.1f} ms/step)")
            result["sweep"][str(B2)] = round(cps2, 2)
            if cps2 > result["value"]:
                result.update(
                    value=round(cps2, 2),
                    vs_baseline=round(cps2 / baseline, 3) if baseline else 1.0,
                    step_time_ms=round(1e3 * el2 / n_steps, 2),
                    batch_size=B2,
                    window="full",
                )
                if flops2 is not None:
                    achieved = flops2 * n_steps / el2
                    result["tflops_per_sec"] = round(achieved / 1e12, 2)
                    if peak:
                        result["mfu"] = round(achieved / peak, 4)
            print(json.dumps(result), flush=True)
        except Exception as e:  # OOM etc.: keep the already-emitted result
            log(f"sweep B={B2} skipped: {e!r}")

    # Eval-decode throughput (encode + on-device batched beam search) in
    # the same artifact.  Strictly additive AFTER the contract lines: a
    # fuller JSON line is re-emitted once the extras exist, so a driver
    # reading either the first or the last JSON line gets valid metrics.
    # (BENCH_EVAL=0 disables.)
    if os.environ.get("BENCH_EVAL", "1") == "1":
        try:
            from sat_tpu.utils.benchmarking import (
                make_chained_decode,
                time_decode_windows,
            )

            log("eval decode: compiling encoder+beam program (beam=3)")
            eval_iters = int(os.environ.get("BENCH_EVAL_ITERS", "5"))

            # BN encoders (resnet50) need running statistics at inference;
            # thread them through or the apply fails (ADVICE r02).
            eval_variables = {"params": state.params}
            if state.batch_stats:
                eval_variables["batch_stats"] = state.batch_stats

            # the SAME measurement core as scripts/bench_eval{,_ab}.py —
            # cross-vehicle deltas are process state, never harness drift
            decode = make_chained_decode(config, eos=1, beam_size=3)
            compile_s, windows_ms, _ = time_decode_windows(
                decode, eval_variables, batch["images"], eval_iters, windows=1
            )
            log(f"eval decode compiled+first in {compile_s:.1f}s")
            result["eval_images_per_sec"] = round(1e3 * B / windows_ms[0], 2)
            result["eval_batch_ms"] = round(windows_ms[0], 1)
            log(f"eval decode: {result['eval_images_per_sec']} images/sec @ beam=3")
            print(json.dumps(result), flush=True)
        except Exception as e:  # pragma: no cover - additive metric only
            log(f"eval decode bench skipped: {e!r}")

    disarm()


def main() -> None:
    if "--probe" in sys.argv:
        probe()
    elif "--run" in sys.argv:
        run_bench()
    else:
        sys.exit(orchestrate())


if __name__ == "__main__":
    main()
