"""sat_tpu.bulk — offline bulk captioning at dataset scale (docs/BULK.md).

``--phase bulk`` streams an arbitrary image corpus (directory tree or
file list, no captions required) through the existing planes and writes
sharded caption JSONL outputs with an atomically-updated resume
manifest:

* **input** — corpus walk + shard planning (:mod:`.corpus`), riding the
  shard-cache build, crc32c row integrity and the quarantine ledger
  (``data.shards``, ``data.integrity``, ``resilience.quarantine``) so
  poison images are substituted, never fatal;
* **decode** — the serve engine's AOT-warmed path (lineage param load,
  quantize-once, ``PagedSlotPool`` continuous stepped decode) embedded
  headless, no HTTP (:mod:`.runner`);
* **output** — ``captions_<shard>.jsonl`` + crc32c sidecars with
  tmp+rename atomicity (:mod:`.writer`) and the ``bulk_manifest.json``
  resume frontier (:mod:`.manifest`), making the job crash-only: kill
  -9 anywhere, relaunch (``--supervise``), completed shards are skipped
  bitwise-identically.

Only :mod:`.runner` touches jax (lazily, inside ``run_bulk``); the
corpus/manifest/writer control plane is jax-free so supervisors and
host-only tools can plan and verify bulk runs without a backend
(``tests/test_device_diag.py`` enforces this).
"""

from .corpus import plan_shards, resolve_corpus  # noqa: F401
from .manifest import (  # noqa: F401
    corpus_fingerprint,
    load_manifest,
    new_manifest,
    write_manifest,
)
from .writer import ShardWriter, shard_filename, verify_shard  # noqa: F401


def run_bulk(config, model_file=None):
    """Lazy re-export: importing :mod:`sat_tpu.bulk` must not pull jax."""
    from .runner import run_bulk as _run

    return _run(config, model_file=model_file)
