"""Export a sat_tpu checkpoint into the reference's flat TF1 npy layout.

Migration in the reverse direction of ``--load`` + reference import: the
output file is a ``{var.name: value}`` dict exactly as the reference's own
``save()`` writes (/root/reference/base_model.py:242-249), so the
reference's ``load()`` (per-name assign with missing-key tolerance,
base_model.py:270-277) ingests a sat_tpu-trained model directly.
Optimizer slots are not exported.

Usage: python scripts/export_reference.py <checkpoint.npz> <out.npy>
       [--config config.json]

The config sidecar (written next to every checkpoint) supplies the model
architecture; pass --config explicitly if the checkpoint was moved away
from its sidecar.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("checkpoint", help="sat_tpu .npz checkpoint")
    ap.add_argument("out", help="output .npy in reference layout")
    ap.add_argument(
        "--config", default=None,
        help="config.json (default: sidecar next to the checkpoint)",
    )
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")  # host-side tensor shuffling

    from sat_tpu.config import Config
    from sat_tpu.train.checkpoint import (
        export_reference_checkpoint,
        restore_checkpoint,
    )
    from sat_tpu.train.step import create_train_state

    config_path = args.config or os.path.join(
        os.path.dirname(os.path.abspath(args.checkpoint)), "config.json"
    )
    config = Config.load(config_path)
    state = create_train_state(jax.random.PRNGKey(0), config)
    state, count = restore_checkpoint(state, args.checkpoint)
    print(f"{count} tensors restored from {args.checkpoint}")
    n = export_reference_checkpoint(state, args.out)
    print(f"{n} tensors exported in reference layout -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
