"""Online capacity model + encode-cache opportunity probe.

Built on the same feeds the metering ledger produces (occupancy-ms,
request counts) plus the serve spans the batcher already records
(steps/dispatch, encode-lane geometry), this module answers two
forward-looking questions no raw counter does:

* **How close to the ceiling is this replica?**  The pool's effective
  captions/s ceiling is ``slots / mean_occupancy_s`` — how fast finished
  requests vacate slots at the *current* traffic mix (caption lengths,
  fused-window depths, encode-lane fill all priced in, because occupancy
  is measured, not modeled).  Headroom is the unused fraction of slot
  capacity; the SLO engine can burn on it (``capacity_headroom``
  objective, a ``gauge_floor``), paging BEFORE latency melts instead of
  after.

* **Would an encode cache pay for itself?**  A crc32c-keyed sliding
  sketch measures the *would-be* hit ratio of a bounded encode cache on
  live traffic — the Zipf evidence ROADMAP item 2 needs before a line
  of cache code is written.  Keys are post-image hashes; no pixels are
  retained, so the probe is as cheap as a dict lookup and safe to leave
  on.

Everything here is host-side arithmetic over already-collected numbers:
``maybe_update`` is rate-limited (once per ``interval_s``, except the
very first publish, which always goes through so early scrapes never
see an empty capacity block) and called
from boundaries that already run per request or per scrape — zero
device syncs, zero steady-state recompiles.

Deliberately jax-free, like the rest of ``sat_tpu/telemetry``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional


class EncodeCacheSketch(object):
    """Sliding-window membership sketch over request image keys.

    ``observe(key)`` reports whether the key was seen within the last
    ``window`` observations — exactly the hit a ``window``-entry LRU-ish
    encode cache would have scored.  O(1) per observation: a deque for
    recency eviction plus a refcount dict for membership (the same key
    may appear several times inside one window)."""

    def __init__(self, window: int = 4096) -> None:
        self._window = max(int(window), 1)
        self._ring: collections.deque = collections.deque()
        self._counts: Dict[int, int] = {}
        self._lock = threading.Lock()
        self.lookups = 0
        self.hits = 0

    def observe(self, key: int) -> bool:
        """Record one request's image key; True when a cache of this
        window size would have hit."""
        with self._lock:
            self.lookups += 1
            hit = key in self._counts
            if hit:
                self.hits += 1
            self._ring.append(key)
            self._counts[key] = self._counts.get(key, 0) + 1
            if len(self._ring) > self._window:
                old = self._ring.popleft()
                left = self._counts[old] - 1
                if left:
                    self._counts[old] = left
                else:
                    del self._counts[old]
            return hit

    def ratio(self) -> float:
        with self._lock:
            return self.hits / self.lookups if self.lookups else 0.0


class CapacityModel(object):
    """Windowed capacity gauges from ledger totals + span aggregates.

    Keeps the previous cumulative snapshot and differences against it on
    each (rate-limited) update, so every gauge reflects the LAST window
    of real traffic, not the lifetime average — a replica that was busy
    an hour ago but idle now shows full headroom."""

    def __init__(
        self,
        tel,
        ledger,
        slots: int,
        interval_s: float = 1.0,
        sketch: Optional[EncodeCacheSketch] = None,
        cache=None,
        clock=time.monotonic,
    ) -> None:
        self._tel = tel
        self._ledger = ledger
        self._slots = max(int(slots), 1)
        self._interval = float(interval_s)
        self._sketch = sketch
        # the real EncodeCache (when --encode_cache on): its measured hit
        # ratio closes the loop on the sketch's would-hit prediction
        self._cache = cache
        self._clock = clock
        self._lock = threading.Lock()
        self._t_last = clock()
        # previous cumulative readings (requests, occupancy_ms,
        # steps-per-dispatch count/total, lane images/slots)
        self._prev = dict.fromkeys(
            ("req", "occ_ms", "spd_n", "spd_tot", "lane_img", "lane_slot"),
            0.0,
        )
        self._ceiling = 0.0  # last known, held across idle windows
        self._published = False  # first publish bypasses the rate limit

    def _cumulative(self) -> Dict[str, float]:
        snap = self._ledger.snapshot() if self._ledger is not None else {}
        req = sum(r["requests"] for r in snap.values())
        occ = sum(r["occupancy_ms"] for r in snap.values())
        agg = self._tel.aggregates()
        spd = agg.get("serve/steps_per_dispatch", (0, 0, 0))
        ctr = self._tel.counters()
        return {
            "req": float(req),
            "occ_ms": float(occ),
            # record() stores raw step counts in the duration slot, so
            # total "ns" here is total steps and count is dispatches
            "spd_n": float(spd[0]),
            "spd_tot": float(spd[1]),
            "lane_img": float(ctr.get("serve/encode_images", 0.0)),
            "lane_slot": float(ctr.get("serve/encode_lane_slots", 0.0)),
        }

    def maybe_update(self, force: bool = False) -> None:
        """Recompute and publish the capacity gauges, at most once per
        ``interval_s`` (call freely from request funnels and scrape
        paths; off-interval calls cost one clock read)."""
        now = self._clock()
        with self._lock:
            window_s = now - self._t_last
            if not force and self._published and window_s < self._interval:
                # Rate-limited — except the very first publish, which must
                # not race the interval: a scrape that lands before any
                # update would otherwise see an empty capacity block.
                return
            self._t_last = now
            cur = self._cumulative()
            prev, self._prev = self._prev, cur
        if window_s <= 0:
            return
        d_req = cur["req"] - prev["req"]
        d_occ_s = (cur["occ_ms"] - prev["occ_ms"]) / 1e3
        # Occupancy is credited at retire, so a window can momentarily
        # absorb more occupancy-seconds than it spans; clamp to [0, 1].
        busy = min(max(d_occ_s / (self._slots * window_s), 0.0), 1.0)
        if d_req > 0 and d_occ_s > 0:
            self._ceiling = self._slots * d_req / d_occ_s
        tel = self._tel
        self._published = True
        tel.gauge("capacity/slot_busy_ratio", round(busy, 4))
        tel.gauge("capacity/headroom_pct", round(100.0 * (1.0 - busy), 2))
        tel.gauge("capacity/ceiling_captions_per_s", round(self._ceiling, 3))
        tel.gauge(
            "capacity/completed_per_s",
            round(d_req / window_s, 3) if d_req > 0 else 0.0,
        )
        d_disp = cur["spd_n"] - prev["spd_n"]
        if d_disp > 0:
            tel.gauge(
                "capacity/steps_per_dispatch",
                round((cur["spd_tot"] - prev["spd_tot"]) / d_disp, 3),
            )
        d_slot = cur["lane_slot"] - prev["lane_slot"]
        if d_slot > 0:
            tel.gauge(
                "capacity/encode_lane_fill",
                round((cur["lane_img"] - prev["lane_img"]) / d_slot, 4),
            )
        if self._sketch is not None and self._sketch.lookups:
            tel.gauge(
                "capacity/encode_cache_would_hit_ratio",
                round(self._sketch.ratio(), 4),
            )
        if self._cache is not None and self._cache.lookups:
            actual = self._cache.hit_ratio()
            tel.gauge("capacity/encode_cache_hit_ratio", round(actual, 4))
            if self._sketch is not None and self._sketch.lookups:
                # prediction-vs-reality residual: positive means the sketch
                # over-promised (e.g. its window outlives the real ring)
                tel.gauge(
                    "capacity/encode_cache_reconcile_delta",
                    round(self._sketch.ratio() - actual, 4),
                )
