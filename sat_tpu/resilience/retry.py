"""Retrying host IO: jittered exponential backoff with error classification.

On shared cluster filesystems the common failure is not "the file is
gone" but "the mount hiccuped for 200 ms" — EIO/EAGAIN/ESTALE-class
errors that a second attempt clears.  ``retry_io`` wraps the durable-IO
call sites (checkpoint read/write, shard manifest + shard mmap opens,
caption-file reads — see the callers in ``utils.fileio``,
``train.checkpoint``, ``data.shards``, ``data.coco``) with bounded
retries, exponential backoff, and jitter so a fleet of preempted workers
relaunching together doesn't hammer the filesystem in lockstep.

Classification is deliberate, not blanket: errors that signal a *wrong
program or environment* (missing file, permission, a path that is a
directory, corrupt archive contents) fail immediately — retrying them
only hides the real bug — while errors that signal *transient transport
trouble* back off and retry.  Everything that is not an OSError at all
propagates untouched.

No jax, and no sat_tpu imports beyond ``faultinject`` (the injection
point ``SAT_FI_IO_FAILURES`` lands here) and the equally jax-free
``telemetry`` (each retry ticks the ``io/retries`` counter), so the
wrapper is usable from host-only tools like ``scripts/bench_ckpt.py``.
"""

from __future__ import annotations

import errno
import random
import sys
import time
from typing import Callable, Optional, Tuple, TypeVar

from .faultinject import consume_io_fault
from .. import telemetry

T = TypeVar("T")

# Transient-transport errnos: worth a second attempt.
RETRYABLE_ERRNOS = frozenset(
    getattr(errno, name)
    for name in (
        "EIO", "EAGAIN", "EBUSY", "EINTR", "ETIMEDOUT", "ESTALE",
        "ENETDOWN", "ENETUNREACH", "ENETRESET", "ECONNRESET",
        "ECONNABORTED", "EREMOTEIO",
    )
    if hasattr(errno, name)
)

# Wrong-program/environment OSError subclasses: never retried, even though
# they share the OSError base with the transient family.
FATAL_OSERROR_TYPES = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
    FileExistsError,
)

# Process-wide defaults, set once from Config (``configure`` below) so
# deep call sites (fileio, shards) honor --io_retries without threading a
# config through every layer.
_defaults = {"retries": 3, "base_delay_s": 0.05}

# Module-level PRNG: jitter is decorrelation across processes, not
# cryptography; a fixed seed keeps single-process test runs deterministic
# while PIDs decorrelate a real fleet.
_jitter_rng = random.Random(0x5A7)


def configure(retries: Optional[int] = None, base_delay_s: Optional[float] = None) -> None:
    """Install process-wide retry defaults (called with Config values at
    runtime entry; explicit ``retry_io`` kwargs always win)."""
    if retries is not None:
        _defaults["retries"] = max(0, int(retries))
    if base_delay_s is not None:
        _defaults["base_delay_s"] = float(base_delay_s)  # sync-ok: host config scalar


def backoff_delay(
    attempt: int,
    *,
    base_delay_s: float,
    max_delay_s: float = 2.0,
    jitter: Tuple[float, float] = (0.5, 1.5),
    rng: Optional[random.Random] = None,
) -> float:
    """Jittered exponential backoff for retry ``attempt`` (0-based):
    ``base * 2**attempt`` capped at ``max_delay_s``, scaled by a uniform
    draw from ``jitter``.  Shared by :func:`retry_io` and the crash-only
    supervisor (``resilience.supervisor``) so every retry loop in the
    fleet decorrelates the same way."""
    delay = min(float(base_delay_s) * (2.0 ** attempt), max_delay_s)  # sync-ok: host arithmetic
    return delay * (rng or _jitter_rng).uniform(*jitter)


def is_retryable(exc: BaseException) -> bool:
    """Transient vs fatal: the classification ``retry_io`` applies."""
    if not isinstance(exc, OSError):
        return False
    if isinstance(exc, FATAL_OSERROR_TYPES):
        return False
    if isinstance(exc, (TimeoutError, BlockingIOError, InterruptedError, ConnectionError)):
        return True
    return exc.errno in RETRYABLE_ERRNOS


def retry_io(
    fn: Callable[[], T],
    *,
    desc: str,
    retries: Optional[int] = None,
    base_delay_s: Optional[float] = None,
    max_delay_s: float = 2.0,
    jitter: Tuple[float, float] = (0.5, 1.5),
    sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Run ``fn()`` with up to ``retries`` retries on transient IO errors.

    Backoff before retry k (0-based) is ``base * 2**k`` capped at
    ``max_delay_s``, scaled by a uniform jitter draw from ``jitter``.
    Fatal errors (see :func:`is_retryable`) raise immediately; the final
    transient failure raises with the full retry history behind it.
    ``desc`` names the operation in warnings and is what
    ``SAT_FI_IO_FAILURES=n:substr`` matches against.
    """
    budget = _defaults["retries"] if retries is None else max(0, int(retries))
    base = _defaults["base_delay_s"] if base_delay_s is None else float(base_delay_s)  # sync-ok: host config scalar
    for attempt in range(budget + 1):
        try:
            consume_io_fault(desc)
            return fn()
        except BaseException as e:
            if not is_retryable(e) or attempt == budget:
                raise
            telemetry.count("io/retries")
            delay = backoff_delay(
                attempt,
                base_delay_s=base,
                max_delay_s=max_delay_s,
                jitter=jitter,
            )
            print(
                f"sat_tpu: transient IO error on {desc} "
                f"(attempt {attempt + 1}/{budget + 1}): {e} — "
                f"retrying in {delay * 1e3:.0f} ms",
                file=sys.stderr,
                flush=True,
            )
            sleep(delay)
    raise AssertionError("unreachable")  # loop always returns or raises
