"""Observability-layer cost accounting: tracing + exposition overhead.

ISSUE 9's contract: the request-scoped tracing layer and the Prometheus
exposition must be cheap enough to leave on for every request of every
serving process — tracing + exposition under 1% of a 30 ms step-scale
unit of work, and per-request tracing under 2% of a nominal closed-loop
request.  This bench puts numbers on both without jax (everything
measured is pure host work, same rationale as bench_telemetry.py):

* ``tracing``: the full per-request trace lifecycle the serve path pays —
  ``begin`` (id sanitize/mint), five phase ``mark``s, and ``finish``
  (record build + rotating access.jsonl append + retention ring).
* ``exposition``: one ``promtext.render`` over a recorder populated with
  a realistic serve-shaped registry (the per-scrape cost; scrapes are
  15s-cadence in production, so this is *way* off the hot path, but the
  gate keeps a regression from making scrapes disruptive).

Prints BENCH-contract JSON lines on stdout accepted by
``check_regression.py``.  Exit 0 when both gates hold, 1 otherwise.

Usage: python scripts/bench_obs.py [--iters 2000] [--step-ms 30]
       [--request-ms 30] [--workdir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sat_tpu import telemetry
from sat_tpu.telemetry import promtext, tracectx

_T0 = time.perf_counter()

# gates (ISSUE 9 satellite): tracing+exposition < 1% of a step-scale unit
# of work; per-request tracing < 2% of a closed-loop request
STEP_GATE_PCT = 1.0
REQUEST_GATE_PCT = 2.0


def log(msg: str) -> None:
    print(f"[bench_obs +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _trace_lifecycle(tracer: tracectx.RequestTracer, iters: int) -> float:
    """Seconds per full request-trace lifecycle (begin + 5 marks +
    finish with the access.jsonl append)."""
    t_start = time.perf_counter()
    for i in range(iters):
        trace = tracer.begin(f"bench-{i:08d}")
        t0 = trace.t_start_ns
        for phase in tracectx.PHASES:
            trace.mark(phase, t0, 1_000_000)
        tracer.finish(trace, 200, 30_000_000, bucket=16)
    return (time.perf_counter() - t_start) / iters


def _populate(tel, requests: int = 512) -> None:
    """Give the recorder a serve-shaped registry so render() iterates a
    realistic name population."""
    for name in ("serve/request", "serve/queue_wait", "serve/preprocess",
                 "serve/dispatch", "serve/detok"):
        for _ in range(64):
            tel.record(name, time.perf_counter_ns(), 1_000_000)
    for i in range(requests):
        tel.count("serve/http_requests")
        tel.count("serve/completed")
    for b in (1, 4, 16, 32):
        tel.count(f"serve/bucket_{b}", 7)
    tel.gauge("serve/queue_depth", 3)
    tel.gauge("serve/ready", 1)
    for i in range(8):
        tel.gauge(f"slo/objective_{i}_burn", 0.4)


def _render_cost(tel, iters: int) -> float:
    t_start = time.perf_counter()
    for _ in range(iters):
        text = promtext.render(tel, extra={"steps_per_s": 3.2})
    assert text.endswith("sat_up 1\n")
    return (time.perf_counter() - t_start) / iters


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--iters", type=int, default=2000,
                    help="request-trace lifecycles / renders per measurement")
    ap.add_argument("--step-ms", type=float, default=30.0,
                    help="step-scale work unit the combined overhead is "
                         "judged against")
    ap.add_argument("--request-ms", type=float, default=30.0,
                    help="nominal closed-loop request latency the tracing "
                         "cost is judged against")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_obs_")
    made_workdir = args.workdir is None
    try:
        tel = telemetry.enable(capacity=65536)
        _populate(tel)

        tracer = tracectx.RequestTracer(
            path=os.path.join(workdir, "access.jsonl"),
            cap_bytes=8 * 1024 * 1024,
        )
        _trace_lifecycle(tracer, 200)  # warm (interning, first open)
        trace_s = _trace_lifecycle(tracer, args.iters)
        trace_us = trace_s * 1e6

        _render_cost(tel, 20)  # warm
        render_s = _render_cost(tel, max(200, args.iters // 10))
        render_us = render_s * 1e6
        telemetry.disable()

        # combined per-unit-of-work cost: one traced request + one
        # amortized scrape share (15 s cadence vs ~33 req/s at 30 ms —
        # charge 1/500th of a render per request, rounded up to 1/100th
        # to stay conservative)
        combined_us = trace_us + render_us / 100.0
        step_pct = 100.0 * (combined_us / 1e3) / args.step_ms
        request_pct = 100.0 * (trace_us / 1e3) / args.request_ms
        log(f"trace lifecycle {trace_us:.2f} us, render {render_us:.2f} us "
            f"-> {step_pct:.4f}% of a {args.step_ms:.0f} ms step, "
            f"{request_pct:.4f}% of a {args.request_ms:.0f} ms request")

        rows = [
            {
                "metric": "obs_tracing_exposition_overhead",
                "value": round(step_pct, 4),
                "unit": "%_of_step",
                "vs_baseline": STEP_GATE_PCT,
                "trace_lifecycle_us": round(trace_us, 3),
                "render_us": round(render_us, 3),
                "step_ms_assumed": args.step_ms,
                **telemetry.bench_stamp(),
            },
            {
                "metric": "obs_request_tracing_overhead",
                "value": round(request_pct, 4),
                "unit": "%_of_request",
                "vs_baseline": REQUEST_GATE_PCT,
                "trace_lifecycle_us": round(trace_us, 3),
                "request_ms_assumed": args.request_ms,
                **telemetry.bench_stamp(),
            },
        ]
        for row in rows:
            print(json.dumps(row), flush=True)
        ok = step_pct <= STEP_GATE_PCT and request_pct <= REQUEST_GATE_PCT
        if not ok:
            log(f"GATE FAIL: step {step_pct:.3f}% (bar {STEP_GATE_PCT}%) "
                f"request {request_pct:.3f}% (bar {REQUEST_GATE_PCT}%)")
        return 0 if ok else 1
    finally:
        telemetry.disable()
        if made_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
