from .coco import CocoCaptions
from .dataset import (
    DataSet,
    build_vocabulary,
    prepare_eval_data,
    prepare_test_data,
    prepare_train_data,
)
from .images import ILSVRC_2012_MEAN, ImageLoader, PrefetchLoader
from .tokenizer import PUNCTUATIONS, tokenize, tokenize_captions, tokenize_no_punct
from .vocabulary import Vocabulary

__all__ = [
    "CocoCaptions",
    "DataSet",
    "Vocabulary",
    "ImageLoader",
    "PrefetchLoader",
    "ILSVRC_2012_MEAN",
    "PUNCTUATIONS",
    "tokenize",
    "tokenize_captions",
    "tokenize_no_punct",
    "prepare_train_data",
    "prepare_eval_data",
    "prepare_test_data",
    "build_vocabulary",
]
