"""Input-pipeline A/B: live JPEG decode vs mmap shard-cache batch gather.

PERF.md's host-pipeline measurements identified per-step JPEG decode as
the binding bottleneck on this 1-core host (2.5-4.5 ms/image ⇒ 160-290 ms
of serial codec work per B=64 batch against a ~30 ms device step).  This
bench quantifies the fix (sat_tpu/data/shards.py): it materializes a
shard cache for a synthetic image set, then A/Bs per-batch feed time —

* ``sync`` window: batch assembly cost alone.  Live arm: thread-pool JPEG
  decode exactly as ``PrefetchLoader`` does it; shard arm: one mmap
  fancy-index gather per batch.
* ``overlap`` window: exposed host time per batch when a (simulated,
  ``--device-ms``) device step overlaps the prefetching loader — the
  number the train loop actually pays.

Prints BENCH-contract JSON lines on stdout ({"metric", "value", "unit",
"vs_baseline", ...extras}); the first line lands right after the sync A/B
and a fuller line re-emits the same schema with the overlap numbers, so a
driver reading either the first or the last JSON line gets a valid
metric.  ``value`` is the sync-feed speedup (live / shard, ×).  No jax
import anywhere: this is a pure host-side measurement and must never
wedge on an unreachable accelerator backend.

Usage: python scripts/bench_input.py [--batch 64] [--images 128]
       [--image-size 224] [--src-size 480] [--epochs 3] [--device-ms 30]
       [--host-preprocess] [--workdir DIR] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_input +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _write_jpegs(out_dir: str, n: int, src_size: int, seed: int = 0) -> list:
    """Synthetic photo-entropy JPEGs: smooth structure + noise, so the
    entropy decoder does realistic work (PERF.md measured 2.5-4.5 ms/image
    across photo/noise entropy at 640x480)."""
    import cv2

    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    h, w = (src_size * 3) // 4, src_size
    yy, xx = np.mgrid[0:h, 0:w]
    files = []
    for i in range(n):
        base = (
            96 + 80 * np.sin(xx / (17.0 + i % 7) + i)
            + 60 * np.cos(yy / (23.0 + i % 5))
        )
        img = np.clip(
            base[..., None] + rng.normal(0, 18, (h, w, 3)), 0, 255
        ).astype(np.uint8)
        f = os.path.join(out_dir, f"img_{i:05d}.jpg")
        cv2.imwrite(f, img, [int(cv2.IMWRITE_JPEG_QUALITY), 90])
        files.append(f)
    return files


def _batches(files: list, B: int, n_batches: int) -> list:
    """Deterministic batch file-lists cycling the image set."""
    out = []
    for b in range(n_batches):
        out.append([files[(b * B + i) % len(files)] for i in range(B)])
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--images", type=int, default=128)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--src-size", type=int, default=480,
                    help="source JPEG width (height = 3/4 width)")
    ap.add_argument("--sync-batches", type=int, default=5,
                    help="timed batches per arm in the sync window")
    ap.add_argument("--epochs", type=int, default=3,
                    help="dataset epochs per arm in the overlap window")
    ap.add_argument("--device-ms", type=float, default=30.0,
                    help="simulated device step per batch (PERF.md's ~30ms)")
    ap.add_argument("--host-preprocess", action="store_true",
                    help="A/B the raw=False path (float32 mean-sub on host, "
                         "config.device_preprocess=false) instead of the "
                         "default uint8 raw feed")
    ap.add_argument("--workdir", default=None,
                    help="keep images + shards here (default: fresh tmp dir, "
                         "removed on exit)")
    ap.add_argument("--out", default=None, help="also write the final JSON here")
    args = ap.parse_args()

    from sat_tpu.data import DataSet, ImageLoader, PrefetchLoader
    from sat_tpu.data.shards import build_shard_cache

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_input_")
    cleanup = args.workdir is None
    B, S = args.batch, args.image_size
    raw = not args.host_preprocess
    loader = ImageLoader(size=S, raw=raw)

    try:
        log(f"writing {args.images} synthetic JPEGs ({args.src_size}px) "
            f"under {workdir}")
        files = _write_jpegs(os.path.join(workdir, "images"), args.images,
                             args.src_size)

        # --- sync window: per-batch assembly cost, no overlap ------------
        batches = _batches(files, B, args.sync_batches + 1)
        from concurrent.futures import ThreadPoolExecutor

        log(f"live-decode sync baseline: {args.sync_batches} batches of {B}")
        live_ms = []
        with ThreadPoolExecutor(max_workers=8) as pool:
            for i, fs in enumerate(batches):
                t0 = time.perf_counter()
                np.stack(list(pool.map(loader.load_image, fs)))
                dt = 1e3 * (time.perf_counter() - t0)
                if i:  # first batch warms the page cache for both arms
                    live_ms.append(dt)
        live_med = float(np.median(live_ms))
        log(f"live decode: median {live_med:.1f} ms/batch")

        t0 = time.perf_counter()
        cache = build_shard_cache(
            files, os.path.join(workdir, "shards"), S, progress=False
        )
        build_s = time.perf_counter() - t0
        log(f"shard cache built in {build_s:.1f}s ({len(cache)} rows)")

        shard_ms = []
        for i, fs in enumerate(batches):
            t0 = time.perf_counter()
            g = cache.gather(fs)
            if not raw:
                g = g.astype(np.float32) - loader.mean
            dt = 1e3 * (time.perf_counter() - t0)
            if i:
                shard_ms.append(dt)
        shard_med = float(np.median(shard_ms))
        log(f"shard gather: median {shard_med:.2f} ms/batch")

        speedup = live_med / shard_med if shard_med > 0 else float("inf")
        result = {
            "metric": "input_feed_speedup",
            "value": round(speedup, 2),
            "unit": "x",
            "vs_baseline": 1.0,  # no previously recorded number
            "live_ms_per_batch": round(live_med, 2),
            "shard_ms_per_batch": round(shard_med, 3),
            "batch_size": B,
            "image_size": S,
            "images": args.images,
            "raw_feed": raw,
            "build_s": round(build_s, 2),
            "window": "sync",
        }
        from sat_tpu.telemetry import bench_stamp

        result.update(bench_stamp())
        print(json.dumps(result), flush=True)  # first contract line, early

        # --- overlap window: exposed host wait behind a simulated step --
        ds = DataSet(list(range(len(files))), files, B)
        sleep_s = args.device_ms / 1e3

        def exposed(shard_cache):
            pl = PrefetchLoader(
                ds, ImageLoader(size=S, raw=raw),
                num_workers=8, prefetch_depth=2, shard_cache=shard_cache,
            )
            waits = []
            for _ in range(args.epochs):
                it = iter(pl)
                while True:
                    t0 = time.perf_counter()
                    batch = next(it, None)
                    if batch is None:
                        break
                    waits.append(1e3 * (time.perf_counter() - t0))
                    time.sleep(sleep_s)  # the "device step"
            return float(np.median(waits))

        log(f"overlap window: live arm ({args.epochs} epochs, "
            f"{args.device_ms:.0f}ms simulated step)")
        live_exp = exposed(None)
        log(f"overlap window: shard arm")
        shard_exp = exposed(cache)
        log(f"exposed host wait: live {live_exp:.1f} ms/batch, "
            f"shard {shard_exp:.2f} ms/batch")

        result.update(
            window="overlap",
            device_step_ms=args.device_ms,
            live_exposed_ms_per_batch=round(live_exp, 2),
            shard_exposed_ms_per_batch=round(shard_exp, 3),
            exposed_speedup=round(live_exp / shard_exp, 2)
            if shard_exp > 0 else float("inf"),
        )
        print(json.dumps(result), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(result, f, indent=2)
        return 0
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
