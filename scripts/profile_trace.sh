#!/bin/bash
# Capture one real jax.profiler trace of the PrefetchLoader-fed train hot
# loop on the current backend (round-1 ask #8: back the
# "loader-hides-decode" claim with a trace, PERF.md §host-input-pipeline).
# Writes <outdir>/profile_done.txt on success so tpu_retry.sh can treat
# the trace as a stage artifact.
#
# Live-capture mode (ISSUE 9): point it at an already-running caption
# server and it opens an on-demand profiler window over HTTP instead of
# launching a fresh training run — no restart, no config edit:
#
#   bash scripts/profile_trace.sh --live HOST:PORT [duration_ms]
#
# The server answers 200 with the capture dir, or 409 if a window is
# already open (single-capture latch).  For a *training* process, send
# SIGUSR2 instead (`kill -USR2 <pid>`); the run opens a window of
# profile_window_ms at the next log boundary.  See OBSERVABILITY.md.
#
# Usage: bash scripts/profile_trace.sh [outdir]
#        bash scripts/profile_trace.sh --live HOST:PORT [duration_ms]
set -u
if [ "${1:-}" = "--live" ]; then
  ADDR=${2:?usage: profile_trace.sh --live HOST:PORT [duration_ms]}
  DUR=${3:-2000}
  BODY=$(curl -s -X POST "http://$ADDR/profile?duration_ms=$DUR") || {
    echo "live capture failed: server at $ADDR unreachable"; exit 1; }
  echo "$BODY"
  case "$BODY" in
    *profile_dir*) echo "profiler window open for ${DUR} ms"; exit 0 ;;
    *"in progress"*) echo "capture already in progress (409)"; exit 1 ;;
    *) echo "live capture refused"; exit 1 ;;
  esac
fi
OUT=${1:-/root/repo/runs/tpu_session_r3}
cd "$(dirname "$0")/.."
mkdir -p "$OUT"

if [ ! -f "$OUT/profile_run/captions.json" ]; then
  timeout 300 python scripts/quality_run.py --corpus-only --out "$OUT/profile_run" \
    >"$OUT/profile_corpus.log" 2>&1 || { echo "corpus gen failed"; exit 1; }
fi

PROF="$OUT/profile_run_trace"
timeout 700 python -m sat_tpu.cli --phase=train \
  --set train_image_dir="$OUT/profile_run/images" \
  --set train_caption_file="$OUT/profile_run/captions.json" \
  --set vocabulary_file="$OUT/profile_run/vocabulary_basic.csv" \
  --set temp_annotation_file="$OUT/profile_run/anns_basic.csv" \
  --set temp_data_file="$OUT/profile_run/data_basic.npy" \
  --set save_dir="$OUT/profile_run/models2" \
  --set summary_dir="$OUT/profile_run/summary2" \
  --set max_train_ann_num=none --set batch_size=32 --set num_epochs=30 \
  --set max_steps=25 --set save_period=0 \
  --set profile_dir="$PROF" --set profile_start_step=8 \
  --set profile_num_steps=5 >"$OUT/profile_train.log" 2>&1
rc=$?
# a COMPLETE trace only: partial dirs from a mid-trace kill don't count
if [ "$rc" -eq 0 ] && { ls "$PROF"/plugins/profile/*/*.xplane.pb >/dev/null 2>&1 || \
     ls "$PROF"/plugins/profile/*/*.trace.json.gz >/dev/null 2>&1; }; then
  echo "trace captured under $PROF" | tee "$OUT/profile_done.txt"
else
  echo "trace capture failed (rc=$rc) — see $OUT/profile_train.log"
  exit 1
fi
