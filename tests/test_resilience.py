"""Resilience subsystem: fault injection, checkpoint lineage, anomaly
sentinel, graceful preemption, retrying host IO (docs/RESILIENCE.md).

The integration tests drive every recovery path end-to-end through
``runtime.train`` with the ``SAT_FI_*`` injection knobs, on the same tiny
model the runtime tests use; the unit tests pin the layer contracts
(retry classification + backoff, lineage verify/walk-back/retention,
sentinel policies) without touching a training loop.
"""

import errno
import os
import signal
import time
from typing import Dict, NamedTuple

import numpy as np
import pytest

from sat_tpu import runtime
from sat_tpu.config import Config
from sat_tpu.resilience import lineage
from sat_tpu.resilience.faultinject import (
    FaultPlan,
    InjectedIOError,
    SimulatedPreemption,
    corrupt_byte,
)
from sat_tpu.resilience.preempt import GracefulShutdown
from sat_tpu.resilience.retry import is_retryable, retry_io
from sat_tpu.resilience.sentinel import MAX_ROLLBACKS, AnomalySentinel
from sat_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
    state_to_flat,
)

SMALL_MODEL = dict(
    image_size=32,
    dim_embedding=16,
    num_lstm_units=16,
    dim_initialize_layer=16,
    dim_attend_layer=16,
    dim_decode_layer=32,
    compute_dtype="float32",
    save_period=3,
    log_every=1,
    num_epochs=1,
    num_data_workers=2,
)


# ---------------------------------------------------------------------------
# retry_io: backoff + classification
# ---------------------------------------------------------------------------


def _flaky(failures, exc_factory):
    """A zero-arg fn failing ``failures`` times before returning 'done'."""
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] <= failures:
            raise exc_factory()
        return "done"

    return fn, calls


def test_retry_backoff_sequence_and_success():
    fn, calls = _flaky(3, lambda: OSError(errno.EIO, "mount hiccup"))
    sleeps = []
    out = retry_io(
        fn,
        desc="unit",
        retries=3,
        base_delay_s=0.1,
        jitter=(1.0, 1.0),  # disable jitter: the sequence is exact
        sleep=sleeps.append,
    )
    assert out == "done"
    assert calls["n"] == 4
    np.testing.assert_allclose(sleeps, [0.1, 0.2, 0.4])


def test_retry_delay_capped():
    fn, _ = _flaky(3, lambda: OSError(errno.ESTALE, "stale handle"))
    sleeps = []
    retry_io(
        fn,
        desc="unit",
        retries=3,
        base_delay_s=1.0,
        max_delay_s=1.5,
        jitter=(1.0, 1.0),
        sleep=sleeps.append,
    )
    np.testing.assert_allclose(sleeps, [1.0, 1.5, 1.5])


def test_retry_fatal_raises_immediately():
    fn, calls = _flaky(99, FileNotFoundError)
    sleeps = []
    with pytest.raises(FileNotFoundError):
        retry_io(fn, desc="unit", retries=3, sleep=sleeps.append)
    assert calls["n"] == 1  # wrong-environment errors never retry
    assert sleeps == []


def test_retry_exhausted_raises_last_error():
    fn, calls = _flaky(99, lambda: OSError(errno.EIO, "still down"))
    sleeps = []
    with pytest.raises(OSError, match="still down"):
        retry_io(fn, desc="unit", retries=2, base_delay_s=0.0, sleep=sleeps.append)
    assert calls["n"] == 3  # 1 try + 2 retries
    assert len(sleeps) == 2


def test_retry_non_oserror_propagates_untouched():
    fn, calls = _flaky(99, lambda: ValueError("bad payload"))
    with pytest.raises(ValueError):
        retry_io(fn, desc="unit", retries=3, sleep=lambda s: None)
    assert calls["n"] == 1


def test_is_retryable_classification():
    assert is_retryable(OSError(errno.EIO, "x"))
    assert is_retryable(OSError(errno.ESTALE, "x"))
    assert is_retryable(TimeoutError())
    assert is_retryable(ConnectionResetError())
    assert is_retryable(InjectedIOError("x", 0))
    assert not is_retryable(FileNotFoundError())
    assert not is_retryable(PermissionError())
    assert not is_retryable(IsADirectoryError())
    assert not is_retryable(ValueError())


def test_injected_io_failures_env(monkeypatch):
    monkeypatch.setenv("SAT_FI_IO_FAILURES", "2")
    fn, calls = _flaky(0, RuntimeError)  # fn itself never fails
    sleeps = []
    out = retry_io(fn, desc="anything", retries=3, base_delay_s=0.0, sleep=sleeps.append)
    assert out == "done"
    assert calls["n"] == 1  # injection fires BEFORE fn; fn ran once
    assert len(sleeps) == 2  # two injected attempts were retried


def test_injected_io_failures_substring_filter(monkeypatch):
    monkeypatch.setenv("SAT_FI_IO_FAILURES", "5:manifest")
    ok, _ = _flaky(0, RuntimeError)
    # non-matching description: untouched, no retries
    sleeps = []
    assert retry_io(ok, desc="read checkpoint", retries=0, sleep=sleeps.append) == "done"
    assert sleeps == []
    # matching description with no retry budget: the injection surfaces
    fn2, _ = _flaky(0, RuntimeError)
    with pytest.raises(InjectedIOError):
        retry_io(fn2, desc="read shard manifest", retries=0, sleep=sleeps.append)


# ---------------------------------------------------------------------------
# lineage: sidecars, verification, LAST_GOOD, retention
# ---------------------------------------------------------------------------


def _write_npz(path, **arrays):
    if not arrays:
        arrays = {"w": np.arange(8, dtype=np.float32)}
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    return path


def test_sidecar_catches_bit_rot(tmp_path):
    path = _write_npz(str(tmp_path / "3.npz"))
    lineage.write_sidecar(path)
    assert lineage.verify_checkpoint(path) == (True, "sha256 ok")
    corrupt_byte(path)
    ok, reason = lineage.verify_checkpoint(path)
    assert not ok and "sha256 mismatch" in reason


def test_zip_crc_fallback_without_sidecar(tmp_path):
    path = _write_npz(str(tmp_path / "3.npz"))
    ok, reason = lineage.verify_checkpoint(path)
    assert ok and "no sidecar" in reason
    corrupt_byte(path)
    ok, _ = lineage.verify_checkpoint(path)
    assert not ok


def test_truncated_and_empty_checkpoints_rejected(tmp_path):
    path = _write_npz(str(tmp_path / "6.npz"))
    lineage.write_sidecar(path)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    ok, _ = lineage.verify_checkpoint(path)
    assert not ok
    empty = str(tmp_path / "9.npz")
    open(empty, "wb").close()
    assert lineage.verify_checkpoint(empty) == (False, "empty file")
    assert lineage.verify_checkpoint(str(tmp_path / "12.npz"))[0] is False  # missing


def test_last_good_walks_back_past_rot(tmp_path):
    d = str(tmp_path)
    for step in (3, 6, 9):
        lineage.write_sidecar(_write_npz(os.path.join(d, f"{step}.npz")))
    lineage.mark_last_good(d, 9)
    assert lineage.last_good_checkpoint(d).endswith("9.npz")
    corrupt_byte(os.path.join(d, "9.npz"))
    assert lineage.last_good_checkpoint(d).endswith("6.npz")
    corrupt_byte(os.path.join(d, "6.npz"))
    assert lineage.last_good_checkpoint(d).endswith("3.npz")
    corrupt_byte(os.path.join(d, "3.npz"))
    assert lineage.last_good_checkpoint(d) is None


def test_last_good_never_returns_unblessed_newer(tmp_path):
    d = str(tmp_path)
    for step in (3, 6):
        lineage.write_sidecar(_write_npz(os.path.join(d, f"{step}.npz")))
    lineage.mark_last_good(d, 3)
    # 6.npz verifies fine but was never blessed (e.g. written while the
    # sentinel was unhealthy) — the pointer bounds the walk
    assert lineage.last_good_checkpoint(d).endswith("3.npz")


def test_retention_protects_last_good(tmp_path):
    d = str(tmp_path)
    for step in (1, 2, 3, 4, 5):
        lineage.write_sidecar(_write_npz(os.path.join(d, f"{step}.npz")))
    lineage.mark_last_good(d, 2)
    deleted = lineage.apply_retention(d, keep=2)
    assert lineage.checkpoint_steps(d) == [2, 4, 5]
    assert all(os.path.basename(p).startswith(("1.", "3.")) for p in deleted)
    assert not os.path.exists(os.path.join(d, "1.npz.sha256"))
    assert lineage.apply_retention(d, keep=0) == []  # 0 keeps everything


def test_finalize_save_blessing_rules(tmp_path):
    d = str(tmp_path)
    p3 = _write_npz(os.path.join(d, "3.npz"))
    assert lineage.finalize_save(d, p3, 3, healthy=True, keep=0)
    assert lineage.last_good_step(d) == 3
    # unhealthy save verifies but is not blessed
    p6 = _write_npz(os.path.join(d, "6.npz"))
    assert lineage.finalize_save(d, p6, 6, healthy=False, keep=0)
    assert lineage.last_good_step(d) == 3
    # corrupt-after-sidecar (the SAT_FI_CORRUPT_CKPT_STEP window): the
    # early hash pins the intended bytes, so the verify must fail and
    # the pointer must hold
    p9 = _write_npz(os.path.join(d, "9.npz"))
    lineage.write_sidecar(p9)
    corrupt_byte(p9)
    assert not lineage.finalize_save(d, p9, 9, healthy=True, keep=0)
    assert lineage.last_good_step(d) == 3


# ---------------------------------------------------------------------------
# checkpoint-layer restore + latest_checkpoint hygiene (fake states: no
# model init, the contracts are pure host IO)
# ---------------------------------------------------------------------------


class FakeState(NamedTuple):
    params: Dict
    batch_stats: Dict
    opt_state: Dict
    step: np.ndarray

    def _replace_step(self, step):  # pragma: no cover - readability alias
        return self._replace(step=step)


def _fake_state(step, value=0.0):
    return FakeState(
        params={"w": np.full((4,), value, np.float32)},
        batch_stats={},
        opt_state={"mu": {"w": np.full((4,), value / 10.0, np.float32)}},
        step=np.asarray(step, np.int32),
    )


def test_latest_checkpoint_skips_temp_partial_and_foreign_files(tmp_path):
    config = Config(save_dir=str(tmp_path))
    for step, value in ((3, 1.0), (6, 2.0)):
        save_checkpoint(_fake_state(step, value), config)
    # junk a preempted/misbehaving process could leave behind
    open(str(tmp_path / "9.npz.tmp"), "wb").write(b"partial")
    open(str(tmp_path / "12.npz"), "wb").close()  # zero-byte torn write
    open(str(tmp_path / "slim.npz"), "wb").write(b"trimmed-for-eval")
    os.mkdir(str(tmp_path / "15.npz"))
    open(str(tmp_path / "tmpab12.tmp"), "wb").write(b"x")
    assert latest_checkpoint(str(tmp_path)).endswith("6.npz")


def test_restore_walks_back_past_corrupt_and_truncated(tmp_path, capsys):
    config = Config(save_dir=str(tmp_path))
    for step, value in ((3, 1.0), (6, 2.0), (9, 3.0)):
        save_checkpoint(_fake_state(step, value), config)
    corrupt_byte(str(tmp_path / "9.npz"))
    with open(str(tmp_path / "6.npz"), "r+b") as f:
        f.truncate(os.path.getsize(str(tmp_path / "6.npz")) // 3)
    restored, count = restore_checkpoint(_fake_state(0), save_dir=str(tmp_path))
    assert count == 2  # params/w + optimizer mu/w
    assert int(restored.step) == 3
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), np.full((4,), 1.0))
    err = capsys.readouterr().err
    assert "9.npz" in err and "6.npz" in err and "walking back" in err


def test_restore_raises_when_nothing_verifiable(tmp_path):
    config = Config(save_dir=str(tmp_path))
    save_checkpoint(_fake_state(3, 1.0), config)
    corrupt_byte(str(tmp_path / "3.npz"))
    with pytest.raises(FileNotFoundError, match="no verifiable checkpoint"):
        restore_checkpoint(_fake_state(0), save_dir=str(tmp_path))


# ---------------------------------------------------------------------------
# anomaly sentinel (pure host-float decisions)
# ---------------------------------------------------------------------------


def test_sentinel_off_ignores_everything():
    s = AnomalySentinel("off")
    assert s.check(1, {"loss": float("nan")}) == "ok"
    assert s.healthy and not s.suppress_save


def test_sentinel_warn_reports_and_recovers():
    s = AnomalySentinel("warn")
    assert s.check(1, {"loss": 2.0}) == "ok"
    assert s.check(2, {"loss": float("nan")}) == "warn"
    assert not s.healthy and not s.suppress_save  # warn never blocks saves
    assert s.check(3, {"loss": float("inf")}) == "warn"
    assert s.check(4, {"loss": 2.0}) == "ok"  # self-recovered
    assert s.healthy and s.anomalies == 2


def test_sentinel_skip_suppresses_saves_while_unhealthy():
    s = AnomalySentinel("skip")
    assert s.check(1, {"loss": float("nan")}) == "skip"
    assert s.suppress_save
    assert s.check(2, {"loss": 1.0}) == "ok"
    assert not s.suppress_save


def test_sentinel_rollback_budget_degrades_to_warn():
    s = AnomalySentinel("rollback")
    for _ in range(MAX_ROLLBACKS):
        assert s.check(1, {"loss": float("nan")}) == "rollback"
        s.note_rolled_back()
        assert s.healthy
    assert s.check(2, {"loss": float("nan")}) == "warn"
    assert s.rollbacks == MAX_ROLLBACKS


def test_sentinel_loss_spike_detection():
    s = AnomalySentinel("warn", spike_factor=10.0)
    for step in range(1, 6):
        assert s.check(step, {"loss": 2.0}) == "ok"
    assert s.check(6, {"loss": 50.0}) == "warn"  # 25x the running mean
    assert "spiked" in s.last_reason
    # the spike did not drag the EMA up: a second spike still trips
    assert s.check(7, {"loss": 50.0}) == "warn"


# ---------------------------------------------------------------------------
# graceful shutdown
# ---------------------------------------------------------------------------


def test_graceful_shutdown_catches_sigterm_and_restores_handler():
    before = signal.getsignal(signal.SIGTERM)
    with GracefulShutdown() as s:
        assert not s.stop_requested
        signal.raise_signal(signal.SIGTERM)
        deadline = time.time() + 5.0
        while not s.stop_requested and time.time() < deadline:
            time.sleep(0.01)
        assert s.stop_requested
        assert s.signal_name == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is before


# ---------------------------------------------------------------------------
# summary writer degradation
# ---------------------------------------------------------------------------


def test_summary_writer_close_idempotent_and_post_close_writes_noop(tmp_path):
    from sat_tpu.utils.summary import SummaryWriter

    w = SummaryWriter(str(tmp_path))
    w.scalars(1, {"loss": 1.0})
    w.close()
    w.close()  # second close must not raise (with-block + ExitStack both hit it)
    w.scalars(2, {"loss": 2.0})  # post-close writes are silently dropped
    w.flush()
    lines = open(str(tmp_path / "metrics.jsonl")).read().strip().splitlines()
    assert len(lines) == 1


def test_summary_writer_degrades_on_io_failure(tmp_path, capsys):
    from sat_tpu.utils.summary import SummaryWriter

    w = SummaryWriter(str(tmp_path))
    w._events.close()  # simulate the filesystem yanking the handle
    w._jsonl.close()
    w.scalars(1, {"loss": 1.0})  # must warn, not raise
    w.scalars(2, {"loss": 2.0})
    w.close()
    err = capsys.readouterr().err
    assert err.count("summary writer disabled") == 1  # warned exactly once


# ---------------------------------------------------------------------------
# fault-injection plan plumbing
# ---------------------------------------------------------------------------


def test_fault_plan_inert_by_default_and_parses_env():
    assert FaultPlan.from_env({}).inert
    plan = FaultPlan.from_env(
        {"SAT_FI_DIE_AT_STEP": "5", "SAT_FI_NAN_AT_STEP": "7"}
    )
    assert not plan.inert
    assert plan.die_at_step == 5 and plan.nan_at_step == 7
    with pytest.raises(ValueError, match="expected an integer"):
        FaultPlan.from_env({"SAT_FI_DIE_AT_STEP": "soon"})


def test_fault_plan_die_fires_exactly_once():
    plan = FaultPlan(die_at_step=3)
    plan.maybe_kill(2)  # below threshold: nothing
    with pytest.raises(SimulatedPreemption):
        plan.maybe_kill(3)
    plan.maybe_kill(4)  # fired already: the 'process' died once


# ---------------------------------------------------------------------------
# end-to-end recovery paths through runtime.train (tiny model; compile
# cache shared with the runtime tests keeps these fast)
# ---------------------------------------------------------------------------


def _cfg(coco_fixture, tmp_path, name, **kw):
    return coco_fixture["config"].replace(
        **{
            **SMALL_MODEL,
            "save_dir": str(tmp_path / name),
            "summary_dir": str(tmp_path / (name + "_s")),
            **kw,
        }
    )


def test_injected_preemption_resume_bitwise_matches_control(
    coco_fixture, tmp_path, monkeypatch
):
    """SAT_FI_DIE_AT_STEP=k: the run dies abruptly, resume from the last
    periodic checkpoint replays to a bitwise-identical final state."""
    want = runtime.train(_cfg(coco_fixture, tmp_path, "control"))
    assert int(want.step) == 6

    cfg = _cfg(coco_fixture, tmp_path, "preempted")
    monkeypatch.setenv("SAT_FI_DIE_AT_STEP", "5")
    with pytest.raises(SimulatedPreemption):
        runtime.train(cfg)
    monkeypatch.delenv("SAT_FI_DIE_AT_STEP")
    # steps 4-5 ran but died before any later save: 3.npz is the survivor
    assert latest_checkpoint(cfg.save_dir).endswith("3.npz")
    assert lineage.last_good_step(cfg.save_dir) == 3

    state = runtime.setup_state(cfg, load=True)
    assert int(state.step) == 3
    state = runtime.train(cfg, state=state)
    assert int(state.step) == 6

    got, ref = state_to_flat(state), state_to_flat(want)
    assert set(got) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)


def test_injected_sigterm_stops_gracefully_with_final_checkpoint(
    coco_fixture, tmp_path, monkeypatch, capsys
):
    """SAT_FI_SIGTERM_AT_STEP=k: train() returns normally at the next step
    boundary with the final checkpoint flushed and blessed."""
    cfg = _cfg(coco_fixture, tmp_path, "sigterm")
    monkeypatch.setenv("SAT_FI_SIGTERM_AT_STEP", "4")
    state = runtime.train(cfg)
    assert int(state.step) == 4  # stopped at the boundary, not mid-epoch end
    assert latest_checkpoint(cfg.save_dir).endswith("4.npz")
    assert lineage.last_good_step(cfg.save_dir) == 4
    err = capsys.readouterr().err
    assert "SIGTERM" in err and "relaunch with --load" in err
    monkeypatch.delenv("SAT_FI_SIGTERM_AT_STEP")
    resumed = runtime.setup_state(cfg, load=True)
    assert int(resumed.step) == 4


def test_injected_nan_warn_policy_withholds_blessing(
    coco_fixture, tmp_path, monkeypatch
):
    """policy=warn: training continues, poisoned checkpoints still land,
    but LAST_GOOD stays at the last clean save."""
    cfg = _cfg(coco_fixture, tmp_path, "nanwarn", anomaly_policy="warn")
    monkeypatch.setenv("SAT_FI_NAN_AT_STEP", "4")
    state = runtime.train(cfg)
    assert int(state.step) == 6
    flat = state_to_flat(state)
    assert any(
        not np.all(np.isfinite(v))
        for k, v in flat.items()
        if k.startswith("params/") and np.asarray(v).dtype.kind == "f"
    )
    assert latest_checkpoint(cfg.save_dir).endswith("6.npz")  # still written
    assert lineage.last_good_step(cfg.save_dir) == 3  # but never blessed
    assert lineage.last_good_checkpoint(cfg.save_dir).endswith("3.npz")


def test_injected_nan_skip_policy_suppresses_writes(
    coco_fixture, tmp_path, monkeypatch, capsys
):
    """policy=skip: no checkpoint churn while unhealthy — the poisoned
    tail (including the final save) never reaches disk."""
    cfg = _cfg(coco_fixture, tmp_path, "nanskip", anomaly_policy="skip")
    monkeypatch.setenv("SAT_FI_NAN_AT_STEP", "4")
    state = runtime.train(cfg)
    assert int(state.step) == 6
    assert latest_checkpoint(cfg.save_dir).endswith("3.npz")
    assert lineage.checkpoint_steps(cfg.save_dir) == [3]
    assert "final checkpoint suppressed" in capsys.readouterr().err


def test_injected_nan_rollback_policy_recovers(
    coco_fixture, tmp_path, monkeypatch
):
    """policy=rollback: restore LAST_GOOD, skip the poison window, finish
    the epoch with finite params and a blessed final checkpoint."""
    cfg = _cfg(coco_fixture, tmp_path, "nanroll", anomaly_policy="rollback")
    monkeypatch.setenv("SAT_FI_NAN_AT_STEP", "4")
    state = runtime.train(cfg)
    assert int(state.step) == 6
    flat = state_to_flat(state)
    for name, value in flat.items():
        if np.asarray(value).dtype.kind == "f":
            assert np.all(np.isfinite(value)), name
    assert lineage.last_good_step(cfg.save_dir) == 6


def test_injected_checkpoint_corruption_not_blessed(
    coco_fixture, tmp_path, monkeypatch
):
    """SAT_FI_CORRUPT_CKPT_STEP=k: the byte flipped between write and
    verify is caught; LAST_GOOD skips the rotten file and restore walks
    past it."""
    cfg = _cfg(coco_fixture, tmp_path, "rot")
    monkeypatch.setenv("SAT_FI_CORRUPT_CKPT_STEP", "3")
    state = runtime.train(cfg)
    assert int(state.step) == 6
    assert not lineage.verify_checkpoint(os.path.join(cfg.save_dir, "3.npz"))[0]
    assert lineage.last_good_step(cfg.save_dir) == 6
    resumed = runtime.setup_state(cfg, load=True)
    assert int(resumed.step) == 6


def test_keep_checkpoints_retention_through_train(coco_fixture, tmp_path):
    """--keep_checkpoints through the real loop: old files rotate out,
    the newest N plus LAST_GOOD survive."""
    cfg = _cfg(
        coco_fixture, tmp_path, "keep", save_period=1, keep_checkpoints=2
    )
    state = runtime.train(cfg)
    assert int(state.step) == 6
    assert lineage.checkpoint_steps(cfg.save_dir) == [5, 6]
