"""Context parallelism: the attention grid sharded across devices.

The reference's attention always sees the whole 196/49-position context
grid on one device (/root/reference/model.py:395-436); nothing in its
design scales past one GPU's memory.  Here the grid's N axis shards over
the mesh's ``model`` axis and the soft-attention becomes a distributed
softmax — the same blockwise pattern ring/all-to-all sequence parallelism
uses for long sequences, applied to the visual context axis:

* each device scores only its local context block (local fc_1a matmul —
  the dominant FLOPs — runs on 1/cp of the grid);
* softmax normalizes globally via ``lax.pmax`` (max, stop-gradient) and
  ``lax.psum`` (denominator) over ICI;
* the attended context vector is a ``lax.psum`` of local partial sums;
* LSTM / embedding / vocab-logit compute stays replicated per shard
  (identical on every member, so no further communication).

Exactness: the distributed softmax is algebraically identical to the
single-device one; tests pin loss/alpha equality on the CPU mesh.

Dropout under CP: masks on *context-sharded* tensors fold the shard index
into the key (independent masks per block — matches the iid masks a
single device would draw); masks on *replicated* tensors use the shared
key so every shard keeps bitwise-identical activations.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..models.decoder import (
    DecoderState,
    _dense,
    _dropout,
    _l1,
    decode_logits,
    lstm_step,
    precompute_attend,
)
from ..ops.beam_search import BeamResult, run_search, tile_beams
from ..train.step import TrainState, split_trainable
from ..train.optimizer import make_optimizer
from ..nn.layers import regularization_loss
from ..models.captioner import encode, token_ce

AXIS = "model"  # the mesh axis the context grid shards over


def validate_cp_mesh(config: Config, mesh: Mesh) -> None:
    """A CP degree must exactly spend the mesh's model axis — shared by the
    train and decode dispatchers in runtime.py."""
    if mesh.shape.get(AXIS, 1) != config.context_parallel:
        raise ValueError(
            f"context_parallel={config.context_parallel} requires "
            f"mesh '{AXIS}' axis of that size, got {dict(mesh.shape)}"
        )


def _cp_attend(
    params,
    config: Config,
    ctx_local: jnp.ndarray,
    output: jnp.ndarray,
    train: bool,
    rng: Optional[jax.Array],
    with_activity: bool = False,
    ctx_proj: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Distributed soft attention.  ctx_local: [B, N_local, D] (this
    shard's block).  Returns (context [B, D] replicated, alpha_local
    [B, N_local]) — plus, when with_activity (static), the L1 activity
    partials as (ctx_sharded, model_replicated): the t1 sum is a
    per-context-shard partial (psum over AXIS and 'data' at the end),
    the t2 sum is replicated across AXIS (psum over 'data' only).

    ctx_proj: hoisted context half of the attention MLP for THIS shard's
    block (``precompute_attend(params, config, ctx_local)`` — the
    per-position weights make the hoist shard-local).  Inference only,
    same contract as ``decoder_step``'s ctx_proj: ignored when train=True
    (per-step context dropout invalidates it)."""
    p = params["attend"]
    rate = config.fc_drop_rate
    dt = jnp.dtype(config.compute_dtype)
    idx = jax.lax.axis_index(AXIS)
    n_local = ctx_local.shape[1]
    act_ctx = act_rep = jnp.float32(0)
    hoisted = ctx_proj is not None and not train

    if train:
        kc, ko, kt = jax.random.split(rng, 3)
        # context-sharded tensor: per-shard independent mask
        ctx_in = _dropout(jax.random.fold_in(kc, idx), ctx_local, rate, train)
        # replicated tensor: shared mask (keeps shards bitwise identical)
        output = _dropout(ko, output, rate, train)
    else:
        ctx_in = ctx_local

    if config.num_attend_layers == 1:
        logits_local = (
            ctx_proj if hoisted else _dense(p["fc_a"], ctx_in, dtype=dt)[..., 0]
        )                                                           # [B, Nl]
        # fc_b is position-specific h→N_global; slice this shard's block
        logits_h = _dense(p["fc_b"], output, dtype=dt)              # [B, Ng]
        logits_local = logits_local + jax.lax.dynamic_slice_in_dim(
            logits_h, idx * n_local, n_local, axis=1
        )
    else:
        t1 = (
            ctx_proj
            if hoisted
            else _dense(p["fc_1a"], ctx_in, activation="tanh", dtype=dt)
        )                                                              # [B,Nl,da]
        t2 = _dense(p["fc_1b"], output, activation="tanh", dtype=dt)   # [B,da]
        if with_activity:
            act_ctx, act_rep = _l1(t1), _l1(t2)
        temp = t1 + t2[:, None, :]
        if train:
            temp = _dropout(jax.random.fold_in(kt, idx), temp, rate, train)
        logits_local = _dense(p["fc_2"], temp, dtype=dt)[..., 0]       # [B,Nl]

    logits_local = logits_local.astype(jnp.float32)
    # distributed softmax: global max (stop-grad, like jax.nn.softmax —
    # via all_gather+max, which is differentiable where pmax is not),
    # local exp, global denominator
    m = jax.lax.stop_gradient(
        jnp.max(
            jax.lax.all_gather(jnp.max(logits_local, axis=-1), AXIS), axis=0
        )
    )                                                                # [B]
    e = jnp.exp(logits_local - m[:, None])                           # [B,Nl]
    denom = jax.lax.psum(jnp.sum(e, axis=-1), AXIS)                  # [B]
    alpha_local = e / denom[:, None]

    # attended context: psum of local partial weighted sums
    context = jax.lax.psum(
        (ctx_local * alpha_local[..., None]).sum(axis=1), AXIS
    )                                                                # [B,D]
    if with_activity:
        return context, alpha_local, (act_ctx, act_rep)
    return context, alpha_local


def cp_beam_search(
    params,
    config: Config,
    ctx_local: jnp.ndarray,
    eos_id: int,
    beam_size: Optional[int] = None,
    max_len: Optional[int] = None,
    valid_size: Optional[int] = None,
    return_alphas: bool = False,
):
    """Context-parallel beam search — runs INSIDE shard_map over
    ('data', AXIS) with ``ctx_local`` [B, N_local, D] this model-shard's
    context block and the batch rows this data-shard's.

    The attend is the distributed softmax (:func:`_cp_attend` with the
    context half of its MLP hoisted out of the T×K loop via ctx_proj);
    everything downstream of the psum'd context vector — LSTM, vocab
    logits, the whole :func:`~sat_tpu.ops.beam_search.run_search` engine
    (top-k, beam gathers, eos bookkeeping) — computes on replicated
    values, identically on every member of the model axis, so the
    returned words/scores/lengths are replicated over AXIS and the
    alphas come back context-sharded [B, K, T, N_local] (concatenate
    over AXIS to recover the global maps).

    Exactness: same algebra as the single-device search; the CPU-mesh
    test pins word/score equality against :func:`beam_search`.
    """
    K = beam_size or config.beam_size
    B, n_local, D = ctx_local.shape

    cp = jax.lax.psum(1, AXIS)
    context_mean = jax.lax.psum(ctx_local.mean(axis=1) / cp, AXIS)
    state0 = _cp_init_state(params, config, context_mean, train=False, rng=None)
    state0 = DecoderState(*(tile_beams(s, K) for s in state0))

    ctx_tiled = tile_beams(ctx_local, K)
    proj_tiled = tile_beams(precompute_attend(params, config, ctx_local), K)

    def step_fn(state, last_word):
        return _cp_decoder_step(
            params, config, ctx_tiled, state, last_word,
            train=False, rng=None, ctx_proj=proj_tiled,
        )

    # early exit is exact and shard-consistent: the cond reduces over
    # replicated fin/live scores, so every model shard computes the same
    # trip count (no collective in the predicate)
    return run_search(
        config, step_fn, state0, B, eos_id,
        beam_size=K, max_len=max_len, valid_size=valid_size,
        return_alphas=return_alphas, alpha_width=n_local,
    )


def make_context_parallel_beam_search(
    config: Config,
    mesh: Mesh,
    eos_id: int,
    beam_size: Optional[int] = None,
    valid_size: Optional[int] = None,
    return_alphas: bool = False,
):
    """Jitted (variables, images) -> BeamResult with the encoder running
    data-parallel under GSPMD and the decode under explicit shard_map CP —
    the eval twin of :func:`make_context_parallel_train_step`, so a
    CP-configured ``--phase=eval`` decodes under the SAME placement it
    trained with (VERDICT r02 weak #4), with the attend FLOPs and the
    context grid's memory split over the model axis instead of idling it.

    Returned alphas are reassembled to the global [B, K, T, N] layout by
    the shard_map out_spec (concatenation over AXIS).
    """
    K = beam_size or config.beam_size
    batch_sh = NamedSharding(mesh, P("data"))
    rep = P()
    data_specs = P("data")

    out_specs = BeamResult(
        words=data_specs, log_scores=data_specs, lengths=data_specs,
        alphas=P("data", None, None, AXIS) if return_alphas else None,
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(rep, P("data", AXIS, None)),
        out_specs=out_specs,
        check_vma=False,
    )
    def sharded_decode(decoder_params, contexts):
        return cp_beam_search(
            decoder_params, config, contexts, eos_id,
            beam_size=K, valid_size=valid_size, return_alphas=return_alphas,
        )

    def caption(variables, images):
        contexts, _ = encode(variables, config, images, train=False)
        return sharded_decode(variables["params"]["decoder"], contexts)

    return jax.jit(
        caption,
        in_shardings=(None, batch_sh),
        out_shardings=BeamResult(
            words=batch_sh, log_scores=batch_sh, lengths=batch_sh,
            alphas=batch_sh if return_alphas else None,
        ),
    )


def _cp_decoder_step(
    params,
    config: Config,
    ctx_local: jnp.ndarray,
    state: DecoderState,
    word: jnp.ndarray,
    train: bool,
    rng: Optional[jax.Array],
    with_activity: bool = False,
    ctx_proj: Optional[jnp.ndarray] = None,
):
    """decoder_step twin with distributed attention; everything after the
    attend runs replicated (same values on every context shard).

    with_activity (static) appends the step's L1 activity partials
    (ctx_sharded, model_replicated) to the return tuple.
    ctx_proj: hoisted attend projection, inference only (see _cp_attend)."""
    if train:
        k_att, k_in, k_out, k_state, k_dec = jax.random.split(rng, 5)
    else:
        k_att = k_in = k_out = k_state = k_dec = None
    ldr = config.lstm_drop_rate
    act_ctx = act_rep = jnp.float32(0)

    attended = _cp_attend(
        params, config, ctx_local, state.output, train, k_att,
        with_activity=with_activity, ctx_proj=ctx_proj,
    )
    if with_activity:
        context, alpha_local, (act_ctx, act_rep) = attended
    else:
        context, alpha_local = attended
    word_embed = params["word_embedding"]["weights"][word]

    lstm_input = jnp.concatenate([context, word_embed], axis=-1)
    lstm_input = _dropout(k_in, lstm_input, ldr, train)
    new_c, new_h = lstm_step(
        params["lstm"], state.memory, state.recurrent, lstm_input,
        dtype=jnp.dtype(config.compute_dtype),
    )
    emitted = _dropout(k_out, new_h, ldr, train)
    recurrent_h = _dropout(k_state, new_h, ldr, train)

    expanded = jnp.concatenate([emitted, context, word_embed], axis=-1)
    logits = decode_logits(
        params, config, expanded, train, k_dec, with_activity=with_activity
    )
    new_state = DecoderState(memory=new_c, output=emitted, recurrent=recurrent_h)
    if with_activity:
        logits, dec_act = logits  # decode temp is model-replicated
        return new_state, logits, alpha_local, (act_ctx, act_rep + dec_act)
    return new_state, logits, alpha_local


def _cp_loss_body(
    params,
    config: Config,
    ctx_local: jnp.ndarray,
    sentences: jnp.ndarray,
    masks: jnp.ndarray,
    rng: Optional[jax.Array],
    train: bool,
):
    """Runs INSIDE shard_map over ('data', 'model').  Batch rows are this
    data-shard's; ctx_local is this model-shard's context block.  Returns
    replicated (total_wo_reg, metrics)."""
    B, T = sentences.shape
    if rng is None:
        rng = jax.random.PRNGKey(0)
    # iid dropout across data shards: the rng arrives replicated (in_specs
    # P()), so without this fold rows i and i+B_local on different 'data'
    # shards would draw bitwise-identical masks — diverging from the iid
    # masks a single device draws over the global batch.  (Context-sharded
    # tensors additionally fold the 'model' shard index at use sites.)
    rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
    k_init, k_steps = jax.random.split(rng)

    # fc L1 activity regularization rides the same static-flag path as
    # teacher_forced_decode (reference utils/nn.py:40-43; gate is train)
    with_activity = train and config.fc_activity_regularizer_scale > 0

    # init from the GLOBAL mean context: local partial mean + psum
    n_local = ctx_local.shape[1]
    cp = jax.lax.psum(1, AXIS)
    context_mean = jax.lax.psum(ctx_local.mean(axis=1) / cp, AXIS)
    state = _cp_init_state(
        params, config, context_mean, train, k_init, with_activity=with_activity
    )
    init_act = jnp.float32(0)
    if with_activity:
        state, init_act = state

    words_in = jnp.concatenate(
        [jnp.zeros((B, 1), sentences.dtype), sentences[:, :-1]], axis=1
    )
    step_rngs = jax.random.split(k_steps, T)

    def body(state, xs):
        word_t, rng_t = xs
        out = _cp_decoder_step(
            params, config, ctx_local, state, word_t, train, rng_t,
            with_activity=with_activity,
        )
        if with_activity:
            state, logits, alpha_local, acts = out
            return state, (logits, alpha_local, acts)
        state, logits, alpha_local = out
        return state, (logits, alpha_local)

    if train and config.remat_decoder:
        # same remat story as teacher_forced_decode: regenerate dropout
        # masks/elementwise from rng_t in backward instead of stacking
        # residuals; the psum collectives sit on the dot path and stay saved
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_saveable,
            prevent_cse=False,
        )

    _, ys = jax.lax.scan(body, state, (words_in.T, step_rngs))
    if with_activity:
        logits, alphas_local, (acts_ctx, acts_rep) = ys
        # ctx-sharded partials (t1) sum over BOTH axes; model-replicated
        # ones (t2 / decode temp / init MLP) over 'data' only — summing a
        # replicated value over AXIS would multiply it by the CP degree
        fc_activity = jax.lax.psum(
            jax.lax.psum(acts_ctx.sum(), AXIS) + acts_rep.sum() + init_act,
            "data",
        )
    else:
        logits, alphas_local = ys
    logits = logits.transpose(1, 0, 2)           # [B, T, V]
    alphas_local = alphas_local.transpose(1, 0, 2)  # [B, T, Nl]

    masks = masks.astype(jnp.float32)
    # shared per-token CE (models/captioner.py token_ce): config.ce_dtype
    # applies identically here and on the single-device path
    ce = token_ce(logits, sentences, config, train=train)
    # global normalization: batch is sharded over 'data'
    ce_sum = jax.lax.psum((ce * masks).sum(), "data")
    mask_sum = jax.lax.psum(masks.sum(), "data")
    cross_entropy_loss = ce_sum / mask_sum

    # doubly stochastic attention penalty over the GLOBAL (B_global, N_global)
    masked = alphas_local * masks[..., None]
    attentions_local = masked.sum(axis=1)        # [B, Nl]
    diffs = 1.0 - attentions_local
    n_global = jax.lax.psum(jnp.float32(n_local), AXIS)
    b_global = jax.lax.psum(jnp.float32(B), "data")
    sq = jax.lax.psum(jax.lax.psum(jnp.sum(diffs * diffs), AXIS), "data")
    attention_loss = config.attention_loss_factor * 0.5 * sq / (
        b_global * n_global
    )

    predictions = jnp.argmax(logits, axis=-1)
    correct = jax.lax.psum(((predictions == sentences) * masks).sum(), "data")
    accuracy = correct / mask_sum

    total = cross_entropy_loss + attention_loss
    metrics = {
        "cross_entropy_loss": cross_entropy_loss,
        "attention_loss": attention_loss,
        "accuracy": accuracy,
    }
    if with_activity:
        # scale applied by the caller, into the same reg bucket the
        # reference sums via tf.losses.get_regularization_loss()
        metrics["fc_activity"] = fc_activity
    return total, metrics


def _cp_init_state(params, config, context_mean, train, rng, with_activity=False):
    """init_state from an already-reduced global context mean (the mean is
    computed with a psum outside; the MLP itself is replicated).

    with_activity (static) returns (state, model-replicated L1 partial)."""
    p = params["initialize"]
    rate = config.fc_drop_rate
    dt = jnp.dtype(config.compute_dtype)
    act = jnp.float32(0)
    if train:
        k0, k1, k2 = jax.random.split(rng, 3)
        context_mean = _dropout(k0, context_mean, rate, train)
    if config.num_initialize_layers == 1:
        memory = _dense(p["fc_a"], context_mean, dtype=dt)
        output = _dense(p["fc_b"], context_mean, dtype=dt)
    else:
        ta = _dense(p["fc_a1"], context_mean, activation="tanh", dtype=dt)
        tb = _dense(p["fc_b1"], context_mean, activation="tanh", dtype=dt)
        act = _l1(ta) + _l1(tb)
        if train:
            ta = _dropout(k1, ta, rate, train)
            tb = _dropout(k2, tb, rate, train)
        memory = _dense(p["fc_a2"], ta, dtype=dt)
        output = _dense(p["fc_b2"], tb, dtype=dt)
    state = DecoderState(memory=memory, output=output, recurrent=output)
    return (state, act) if with_activity else state


def make_context_parallel_loss(config: Config, mesh: Mesh, train: bool = True):
    """(decoder_params, contexts, sentences, masks, rng) -> (loss, metrics).

    contexts arrive GLOBAL [B, N, D]; shard_map splits batch over 'data'
    and the context axis over 'model'.  Decoder params replicated (the
    'model' axis is spent on the context grid here, not vocab TP)."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P("data", AXIS, None), P("data", None), P("data", None), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def f(params, contexts, sentences, masks, rng):
        return _cp_loss_body(
            params, config, contexts, sentences, masks, rng, train
        )

    return f


def make_context_parallel_train_step(config: Config, mesh: Mesh):
    """Full train step with context-parallel decoding: encoder runs
    data-parallel under GSPMD, the decoder under explicit shard_map CP.
    State must be replicated (use shard_train_state with a (dp,1) spec or
    plain create_train_state placed on the mesh)."""
    optimizer = make_optimizer(config)
    cp_loss = make_context_parallel_loss(config, mesh, train=True)

    def train_step(state: TrainState, batch: Dict[str, Any], rng: jax.Array):
        trainable, frozen = split_trainable(state.params, config)

        def loss_fn(trainable_params):
            params = {**frozen, **trainable_params}
            variables: Dict[str, Any] = {"params": params}
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
            conv_act_scale = (
                config.conv_activity_regularizer_scale if config.train_cnn else 0.0
            )
            contexts, enc_state = encode(
                variables, config, batch["images"], config.train_cnn,
                collect_activity=conv_act_scale > 0,
            )
            conv_activity = enc_state.pop("activity_l1", jnp.float32(0))
            core, metrics = cp_loss(
                params["decoder"],
                contexts,
                batch["word_idxs"],
                batch["masks"],
                rng,
            )
            metrics = dict(metrics)
            reg = regularization_loss(
                params,
                fc_scale=config.fc_kernel_regularizer_scale,
                conv_scale=config.conv_kernel_regularizer_scale,
                train_cnn=config.train_cnn,
            )
            # activity terms join the reg bucket (compute_loss parity)
            reg = (
                reg
                + config.fc_activity_regularizer_scale
                * metrics.pop("fc_activity", jnp.float32(0))
                + conv_act_scale * conv_activity
            )
            total = core + reg
            metrics["reg_loss"] = reg
            metrics["total_loss"] = total
            return total, (metrics, enc_state)

        import optax

        grads, (metrics, enc_state) = jax.grad(loss_fn, has_aux=True)(trainable)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, trainable)
        new_trainable = optax.apply_updates(trainable, updates)
        new_state = state._replace(
            params={**state.params, **new_trainable},
            # thread BN running stats from the encoder (train_cnn with a BN
            # backbone), mirroring make_train_step's model_state handling
            batch_stats=enc_state.get("batch_stats", state.batch_stats),
            opt_state=new_opt_state,
            step=state.step + 1,
        )
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    return jax.jit(train_step, donate_argnums=(0,))
