"""Train / eval / test runtimes — the framework's driving loops.

Equivalent of the reference ``BaseModel.train/eval/test``
(/root/reference/base_model.py:39-161) redesigned TPU-first:

* the train loop consumes an async prefetch pipeline (the reference decodes
  images synchronously inside the loop, base_model.py:53) and runs ONE
  compiled XLA program per step;
* eval/test drive the on-device batched beam search (one device dispatch
  per batch, vs the reference's ~beam×20 sess.run round-trips per image,
  base_model.py:184-212);
* checkpoints every ``save_period`` steps (base_model.py:61-62), summaries
  via the TensorBoard-compatible writer (base_model.py:46-47,63);
* artifact parity: ``results.json`` + COCO scoring for eval
  (base_model.py:109-117), ``results.csv`` + captioned images for test
  (base_model.py:144-160).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from .config import Config
from .data.dataset import DataSet, prepare_eval_data, prepare_test_data, prepare_train_data
from .data.images import ImageLoader, PrefetchLoader
from .data.vocabulary import Vocabulary
from .evalcap.eval import CocoEvalCap
from .models.captioner import encode
from .ops.beam_search import beam_search_jit
from .train.checkpoint import (
    AsyncCheckpointWriter,
    apply_cnn_import,
    import_reference_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from .resilience import AnomalySentinel, FaultPlan, GracefulShutdown, lineage
from .resilience import retry as _retry
from .resilience.lineage import CheckpointWriteError
from .resilience.supervisor import RESTARTS_ENV
from .resilience.watchdog import Watchdog, deadlines_from_config
from .train.step import TrainState, create_train_state, make_jit_train_step
from . import telemetry
from .utils.fileio import atomic_write
from .utils.progress import Progress, track
from .utils.summary import SummaryWriter


# ---------------------------------------------------------------------------
# input feed shared by all three phases
# ---------------------------------------------------------------------------


# One QuarantineManager per ledger path (i.e. per run): the systemic-
# corruption ceiling is a run-level judgement, so the train and eval
# loaders of one run must share the bookkeeping.
_QUARANTINES: Dict[str, "object"] = {}


def _quarantine_for(config: Config):
    from .resilience.quarantine import QuarantineManager, ledger_path_for

    path = ledger_path_for(config)
    q = _QUARANTINES.get(path)
    if q is None:
        q = QuarantineManager(
            path, max_fraction=config.quarantine_max_fraction
        )
        _QUARANTINES[path] = q
    return q


def make_loader(config: Config, dataset: DataSet) -> PrefetchLoader:
    """The host-side input pipeline for a dataset: shard-cache resolution
    (build-or-load per ``config.shard_cache``; falls back to live JPEG
    decode when no valid cache exists — see data.shards) + the prefetching
    batch assembler, with the run's quarantine wired in (bad records are
    contained and substituted instead of crashing the run — see
    resilience.quarantine; direct PrefetchLoader construction without a
    quarantine keeps the old raise-through behavior).  All three phase
    loops build their feed here so the cache policy is applied
    uniformly."""
    from .data.shards import resolve_shard_cache

    return PrefetchLoader(
        dataset,
        ImageLoader(size=config.image_size, raw=config.device_preprocess),
        num_workers=config.num_data_workers,
        prefetch_depth=config.prefetch_depth,
        shard_cache=resolve_shard_cache(config, dataset.image_files),
        quarantine=_quarantine_for(config),
    )


def device_prefetch(loader, ahead: int = 1):
    """Double-buffered host→device feed: dispatch batch k+1's transfer
    before the consumer syncs on step k.

    ``jax.device_put`` is asynchronous — it enqueues the host→HBM copy and
    returns immediately — so holding ``ahead`` already-dispatched batches
    in a ring overlaps every batch's transfer with the previous step's
    device compute; the step dispatch then consumes an array that is
    already (or almost) resident instead of paying the copy on its
    critical path.  Array leaves are transferred; everything else
    ('files') passes through.  Single-device feed only: the mesh paths
    place batches through ``make_global_batch``, which owns its own
    per-device placement.
    """
    from collections import deque

    def put(batch):
        # the span times only the (async) transfer DISPATCH — it runs
        # inside the feed's data wait, so the breakdown reports it as a
        # nested interval, not a phase of its own
        with telemetry.span("feed/device_put"):
            return {
                k: jax.device_put(v) if isinstance(v, np.ndarray) else v
                for k, v in batch.items()
            }

    buf = deque()
    for batch in loader:
        buf.append(put(batch))
        if len(buf) > ahead:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


def _watched_iter(it, wd, name: str):
    """Bracket every fetch from ``it`` with a watchdog phase guard, so a
    feed that stops producing (dead worker pool, wedged host IO) trips the
    ``data_wait`` deadline instead of hanging the loop silently."""
    it = iter(it)
    while True:
        with wd.phase(name):
            try:
                item = next(it)
            except StopIteration:
                return
        yield item


# ---------------------------------------------------------------------------
# state setup shared by all three phases
# ---------------------------------------------------------------------------


def setup_state(
    config: Config,
    load: bool = False,
    model_file: Optional[str] = None,
    load_cnn: bool = False,
    cnn_model_file: Optional[str] = None,
    seed: Optional[int] = None,
) -> TrainState:
    """Initialize the train state, optionally restoring a checkpoint and/or
    importing a pretrained CNN — the main.py load sequence
    (/root/reference/main.py:49-53)."""
    if seed is None:
        seed = config.seed
    state = create_train_state(jax.random.PRNGKey(seed), config)
    if load or model_file:
        if model_file and model_file.endswith(".npy"):
            # a checkpoint written by the *reference* itself (flat TF1
            # var.name dict, base_model.py:242-249) — imported via the
            # name-translation path so reference-trained models run here
            state, count = import_reference_checkpoint(state, model_file)
        else:
            from .data.vocabulary import vocab_fingerprint

            state, count = restore_checkpoint(
                state,
                model_file=model_file,
                save_dir=config.save_dir,
                # fail fast on a vocabulary swap instead of silently
                # skipping the mismatched embedding (partial restore)
                expect_vocab=vocab_fingerprint(
                    config.vocabulary_file, config.vocabulary_size
                ),
            )
        if count == 0:
            raise ValueError(
                f"checkpoint {model_file or config.save_dir} restored 0 tensors"
            )
        print(f"{count} tensors loaded from checkpoint (step {int(state.step)}).")
    if load_cnn and cnn_model_file:
        state, count = apply_cnn_import(state, cnn_model_file)
        print(f"{count} pretrained CNN tensors loaded.")
    return state


class ProfilerWindow:
    """Shared ``jax.profiler`` trace-window bookkeeping for the step loops
    (train and both decode paths): trigger once at step >= start — resume-
    aware, like train always was — capture ``profile_num_steps`` steps,
    block on a sync target before stopping, and guarantee closure on loop
    exit.  A window left open would poison the process's NEXT
    ``start_trace`` (evaluate_sweep re-enters decode repeatedly), so
    callers close() in a finally/ExitStack."""

    def __init__(self, config: Config, max_start: Optional[int] = None) -> None:
        self._dir = config.profile_dir
        self._start = config.profile_start_step
        if max_start is not None:
            # decode loops pass their batch count: profile_start_step is a
            # train-step knob (default 5), and a short eval must still
            # trace rather than silently never opening the window
            self._start = min(self._start, max(max_start, 0))
        self._num = max(config.profile_num_steps, 1)
        self._on = False
        self._fired = False
        self._stop_at = -1
        self._last_sync = None

    def before_step(self, i: int) -> None:
        """Call before dispatching step ``i``; opens the window once.

        ``start_trace`` raises when another trace is already live in the
        process — e.g. an outer ``jax.profiler`` session running
        alongside ``--trace_export``'s host-side export, or a sweep whose
        previous window leaked.  The window must not take the run down
        for that: it marks itself fired FIRST (so a failed open is never
        retried every subsequent step) and degrades the collision to a
        warning, leaving ``_on`` false so ``after_step``/``__exit__``
        never issue the double ``stop_trace`` that would close the OUTER
        trace and leak this window's dir."""
        if self._dir and not self._fired and i >= self._start:
            self._fired = True
            self._stop_at = i + self._num
            try:
                jax.profiler.start_trace(self._dir)
            except Exception as e:
                print(
                    f"sat_tpu: profiler window skipped — start_trace failed "
                    f"(another trace active?): {e}",
                    file=sys.stderr,
                    flush=True,
                )
                return
            self._on = True

    def _stop(self) -> None:
        """Close the trace this window opened; a stop failure (the trace
        was stopped under us) degrades to a warning but still marks the
        window closed so it is never double-stopped."""
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            print(
                f"sat_tpu: profiler stop_trace failed ({e})",
                file=sys.stderr,
                flush=True,
            )
        self._on = False
        self._last_sync = None

    def after_step(self, i: int, sync) -> None:
        """Call after dispatching step ``i``; closes the window when the
        configured step count has been captured (blocks on ``sync`` so
        the trace contains completed device work)."""
        self._last_sync = sync  # __exit__'s sync target if the loop ends early
        if self._on and i + 1 >= self._stop_at:
            jax.block_until_ready(sync)  # sync-ok: trace-window close only
            self._stop()

    def __enter__(self) -> "ProfilerWindow":
        return self

    def __exit__(self, *exc) -> None:
        """Idempotent tail/error-path stop (loop ended inside the window,
        or an exception fired mid-window) — blocks on the last
        ``after_step`` sync target so the trace holds completed work."""
        if self._on:
            if self._last_sync is not None:
                try:
                    jax.block_until_ready(self._last_sync)  # sync-ok: window close
                except Exception:
                    pass  # sync target may be poisoned on the error path
            self._stop()
        self._last_sync = None


# ---------------------------------------------------------------------------
# telemetry wiring (docs/OBSERVABILITY.md)
# ---------------------------------------------------------------------------

# train-loop phase decomposition: disjoint sub-intervals of "train/step"
# (their totals + the "other" residual reconstruct measured wall time) and
# the nested spans that occur INSIDE a phase (reported, not summed)
_TRAIN_PHASES = (
    "train/data_wait", "train/dispatch", "train/log_sync",
    "train/summary", "train/checkpoint",
)
_TRAIN_NESTED = ("feed/device_put", "ckpt/write", "ckpt/snapshot")
_DECODE_PHASES = ("decode/data_wait", "decode/dispatch", "decode/drain")
_DECODE_NESTED = ("feed/device_put",)

_compile_listener_installed = False


def _install_compile_listener() -> None:
    """Feed XLA compile count/seconds into the active telemetry recorder.

    ``jax.monitoring`` listeners cannot be unregistered, so install ONE
    process-wide callback that dispatches through ``telemetry.get()`` —
    re-running train() in the same process (tests, sweeps) never stacks a
    second listener, and with telemetry off the callback hits the null
    object."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    _compile_listener_installed = True
    try:
        from jax import monitoring

        def _cb(event: str, duration: float, **kw) -> None:
            if "compil" in event:
                tel = telemetry.get()
                tel.count("jax/compiles")
                tel.count("jax/compile_s", duration)

        monitoring.register_event_duration_secs_listener(_cb)
    except Exception:
        pass  # observability never takes the run down


def _timed_iter(it, tel, name: str):
    """Yield from ``it``, recording each ``next()`` wait as a ``name``
    span — the feed-starvation phase of the consuming loop."""
    it = iter(it)
    while True:
        t0 = time.perf_counter_ns()
        try:
            item = next(it)
        except StopIteration:
            return
        tel.record(name, t0, time.perf_counter_ns() - t0)
        yield item


def _telemetry_dir(config: Config) -> str:
    return config.telemetry_dir or os.path.join(config.summary_dir, "telemetry")


def _telemetry_begin(config: Config):
    """Install the run's telemetry implementation (fresh buffers when on,
    the null object when off) and the process-wide compile listener."""
    if config.telemetry:
        tel = telemetry.enable(config.telemetry_buffer)
        from .telemetry import xla as xla_acct

        xla_acct.reset()  # per-run compile accounting (compile_report.json)
    else:
        tel = telemetry.disable()
    _install_compile_listener()
    return tel


def _device_static() -> dict:
    """Heartbeat ``static`` device facts: backend plus the first local
    device's kind/platform (degrades to just the backend when device
    objects don't expose them)."""
    static = {
        "backend": jax.default_backend(),
        "num_devices": jax.device_count(),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
    }
    try:
        d0 = jax.local_devices()[0]
        static["device_kind"] = d0.device_kind
        static["device_platform"] = d0.platform
    except Exception:
        pass
    return static


def _fleet_gather(vec):
    """Collective transport for ``FleetPlane.tick``: all-gather one ~6
    float64 host vector across processes.  The fleet module is jax-free,
    so the runtime injects this; any failure (no distributed init, mixed
    topologies mid-teardown) returns None and the plane falls back to
    reading sidecar files.  Single-process runs skip the collective
    entirely — the sidecar path is already exact."""
    if jax.process_count() == 1:
        return None
    try:
        from jax.experimental import multihost_utils

        out = multihost_utils.process_allgather(vec)
        return np.asarray(out, dtype=np.float64)  # sync-ok: ~6 host scalars/process at the log boundary
    except Exception:
        return None


def _device_memory_sampler():
    """Heartbeat sampler: per-device HBM bytes-in-use via the backend's
    ``memory_stats()``.  CPU devices return None (or raise) — the sampler
    then contributes nothing and the heartbeat degrades gracefully, per
    docs/OBSERVABILITY.md."""

    def sample() -> dict:
        per: dict = {}
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats and "bytes_in_use" in stats:
                per[str(d.id)] = int(stats["bytes_in_use"])
        return {"hbm_bytes_in_use": per} if per else {}

    return sample


def _telemetry_finish(tel, config: Config, phase: str) -> None:
    """End-of-run exports: Chrome trace JSON, the per-phase step-time
    breakdown (printed + saved), run from an ExitStack callback so an
    interrupted run still leaves its trace behind."""
    from .telemetry import exporters

    tdir = _telemetry_dir(config)
    trace_path = config.trace_export or os.path.join(
        tdir, "trace.json" if phase == "train" else f"trace-{phase}.json"
    )
    exporters.export_chrome_trace(tel, trace_path)
    step_span, phases, nested = (
        ("train/step", _TRAIN_PHASES, _TRAIN_NESTED)
        if phase == "train"
        else ("decode/batch", _DECODE_PHASES, _DECODE_NESTED)
    )
    report = exporters.step_breakdown(tel, step_span, phases, nested)
    if report is not None:
        print(exporters.format_breakdown(report), flush=True)
        exporters.save_breakdown(
            report,
            os.path.join(
                tdir,
                "breakdown.json" if phase == "train" else f"breakdown-{phase}.json",
            ),
        )
    # compile-time cost/memory accounting (telemetry/xla.py): one report
    # per phase, surfaced in the end-of-run printout next to the breakdown
    from .telemetry import xla as xla_acct

    summary = xla_acct.format_summary()
    if summary is not None:
        print(summary, flush=True)
        xla_acct.write_report(
            os.path.join(
                tdir,
                "compile_report.json"
                if phase == "train"
                else f"compile_report-{phase}.json",
            )
        )


# ---------------------------------------------------------------------------
# train
# ---------------------------------------------------------------------------


def train(
    config: Config,
    state: Optional[TrainState] = None,
    dataset: Optional[DataSet] = None,
    seed: Optional[int] = None,
) -> TrainState:
    """Epoch × batch training loop (reference base_model.py:39-68).

    With ``mesh_shape`` spanning more than one device the same loop runs
    SPMD: state sharded per the (data, model) placement rules, batches
    data-sharded, XLA inserting the gradient all-reduce — the synchronous
    upgrade of the reference's async PS strategy (SURVEY.md §2.13)."""
    if seed is None:
        seed = config.seed
    if dataset is None:
        # the explicit kwarg must drive the WHOLE run — shuffle order
        # included — not just init/dropout (batch order is f(seed, epoch))
        dataset = prepare_train_data(
            config if seed == config.seed else config.replace(seed=seed)
        )
    if dataset.count == 0:
        raise ValueError(
            "training dataset is empty after preparation — every caption was "
            "filtered out (cap-length <= max_caption_length and vocab "
            "filters, reference coco.py:323-361) or the caption file has no "
            "annotations; check train_caption_file/max_caption_length"
        )
    if state is None:
        state = setup_state(config, seed=seed)

    if int(np.prod(config.mesh_shape)) > 1:
        from .parallel import make_mesh, make_parallel_train_step, sync_processes
        from .parallel.collectives import make_global_batch
        from .parallel.data import mesh_data_shard, process_local_dataset
        from .parallel.sharding import shard_train_state

        mesh = make_mesh(config)
        if config.context_parallel > 1:
            # 'model' axis spent on the context grid (distributed-softmax
            # attention) instead of vocab TP; params stay replicated
            from .parallel.context import (
                make_context_parallel_train_step,
                validate_cp_mesh,
            )

            validate_cp_mesh(config, mesh)
            # realign before the sharded placement: its cross-host
            # assert_equal opens a fresh communicator rendezvous
            sync_processes("sat_tpu:shard_state")
            placement_config = config.replace(vocabulary_size=-1)
            state = shard_train_state(
                state, placement_config, mesh
            )  # vocab rule disabled → fully replicated placement
            train_step = make_context_parallel_train_step(config, mesh)
        else:
            sync_processes("sat_tpu:shard_state")
            placement_config = config
            state = shard_train_state(state, config, mesh)
            train_step = make_parallel_train_step(config, mesh)
        # sentinel rollback restores host-side numpy leaves; mesh runs must
        # re-place them with the same sharding rules as the initial state
        reshard_state = lambda s: shard_train_state(s, placement_config, mesh)  # noqa: E731
        # feed keyed on the DATA-axis layout: processes along the model
        # axis (CP / cross-host TP) share a data row and feed identical
        # replicas of it (mesh_data_shard docstring)
        shard_idx, n_shards = mesh_data_shard(mesh)
        dataset = process_local_dataset(
            dataset, process_index=shard_idx, process_count=n_shards
        )
        place_batch = lambda b: make_global_batch(mesh, b)  # noqa: E731
        wrap_feed = lambda l: l  # noqa: E731 — make_global_batch places
    else:
        train_step = make_jit_train_step(config)
        place_batch = lambda b: b  # noqa: E731
        reshard_state = lambda s: s  # noqa: E731 — jit re-places on dispatch
        # async device slot: batch k+1's host→HBM transfer is dispatched
        # while step k still runs, so the step never pays the copy
        wrap_feed = device_prefetch
    loader = make_loader(config, dataset)
    # Typed key with the configured bit-generator impl: dropout-mask
    # generation is ~40% of the flagship train step under threefry (the
    # decoder draws ~130M mask bits/step); config.rng_impl="rbg" routes it
    # to the TPU hardware generator instead.  Param init (above) stays on
    # threefry so weights are impl-independent.
    root_rng = jax.random.key(seed + 1, impl=config.rng_impl)

    # Host-side step counter: fetching int(state.step) every iteration would
    # block the host on the just-dispatched device step, serializing the loop
    # with the device and defeating async dispatch + prefetch.  Sync once
    # here (resume-aware), then count locally; device_get only when logging.
    step = int(state.step)
    stopped = False
    # resilience wiring (docs/RESILIENCE.md): process-wide IO-retry knobs,
    # the env-armed fault plan (inert in production — every hook is a
    # host-side compare), the log-boundary anomaly sentinel, and graceful
    # SIGTERM/SIGINT draining
    _retry.configure(config.io_retries, config.io_retry_base_s)
    plan = FaultPlan.from_env()
    sentinel = AnomalySentinel(config.anomaly_policy, config.anomaly_spike_factor)
    # async checkpointing: the step loop pays only the device→host
    # snapshot; serialization + disk write overlap the following steps
    # (AsyncCheckpointWriter docstring; sync fallback multi-host/off)
    async_writer = (
        AsyncCheckpointWriter()
        if config.async_checkpoint and jax.process_count() == 1
        else None
    )
    ckpt_save = async_writer.save if async_writer else save_checkpoint
    # host-side tracing (docs/OBSERVABILITY.md): fresh ring buffers when
    # config.telemetry, the null object otherwise — the off path leaves
    # run behavior bit-for-bit unchanged
    tel = _telemetry_begin(config)
    # incarnation number under `--supervise`: the restart loop exports it
    # so heartbeat.json can show how many times this run has come back
    tel.gauge("supervisor/restarts", int(os.environ.get(RESTARTS_ENV, "0") or 0))
    # hang/wedge watchdog (docs/RESILIENCE.md): a side thread observing the
    # phase guards below, escalating gauges → stack dump → abort with exit
    # code 86 when a tracked phase stops completing.  Constructed always so
    # the guards are uniform; the observer thread only runs when
    # config.watchdog_interval > 0 (unstarted, a guard is two dict writes).
    wd = Watchdog(
        deadlines_from_config(config),
        poll_s=config.watchdog_interval or 1.0,
        grace_s=config.watchdog_grace_s,
        dump_path=os.path.join(_telemetry_dir(config), "watchdog_stacks.txt"),
        pre_abort=async_writer.flush if async_writer else None,
        tel=tel,
    )
    compile_probed = False  # train_step analyzed once, on the first batch
    import contextlib

    final_path: Optional[str] = None
    # on-demand live profiler window (telemetry/profwin.py): armed below
    # when telemetry is on; SIGUSR2 latches a flag the log boundary drains
    profile_trigger = None
    profile_latch = None
    # fleet telemetry plane + black-box flight recorder (docs/
    # OBSERVABILITY.md "Fleet & Postmortem"): built below when configured
    fleet_plane = None
    bb = None
    # the ExitStack drains the async writer LAST (after SummaryWriter
    # closes), on success and on exception alike — queued checkpoint
    # writes survive an interrupt and worker failures surface
    with contextlib.ExitStack() as _stack, SummaryWriter(
        config.summary_dir
    ) as writer, GracefulShutdown() as shutdown:
        if tel.enabled:
            # LIFO: trace/breakdown export runs last, after the heartbeat's
            # final beat, which itself runs after the async writer drains —
            # the artifacts see the final step and the final checkpoint
            _stack.callback(_telemetry_finish, tel, config, "train")
            if config.heartbeat_interval > 0:
                from .telemetry.heartbeat import Heartbeat

                hb = Heartbeat(
                    os.path.join(_telemetry_dir(config), "heartbeat.json"),
                    config.heartbeat_interval,
                    tel,
                    static={"phase": "train", **_device_static()},
                    sampler=_device_memory_sampler(),
                )
                _stack.callback(hb.stop)
                hb.start()
            else:
                hb = None
            # read-only Prometheus scrape endpoint (telemetry/promtext.py)
            # riding the heartbeat payload — zero new syncs, a bind
            # failure degrades to a warning
            if config.metrics_port > 0:
                from .telemetry.promtext import MetricsListener

                ml = MetricsListener(
                    "127.0.0.1",
                    config.metrics_port,
                    tel,
                    payload_fn=hb.payload if hb is not None else None,
                )
                if ml.start():
                    _stack.callback(ml.stop)
            # SLO engine (telemetry/slo.py): declared train objectives
            # (captions/s floor, checkpoint-age ceiling) evaluated on a
            # side thread; transitions land in slo.jsonl and slo/* gauges
            # surface in heartbeat.json
            from .telemetry.slo import SLOEngine, objectives_from_config

            slo_objectives = objectives_from_config(config, "train")
            if slo_objectives:
                slo_engine = SLOEngine(
                    tel,
                    slo_objectives,
                    jsonl_path=os.path.join(
                        _telemetry_dir(config), "slo.jsonl"
                    ),
                    cap_bytes=int(config.telemetry_log_cap_mb * 1e6),
                    fast_s=config.slo_window_fast_s,
                    slow_s=config.slo_window_slow_s,
                ).start(
                    interval_s=max(
                        0.1, min(5.0, config.slo_window_fast_s / 4)
                    )
                )
                _stack.callback(slo_engine.stop)
            # SIGUSR2 → bounded live profiler capture, drained at the log
            # boundary (signals are async; profiler starts are not)
            import signal as _signal

            from .telemetry.profwin import ProfileLatch, SignalTrigger

            profile_latch = ProfileLatch(_telemetry_dir(config))
            _stack.callback(profile_latch.stop_now)
            profile_trigger = SignalTrigger()
            if hasattr(_signal, "SIGUSR2"):
                profile_trigger.install(_signal.SIGUSR2)
            # fleet plane (telemetry/fleet.py): every process writes a
            # heartbeat_p<i>.json sidecar at the log boundary; process 0
            # merges the fleet view into fleet.json + fleet/* gauges.
            # finish() is registered so the terminal step is recorded
            # even when the loop dies between boundaries.
            if config.fleet_telemetry:
                from .telemetry.fleet import FleetPlane

                fleet_dir = config.fleet_dir or _telemetry_dir(config)
                fleet_plane = FleetPlane(
                    fleet_dir,
                    jax.process_index(),
                    jax.process_count(),
                    tel,
                    straggler_factor=config.straggler_factor,
                    history_cap_bytes=int(config.telemetry_log_cap_mb * 1e6),
                )
                _stack.callback(fleet_plane.finish)
        # black-box flight recorder (telemetry/blackbox.py): bounded
        # on-disk ring journaling recent state; abnormal exits (watchdog
        # 86, corruption 87, sentinel trip, uncaught exception, SIGTERM
        # mid-checkpoint) dump a postmortem bundle from it.  The ExitStack
        # runs the finalizer chain on clean teardown; the atexit hook
        # covers paths that unwind without reaching it.
        if config.blackbox:
            from .resilience.quarantine import ledger_path_for
            from .telemetry import blackbox as _blackbox

            _bb_tdir = _telemetry_dir(config)
            bb = _blackbox.BlackBox(os.path.join(_bb_tdir, "blackbox"), tel)
            _blackbox.install(
                bb,
                telemetry_dir=_bb_tdir,
                fleet_dir=(
                    fleet_plane.fleet_dir if fleet_plane is not None else ""
                ),
                config_snapshot=config.to_dict(),
                quarantine_ledger=ledger_path_for(config),
            )
            _stack.callback(_blackbox.run_finalizers)
            bb.event("train_start", step=step)
        if async_writer:
            _stack.callback(async_writer.close)
        if config.watchdog_interval > 0:
            # LIFO: the observer stops BEFORE the writer drain above runs,
            # so a slow final drain is never mistaken for a wedge
            _stack.callback(wd.stop)
            wd.start()
        # resume-aware trace window (>= start, once); the ExitStack exit
        # keeps an exception mid-window from leaving the profiler open
        prof = _stack.enter_context(ProfilerWindow(config))
        if int(np.prod(config.mesh_shape)) > 1:
            # realign before the first step dispatch: its execution opens
            # the per-axis communicators (fresh rendezvous windows), and
            # loader startup / executable cache loads drift processes
            # apart (sync_processes docstring; imported with the mesh
            # machinery above under this same condition)
            sync_processes("sat_tpu:first_step")
        while True:  # re-entered only by a sentinel rollback
            rollback = False
            # Mid-epoch resume: batch order is a pure function of (seed,
            # epoch) (DataSet._set_epoch), so the cursor IS the global step
            # — fast-forward to exactly where the checkpointed run stopped
            # and the resumed run replays the identical batch + dropout-key
            # sequence.  A rollback re-enters here with restored weights
            # and the cursor already PAST the poison step.
            start_epoch, skip_batches = divmod(step, dataset.num_batches)
            if start_epoch < config.num_epochs:
                dataset.seek(start_epoch, skip_batches)
            for epoch in range(start_epoch, config.num_epochs):
                # per-batch visibility, tqdm-style (reference
                # base_model.py:49-50); metric-free so the async dispatch
                # chain never syncs for it
                bar = Progress(
                    dataset.num_batches,
                    desc=f"epoch {epoch + 1}/{config.num_epochs}",
                    initial=skip_batches if epoch == start_epoch else 0,
                )
                # step span boundary: each iteration records data_wait
                # (inside _timed_iter) + body phases, and the step total
                # from the previous boundary — no extra syncs, ~1 µs/step
                step_t0 = time.perf_counter_ns()
                for batch in _timed_iter(
                    _watched_iter(wrap_feed(loader), wd, "data_wait"),
                    tel,
                    "train/data_wait",
                ):
                  # watchdog net around the whole body: a wedge landing
                  # between the finer-grained guards still trips the
                  # 'step' deadline (deadlines_from_config docstring)
                  with wd.phase("step"):
                    if config.max_steps and step >= config.max_steps:
                        stopped = True
                        break
                    plan.maybe_kill(step)  # injected preemption (inert unarmed)
                    plan.maybe_wedge(step)  # injected silent hang (inert unarmed)
                    plan.maybe_slow(step)  # injected slow-but-alive step
                    if shutdown.stop_requested:
                        # stop at the step boundary: the final save below
                        # flushes through the writer and train() returns
                        # cleanly so the CLI can exit 0 for the supervisor
                        stopped = True
                        break
                    prof.before_step(step)
                    placed = place_batch(
                        {
                            "images": batch["images"],
                            "word_idxs": batch["word_idxs"],
                            "masks": batch["masks"],
                        }
                    )
                    step_rng = jax.random.fold_in(root_rng, step)
                    if tel.enabled and not compile_probed:
                        # AOT cost/memory accounting BEFORE the first
                        # dispatch: lowering reads only avals (donated
                        # buffers stay intact) and seeds the same
                        # lower/compile caches the call below hits, so
                        # the step is not compiled twice
                        compile_probed = True
                        from .telemetry import xla as xla_acct

                        xla_acct.analyze(
                            "train_step", train_step, state, placed,
                            step_rng, tel=tel,
                        )
                    with tel.span("train/dispatch"), wd.phase("dispatch"):
                        state, metrics = train_step(state, placed, step_rng)
                    prof.after_step(step, state)
                    step += 1  # == int(state.step), without a device sync
                    tel.gauge("train/step", step)
                    # injected NaN gradient (inert unarmed): poisons params
                    # and metrics exactly as a diverged update would
                    state, metrics = plan.maybe_poison(step, state, metrics)
                    if step % config.log_every == 0:
                        # the loop's ONE host sync — the sentinel reads
                        # these already-fetched floats, adding no syncs
                        with tel.span("train/log_sync"):
                            host = {
                                k: float(v)  # sync-ok: the loop's ONE log-boundary fetch
                                for k, v in jax.device_get(metrics).items()
                            }
                        writer.scalars(step, host)
                        if tel.enabled:
                            from .telemetry import exporters

                            # diag taps (telemetry/device.py) ride the
                            # host dict just fetched: gauging them here
                            # lands the last-known snapshot in
                            # telemetry.jsonl and heartbeat.json without
                            # touching the device again
                            for k, v in host.items():
                                if k.startswith("diag/"):
                                    tel.gauge(k, v)
                            exporters.append_jsonl(
                                tel,
                                os.path.join(
                                    _telemetry_dir(config), "telemetry.jsonl"
                                ),
                                step,
                                cap_bytes=int(
                                    config.telemetry_log_cap_mb * 1e6
                                ),
                            )
                            # SIGUSR2 since the last boundary → start a
                            # bounded live profiler window (refusals —
                            # capture already running — just log)
                            if (
                                profile_trigger is not None
                                and profile_trigger.pop()
                            ):
                                ok, info = profile_latch.start(
                                    config.profile_window_ms
                                )
                                print(
                                    "sat_tpu: live profiler window "
                                    + (f"-> {info}" if ok else f"refused ({info})"),
                                    file=sys.stderr,
                                    flush=True,
                                )
                        # fleet tick: every process writes its sidecar
                        # (and joins the gather when available); only
                        # process 0 aggregates.  Black-box journal rides
                        # the same boundary — both are pure host IO.
                        if fleet_plane is not None:
                            with tel.span("fleet/tick"):
                                fleet_plane.tick(step, gather_fn=_fleet_gather)
                        if bb is not None:
                            bb.journal(step)
                        if sentinel.check(step, host) == "rollback":
                            if bb is not None:
                                from .telemetry import blackbox as _bbx

                                bb.event(
                                    "anomaly_rollback",
                                    step=step,
                                    reason=sentinel.last_reason,
                                )
                                _bbx.dump(
                                    "anomaly_rollback",
                                    step=step,
                                    reason_detail=sentinel.last_reason,
                                )
                            rollback = True
                            break
                    if (
                        config.var_summary_period
                        and step % config.var_summary_period == 0
                    ):
                        with tel.span("train/summary"):
                            writer.variable_stats(step, state.params)
                    if (
                        config.save_period
                        and step % config.save_period == 0
                        and not sentinel.suppress_save
                    ):
                        with tel.span("train/checkpoint"), wd.phase("checkpoint"):
                            ckpt_save(state, config, healthy=sentinel.healthy)
                    bar.update()
                    now = time.perf_counter_ns()
                    tel.record("train/step", step_t0, now - step_t0)
                    step_t0 = now
                bar.close()
                if stopped or rollback:
                    break
                print(f"epoch {epoch + 1}/{config.num_epochs} done (step {int(state.step)})")
            if rollback:
                if async_writer:
                    # the save that blessed LAST_GOOD may still be queued;
                    # the pointer is only readable once it drains
                    async_writer.flush()
                restored = _restore_last_good(state, config, step)
                if restored is None:
                    # nothing verifiable to roll back to — degrade to warn
                    # and keep training rather than dying here
                    sentinel.policy = "warn"
                else:
                    state = reshard_state(restored)
                    sentinel.note_rolled_back()
                continue
            break
        # the final save rides the same queue: submission order guarantees
        # it lands AFTER any still-draining periodic write (config.json
        # must end at the final step), and the ExitStack close joins the
        # worker before train() returns
        if sentinel.suppress_save:
            print(
                "sat_tpu: final checkpoint suppressed — metrics were "
                f"anomalous under anomaly_policy=skip ({sentinel.last_reason})",
                file=sys.stderr,
                flush=True,
            )
        else:
            # defer(): a second (force-kill) SIGTERM arriving while the
            # final write is in flight is held until the flush below has
            # landed AND verified — the one window where the old behavior
            # could kill the run between rename and verify
            with shutdown.defer():
                final_path = ckpt_save(state, config, healthy=sentinel.healthy)
                if async_writer:
                    async_writer.flush()
        if shutdown.stop_requested:
            print(
                f"sat_tpu: stopped on {shutdown.signal_name} at step {step}; "
                "final checkpoint flushed — relaunch with --load to resume",
                file=sys.stderr,
                flush=True,
            )
            if bb is not None:
                # the stop raced the final checkpoint (defer() held the
                # force-kill window open) — leave a bundle so a later
                # "did the tail land?" question has an answer
                from .telemetry import blackbox as _bbx

                bb.event(
                    "sigterm_stop", step=step, signal=shutdown.signal_name
                )
                _bbx.dump(
                    "sigterm_during_checkpoint",
                    exit_code=0,
                    step=step,
                    signal=shutdown.signal_name,
                    final_checkpoint=final_path or "",
                )
    # the writer is drained here; the final save must actually be on disk
    # and restorable before train() reports success (a lost final
    # checkpoint silently discards the training tail)
    if final_path is not None and jax.process_index() == 0:
        ok, reason = lineage.verify_checkpoint(final_path)
        if not ok:
            raise CheckpointWriteError(
                f"final checkpoint {final_path} did not land: {reason}"
            )
    return state


def _restore_last_good(
    state: TrainState, config: Config, step: int
) -> Optional[TrainState]:
    """Sentinel-rollback restore: load the newest verifiable ``LAST_GOOD``
    checkpoint into the (poisoned) state skeleton, keeping the HOST step
    counter — the loader then fast-forwards PAST the poison step instead
    of replaying it (with deterministic dropout keys a replay would just
    reproduce the same divergence).  Returns None when nothing verifiable
    exists (caller degrades to warn)."""
    path = lineage.last_good_checkpoint(config.save_dir)
    if path is None:
        print(
            "sat_tpu: rollback requested but save_dir holds no verifiable "
            f"LAST_GOOD checkpoint ({config.save_dir})",
            file=sys.stderr,
            flush=True,
        )
        return None
    restored, count = restore_checkpoint(state, model_file=path)
    if count == 0:
        print(
            f"sat_tpu: rollback restore from {path} loaded 0 tensors",
            file=sys.stderr,
            flush=True,
        )
        return None
    print(
        f"sat_tpu: rolled back to {path} "
        f"(step {int(np.asarray(restored.step))}); resuming forward at "  # sync-ok: rollback epilogue, off the hot path
        f"step {step}, skipping the poison window",
        file=sys.stderr,
        flush=True,
    )
    # device-owned copy, not a numpy scalar: the step leaf is donated into
    # train_step along with the rest of the state (see _assign_leaves)
    return restored._replace(step=jax.numpy.array(np.asarray(step, np.int32)))  # sync-ok: host int, not a device value


# ---------------------------------------------------------------------------
# shared decoding driver
# ---------------------------------------------------------------------------


def _eos_id(vocabulary: Vocabulary) -> int:
    """Vocabulary index of the '.' terminator (reference base_model.py:229)."""
    return vocabulary.word2idx["."]


def decode_dataset(
    config: Config,
    state: TrainState,
    dataset: DataSet,
    vocabulary: Vocabulary,
) -> List[Dict[str, Any]]:
    """Beam-search every image; returns [{image_id, image_file, caption,
    prob}] with last-batch padding dropped and per-image dedup — the
    reference's fake_count/set handling (base_model.py:83-88)."""
    variables: Dict[str, Any] = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats

    eos = _eos_id(vocabulary)

    # Mesh-parallel decoding: encoder + beam search in one jitted program
    # with the image batch sharded over 'data' — eval/test scale over the
    # mesh exactly like training does (reference capability:
    # base_model.py:70-117, which is strictly single-device).  Multi-host:
    # each process feeds its shard of the dataset and the beam results are
    # all-gathered so every host assembles the full result list.
    if int(np.prod(config.mesh_shape)) > 1:
        from .parallel import make_mesh, sync_processes
        from .parallel.collectives import make_global_batch
        from .parallel.data import mesh_data_shard, process_local_dataset
        from .parallel.sharding import named_shardings
        from .parallel.train import make_parallel_beam_search

        mesh = make_mesh(config)
        dp = mesh.shape.get("data", 1)
        if config.batch_size % dp != 0:
            raise ValueError(
                f"batch_size={config.batch_size} not divisible by the "
                f"data-axis size {dp} for mesh decoding"
            )
        # Placement mirrors training's (docs/PARALLELISM.md):
        # * vocab-TP runs: embedding table + softmax projection shard over
        #   'model' instead of idling it, and GSPMD compiles the TP decode
        #   (sharded logits, collective softmax/top-k) from the shardings
        #   alone;
        # * context-parallel runs trained with params REPLICATED
        #   (train() above, the 'model' axis was spent on the context
        #   grid) — eval keeps that placement AND spends the 'model' axis
        #   the same way: shard_map context-parallel beam search with the
        #   grid sharded and the distributed-softmax attend
        #   (parallel/context.py cp_beam_search).
        if config.context_parallel > 1:
            from .parallel.context import (
                make_context_parallel_beam_search,
                validate_cp_mesh,
            )

            validate_cp_mesh(config, mesh)
            placement_config = config.replace(vocabulary_size=-1)  # replicated
            make_caption_fn = make_context_parallel_beam_search
        else:
            placement_config = config
            make_caption_fn = make_parallel_beam_search
        # realign before the sharded placement (fresh communicator
        # rendezvous — see sync_processes): eval is reached after
        # unsynchronized host work (data prep, training epilogue)
        sync_processes("sat_tpu:shard_eval_variables")
        variables = jax.device_put(
            variables, named_shardings(variables, placement_config, mesh)
        )
        caption_fn = make_caption_fn(
            config, mesh, eos,
            beam_size=config.beam_size,
            valid_size=len(vocabulary.words),
            return_alphas=config.save_attention_maps,
        )

        def run_batch(batch):
            images = make_global_batch(mesh, {"images": batch["images"]})
            return caption_fn(variables, images["images"])

        pc = jax.process_count()
        if pc > 1:
            # split keyed on the data axis, not the process count: under
            # CP the model-axis processes all feed (and decode) the same
            # rows, so a pure-CP mesh gives (0, 1) — no split at all
            shard_idx, n_shards = mesh_data_shard(mesh)
            local_ds = process_local_dataset(
                dataset, process_index=shard_idx, process_count=n_shards
            )
            loader = make_loader(config, local_ds)
            from .utils.dist import gather_tree_replicated

            gathered = []
            # realign before the first decode dispatch (fresh per-axis
            # communicator windows — see the train-loop twin)
            sync_processes("sat_tpu:first_decode")
            # same knobs as the other loops; start clamped to batch count
            with ProfilerWindow(
                config, max_start=local_ds.num_batches - 1
            ) as prof:
                for b, batch in enumerate(
                    track(loader, local_ds.num_batches, desc="decode(mesh)")
                ):
                    prof.before_step(b)
                    out = run_batch(batch)
                    prof.after_step(b, out.words)
                    # assembly only consumes beam 0: slice on device, then
                    # one batched cross-host gather for the whole tuple
                    # (the beam-0 [B,T,N] alphas ride the same gather when
                    # attention maps are requested — VERDICT r2 weak #5)
                    best = jax.tree_util.tree_map(
                        lambda x: x[:, 0],
                        (out.words, out.lengths, out.log_scores)
                        + ((out.alphas,) if out.alphas is not None else ()),
                    )
                    gathered.append(
                        tuple(
                            np.asarray(x) for x in gather_tree_replicated(best)  # sync-ok: decode drain boundary (gathered beam-0)
                        )
                    )
            return _assemble_mesh_results(dataset, vocabulary, gathered)

    else:

        @jax.jit
        def encode_fn(variables, images):
            contexts, _ = encode(variables, config, images, train=False)
            return contexts

        decode_probed = []  # compile accounting fires once, on batch 0

        def run_batch(batch):
            contexts = encode_fn(variables, batch["images"])
            beam_kwargs = dict(
                beam_size=config.beam_size,
                valid_size=len(vocabulary.words),
                return_alphas=config.save_attention_maps,
            )
            if not decode_probed:
                decode_probed.append(True)
                tel_now = telemetry.get()
                if tel_now.enabled:
                    from .telemetry import xla as xla_acct

                    xla_acct.analyze(
                        "decode/encode", encode_fn, variables,
                        batch["images"], tel=tel_now,
                    )
                    xla_acct.analyze(
                        "decode/beam_search", beam_search_jit,
                        state.params["decoder"], config, contexts, eos,
                        tel=tel_now, **beam_kwargs,
                    )
            return beam_search_jit(
                state.params["decoder"], config, contexts, eos, **beam_kwargs
            )

    loader = make_loader(config, dataset)

    results: List[Dict[str, Any]] = []
    seen = set()
    emitted = 0
    # depth-1 pipeline: dispatch batch n+1 to the device before fetching
    # batch n's results, so host-side decode of words/captions overlaps
    # device-side beam search (np.asarray is the sync point)
    prev: Optional[Tuple[Any, List[str]]] = None

    def drain(out, files):
        nonlocal emitted
        words = np.asarray(out.words[:, 0])        # best caption per image  # sync-ok: decode drain boundary
        lengths = np.asarray(out.lengths[:, 0])  # sync-ok: decode drain boundary
        scores = np.asarray(out.log_scores[:, 0])  # sync-ok: decode drain boundary
        alphas = (
            np.asarray(out.alphas[:, 0]) if out.alphas is not None else None  # sync-ok: decode drain boundary
        )
        for i, image_file in enumerate(files):
            if emitted >= dataset.count:           # fake_count padding
                break
            # eval/test DataSets are unshuffled, so batch order is
            # image_ids order (reference drops fake_count the same way,
            # base_model.py:86-88)
            image_id = int(dataset.image_ids[emitted])
            emitted += 1
            if image_id in seen:                   # reference's set() dedup
                continue
            seen.add(image_id)
            length = max(1, int(lengths[i]))
            caption = vocabulary.get_sentence(words[i, :length])
            row = {
                "image_id": image_id,
                "image_file": str(image_file),
                "caption": caption,
                "prob": float(np.exp(scores[i])),  # sync-ok: host numpy, already drained
            }
            if alphas is not None:
                row["words"] = [
                    vocabulary.words[w] for w in words[i, :length]
                ]
                row["alphas"] = alphas[i, :length]    # [len, N]
            results.append(row)

    # profiler window over the decode loop — same knobs and semantics as
    # train's (shared ProfilerWindow), start clamped to the batch count so
    # a short eval still traces; the trace shows how much of the batch
    # time is the beam program vs encode vs dispatch
    # single-device decode gets the async device slot too (the mesh paths
    # place batches through make_global_batch inside run_batch)
    feed = (
        device_prefetch(loader)
        if int(np.prod(config.mesh_shape)) == 1
        else loader
    )
    # host tracing over the decode loop: data_wait / dispatch / drain per
    # batch (the drain of batch n overlaps batch n+1's device beam search
    # — the breakdown shows whether the host decode keeps up)
    tel = _telemetry_begin(config)
    # black-box flight recorder for decode (same contract as train's):
    # journal per batch so an uncaught exception mid-eval still leaves a
    # postmortem bundle behind via the CLI's exception handler
    dec_bb = None
    if config.blackbox:
        from .resilience.quarantine import ledger_path_for
        from .telemetry import blackbox as _blackbox

        _dec_tdir = _telemetry_dir(config)
        dec_bb = _blackbox.BlackBox(os.path.join(_dec_tdir, "blackbox"), tel)
        _blackbox.install(
            bb=dec_bb,
            telemetry_dir=_dec_tdir,
            config_snapshot=config.to_dict(),
            quarantine_ledger=ledger_path_for(config),
        )
        dec_bb.event("decode_start", batches=dataset.num_batches)
    try:
        with ProfilerWindow(config, max_start=dataset.num_batches - 1) as prof:
            # per-batch visibility during decode (reference
            # base_model.py:82,131 tqdm-bars eval/test; a full-COCO eval
            # would otherwise run silent)
            batch_t0 = time.perf_counter_ns()
            for b, batch in enumerate(
                track(
                    _timed_iter(feed, tel, "decode/data_wait"),
                    dataset.num_batches,
                    desc="decode",
                )
            ):
                prof.before_step(b)
                with tel.span("decode/dispatch"):
                    out = run_batch(batch)         # async dispatch
                prof.after_step(b, out.words)
                if prev is not None:
                    with tel.span("decode/drain"):
                        drain(*prev)
                prev = (out, batch["files"])
                now = time.perf_counter_ns()
                tel.record("decode/batch", batch_t0, now - batch_t0)
                batch_t0 = now
                if dec_bb is not None:
                    dec_bb.journal(b)
        if prev is not None:
            with tel.span("decode/drain"):
                drain(*prev)
    finally:
        if dec_bb is not None:
            from .telemetry import blackbox as _blackbox

            _blackbox.run_finalizers()
        if tel.enabled:
            _telemetry_finish(tel, config, "decode")
    return results


def _assemble_mesh_results(
    dataset: DataSet,
    vocabulary: Vocabulary,
    gathered: List[Tuple[np.ndarray, ...]],
) -> List[Dict[str, Any]]:
    """Merge all-gathered multi-host beam-0 results back into dataset order.

    ``gathered[b]`` = (words [B,T], lengths [B], scores [B][, alphas
    [B,T,N] when attention maps were requested]) for global batch ``b`` —
    the best beam per image, already gathered to every host.
    Row layout: each process's shard view holds the contiguous block of
    the global batch its data row owns, and ``make_global_batch`` places
    block ``r`` at global rows ``[r*Bl, (r+1)*Bl)`` — so gathered batch
    ``b`` row ``m`` IS position ``b*B + m`` of the global order, which
    for the unshuffled eval set is dataset row ``b*B + m``.  Positions at
    or past ``dataset.count`` are the trailing fake_count padding and are
    dropped; then the usual per-image dedup applies (reference
    base_model.py:83-88).
    """
    by_row: Dict[int, Tuple] = {}
    for b, batch_arrays in enumerate(gathered):
        B = batch_arrays[0].shape[0]
        for m in range(B):
            g = b * B + m
            if g < dataset.count:                # trailing fake_count pad
                by_row[g] = tuple(a[m] for a in batch_arrays)

    results: List[Dict[str, Any]] = []
    seen = set()
    for g in sorted(by_row):                     # dataset order + dedup
        image_id = int(dataset.image_ids[g])
        if image_id in seen:
            continue
        seen.add(image_id)
        word_row, length, score, *rest = by_row[g]
        length = max(1, int(length))
        row: Dict[str, Any] = {
            "image_id": image_id,
            "image_file": str(dataset.image_files[g]),
            "caption": vocabulary.get_sentence(word_row[:length]),
            "prob": float(np.exp(score)),  # sync-ok: host numpy, already drained
        }
        if rest:                                 # gathered beam-0 alphas
            row["words"] = [vocabulary.words[w] for w in word_row[:length]]
            row["alphas"] = rest[0][:length]     # [len, N]
        results.append(row)
    return results


def _render_attention_panel(
    image_file: str,
    words: List[str],
    alphas: np.ndarray,
    out_file: str,
) -> None:
    """Per-word attention figure (Xu et al. fig. 5): the image, then one
    tile per generated word with its soft-attention map α upsampled from
    the context grid and overlaid.  alphas: [len(words), N], N a square
    grid (196 → 14×14 for VGG16, 49 → 7×7 for ResNet50).

    Composited directly with cv2 (colormap + blend + grid + putText)
    rather than matplotlib: measured ~20x faster per panel on this host
    (matplotlib's tight_layout alone dominated), which matters because
    eval renders one panel per image."""
    import cv2

    bgr = cv2.imread(image_file, cv2.IMREAD_COLOR)
    if bgr is None:
        raise FileNotFoundError(image_file)
    h, w = bgr.shape[:2]
    tile_w = max(180, min(w, 360))
    tile_h = int(round(tile_w * h / w))
    base = cv2.resize(bgr, (tile_w, tile_h), interpolation=cv2.INTER_AREA)
    g = int(round(np.sqrt(alphas.shape[1])))
    # one shared color scale across the caption: per-tile autoscaling
    # would stretch a near-uniform map to the same contrast as a sharply
    # peaked one, faking localization
    vmax = float(alphas.max()) or 1.0  # sync-ok: host numpy, render path

    label_h = 22
    pad = 6

    def tile(image, label):
        canvas = np.full(
            (label_h + tile_h, tile_w, 3), 255, dtype=np.uint8
        )
        cv2.putText(
            canvas, label[:24], (4, label_h - 7),
            cv2.FONT_HERSHEY_SIMPLEX, 0.45, (0, 0, 0), 1, cv2.LINE_AA,
        )
        canvas[label_h:, :, :] = image
        return canvas

    tiles = [tile(base, "input")]
    for t, word in enumerate(words):
        amap = cv2.resize(
            alphas[t].reshape(g, g).astype(np.float32), (tile_w, tile_h),
            interpolation=cv2.INTER_CUBIC,
        )
        amap_u8 = np.clip(amap / vmax * 255.0, 0.0, 255.0).astype(np.uint8)
        heat = cv2.applyColorMap(amap_u8, cv2.COLORMAP_JET)
        blend = cv2.addWeighted(base, 0.4, heat, 0.6, 0.0)
        tiles.append(tile(blend, word))

    cols = min(5, len(tiles))
    rows = -(-len(tiles) // cols)
    cell_h, cell_w = tiles[0].shape[:2]
    panel = np.full(
        (rows * (cell_h + pad) + pad, cols * (cell_w + pad) + pad, 3),
        255, dtype=np.uint8,
    )
    for idx, t_img in enumerate(tiles):
        r, c = divmod(idx, cols)
        y = pad + r * (cell_h + pad)
        x = pad + c * (cell_w + pad)
        panel[y:y + cell_h, x:x + cell_w] = t_img
    cv2.imwrite(out_file, panel)


def _local_render_rows(results: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Partition artifact rendering across processes: every host holds the
    full (all-gathered) result list after a mesh decode, so without this
    N hosts would render N copies of every panel — duplicated work and
    racing non-atomic cv2.imwrite calls on shared storage.  The
    interleaved slice is disjoint; hosts without shared image storage
    skip rows whose source image they can't read (the render helpers
    raise FileNotFoundError only in single-process runs)."""
    pc = jax.process_count()
    if pc == 1:
        return results
    return results[jax.process_index()::pc]


def _save_attention_panels(results: List[Dict[str, Any]], out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    multiproc = jax.process_count() > 1
    for r in _local_render_rows(results):
        if "alphas" not in r:
            continue
        stem = os.path.splitext(os.path.basename(r["image_file"]))[0]
        try:
            _render_attention_panel(
                r["image_file"], r["words"], r["alphas"],
                os.path.join(out_dir, f"{stem}_attention.jpg"),
            )
        except FileNotFoundError:
            if not multiproc:
                raise  # single-process: a missing image is a real error
            # multi-host without shared image storage: this host only has
            # its own data shard's images; another host renders the rest


def _export_attention_artifacts(
    results: List[Dict[str, Any]], out_dir: str
) -> None:
    """Machine-readable attention introspection next to the JPG panels:
    attn.jsonl (per-caption alpha grids + entropy/coverage stats) and the
    self-contained HTML contact sheet (telemetry/exporters.py).  Process
    0 only — every host holds the full result list after a mesh decode,
    and these artifacts are whole-run files, not per-image renders."""
    if jax.process_index() != 0:
        return
    from .telemetry import exporters as tel_exporters

    os.makedirs(out_dir, exist_ok=True)
    n = tel_exporters.export_attention_jsonl(
        results, os.path.join(out_dir, "attn.jsonl")
    )
    sheet = tel_exporters.render_attention_sheet(
        results, os.path.join(out_dir, "attn.html")
    )
    if n:
        print(
            f"attention introspection: {n} captions -> "
            f"{os.path.join(out_dir, 'attn.jsonl')}"
            + (f", contact sheet {sheet}" if sheet else "")
        )


def _render_caption_images(results: List[Dict[str, Any]], out_dir: str) -> None:
    """Captioned-JPG artifacts for this process's render slice (same
    multi-host partition/skip rules as _save_attention_panels)."""
    multiproc = jax.process_count() > 1
    for r in _local_render_rows(results):
        stem = os.path.splitext(os.path.basename(r["image_file"]))[0]
        try:
            _render_caption_image(
                r["image_file"], r["caption"],
                os.path.join(out_dir, f"{stem}_result.jpg"),
            )
        except FileNotFoundError:
            if not multiproc:
                raise


def _render_caption_image(image_file: str, caption: str, out_file: str) -> None:
    """Captioned-JPG artifact (reference base_model.py:96-107), composited
    with cv2 (caption banner above the image) — same ~100x-per-artifact
    speedup story as _render_attention_panel."""
    import cv2

    img = cv2.imread(image_file, cv2.IMREAD_COLOR)
    if img is None:
        raise FileNotFoundError(image_file)
    h, w = img.shape[:2]
    out_w = max(320, min(w, 640))
    out_h = int(round(out_w * h / w))
    img = cv2.resize(img, (out_w, out_h), interpolation=cv2.INTER_AREA)

    # wrap the caption into lines that fit the banner
    font, scale, thick = cv2.FONT_HERSHEY_SIMPLEX, 0.5, 1
    words = caption.split()
    lines, cur = [], ""
    for word in words:
        cand = (cur + " " + word).strip()
        if cv2.getTextSize(cand, font, scale, thick)[0][0] > out_w - 12 and cur:
            lines.append(cur)
            cur = word
        else:
            cur = cand
    if cur:
        lines.append(cur)

    line_h = 20
    banner_h = 8 + line_h * max(1, len(lines))
    canvas = np.full((banner_h + out_h, out_w, 3), 255, dtype=np.uint8)
    for k, line in enumerate(lines):
        cv2.putText(
            canvas, line, (6, 8 + line_h * k + 12),
            font, scale, (0, 0, 0), thick, cv2.LINE_AA,
        )
    canvas[banner_h:, :, :] = img
    cv2.imwrite(out_file, canvas)


# ---------------------------------------------------------------------------
# eval
# ---------------------------------------------------------------------------


def evaluate(
    config: Config,
    state: Optional[TrainState] = None,
    model_file: Optional[str] = None,
    prepared: Optional[Tuple[Any, DataSet, Any]] = None,
) -> Dict[str, float]:
    """Scored beam-search decoding over the eval split
    (reference base_model.py:70-117): results.json + BLEU/METEOR/ROUGE/CIDEr.

    prepared: an existing ``(coco, dataset, vocabulary)`` triple from
    :func:`prepare_eval_data` — callers scoring many checkpoints against
    the same split (evaluate_sweep) pass it so the caption JSON is read
    and indexed once, not once per checkpoint."""
    coco, dataset, vocabulary = prepared or prepare_eval_data(config)
    if state is None:
        state = setup_state(config, load=True, model_file=model_file)

    results = decode_dataset(config, state, dataset, vocabulary)
    payload = [
        {"image_id": r["image_id"], "caption": r["caption"]} for r in results
    ]
    import json

    atomic_write(
        config.eval_result_file, "w", lambda f: json.dump(payload, f)
    )

    if config.save_eval_result_as_image:
        os.makedirs(config.eval_result_dir, exist_ok=True)
        _render_caption_images(results, config.eval_result_dir)
    if config.save_attention_maps:
        _save_attention_panels(results, config.eval_result_dir)
        _export_attention_artifacts(results, config.eval_result_dir)

    coco_res = coco.load_results(payload)
    scorer = CocoEvalCap(coco, coco_res, eval_data=dataset)
    return scorer.evaluate()


def evaluate_sweep(config: Config) -> Dict[int, Dict[str, float]]:
    """Score every checkpoint under save_dir — the reference's eval.sh
    sweep (/root/reference/eval.sh:1-9), in-process.  Writes per-step
    ``<step>.txt`` score dumps next to the checkpoints and returns
    {step: scores} for model selection.

    The reference's sweep launches one full process per checkpoint; the
    in-process upgrade this exists for means the expensive invariants are
    paid ONCE across the sweep — the eval split is prepared a single time
    and every checkpoint restores into one initialized state skeleton, so
    sweep cost is O(prep) + N×O(restore + decode)."""
    # the lineage scan skips temp/partial/zero-byte files, so an in-flight
    # or torn write never enters the sweep
    steps = lineage.checkpoint_steps(config.save_dir)
    prepared = prepare_eval_data(config)
    skeleton = create_train_state(jax.random.PRNGKey(config.seed), config)
    sweep: Dict[int, Dict[str, float]] = {}
    for step in steps:
        path = os.path.join(config.save_dir, f"{step}.npz")
        state, count = restore_checkpoint(skeleton, model_file=path)
        if count == 0:
            raise ValueError(f"checkpoint {path} restored 0 tensors")
        scores = evaluate(config, state=state, prepared=prepared)
        sweep[step] = scores
        atomic_write(
            os.path.join(config.save_dir, f"{step}.txt"),
            "w",
            lambda f: f.writelines(f"{k}: {v:.4f}\n" for k, v in scores.items()),
        )
    return sweep


# ---------------------------------------------------------------------------
# test
# ---------------------------------------------------------------------------


def test(
    config: Config,
    state: Optional[TrainState] = None,
    model_file: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Caption arbitrary JPEGs (reference base_model.py:119-161):
    captioned images + results.csv."""
    dataset, vocabulary = prepare_test_data(config)
    if dataset.count == 0:
        print(f"no images found in {config.test_image_dir}")
        return []
    if state is None:
        state = setup_state(config, load=True, model_file=model_file)

    results = decode_dataset(config, state, dataset, vocabulary)

    os.makedirs(config.test_result_dir, exist_ok=True)
    _render_caption_images(results, config.test_result_dir)
    if config.save_attention_maps:
        _save_attention_panels(results, config.test_result_dir)
        _export_attention_artifacts(results, config.test_result_dir)

    import pandas as pd

    pd.DataFrame(
        {
            "image_files": [r["image_file"] for r in results],
            "caption": [r["caption"] for r in results],
            "prob": [r["prob"] for r in results],
        }
    ).to_csv(config.test_result_file)
    return results
