"""Online caption-quality signals + streaming drift detection.

The model-quality observability plane (docs/OBSERVABILITY.md "Caption
quality"): everything here runs HOST-SIDE at the serve/bulk detok
boundary on arrays the drain already synced — the quality plane adds
zero device transfers, and the sync lint covers this module to keep it
that way.

Three layers, all jax-free (the telemetry-core import gate pins that):

* **signal extraction** — per-request scalars from the drained beam
  arrays: beam log-prob margin (top1 - top2), length-normalized
  log-prob, caption length, distinct-token ratio, repeated-bigram
  rate, unk/OOV rate, eos-truncation flag, and — when the engine was
  warmed with ``return_alphas`` — the online versions of the paper's
  attention diagnostics: coverage deviation (the unscaled
  doubly-stochastic penalty of Xu et al. eq. 14, the same formula as
  ``telemetry/device.py``'s training tap) and mean attention entropy.
* **streaming drift** — one :class:`FixedBinSketch` per signal
  (O(1)/request rotating window), a frozen reference distribution
  (captured from the first window of traffic, or loaded/exported as
  ``quality_reference.json``), and per-signal PSI drift scores
  published as ``quality/*`` gauges.
* **shared "quality" definitions** — the lifecycle canary's
  caption-divergence scoring lives here too (``lifecycle/canary.py``
  re-exports it), so the canary gate and steady-state drift share one
  definition of caption quality.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

REFERENCE_SCHEMA_VERSION = 1

# a current-window bin whose REFERENCE probability is below this is a
# "drift contributor": the request landed where the reference says
# traffic essentially never lands (exemplar trigger)
RARE_REFERENCE_PROB = 1e-3

# -- per-request signal extraction ------------------------------------------

# (name, lo, hi) — the static fixed-bin sketch ranges.  Static on
# purpose: a reference exported by one process must bin identically in
# another, so the edges are part of the schema, not the data.
SIGNALS: Tuple[Tuple[str, float, float], ...] = (
    ("margin", 0.0, 10.0),
    ("norm_logprob", -10.0, 0.0),
    ("caption_len", 0.0, 64.0),
    ("distinct_ratio", 0.0, 1.0),
    ("repeat_bigram", 0.0, 1.0),
    ("unk_rate", 0.0, 1.0),
    ("eos_trunc", 0.0, 1.0),
    ("coverage_dev", 0.0, 4.0),
    ("attn_entropy", 0.0, 8.0),
)

SIGNAL_NAMES: Tuple[str, ...] = tuple(name for name, _, _ in SIGNALS)


def host_coverage_deviation(alphas: np.ndarray, steps: int) -> float:
    """mean_i (1 - Σ_{t<steps} α_ti)² for ONE caption's [T, N] attention
    maps — the host twin of ``telemetry/device.py``'s
    ``alpha_coverage_deviation`` (identical for B=1 with a
    first-``steps`` mask; pinned by tests/test_quality.py)."""
    steps = max(0, min(int(steps), alphas.shape[0]))
    a = np.asarray(alphas[:steps], np.float32)  # sync-ok: host numpy, already drained
    coverage = a.sum(axis=0)  # [N]
    d = 1.0 - coverage
    return float(np.mean(d * d))  # sync-ok: host numpy, already drained


def host_attention_entropy(alphas: np.ndarray, steps: int) -> float:
    """Mean per-word attention entropy over the first ``steps`` rows of
    ONE caption's [T, N] maps — the host twin of ``device.py``'s
    ``attention_entropy`` (same clip floor)."""
    steps = max(0, min(int(steps), alphas.shape[0]))
    if steps == 0:
        return 0.0
    a = np.asarray(alphas[:steps], np.float32)  # sync-ok: host numpy, already drained
    h = -np.sum(a * np.log(np.clip(a, 1e-10, 1.0)), axis=-1)  # [steps]
    return float(np.mean(h))  # sync-ok: host numpy, already drained


def extract_signals(
    words: np.ndarray,
    lengths: np.ndarray,
    scores: np.ndarray,
    *,
    vocab_size: int,
    eos_id: int,
    alphas: Optional[np.ndarray] = None,
) -> Dict[str, float]:
    """ONE request's quality signals from its drained beam arrays.

    ``words`` [K, T] int ids, ``lengths`` [K], ``scores`` [K] summed
    log-probs — exactly the per-row slices the detok loop already holds
    (beam 0 is the ranked-best hypothesis).  ``alphas`` [K, T, N] adds
    the attention diagnostics when the engine drained them.  Pure host
    arithmetic; deterministic, so the bulk plane can stamp these into
    bitwise-reproducible shard rows.
    """
    K = int(words.shape[0])
    length = max(1, int(lengths[0]))
    ids = [int(w) for w in words[0, :length]]
    top1 = float(scores[0])  # sync-ok: host numpy, already drained
    margin = top1 - float(scores[1]) if K >= 2 else 0.0  # sync-ok: host numpy, already drained
    oov = sum(1 for i in ids if i <= 0 or i >= vocab_size)
    distinct = len(set(ids)) / length
    if length >= 2:
        bigrams = list(zip(ids, ids[1:]))
        repeat = 1.0 - len(set(bigrams)) / len(bigrams)
    else:
        repeat = 0.0
    sig = {
        "margin": margin,
        "norm_logprob": top1 / length,
        "caption_len": float(length),  # sync-ok: host scalar, no device value
        "distinct_ratio": distinct,
        "repeat_bigram": repeat,
        "unk_rate": oov / length,
        "eos_trunc": 0.0 if int(eos_id) in ids else 1.0,
    }
    if alphas is not None:
        sig["coverage_dev"] = host_coverage_deviation(alphas[0], length)
        sig["attn_entropy"] = host_attention_entropy(alphas[0], length)
    return sig


# -- canary divergence (shared definition; lifecycle/canary re-exports) -----


def caption_divergence(incumbent: str, candidate: str) -> float:
    """Token Jaccard distance between two captions in [0, 1] — the
    lifecycle canary's "did the model change what it says" score."""
    a = set(incumbent.split())
    b = set(candidate.split())
    if not a and not b:
        return 0.0
    union = a | b
    if not union:
        return 0.0
    return 1.0 - len(a & b) / len(union)


class DivergenceGauge:
    """EWMA of shadow-pair divergences; one float of state, no locks
    needed beyond the GIL (single shadow worker updates it)."""

    def __init__(self, alpha: float = 0.3) -> None:
        self.alpha = float(alpha)  # sync-ok: host config scalar
        self.value: Optional[float] = None
        self.samples = 0

    def update(self, divergence: float) -> float:
        d = min(1.0, max(0.0, float(divergence)))  # sync-ok: host scalar
        self.value = (
            d
            if self.value is None
            else self.alpha * d + (1 - self.alpha) * self.value
        )
        self.samples += 1
        return self.value


# -- streaming sketches + PSI -----------------------------------------------


class FixedBinSketch:
    """Fixed-bin histogram over a rotating window — O(1) per update.

    The window is a deque of (bin, value); an update appends one entry,
    bumps its bin count, and evicts exactly one stale entry once the
    window is full (the ``capacity.py`` rotation discipline — cost never
    grows with traffic).  Bin edges are static (part of the reference
    schema), uniform over [lo, hi] with both tails clamped into the
    terminal bins.
    """

    __slots__ = ("lo", "hi", "bins", "window", "counts", "_entries", "_sum")

    def __init__(
        self, lo: float, hi: float, bins: int = 16, window: int = 256
    ) -> None:
        if not hi > lo:
            raise ValueError(f"FixedBinSketch: hi {hi} must be > lo {lo}")
        self.lo = float(lo)  # sync-ok: host scalar, no device value
        self.hi = float(hi)  # sync-ok: host scalar, no device value
        self.bins = int(bins)
        self.window = max(1, int(window))
        self.counts = [0] * self.bins
        self._entries: deque = deque()
        self._sum = 0.0

    def bin_of(self, x: float) -> int:
        frac = (float(x) - self.lo) / (self.hi - self.lo)  # sync-ok: host scalar, no device value
        return min(self.bins - 1, max(0, int(frac * self.bins)))

    def update(self, x: float) -> None:
        b = self.bin_of(x)
        self._entries.append((b, float(x)))  # sync-ok: host scalar, no device value
        self.counts[b] += 1
        self._sum += float(x)  # sync-ok: host scalar, no device value
        if len(self._entries) > self.window:
            old_b, old_x = self._entries.popleft()
            self.counts[old_b] -= 1
            self._sum -= old_x

    @property
    def total(self) -> int:
        return len(self._entries)

    def mean(self) -> float:
        n = len(self._entries)
        return self._sum / n if n else 0.0

    def probs(self) -> List[float]:
        n = len(self._entries)
        if not n:
            return [0.0] * self.bins
        return [c / n for c in self.counts]


def psi(
    current: Sequence[float], reference: Sequence[float], eps: float = 1e-4
) -> float:
    """Population Stability Index between two binned distributions:
    Σ (p - q)·ln(p/q) with epsilon smoothing.  0 for identical windows;
    the classic operating points are ~0.1 (investigate) and ~0.25
    (population shifted).  Either side empty → 0 (no evidence yet)."""
    p = [max(float(v), 0.0) for v in current]  # sync-ok: host scalar, no device value
    q = [max(float(v), 0.0) for v in reference]  # sync-ok: host scalar, no device value
    if sum(p) <= 0 or sum(q) <= 0:
        return 0.0
    p = [max(v, eps) for v in p]
    q = [max(v, eps) for v in q]
    ps, qs = sum(p), sum(q)
    p = [v / ps for v in p]
    q = [v / qs for v in q]
    return float(sum((a - b) * math.log(a / b) for a, b in zip(p, q)))  # sync-ok: host scalar, no device value


# -- frozen reference -------------------------------------------------------


class QualityReference:
    """The frozen per-signal distributions drift is scored against.

    Round-trips through ``quality_reference.json`` so one process's
    steady-state traffic can gate another's (export via GET
    /quality_reference, load via --quality_reference).
    """

    def __init__(
        self,
        probs: Dict[str, List[float]],
        counts: Optional[Dict[str, int]] = None,
        fingerprint: Optional[Dict] = None,
    ) -> None:
        self.probs = {k: list(v) for k, v in probs.items()}
        self.counts = dict(counts or {})
        self.fingerprint = dict(fingerprint or {})

    @classmethod
    def from_sketches(
        cls,
        sketches: Dict[str, FixedBinSketch],
        fingerprint: Optional[Dict] = None,
    ) -> "QualityReference":
        return cls(
            probs={k: s.probs() for k, s in sketches.items()},
            counts={k: s.total for k, s in sketches.items()},
            fingerprint=fingerprint,
        )

    def to_payload(self) -> Dict:
        return {
            "schema_version": REFERENCE_SCHEMA_VERSION,
            "signals": {
                name: {
                    "lo": lo,
                    "hi": hi,
                    "probs": [round(p, 8) for p in self.probs.get(name, [])],
                    "count": self.counts.get(name, 0),
                }
                for name, lo, hi in SIGNALS
                if name in self.probs
            },
            "fingerprint": self.fingerprint,
        }

    @classmethod
    def from_payload(cls, payload: Dict) -> "QualityReference":
        version = payload.get("schema_version")
        if version != REFERENCE_SCHEMA_VERSION:
            raise ValueError(
                f"quality reference schema {version!r} != "
                f"{REFERENCE_SCHEMA_VERSION}"
            )
        signals = payload.get("signals", {})
        return cls(
            probs={k: list(v.get("probs", [])) for k, v in signals.items()},
            counts={k: int(v.get("count", 0)) for k, v in signals.items()},
            fingerprint=payload.get("fingerprint") or {},
        )

    def save(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_payload(), f, sort_keys=True, indent=1)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "QualityReference":
        with open(path) as f:
            return cls.from_payload(json.load(f))


# -- the streaming monitor --------------------------------------------------


class QualityMonitor:
    """Per-request quality accounting: rotating sketches (global + a
    per-tenant cut), PSI drift vs the frozen reference, and the outlier
    verdicts that arm the exemplar flight recorder.

    ``observe`` is the per-request hot-path entry (detok thread):
    O(signals) sketch updates and threshold checks.  PSI recomputation
    and gauge publication are rate-limited to ``publish_interval_s`` so
    a traffic burst pays sketch-update cost only.  Thread-safe: serve
    detok and lifecycle shadow workers may observe concurrently.
    """

    def __init__(
        self,
        *,
        window: int = 256,
        bins: int = 16,
        reference: Optional[QualityReference] = None,
        margin_min: float = 0.0,
        unk_max: float = 1.0,
        publish_interval_s: float = 0.25,
        tel=None,
        clock=time.monotonic,
    ) -> None:
        from . import get as _get_tel

        self.window = int(window)
        self.bins = int(bins)
        self.margin_min = float(margin_min)  # sync-ok: host scalar, no device value
        self.unk_max = float(unk_max)  # sync-ok: host scalar, no device value
        self.publish_interval_s = float(publish_interval_s)  # sync-ok: host scalar, no device value
        self._tel = tel if tel is not None else _get_tel()
        self._clock = clock
        self._lock = threading.Lock()
        self._sketches = self._fresh_sketches()
        self._tenant_sketches: Dict[str, Dict[str, FixedBinSketch]] = {}
        self.reference = reference
        self.reference_source = "file" if reference is not None else ""
        self.requests = 0
        self.outliers = 0
        self._t_published = -math.inf
        self._psi: Dict[str, float] = {}
        self._tenant_psi_max: Dict[str, float] = {}

    def _fresh_sketches(self) -> Dict[str, FixedBinSketch]:
        return {
            name: FixedBinSketch(lo, hi, self.bins, self.window)
            for name, lo, hi in SIGNALS
        }

    # -- hot path ----------------------------------------------------------

    def observe(
        self, signals: Dict[str, float], tenant: str = ""
    ) -> List[str]:
        """Fold one request's signals in; returns the outlier reasons
        (empty = unremarkable) the caller feeds the exemplar recorder."""
        reasons: List[str] = []
        with self._lock:
            self.requests += 1
            for name, value in signals.items():
                sketch = self._sketches.get(name)
                if sketch is not None:
                    sketch.update(value)
            if tenant:
                lanes = self._tenant_sketches.get(tenant)
                if lanes is None:
                    lanes = self._fresh_sketches()
                    self._tenant_sketches[tenant] = lanes
                for name, value in signals.items():
                    if name in lanes:
                        lanes[name].update(value)
            if (
                self.reference is None
                and self._sketches["margin"].total >= self.window
            ):
                # warmup freeze: the first full window IS the reference
                self.reference = QualityReference.from_sketches(
                    self._sketches
                )
                self.reference_source = "warmup"
            margin = signals.get("margin")
            if self.margin_min > 0 and margin is not None:
                if margin < self.margin_min:
                    reasons.append("low_margin")
            unk = signals.get("unk_rate")
            if self.unk_max < 1 and unk is not None and unk > self.unk_max:
                reasons.append("high_unk")
            if signals.get("eos_trunc", 0.0) >= 1.0:
                reasons.append("eos_trunc")
            if self.reference is not None:
                # drift contribution: the request landed in a bin the
                # frozen reference says traffic essentially never hits
                for name in ("margin", "norm_logprob", "coverage_dev"):
                    value = signals.get(name)
                    ref = self.reference.probs.get(name)
                    if value is None or not ref:
                        continue
                    b = self._sketches[name].bin_of(value)
                    if ref[b] < RARE_REFERENCE_PROB:
                        reasons.append(f"drift_{name}")
            if reasons:
                self.outliers += 1
        self.maybe_publish()
        return reasons

    # -- drift scoring + publication ---------------------------------------

    def drift_scores(self) -> Dict[str, float]:
        """Per-signal PSI vs the frozen reference ({} until frozen)."""
        with self._lock:
            if self.reference is None:
                return {}
            out = {}
            for name, sketch in self._sketches.items():
                ref = self.reference.probs.get(name)
                if not ref or not sketch.total:
                    continue
                out[name] = psi(sketch.probs(), ref)
            return out

    def maybe_publish(self, force: bool = False) -> None:
        """Rate-limited gauge refresh (scrape paths call with force)."""
        now = self._clock()
        if not force and now - self._t_published < self.publish_interval_s:
            return
        self._t_published = now
        scores = self.drift_scores()
        with self._lock:
            self._psi = scores
            tel = self._tel
            for name, value in scores.items():
                tel.gauge(f"quality/{name}_psi", round(value, 4))
            tel.gauge(
                "quality/psi_max",
                round(max(scores.values()), 4) if scores else 0.0,
            )
            tel.gauge(
                "quality/unk_rate",
                round(self._sketches["unk_rate"].mean(), 4),
            )
            tel.gauge(
                "quality/margin_mean",
                round(self._sketches["margin"].mean(), 4),
            )
            tel.gauge("quality/requests", self.requests)
            tel.gauge("quality/outliers", self.outliers)
            tel.gauge(
                "quality/reference_frozen",
                1 if self.reference is not None else 0,
            )
            self._tenant_psi_max = {}
            if self.reference is not None:
                for tenant, lanes in self._tenant_sketches.items():
                    worst = 0.0
                    for name, sketch in lanes.items():
                        ref = self.reference.probs.get(name)
                        if ref and sketch.total:
                            worst = max(worst, psi(sketch.probs(), ref))
                    self._tenant_psi_max[tenant] = worst
                    tel.gauge(
                        f"quality/tenant_{tenant}_psi_max", round(worst, 4)
                    )

    # -- surfaces ----------------------------------------------------------

    def reference_payload(self) -> Optional[Dict]:
        with self._lock:
            if self.reference is None:
                return None
            return self.reference.to_payload()

    def snapshot(self) -> Dict:
        """The /stats ``quality`` block (and the router's fan-in unit):
        plain floats/ints only, so the fleet merge can sum or max them
        with dict arithmetic."""
        with self._lock:
            return {
                "requests": self.requests,
                "outliers": self.outliers,
                "reference": self.reference_source,
                "psi": {k: round(v, 4) for k, v in self._psi.items()},
                "psi_max": round(max(self._psi.values()), 4)
                if self._psi
                else 0.0,
                "unk_rate": round(self._sketches["unk_rate"].mean(), 4),
                "margin_mean": round(self._sketches["margin"].mean(), 4),
                "tenants": {
                    t: {
                        "psi_max": round(v, 4),
                        "requests": self._tenant_sketches[t]["margin"].total,
                    }
                    for t, v in sorted(self._tenant_psi_max.items())
                },
            }
