"""VGG16 encoder → 196×512 spatial context grid.

Same topology as the reference's build_vgg16 (/root/reference/model.py:24-60):
13 'same'-padded 3×3 convs in 5 blocks, max-pool after the first 4 blocks,
conv5_3's 14×14×512 map reshaped to a [B, 196, 512] context grid.  Module
names match the reference's TF scopes (conv1_1 … conv5_3) so pretrained
``vgg16_no_fc.npy`` checkpoints map 1:1 (see sat_tpu.train.checkpoint).

TPU notes: NHWC layout, bfloat16 conv compute on the MXU, fp32 output for
the attention softmax downstream.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..nn.layers import Conv, max_pool2d

# (name, features, pool_after)
_VGG_LAYERS = [
    ("conv1_1", 64, False), ("conv1_2", 64, True),
    ("conv2_1", 128, False), ("conv2_2", 128, True),
    ("conv3_1", 256, False), ("conv3_2", 256, False), ("conv3_3", 256, True),
    ("conv4_1", 512, False), ("conv4_2", 512, False), ("conv4_3", 512, True),
    ("conv5_1", 512, False), ("conv5_2", 512, False), ("conv5_3", 512, False),
]

DIM_CTX = 512


class VGG16(nn.Module):
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, images, train: bool = False):
        """images: [B, 224, 224, 3] float32 → contexts [B, 196, 512] fp32."""
        x = images.astype(self.dtype)
        for name, features, pool_after in _VGG_LAYERS:
            x = Conv(
                features=features,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name=name,
            )(x)
            if pool_after:
                x = max_pool2d(x)
        b = x.shape[0]
        # 196 contexts at the reference's 224×224 input (model.py:54-59);
        # -1 keeps the module usable at other static image sizes.
        return x.reshape(b, -1, DIM_CTX).astype(jnp.float32)


def quant_forward(conv, images):
    """Topology walker for the quantized serve path (sat_tpu.nn.quant).

    ``conv(name, x, strides=1, relu=False)`` supplies the precision:
    fp32 (calibration observer), bf16, or int8-with-fused-dequant.  The
    walk is the exact __call__ graph above — 13 'SAME' 3×3 convs in 5
    blocks with max-pool after the first 4 — so the only divergence
    between the flax path and the quantized path is the conv arithmetic.
    """
    x = images
    for name, _features, pool_after in _VGG_LAYERS:
        x = conv(name, x, relu=True)
        if pool_after:
            x = max_pool2d(x)
    b = x.shape[0]
    return x.reshape(b, -1, DIM_CTX).astype(jnp.float32)
