"""Host-side image decoding and the async device-feed pipeline.

The reference loads images synchronously inside the train loop
(/root/reference/utils/misc.py:6-36 and base_model.py:53), stalling the
device every step.  Here the same preprocessing (decode → BGR→RGB → resize
224×224 → subtract ILSVRC-2012 per-channel mean) runs in a thread pool that
stays ``prefetch_depth`` batches ahead and hands ready numpy batches to the
device while the previous step is still running.

Preprocessing parity notes (utils/misc.py:13-28):
* cv2 decodes BGR; the reference flips channels to RGB via an axis-swap;
* the per-channel mean is the spatial mean of the Caffe ILSVRC-2012 mean
  image, [104.00698793, 116.66876762, 122.67891434] in (B,G,R) npy order —
  the reference subtracts this vector *as-is* from the RGB image
  (utils/misc.py:27), and we reproduce that exactly since pretrained
  weights were trained against it;
* "center crop" is 224→224, a no-op kept only for shape clarity.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from .. import telemetry
from ..resilience.faultinject import consume_caption_fault, consume_decode_fault
from ..resilience.quarantine import QuarantineManager, SystemicCorruption

# Spatial mean of the Caffe ILSVRC-2012 mean image (BGR npy channel order);
# matches np.load('ilsvrc_2012_mean.npy').mean(1).mean(1) in the reference.
ILSVRC_2012_MEAN = np.array([104.00698793, 116.66876762, 122.67891434], np.float32)

# Suffixes cv2.imread is expected to decode; everything else in a walked
# directory (READMEs, .DS_Store, sidecar JSONs) is skipped, not an error.
IMAGE_SUFFIXES = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


def walk_images(root: str) -> List[str]:
    """Deterministic recursive walk of ``root`` returning every image file
    (by suffix, case-insensitive) in sorted absolute-path order.

    Real corpora directories are mixed-content — checksum manifests,
    thumbnails databases, editor droppings live next to the JPEGs — and a
    bulk job that raises on the first ``README.txt`` three hours in is
    useless.  Non-image files are skipped and counted on the named
    ``data/skipped_nonimage`` counter so the skip volume is observable
    (heartbeat/bench) instead of silent.  The sort is over the final
    absolute paths, so the corpus order — and hence the bulk manifest
    fingerprint (bulk.manifest) — is independent of os.walk's directory
    visit order.
    """
    import os

    files: List[str] = []
    skipped = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()  # deterministic descent (cosmetic; final sort rules)
        for name in filenames:
            if name.lower().endswith(IMAGE_SUFFIXES):
                files.append(os.path.abspath(os.path.join(dirpath, name)))
            else:
                skipped += 1
    if skipped:
        telemetry.get().count("data/skipped_nonimage", skipped)
    return sorted(files)


class ImageLoader:
    """raw=True defers the astype(float32)−mean step to the accelerator
    (models.captioner.encode mean-subtracts uint8 inputs on device):
    numerically IDENTICAL — the resize already happens on the uint8 image,
    mean-sub is the final op either way — but the host skips a float32
    allocation per image and the host→device feed shrinks 4×.  The config
    knob is ``device_preprocess`` (on by default)."""

    def __init__(
        self, mean: Optional[np.ndarray] = None, size: int = 224,
        raw: bool = False,
    ):
        if raw and mean is not None:
            raise ValueError(
                "raw=True defers mean subtraction to the device, which "
                "hardcodes ILSVRC_2012_MEAN (captioner.encode) — a custom "
                "mean would be silently ignored; use raw=False with it"
            )
        self.mean = ILSVRC_2012_MEAN if mean is None else np.asarray(mean, np.float32)  # sync-ok: host constant
        self.size = size
        self.raw = raw

    def _finish_decode(self, image: np.ndarray) -> np.ndarray:
        """Shared post-codec tail: BGR → RGB, resize, contiguous uint8."""
        import cv2

        image = image[:, :, ::-1]  # BGR → RGB
        image = cv2.resize(image, (self.size, self.size))
        return np.ascontiguousarray(image)

    def load_raw(self, image_file: str) -> np.ndarray:
        """Decode → RGB → resize, stopping at the uint8 tensor.  This is
        the canonical post-resize row format the shard cache persists
        (data.shards): both preprocessing modes finish from it — raw=True
        feeds it to the device as-is, raw=False applies the float32 mean
        subtraction — so a cached row is bitwise-interchangeable with a
        live decode in either mode."""
        import cv2

        consume_decode_fault(image_file)  # SAT_FI_BAD_IMAGE_EVERY
        image = cv2.imread(image_file)
        if image is None:
            raise FileNotFoundError(f"cannot decode image: {image_file}")
        return self._finish_decode(image)

    def decode_raw(self, data: bytes) -> np.ndarray:
        """In-memory twin of load_raw for the serving frontend
        (sat_tpu/serve): cv2.imdecode of POSTed bytes runs the identical
        BGR→RGB→resize tail, so a JPEG uploaded over HTTP preprocesses
        bitwise-identically to the same file read from disk."""
        import cv2

        image = cv2.imdecode(np.frombuffer(data, np.uint8), cv2.IMREAD_COLOR)
        if image is None:
            raise ValueError("cannot decode image bytes (not a JPEG/PNG?)")
        return self._finish_decode(image)

    def load_image(self, image_file: str) -> np.ndarray:
        image = self.load_raw(image_file)
        if self.raw:
            return image  # uint8 RGB, device finishes
        return image.astype(np.float32) - self.mean

    def load_bytes(self, data: bytes) -> np.ndarray:
        """decode_raw + this loader's preprocessing mode (see load_image)."""
        image = self.decode_raw(data)
        if self.raw:
            return image
        return image.astype(np.float32) - self.mean

    def load_images(self, image_files: Sequence[str]) -> np.ndarray:
        return np.stack([self.load_image(f) for f in image_files])


class PrefetchDecodeError(RuntimeError):
    """A prefetch worker failed to decode an image.  The bare codec
    error surfaces on the consumer side at an unrelated later batch
    with no clue WHICH record broke; this wrapper carries the file and
    batch coordinates (the original error rides ``__cause__``)."""

    def __init__(
        self, image_file: str, batch_index: int, row: int,
        cause: Optional[BaseException] = None,
    ):
        detail = f": {cause}" if cause is not None else ""
        super().__init__(
            f"cannot decode {image_file!r} "
            f"(batch {batch_index}, row {row}){detail}"
        )
        self.image_file = image_file
        self.batch_index = batch_index
        self.row = row


class PrefetchLoader:
    """Wraps a batch iterator; assembles image batches ahead of the
    consumer in a ring of ``prefetch_depth`` ready slots (a bounded queue
    the producer thread fills and the step loop drains), so the
    accelerator never waits on host-side batch assembly.

    Two assembly paths:

    * **live decode** (default): images run through the thread-pool JPEG
      decode (``ImageLoader``) — 2.5-4.5 ms/image of codec work;
    * **shard gather** (``shard_cache`` given, see ``data.shards``): the
      batch is one fancy-index read per shard out of mmap'd preprocessed
      uint8 tensors — no codec, no per-image allocation; files absent
      from the cache fall back to live decode per image, so a partial
      cache degrades instead of failing.  Bitwise-identical to the live
      path in both preprocessing modes (the shard row IS the live path's
      post-resize uint8 intermediate).

    Yields dicts with 'images' [B,S,S,3] — float32 mean-subtracted, or
    uint8 RGB when the loader runs raw=True (device finishes the
    preprocessing; see ImageLoader) — plus any extra arrays the source
    iterator produced ('word_idxs', 'masks', 'files')."""

    def __init__(
        self,
        dataset,
        image_loader: Optional[ImageLoader] = None,
        num_workers: int = 8,
        prefetch_depth: int = 2,
        shard_cache=None,
        quarantine: Optional[QuarantineManager] = None,
    ):
        self.dataset = dataset
        self.loader = image_loader or ImageLoader()
        self.num_workers = num_workers
        self.prefetch_depth = max(1, prefetch_depth)
        self.shard_cache = shard_cache
        # quarantine=None (default, and every direct construction in
        # tests): failures raise, as they always did.  runtime wires a
        # run-level QuarantineManager in, flipping the data plane to
        # contain-and-substitute (resilience.quarantine)
        self.quarantine = quarantine
        self._pass = 0  # __iter__ count: caption quarantine coordinates
        if shard_cache is not None and shard_cache.image_size != self.loader.size:
            raise ValueError(
                f"shard cache rows are {shard_cache.image_size}px but the "
                f"loader resizes to {self.loader.size}px — the cache was "
                "opened for a different preprocessing"
            )

    def _decode_batch(
        self, batch, pool: ThreadPoolExecutor, pass_idx: int = 0,
        batch_idx: int = 0,
    ):
        with telemetry.span("data/decode_batch"):
            return self._decode_batch_inner(batch, pool, pass_idx, batch_idx)

    def _decode_batch_inner(
        self, batch, pool: ThreadPoolExecutor, pass_idx: int = 0,
        batch_idx: int = 0,
    ):
        if isinstance(batch, tuple):
            files, word_idxs, masks = batch
            out = {
                "word_idxs": np.asarray(word_idxs, np.int32),  # sync-ok: host numpy
                "masks": np.asarray(masks, np.float32),  # sync-ok: host numpy
            }
        else:
            files, out = batch, {}
        files = [str(f) for f in files]
        q = self.quarantine
        # (row, file, reason, exc, kind) — everything that must not be
        # trained on as-is; filled by the replay pre-pass, the gather,
        # the live decode, and the caption anomaly scan below
        bad: List[tuple] = []
        flagged: set = set()
        if q is not None:
            q.note_rows(len(files))
            # replayed ledger: substitute known-bad files proactively,
            # never re-attempting the decode — a file repaired since the
            # original run must not change the replay (bitwise rule)
            for i, f in enumerate(files):
                if q.known_bad_file(f):
                    bad.append((i, f, "replayed_ledger", None, "image"))
                    flagged.add(i)
        if self.shard_cache is not None:
            gather_bad = None if q is None else []
            raw = self.shard_cache.gather(
                files, fallback=self.loader.load_raw, bad_rows=gather_bad
            )
            if gather_bad:
                for i, f, reason, exc in gather_bad:
                    if i not in flagged:
                        bad.append((i, f, reason, exc, "image"))
                        flagged.add(i)
            # the final float32−mean step runs batch-wise here; elementwise
            # it is the exact op the live path applies per image, so the
            # two paths stay bitwise-identical
            out["images"] = (
                raw if self.loader.raw
                else raw.astype(np.float32) - self.loader.mean
            )
        else:
            S = self.loader.size
            dtype = np.uint8 if self.loader.raw else np.float32
            images = np.zeros((len(files), S, S, 3), dtype)
            def _load_one(i):
                if i in flagged:
                    return i, None, None
                try:
                    return i, self.loader.load_image(files[i]), None
                except Exception as e:
                    return i, None, e
            for i, img, exc in pool.map(_load_one, range(len(files))):
                if img is not None:
                    images[i] = img
                elif exc is not None:
                    if q is None:
                        raise PrefetchDecodeError(
                            files[i], batch_idx, i, exc
                        ) from exc
                    bad.append((i, files[i], "decode_failed", exc, "image"))
                    flagged.add(i)
            out["images"] = images
        out["files"] = list(files)
        if "word_idxs" in out:
            for i in range(len(files)):
                if consume_caption_fault():  # SAT_FI_BAD_CAPTION_AT
                    out["word_idxs"][i] = 0
                    out["masks"][i] = 0.0
            if q is not None:
                masks = out["masks"]
                cap = masks.shape[1] if masks.ndim == 2 else 0
                for i in range(len(files)):
                    if i in flagged:
                        continue
                    n_tok = float(masks[i].sum())  # sync-ok: host numpy
                    if q.known_bad_pos(pass_idx, batch_idx, i):
                        reason = "replayed_ledger"
                    elif n_tok == 0:
                        reason = "caption_all_oov"
                    elif cap and n_tok >= cap:
                        reason = "caption_overlength"
                    else:
                        continue
                    bad.append((i, files[i], reason, None, "caption"))
                    flagged.add(i)
        if q is not None and bad:
            self._quarantine_and_substitute(
                out, bad, len(files), pass_idx, batch_idx
            )
        return out

    def _quarantine_and_substitute(
        self, out, bad, n_rows, pass_idx, batch_idx
    ):
        """Ledger every newly bad row, then overwrite each bad row
        wholesale with a deterministically chosen healthy row of the
        same batch — geometry never changes, a replay with the same
        ledger substitutes identically."""
        q = self.quarantine
        bad_set = {b[0] for b in bad}
        healthy = [i for i in range(n_rows) if i not in bad_set]
        for i, f, reason, exc, kind in sorted(bad, key=lambda b: b[0]):
            pos = (pass_idx, batch_idx, i) if kind == "caption" else None
            if reason != "replayed_ledger":
                # may raise SystemicCorruption (the ceiling)
                q.quarantine(f, reason, kind=kind, pos=pos, exc=exc)
            if not healthy:
                raise SystemicCorruption(
                    f"every row of batch {batch_idx} is quarantined "
                    f"(last: {f!r}, {reason}) — no healthy row to "
                    "substitute; the input data is systemically corrupt"
                )
            key = (
                f"image:{f}" if kind == "image"
                else f"caption:{pass_idx}:{batch_idx}:{i}"
            )
            j = healthy[QuarantineManager.substitute_index(key, len(healthy))]
            for k in ("images", "word_idxs", "masks"):
                if k in out:
                    out[k][i] = out[k][j]
            out["files"][i] = out["files"][j]

    def __iter__(self) -> Iterator[dict]:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_depth)
        sentinel = object()
        stop = threading.Event()
        error: List[BaseException] = []

        pass_idx = self._pass
        self._pass += 1

        def producer():
            try:
                with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
                    for batch_idx, batch in enumerate(self.dataset):
                        item = self._decode_batch(
                            batch, pool, pass_idx, batch_idx
                        )
                        # Bounded put that aborts if the consumer went away,
                        # so an abandoned iterator can't pin a thread.
                        while not stop.is_set():
                            try:
                                q.put(item, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if stop.is_set():
                            return
            except BaseException as e:  # surfaced on the consumer side
                error.append(e)
            finally:
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                # depth AFTER the take: 0 = consumer outran the producers
                # (data-starved), maxsize = producers ahead (healthy)
                telemetry.get().gauge("data/prefetch_qsize", q.qsize())
                if item is sentinel:
                    if error:
                        raise error[0]
                    return
                yield item
        finally:
            stop.set()
