"""HTTP frontend for the captioning service (docs/SERVING.md).

A stdlib ``ThreadingHTTPServer`` — one Python thread per in-flight
request, which is exactly the concurrency this workload wants: request
threads spend their time in the JPEG codec (releases the GIL) or parked
on an Event while the batcher owns the device, so host preprocessing of
request n+1 overlaps device decode of batch n with no async framework.

Endpoints:

* ``POST /caption`` — body: JPEG/PNG bytes.  200 → ``{"captions": [{
  "caption", "log_prob", "prob"}, ...beam-ordered], "bucket",
  "model_step"}``.  400 undecodable body, 429 queue/quota shed, 503
  draining, 504 deadline/timeout.  ``X-Deadline-Ms`` (integer) overrides
  ``Config.serve_deadline_ms`` per request.  Under ``--tenants``,
  ``X-Tenant`` selects the tenant (quota bucket, scheduling weight, SLO
  lane; bare/unknown keys map to the default tenant) and ``X-Model``
  pins a resident param set; every 429/503 carries ``X-Shed-Scope:
  tenant|global`` with a scope-matched ``Retry-After`` (tenant bucket
  refill vs. observed service period).
* ``GET /healthz`` — readiness + the run-health heartbeat payload
  (telemetry.Heartbeat — same fields watchers poll from heartbeat.json).
  200 ready, 503 draining/stopped: a load balancer needs only the code.
* ``GET /stats`` — queue depth, bucket histogram, serve counters, and
  p50/p95/p99 latency per serve span (queue_wait / preprocess / dispatch
  / detok / request) from the telemetry ring.
* ``GET /metrics`` — Prometheus text exposition (format 0.0.4) of every
  counter/gauge/span aggregate (telemetry.promtext).
* ``POST /profile?duration_ms=N`` — start a bounded live ``jax.profiler``
  capture into ``<telemetry_dir>/profiles/<ts>/``; 409 while another
  capture runs, duration clamped to the hard cap (telemetry.profwin).
* ``GET /quality_reference`` — export the frozen quality-reference
  distributions (telemetry.quality) for ``--quality_reference`` on
  another replica; 404 with ``--serve_quality off``, 409 before one
  froze.

Every reply — including 400/429/503/504 sheds and 404s — echoes
``X-Request-Id`` (inbound value sanitized, or minted), and each
``POST /caption`` is traced per phase into ``access.jsonl`` plus its own
Perfetto lane (telemetry.tracectx).  Declared SLOs (``slo_*`` config)
are evaluated continuously; a burning objective flips ``/healthz`` to
503 "degraded" with the objective named (telemetry.slo).

Shutdown: SIGTERM/SIGINT (via ``resilience.preempt.GracefulShutdown``)
or ``request_shutdown()`` triggers the drain sequence — readiness flips
first, the batcher rejects new work and completes everything admitted,
then the listener and heartbeat close and ``serve()`` returns 0.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import telemetry
from ..config import Config
from ..data.vocabulary import Vocabulary
from ..lifecycle import LifecycleController
from ..lifecycle import canary as canary_mod
from ..resilience.preempt import GracefulShutdown
from ..telemetry import promtext, tracectx
from ..telemetry.capacity import CapacityModel, EncodeCacheSketch
from ..telemetry.heartbeat import Heartbeat
from ..telemetry.exemplar import ExemplarRecorder
from ..telemetry.metering import MeteringLedger
from ..telemetry.profwin import ProfileLatch
from ..telemetry.quality import QualityMonitor, QualityReference
from ..telemetry.slo import SLOEngine, objectives_from_config
from ..utils.summary import crc32c
from . import handoff
from .batcher import ContinuousBatcher, MicroBatcher, Rejected
from .engine import ServeEngine, load_serving_state
from .slot_pool import PagedSlotPool
from .tenants import TenantRegistry

_LATENCY_SPANS = (
    "serve/request",
    "serve/queue_wait",
    "serve/preprocess",
    "serve/dispatch",
    "serve/step",
    "serve/detok_queue",
    "serve/detok",
)

# /metrics histogram families (telemetry/promtext.py): true cumulative
# _bucket/_sum/_count exposition alongside the percentile gauges, so
# Prometheus picks its own quantiles server-side.  Latency bounds in
# seconds (the Prometheus convention); steps-per-dispatch raw counts
# matching the fused-decode K ladder.
_HISTOGRAMS: Dict[str, promtext.HistogramSpec] = {
    "sat_request_latency_seconds": (
        "serve/request",
        (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
        1e-9,
    ),
    "sat_steps_per_dispatch": (
        "serve/steps_per_dispatch",
        (1.0, 2.0, 4.0, 8.0, 16.0, 32.0),
        1.0,
    ),
}


def _percentiles_ms(tel, name: str) -> Optional[Dict[str, Any]]:
    """p50/p95/p99 (ms) of a span's recorded durations; None when empty.
    Host-side accounting over the telemetry ring — no device data."""
    data = np.asarray(tel.durations_ns(name), np.float64)  # sync-ok: host telemetry ring, not device data
    if data.size == 0:
        return None
    data = np.sort(data) / 1e6
    def pct(p: float) -> float:
        idx = min(data.size - 1, int(p / 100.0 * data.size))
        return round(float(data[idx]), 3)  # sync-ok: host numpy percentile
    return {
        "count": int(data.size),
        "p50": pct(50),
        "p95": pct(95),
        "p99": pct(99),
    }


def _percentiles_raw(tel, name: str) -> Optional[Dict[str, Any]]:
    """Like :func:`_percentiles_ms` but for spans that store raw counts
    (serve/decode_steps records loop iterations, not nanoseconds)."""
    data = np.asarray(tel.durations_ns(name), np.float64)  # sync-ok: host telemetry ring, not device data
    if data.size == 0:
        return None
    data = np.sort(data)
    def pct(p: float) -> float:
        idx = min(data.size - 1, int(p / 100.0 * data.size))
        return round(float(data[idx]), 3)  # sync-ok: host numpy percentile
    return {
        "count": int(data.size),
        "p50": pct(50),
        "p95": pct(95),
        "p99": pct(99),
    }


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "sat-serve"

    def log_message(self, fmt, *args):  # stderr per-request noise: off
        pass

    def _request_id(self) -> str:
        return tracectx.ensure_id(self.headers.get(tracectx.TRACE_HEADER))

    def _send(
        self,
        status: int,
        body: bytes,
        ctype: str,
        rid: str,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        # EVERY reply carries the correlation id — sheds and 404s too,
        # so clients can correlate a reject with their own logs
        self.send_header(tracectx.TRACE_HEADER, rid)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply(
        self,
        status: int,
        payload: Dict[str, Any],
        rid: str,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self._send(
            status, json.dumps(payload).encode(), "application/json", rid,
            headers=headers,
        )

    def do_GET(self) -> None:
        app = self.server.app
        rid = self._request_id()
        route = self.path.split("?", 1)[0]
        if route == "/healthz":
            payload, status = app.healthz()
            self._reply(status, payload, rid)
        elif route == "/stats":
            self._reply(200, app.stats(), rid)
        elif route == "/metrics":
            self._send(
                200, app.metrics_text().encode(), promtext.CONTENT_TYPE, rid
            )
        elif route == "/quality_reference":
            payload, status = app.quality_reference()
            self._reply(status, payload, rid)
        else:
            self._reply(404, {"error": f"no route {self.path}"}, rid)

    def do_POST(self) -> None:
        app = self.server.app
        rid = self._request_id()
        route, _, query = self.path.partition("?")
        if route in ("/reload", "/promote", "/rollback"):
            status, payload = app.admin_lifecycle(route[1:])
            self._reply(status, payload, rid)
            return
        if route == "/profile":
            import urllib.parse

            params = urllib.parse.parse_qs(query)
            try:
                duration_ms = (
                    int(params["duration_ms"][0])
                    if "duration_ms" in params
                    else None
                )
            except (ValueError, IndexError):
                self._reply(
                    400, {"error": "duration_ms must be an integer"}, rid
                )
                return
            ok, info = app.start_profile(duration_ms)
            if ok:
                self._reply(
                    200, {"profile_dir": info, "duration_ms": duration_ms}, rid
                )
            else:
                status = 409 if "in progress" in info else 503
                self._reply(status, {"error": info}, rid)
            return
        if route not in ("/caption", "/encode"):
            self._reply(404, {"error": f"no route {self.path}"}, rid)
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = 0
        if length <= 0:
            self._reply(400, {"error": "empty body; POST image bytes"}, rid)
            return
        body = self.rfile.read(length)
        if route == "/encode":
            # encode tier: image bytes in, framed context grid out
            status, out_body, ctype = app.handle_encode(body)
            self._send(status, out_body, ctype, rid)
            return
        status, payload = app.handle_caption(
            body,
            deadline_ms=self.headers.get("X-Deadline-Ms"),
            request_id=rid,
            tenant=self.headers.get("X-Tenant"),
            model=self.headers.get("X-Model"),
            content_type=self.headers.get("Content-Type"),
        )
        headers = None
        if status in (429, 503) and "retry_after_ms" in payload:
            # RFC 7231 Retry-After is whole seconds; round up so a
            # compliant client never comes back before the hint (the
            # never-0s clamp).  One contract for both shed shapes: 429
            # queue/quota sheds and 503 drain-rejects carry the same
            # header the router's coherent edge shed speaks, and
            # X-Shed-Scope says WHOSE capacity ran out — "tenant" (your
            # bucket/lane; backing off helps only you) vs "global" (the
            # service; everyone should back off).
            secs = max(1, int(-(-payload["retry_after_ms"] // 1000)))
            headers = {
                "Retry-After": str(secs),
                "X-Shed-Scope": payload.get("shed_scope", "global"),
            }
        self._reply(status, payload, rid, headers=headers)


class CaptionServer:
    """Wires engine + micro-batcher + HTTP listener + heartbeat; owns the
    readiness flag and the drain sequence."""

    # ceiling on how long a handler thread waits for its result when the
    # request carries no deadline (a wedged device must not strand
    # connections forever)
    DEFAULT_WAIT_S = 120.0

    def __init__(
        self,
        config: Config,
        engine: ServeEngine,
        host: Optional[str] = None,
        port: Optional[int] = None,
    ) -> None:
        self.config = config
        self.engine = engine
        self._tel = telemetry.get()
        # multi-tenant plane (docs/SERVING.md): the registry maps
        # X-Tenant → quota bucket / scheduling weight / resident model /
        # SLO lane.  The empty --tenants spec is the degenerate
        # single-tenant registry (multi=False): no buckets, no weights
        # table, no per-tenant counters — the pre-tenant serving path,
        # bit for bit.
        self.tenants = TenantRegistry.parse(config.tenants)
        self._load_residents()
        weights = self.tenants.weights() if self.tenants.multi else None
        tdir = config.telemetry_dir or os.path.join(
            config.summary_dir, "telemetry"
        )
        # caption-quality plane (telemetry/quality.py): streaming signal
        # sketches + PSI drift vs a frozen reference, and the exemplar
        # flight recorder for outlier requests.  Off (the default) means
        # no monitor, no recorder, no alphas in the warmed executables —
        # bit-identical to the pre-quality serving path (pinned by
        # tests/test_quality.py).
        self.quality: Optional[QualityMonitor] = None
        self.exemplars: Optional[ExemplarRecorder] = None
        if config.serve_quality == "on":
            reference = None
            if config.serve_quality_reference:
                reference = QualityReference.load(
                    config.serve_quality_reference
                )
            self.quality = QualityMonitor(
                window=config.serve_quality_window,
                reference=reference,
                margin_min=config.serve_quality_margin_min,
                unk_max=config.serve_quality_unk_max,
                tel=self._tel,
            )
            self.exemplars = ExemplarRecorder(
                config.serve_quality_exemplar_dir
                or os.path.join(tdir, "exemplars"),
                budget_mb=config.serve_quality_exemplar_mb,
            )
            # replay context: scripts/replay_exemplar.py boots from THIS
            # meta, never from guessed flags
            self.exemplars.write_meta(
                {
                    "config": config.to_dict(),
                    "model_step": engine.step,
                    "vocab_crc32c": f"{crc32c(chr(10).join(engine.vocabulary.words).encode('utf-8')):08x}",
                }
            )
        # admission knobs come from THIS server's config (which may be a
        # replace() of the engine's — e.g. a tighter queue for the same
        # warmed engine), not the engine's defaults
        self.pool: Optional[PagedSlotPool] = None
        if config.serve_mode == "continuous":
            self.pool = PagedSlotPool(
                engine,
                pages=config.serve_slot_pages,
                page_width=config.serve_page_width,
                tel=self._tel,
            )
            self.batcher = ContinuousBatcher(
                engine,
                pool=self.pool,
                queue_depth=config.serve_queue_depth,
                tel=self._tel,
                on_wedge=self._on_wedge,
                wedge_timeout_ms=config.serve_wedge_timeout_ms,
                weights=weights,
                quality=self.quality,
                exemplars=self.exemplars,
            )
        else:
            self.batcher = MicroBatcher(
                engine,
                max_batch=config.serve_max_batch,
                max_wait_ms=config.serve_max_wait_ms,
                queue_depth=config.serve_queue_depth,
                tel=self._tel,
                on_wedge=self._on_wedge,
                wedge_timeout_ms=config.serve_wedge_timeout_ms,
                weights=weights,
                quality=self.quality,
                exemplars=self.exemplars,
            )
        self._host = host if host is not None else config.serve_host
        self._requested_port = (
            port if port is not None else config.serve_port
        )
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._ready = False
        # admitted /caption requests resident in this process (queued or
        # decoding) — a top-level /healthz load signal for the router's
        # poller alongside queue_depth
        self._in_flight = 0
        self._in_flight_lock = threading.Lock()
        # wedged-batch degraded state (docs/SERVING.md): /healthz reports
        # 503 "degraded" while the engine re-warms after a stuck in-flight
        # batch; requests are still admitted (the batcher is alive) — only
        # the balancer-facing health flips
        self._degraded = False
        self._t_start = time.time()
        self.heartbeat: Optional[Heartbeat] = None
        # fleet observability (telemetry.tracectx/profwin/slo): the
        # request tracer, the live-profile latch, and the SLO engine all
        # share the telemetry dir and the rotating-sink byte cap
        tdir = config.telemetry_dir or os.path.join(
            config.summary_dir, "telemetry"
        )
        cap_bytes = int(config.telemetry_log_cap_mb * 1e6)
        self.tracer = tracectx.RequestTracer(
            path=os.path.join(tdir, "access.jsonl"), cap_bytes=cap_bytes
        )
        self.profiles = ProfileLatch(tdir)
        # cost attribution + capacity plane (telemetry/metering.py,
        # telemetry/capacity.py): the per-tenant ledger, the would-be
        # encode-cache probe, and the headroom model — all host-side
        # arithmetic on already-synced boundaries, only constructed when
        # telemetry is live (attribution rides telemetry-gated windows)
        self.metering: Optional[MeteringLedger] = None
        self.capacity: Optional[CapacityModel] = None
        self._cache_sketch: Optional[EncodeCacheSketch] = None
        if config.serve_metering and self._tel.enabled:
            self.metering = MeteringLedger(
                path=os.path.join(tdir, "metering.jsonl"),
                cap_bytes=cap_bytes,
                tel=self._tel,
            )
            self._cache_sketch = EncodeCacheSketch()
            self.capacity = CapacityModel(
                self._tel,
                self.metering,
                # capacity denominator: decode seats — pool slots in
                # continuous mode, the largest warmed bucket in batch
                # (engine doubles/stubs without buckets fall back to the
                # configured batch ceiling)
                slots=(
                    self.pool.slots
                    if self.pool is not None
                    else max(
                        getattr(engine, "buckets", None)
                        or (config.serve_max_batch,)
                    )
                ),
                sketch=self._cache_sketch,
                # the REAL cache (when --encode_cache on): its measured
                # hit ratio publishes next to the sketch's prediction
                # plus the reconciliation delta
                # getattr: engine doubles in tests don't grow the attr
                cache=getattr(engine, "encode_cache", None),
            )
        self.slo = SLOEngine(
            self._tel,
            objectives_from_config(
                config,
                "serve",
                tenants=self.tenants.slo_lanes(
                    config.slo_serve_p99_ms, config.slo_error_ratio
                ),
            ),
            jsonl_path=os.path.join(tdir, "slo.jsonl"),
            cap_bytes=cap_bytes,
            fast_s=config.slo_window_fast_s,
            slow_s=config.slo_window_slow_s,
        )
        # model-lifecycle plane (sat_tpu/lifecycle): always constructed
        # so the admin endpoints (/reload /promote /rollback) work even
        # without the background poller; the poller thread itself only
        # starts when --model_reload > 0 (controller.start gates it)
        self.lifecycle = LifecycleController(
            config, engine, self.batcher, tel=self._tel
        )

    def _load_residents(self) -> None:
        """Load every registry-declared resident model into the engine
        through the lifecycle loader (integrity + vocab + full-coverage
        guards), each aval-validated against the incumbent so all share
        the warmed AOT executables.  A resident that fails its guards is
        a boot error — a tenant pointed at a model that cannot serve
        must not silently fall back to the incumbent."""
        for alias, path in sorted(self.tenants.models.items()):
            from ..lifecycle.loader import load_candidate

            staged = load_candidate(self.engine, self.config, path)
            self.engine.install_resident(
                alias,
                staged["variables"],
                staged["decoder_params"],
                staged["step"],
                staged["source"],
            )
            print(
                f"sat_tpu: resident model {alias!r} loaded from {path} "
                f"(step {staged['step']})",
                file=sys.stderr,
                flush=True,
            )

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def ready(self) -> bool:
        return self._ready

    @property
    def in_flight(self) -> int:
        with self._in_flight_lock:
            return self._in_flight

    # -- request handlers (HTTP worker threads) ----------------------------

    def _finish_request(
        self,
        trace: "tracectx.RequestTrace",
        status: int,
        payload: Dict[str, Any],
        bucket: Optional[int] = None,
        slot: str = canary_mod.INCUMBENT,
        tenant: Optional[str] = None,
        cost=None,
    ) -> Tuple[int, Dict[str, Any]]:
        """Every terminal /caption reply funnels through here: the access
        log gets its record, the SLO error-ratio counters tick, the
        request's attributed cost is charged to its tenant's meter, and
        the payload learns its request id."""
        total_ns = time.perf_counter_ns() - trace.t_start_ns
        with self._in_flight_lock:
            self._in_flight = max(0, self._in_flight - 1)
        self._tel.count("serve/http_requests")
        if status >= 500:
            self._tel.count("serve/http_5xx")
        if tenant is not None and self.tenants.multi:
            # per-tenant SLO lane feed (same pattern as the canary lane
            # below): each tenant's own latency span and error-ratio
            # counters, so one tenant burning its objectives never
            # muddies another's — and the tenant dimension rides the
            # metric NAME, so /metrics exports it with no promtext
            # changes
            self._tel.count(f"serve/tenant_{tenant}_requests")
            if status >= 500:
                self._tel.count(f"serve/tenant_{tenant}_5xx")
            if status == 429:
                self._tel.count(f"serve/tenant_{tenant}_429")
            self._tel.record(
                f"serve/tenant_{tenant}_request", trace.t_start_ns, total_ns
            )
        if slot == canary_mod.CANARY:
            # the canary SLO engine scores ONLY canary-slot traffic: its
            # own latency span and error-ratio counters, so a bad
            # candidate burns its own objectives while the incumbent's
            # serve-phase SLOs stay clean
            self._tel.count("serve/canary_requests")
            if status >= 500:
                self._tel.count("serve/canary_5xx")
            self._tel.record(
                "serve/canary_request", trace.t_start_ns, total_ns
            )
        meter_tenant = tenant if tenant is not None else "default"
        if self.metering is not None:
            # queue/detok host phases lift straight off the trace — no
            # new timing; device phases arrive attributed on ``cost``
            phases = trace.phases
            self.metering.charge(
                meter_tenant,
                cost=cost,
                queue_ms=phases.get("queue_wait", (0, 0))[1] / 1e6,
                detok_ms=phases.get("detok", (0, 0))[1] / 1e6,
                error=status >= 500,
            )
        if self.capacity is not None:
            self.capacity.maybe_update()  # rate-limited; off-interval = one clock read
        self.tracer.finish(
            trace,
            status,
            total_ns,
            bucket=bucket,
            error=payload.get("error"),
            tenant=tenant,
            cost=cost,
        )
        payload["request_id"] = trace.trace_id
        return status, payload

    def handle_encode(self, body: bytes) -> Tuple[int, bytes, str]:
        """``POST /encode`` (the encode tier's request path): JPEG/PNG
        bytes → a framed context grid (serve/handoff.py) a decode-tier
        replica accepts on /caption.  Stateless per request — no slot,
        no queue — so the encode tier scales on batch-friendly replicas
        with zero decode state."""
        t0 = time.perf_counter_ns()

        def _err(status: int, payload: Dict[str, Any]):
            return status, json.dumps(payload).encode(), "application/json"

        if not self._ready:
            return _err(
                503, {"error": "server is draining; not accepting work"}
            )
        try:
            with self._tel.span("serve/preprocess"):
                image = self.engine.preprocess(body)
        except Exception as e:
            self._tel.count("serve/bad_input")
            return _err(
                400,
                {"error": "bad image",
                 "detail": f"cannot decode image bytes: {e}"},
            )
        try:
            grid = self.engine.encode_one(image)
        except Exception as e:
            self._tel.count("serve/encode_http_errors")
            return _err(500, {"error": f"encode failed: {e}"})
        self._tel.count("serve/encode_http")
        self._tel.record(
            "serve/encode_request", t0, time.perf_counter_ns() - t0
        )
        return (
            200,
            handoff.encode_grid(grid, step=self.engine.step),
            handoff.GRID_CONTENT_TYPE,
        )

    def handle_caption(
        self, body: bytes, deadline_ms=None, request_id=None,
        tenant=None, model=None, content_type=None,
    ) -> Tuple[int, Dict[str, Any]]:
        t_req0 = time.perf_counter_ns()
        trace = self.tracer.begin(request_id)
        trace.t_start_ns = t_req0
        with self._in_flight_lock:
            self._in_flight += 1  # paired decrement in _finish_request
        # tenant resolution: X-Tenant → registry spec (bare and unknown
        # keys map to the default tenant).  tname is None on the
        # degenerate single-tenant registry so no per-tenant counters or
        # payload fields appear — zero behavior change without --tenants
        spec = self.tenants.resolve(tenant)
        tname = spec.name if self.tenants.multi else None
        if tenant and tname is not None and not self.tenants.known(tenant):
            self._tel.count("serve/tenant_unknown")
        if not self._ready:
            return self._finish_request(
                trace,
                503,
                {
                    "error": "server is draining; not accepting work",
                    # same backoff contract as a 429 shed: tell the
                    # client when capacity is expected, never 0 seconds
                    "retry_after_ms": self._retry_hint_ms(),
                    "shed_scope": "global",
                },
                tenant=tname,
            )
        # token-bucket admission quota, enforced BEFORE preprocessing so
        # a flooding tenant is refused at the cost of a dict lookup: a
        # dry bucket is a tenant-scoped 429 whose Retry-After is that
        # bucket's own refill time, not the service p50
        if tname is not None and not self.tenants.try_admit(spec.name):
            self._tel.count("serve/shed")
            self._tel.count(f"serve/tenant_{spec.name}_shed")
            return self._finish_request(
                trace,
                429,
                {
                    "error": (
                        f"tenant {spec.name!r} admission quota exhausted "
                        f"({spec.rps:g} rps); shed"
                    ),
                    "retry_after_ms": self._tenant_retry_ms(spec.name),
                    "shed_scope": "tenant",
                },
                tenant=tname,
            )
        image = None
        context = None
        key = None
        base_ctype = (content_type or "").split(";", 1)[0].strip()
        if base_ctype == handoff.GRID_CONTENT_TYPE:
            # decode-tier ingress: the body is a pre-encoded context grid
            # from an encode-tier replica (serve/handoff.py) — verify the
            # frame (crc32c sidecar) and the aval against OUR warmed
            # executables before any device work
            try:
                grid, header = handoff.decode_grid(body)
                if self.engine.ctx_row_shape is None:
                    raise handoff.HandoffError(
                        "replica has no warmed context aval yet"
                    )
                handoff.check_aval(
                    grid, self.engine.ctx_row_shape,
                    self.engine.ctx_row_dtype,
                )
            except handoff.HandoffError as e:
                self._tel.count("serve/bad_handoff")
                return self._finish_request(
                    trace, 400,
                    {"error": "bad grid", "detail": str(e)},
                    tenant=tname,
                )
            gstep = header.get("step")
            if gstep is not None and int(gstep) != self.engine.step:
                # cross-generation handoff: the encoder ran a different
                # promote generation than this decoder — decoding it
                # would caption with mismatched params
                self._tel.count("serve/stale_handoff")
                return self._finish_request(
                    trace, 409,
                    {
                        "error": (
                            f"grid encoded at model step {gstep}; this "
                            f"replica serves step {self.engine.step}"
                        ),
                    },
                    tenant=tname,
                )
            context = grid
            self._tel.count("serve/grid_requests")
        else:
            try:
                with self._tel.span("serve/preprocess"):
                    image = self.engine.preprocess(body)
            except Exception as e:
                # undecodable POST body: a client problem, not a server
                # crash — counted so a flood of garbage uploads shows in
                # the heartbeat
                self._tel.count("serve/bad_input")
                return self._finish_request(
                    trace,
                    400,
                    {
                        "error": "bad image",
                        "detail": f"cannot decode image bytes: {e}",
                    },
                    tenant=tname,
                )
            if self._cache_sketch is not None:
                # would-be encode-cache probe (telemetry/capacity.py):
                # hash the raw POST bytes (no pixels retained) and ask
                # whether a bounded cache would have hit — the live Zipf
                # evidence the real cache below now reconciles against
                self._cache_sketch.observe(crc32c(body))
            if getattr(self.engine, "encode_cache", None) is not None:
                # content address for the REAL cache: the preprocessed
                # pixels (two byte-identical uploads of one image hash
                # equal here even if their container bytes differ)
                key = crc32c(image.tobytes())
        if deadline_ms is None or deadline_ms == "":
            budget_ms = self.config.serve_deadline_ms
        else:
            try:
                budget_ms = int(deadline_ms)
            except (TypeError, ValueError):
                return self._finish_request(
                    trace,
                    400,
                    {"error": "X-Deadline-Ms must be integer milliseconds"},
                    tenant=tname,
                )
        deadline_unix = (
            time.time() + budget_ms / 1e3 if budget_ms > 0 else None
        )
        # param-slot selection: an explicit X-Model (or the tenant's
        # default model) pins a resident param set; otherwise the
        # lifecycle canary router decides (a deterministic, sticky hash
        # of the request id — outside a canary window every request is
        # incumbent)
        alias = (model or "").strip() or spec.model
        if alias:
            if not self.engine.has_resident(alias):
                return self._finish_request(
                    trace,
                    400,
                    {
                        "error": f"unknown model {alias!r}",
                        "models": list(self.engine.resident_aliases),
                    },
                    tenant=tname,
                )
            slot = alias
        else:
            slot = self.lifecycle.route(trace.trace_id)
        try:
            req = self.batcher.submit(
                image, deadline_unix=deadline_unix, trace=trace, slot=slot,
                tenant=spec.name, raw=body, key=key, context=context,
            )
        except Rejected as e:
            # shed exemplar: a rate-limited sample of refused requests
            # lands in the flight recorder with its image bytes, so a
            # shed storm leaves replayable evidence, not just a counter
            self._record_terminal_exemplar(
                trace, e.status, "shed", tname, body
            )
            payload = {"error": e.reason}
            if e.status in (429, 503):
                # Retry-After computed from the SHEDDING SCOPE: a
                # tenant-lane shed hints the tenant's own bucket refill,
                # a global shed hints the observed service period
                payload["shed_scope"] = e.scope
                payload["retry_after_ms"] = (
                    self._tenant_retry_ms(spec.name)
                    if e.scope == "tenant"
                    else self._retry_hint_ms()
                )
            return self._finish_request(
                trace, e.status, payload, slot=slot, tenant=tname
            )
        wait_s = (
            budget_ms / 1e3 + 5.0 if deadline_unix else self.DEFAULT_WAIT_S
        )
        if not req.done.wait(timeout=wait_s):
            self._tel.count("serve/timeouts")
            self._record_terminal_exemplar(trace, 504, "timeout", tname, body)
            # the request may still be riding decode windows; charge
            # whatever device time it accrued so far — abandoned work is
            # still the tenant's cost
            return self._finish_request(
                trace, 504, {"error": "request timed out in service"},
                slot=slot, tenant=tname, cost=req.cost,
            )
        if req.error is not None:
            payload = {"error": req.error[1]}
            if req.error[0] in (429, 503):
                payload["retry_after_ms"] = self._retry_hint_ms()
                payload["shed_scope"] = "global"
            return self._finish_request(
                trace, req.error[0], payload, bucket=req.bucket, slot=slot,
                tenant=tname, cost=req.cost,
            )
        self._tel.record(
            "serve/request", t_req0, time.perf_counter_ns() - t_req0
        )
        payload = dict(req.result)
        payload["bucket"] = req.bucket
        payload["slot"] = slot
        if tname is not None:
            payload["tenant"] = tname
        if slot == canary_mod.CANARY:
            step = self.engine.candidate_step
            payload["model_step"] = (
                step if step is not None else self.engine.step
            )
        elif alias:
            payload["model"] = alias
            step = self.engine.resident_step(alias)
            payload["model_step"] = (
                step if step is not None else self.engine.step
            )
        else:
            payload["model_step"] = self.engine.step
            # shadow sampling: during a canary window, a sample of
            # incumbent answers is replayed against the candidate to
            # feed the caption-divergence gauge (bounded queue, never
            # blocks this handler thread).  Grid-ingress requests carry
            # no image to replay, so they never shadow.
            if image is not None:
                try:
                    self.lifecycle.maybe_shadow(
                        image, payload["captions"][0]["caption"]
                    )
                except (KeyError, IndexError, TypeError):
                    pass
        return self._finish_request(
            trace, 200, payload, bucket=req.bucket, slot=slot, tenant=tname,
            cost=req.cost,
        )

    def _record_terminal_exemplar(
        self,
        trace: "tracectx.RequestTrace",
        status: int,
        reason: str,
        tenant: Optional[str],
        body: bytes,
    ) -> None:
        """Shed/timeout outliers never reach the detok boundary, so the
        HTTP path records them directly (rate-limited by the recorder;
        failures swallowed — observability never fails a request)."""
        if self.exemplars is None:
            return
        try:
            self.exemplars.record(
                reasons=[reason],
                request_id=trace.trace_id,
                tenant=tenant or "default",
                status=status,
                image_bytes=body,
            )
        except Exception:
            self._tel.count("serve/quality_errors")

    def _retry_hint_ms(self) -> int:
        """Retry-After hint for 429 sheds: about one service period — the
        observed p50 end-to-end latency when we have one, else twice the
        batching window — clamped to a sane band so a cold server never
        tells clients to hammer it or to go away for minutes."""
        p = _percentiles_ms(self._tel, "serve/request")
        hint = (
            p["p50"] if p else 2.0 * max(1.0, self.config.serve_max_wait_ms)
        )
        return int(min(10_000.0, max(50.0, hint)))

    def _tenant_retry_ms(self, name: str) -> int:
        """Retry-After hint for a *tenant-scoped* shed: that tenant's
        own bucket refill time — when its next token exists — not the
        global service period.  Never 0 (the frontend's whole-second
        clamp rounds it up to >= 1 s on the header)."""
        return max(1, int(self.tenants.retry_after_s(name) * 1000.0) + 1)

    def healthz(self) -> Tuple[Dict[str, Any], int]:
        payload = self.heartbeat.payload() if self.heartbeat else {}
        # two degrade causes (docs/RESILIENCE.md): a wedged batch being
        # re-warmed, and a burning SLO — both flip the balancer-facing
        # health while requests are still admitted
        burning = self.slo.burning()
        # tenant-scoped lanes never degrade the replica's fleet-facing
        # health: one tenant burning ITS objective (a flood eating its
        # own quota) must not get the whole replica down-weighted — that
        # would spread tenant A's overload onto tenant B, the exact
        # failure the isolation plane exists to prevent.  The lanes stay
        # visible in slo_burning / /metrics for per-tenant alerting.
        # quality_* lanes are diagnostic the same way: caption drift is
        # a MODEL problem — rolling traffic to a replica serving the
        # same checkpoint fixes nothing, so /healthz stays ok while the
        # drift lanes burn (pinned by the quality_drift chaos scenario).
        service_burning = [
            n for n in burning if not n.startswith(("tenant_", "quality_"))
        ]
        degraded = self._degraded or bool(service_burning)
        payload.update(
            {
                "ready": self._ready,
                "status": (
                    "degraded"
                    if degraded
                    else ("ok" if self._ready else "draining")
                ),
                "uptime_s": round(time.time() - self._t_start, 1),
                # top-level load signals (queue + resident requests +
                # dispatch mode): the fleet router's poller reads these
                # from ONE cheap /healthz fetch per tick instead of the
                # heavier /stats document
                "queue_depth": self.batcher.queue_depth(),
                "in_flight": self.in_flight,
                "serve_mode": self.config.serve_mode,
                # fleet tier (encode/decode/both): the router's poller
                # routes image traffic to encode-capable replicas and
                # grid handoffs to decode-capable ones off this field
                "tier": self.config.serve_tier,
                "buckets": list(self.engine.buckets),
                "model_step": self.engine.step,
                # lifecycle plane: balancers and the fleet router see a
                # canary in flight from the same cheap poll
                "lifecycle_state": self.lifecycle.state,
            }
        )
        candidate = self.engine.candidate_step
        if candidate is not None:
            payload["candidate_step"] = candidate
        if self.tenants.multi:
            payload["tenants"] = sorted(self.tenants.names())
        if burning:
            payload["slo_burning"] = burning
        return payload, (200 if self._ready and not degraded else 503)

    def admin_lifecycle(self, action: str) -> Tuple[int, Dict[str, Any]]:
        """POST /reload | /promote | /rollback.  200 on success, 409 when
        the machine is in the wrong state for the verb (no candidate to
        promote, a cycle already in flight, a rejected/current step)."""
        lc = self.lifecycle
        if action == "reload":
            ok, detail = lc.request_reload()
        elif action == "promote":
            ok, detail = lc.promote()
        elif action == "rollback":
            ok, detail = lc.rollback()
        else:
            return 404, {"error": f"no lifecycle action {action!r}"}
        return (200 if ok else 409), {
            "ok": ok,
            "detail": detail,
            "state": lc.state,
            "model_step": self.engine.step,
        }

    # -- wedge containment (called from the batcher thread) ----------------

    def _on_wedge(self) -> None:
        """A stuck in-flight batch was just failed with 500s: flip health
        to 503 "degraded" so the balancer routes away, and re-warm the
        engine in the background — the AOT warmup rebuilds the compiled
        ladder (cheap under the persistent compile cache) and proves the
        device answers again before health recovers."""
        self._degraded = True
        self._tel.gauge("serve/degraded", 1)
        threading.Thread(
            target=self._rewarm, name="sat-serve-rewarm", daemon=True
        ).start()

    def _rewarm(self) -> None:
        try:
            if self.config.serve_mode == "continuous":
                # re-warm the slot pool (cached compiles) and rebuild the
                # empty carry; in-flight slots were already failed
                self.batcher.rewarm()
            else:
                self.engine.warmup()
        except Exception as e:
            # still wedged — stay degraded; the next wedge timeout (or an
            # operator) escalates
            print(
                f"sat_tpu: serve re-warm failed ({e!r}); staying degraded",
                file=sys.stderr,
                flush=True,
            )
            return
        self._tel.count("serve/rewarms")
        self._degraded = False
        self._tel.gauge("serve/degraded", 0)
        print(
            "sat_tpu: serve engine re-warmed after wedged batch; health "
            "restored",
            file=sys.stderr,
            flush=True,
        )

    def stats(self) -> Dict[str, Any]:
        counters = self._tel.counters()
        prefix = "serve/bucket_"
        histogram = {
            k[len(prefix):]: v
            for k, v in counters.items()
            if k.startswith(prefix)
        }
        latency = {}
        for name in _LATENCY_SPANS:
            p = _percentiles_ms(self._tel, name)
            if p:
                latency[name] = p
        out = {
            "ready": self._ready,
            "serve_mode": self.config.serve_mode,
            "tier": self.config.serve_tier,
            "queue_depth": self.batcher.queue_depth(),
            "in_flight": self.in_flight,
            "buckets": list(self.engine.buckets),
            "bucket_histogram": histogram,
            "warm_compiles": self.engine.warm_compiles,
            "compiles_since_ready": counters.get("jax/compiles", 0)
            - self.engine.compiles_at_ready,
            "counters": {
                k: v
                for k, v in counters.items()
                if k.startswith(("serve/", "jax/"))
            },
            "latency_ms": latency,
            "slo": self.slo.snapshot(),
            "profile_captures": self.profiles.captures,
            "lifecycle": self.lifecycle.snapshot(),
        }
        # raw loop-iteration counts, not ms — how many decode steps each
        # request actually ran (continuous mode retires early; batch mode
        # reports the per-batch monolithic step count)
        steps = _percentiles_raw(self._tel, "serve/decode_steps")
        if steps:
            out["decode_steps"] = steps
        # encoder introspection: the active quant mode plus per-lane
        # encode timing (batch mode records per-bucket lanes, continuous
        # mode per admission-lane width; both feed the aggregate span)
        engine_block: Dict[str, Any] = {
            "encoder_quant": self.engine.encoder_quant,
            "quantize_seconds": round(self.engine.quantize_seconds, 3),
        }
        # fused decode window: how many device steps each dispatch
        # actually ran (the K ladder + on-device early exit live;
        # docs/SERVING.md "Fused decode window")
        spd = _percentiles_raw(self._tel, "serve/steps_per_dispatch")
        if spd:
            engine_block["steps_per_dispatch"] = spd
        enc = _percentiles_ms(self._tel, "serve/encode")
        if enc:
            engine_block["encode_ms"] = enc
        lanes = {}
        for lane in self._encode_lanes():
            p = _percentiles_ms(self._tel, f"serve/encode_lane{lane}")
            if p:
                lanes[str(lane)] = p
        if lanes:
            engine_block["encode_lanes_ms"] = lanes
        out["engine"] = engine_block
        if self.pool is not None:
            out["slot_pool"] = {
                "slots": self.pool.slots,
                "pages": self.pool.pages,
                "page_width": self.pool.width,
                "busy": self.pool.occupancy(),
            }
        if getattr(self.engine, "encode_cache", None) is not None:
            # the cache block: host LRU state + lifetime counters, plus
            # the hit path's own device latency (gather) so operators see
            # what a hit actually costs vs the encode it skipped
            cache_block = self.engine.encode_cache.stats()
            gp = _percentiles_ms(self._tel, "serve/cache_gather")
            if gp:
                cache_block["gather_ms"] = gp
            out["encode_cache"] = cache_block
        if self.tenants.multi:
            out["tenants"] = self._tenant_block(counters)
        if self.metering is not None:
            # per-tenant attributed cost (telemetry/metering.py) — the
            # router fans this block in for the fleet-wide view; present
            # with one "default" row on single-tenant servers too
            out["tenants_cost"] = self.metering.snapshot()
        if self.capacity is not None:
            self.capacity.maybe_update()
            out["capacity"] = {
                name.split("/", 1)[1]: value
                for name, value in self._tel.gauges().items()
                if name.startswith("capacity/")
            }
        if self.quality is not None:
            # per-request quality signals + drift vs the frozen
            # reference (telemetry/quality.py); the router fans this
            # block into the fleet view like tenants_cost
            self.quality.maybe_publish(force=True)
            qblock = self.quality.snapshot()
            if self.exemplars is not None:
                qblock["exemplars"] = self.exemplars.stats()
            out["quality"] = qblock
        return out

    def _tenant_block(self, counters: Dict[str, int]) -> Dict[str, Any]:
        """Per-tenant /stats block: static shape (weight/quota/model)
        plus live queue depth, token balance, request/shed/5xx counters
        and latency percentiles.  Refreshes the serve/tenant_* gauges so
        the heartbeat serve block and /metrics carry the same numbers."""
        depths = self.batcher.tenant_depths()
        admitted = self.batcher.tenant_admitted()
        block: Dict[str, Any] = {}
        for name, shape in self.tenants.describe().items():
            entry = dict(shape)
            entry["queue_depth"] = depths.get(name, 0)
            entry["admitted"] = admitted.get(name, 0)
            tokens = self.tenants.tokens(name)
            if tokens is not None and tokens != float("inf"):  # sync-ok: host sentinel
                entry["tokens"] = round(tokens, 2)
                self._tel.gauge(
                    f"serve/tenant_{name}_tokens", round(tokens, 2)
                )
            self._tel.gauge(
                f"serve/tenant_{name}_queue_depth", depths.get(name, 0)
            )
            for short, counter in (
                ("requests", f"serve/tenant_{name}_requests"),
                ("shed", f"serve/tenant_{name}_shed"),
                ("429", f"serve/tenant_{name}_429"),
                ("5xx", f"serve/tenant_{name}_5xx"),
            ):
                entry[short] = counters.get(counter, 0)
            step = (
                self.engine.resident_step(shape["model"])
                if shape["model"]
                else None
            )
            if step is not None:
                entry["model_step"] = step
            p = _percentiles_ms(self._tel, f"serve/tenant_{name}_request")
            if p:
                entry["latency_ms"] = p
            block[name] = entry
        return block

    def _encode_lanes(self):
        """Every encode-lane width this server can have timed: the bucket
        ladder (batch mode) plus the pool's admission lanes (continuous)."""
        lanes = set(self.engine.buckets)
        if self.pool is not None:
            lanes.update(self.pool.lane_widths)
        return sorted(lanes)

    # -- observability endpoints -------------------------------------------

    def metrics_text(self) -> str:
        """The Prometheus exposition body for ``GET /metrics``."""
        # refresh the decode-step distribution gauges at scrape time so
        # both serve modes export them without a per-request hot-path cost
        steps = _percentiles_raw(self._tel, "serve/decode_steps")
        if steps:
            self._tel.gauge("serve/decode_steps_p50", steps["p50"])
            self._tel.gauge("serve/decode_steps_p95", steps["p95"])
        spd = _percentiles_raw(self._tel, "serve/steps_per_dispatch")
        if spd:
            # fused-window amortization: device steps per host dispatch
            # (p50 tracks the chosen K ladder lane, p95 the deep lane)
            self._tel.gauge("serve/steps_per_dispatch", spd["p50"])
            self._tel.gauge("serve/steps_per_dispatch_p95", spd["p95"])
        enc = _percentiles_ms(self._tel, "serve/encode")
        if enc:
            # scrape-time refresh, same discipline as decode_steps: the
            # serve/encode_ms gauge is the p50 device-encode time (p95
            # rides alongside for burn-rate style alerting)
            self._tel.gauge("serve/encode_ms", enc["p50"])
            self._tel.gauge("serve/encode_ms_p95", enc["p95"])
        if self.tenants.multi:
            # refresh the serve/tenant_* queue/token gauges at scrape
            # time (the tenant dimension rides the metric name, so
            # promtext exports them with no label machinery)
            self._tenant_block(self._tel.counters())
        if getattr(self.engine, "encode_cache", None) is not None:
            # scrape-time refresh of the cache residency gauges (the
            # counters tick live; entries/bytes are host-map reads)
            cstats = self.engine.encode_cache.stats()
            self._tel.gauge("serve/cache_entries", cstats["entries"])
            self._tel.gauge("serve/cache_bytes", cstats["bytes"])
            self._tel.gauge("serve/cache_hit_ratio", cstats["hit_ratio"])
            gp = _percentiles_ms(self._tel, "serve/cache_gather")
            if gp:
                self._tel.gauge("serve/cache_gather_ms_p95", gp["p95"])
        if self.capacity is not None:
            # scrape-time refresh of the capacity/* gauges (headroom,
            # ceiling, lane fill, would-hit + actual hit ratios) —
            # rate-limited, so an aggressive scraper costs one clock read
            self.capacity.maybe_update()
        if self.quality is not None:
            # scrape-time refresh of the quality/* gauges (per-signal
            # PSI, psi_max, unk rate) so the drift SLO lanes and the
            # Prometheus series never lag the rate limiter
            self.quality.maybe_publish(force=True)
        extra = self.heartbeat.payload() if self.heartbeat else None
        return promtext.render(self._tel, extra=extra, histograms=_HISTOGRAMS)

    def quality_reference(self) -> Tuple[Dict[str, Any], int]:
        """GET /quality_reference: export the frozen reference so another
        replica (or the next deploy) can pin drift scoring to THIS
        steady state via ``--quality_reference``.  404 with the quality
        plane off, 409 before warmup traffic froze a reference."""
        if self.quality is None:
            return {"error": "quality plane off; boot with --serve_quality on"}, 404
        payload = self.quality.reference_payload()
        if payload is None:
            return {
                "error": (
                    "no reference frozen yet; serve at least "
                    f"{self.quality.window} requests or load one with "
                    "--quality_reference"
                )
            }, 409
        return payload, 200

    def start_profile(self, duration_ms=None) -> Tuple[bool, str]:
        """Begin a bounded live profiler capture (``POST /profile``);
        409-maps when one is already running."""
        ok, info = self.profiles.start(duration_ms)
        if ok:
            self._tel.count("serve/profile_windows")
        return ok, info

    def export_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace including one lane per retained request
        (tests call this directly; shutdown calls it when
        ``--trace_export`` is set)."""
        from ..telemetry import exporters

        if path is None:
            path = self.config.trace_export
        if not path:
            return None
        return exporters.export_chrome_trace(
            self._tel,
            path,
            extra_events=self.tracer.trace_events(
                getattr(self._tel, "anchor_ns", 0),
                # same lane convention as the host spans (exporters
                # .chrome_trace): pid = process_index, so request lanes
                # land in this host's process group after a fleet merge
                pid=telemetry.process_identity()[0],
            ),
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "CaptionServer":
        self.batcher.start()
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler
        )
        self._httpd.app = self
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="sat-serve-http",
            daemon=True,
        )
        self._http_thread.start()
        if self.config.heartbeat_interval > 0:
            hb_dir = self.config.telemetry_dir or os.path.join(
                self.config.summary_dir, "telemetry"
            )
            try:
                os.makedirs(hb_dir, exist_ok=True)
                self.heartbeat = Heartbeat(
                    os.path.join(hb_dir, "heartbeat.json"),
                    self.config.heartbeat_interval,
                    self._tel,
                    static={
                        "phase": "serve",
                        "port": self.port,
                        "buckets": list(self.engine.buckets),
                        "model_step": self.engine.step,
                    },
                ).start()
            except OSError:
                self.heartbeat = None  # health still served from /healthz
        if self.slo.objectives:
            # tick a few times per fast window so a burn is seen promptly
            self.slo.start(
                interval_s=max(0.1, min(5.0, self.config.slo_window_fast_s / 4))
            )
        self.lifecycle.start()
        if self.config.serve_tier == "encode":
            # an encode-tier replica's whole request path is POST /encode:
            # warm its width-1 executable before ready so the first
            # request never compiles, and extend the zero-recompile
            # ledger past it (same bookkeeping as the pool warmup)
            self.engine.warm_encode_one()
            self.engine.compiles_at_ready = max(
                self.engine.compiles_at_ready,
                self._tel.counters().get("jax/compiles", 0),
            )
        self._ready = True
        self._tel.gauge("serve/ready", 1)
        return self

    def request_shutdown(self) -> None:
        """Programmatic twin of SIGTERM (tests, embedding)."""
        self._stop.set()

    def shutdown(self) -> None:
        """Drain sequence: readiness flips first (the balancer stops
        routing), the batcher rejects new work and completes everything
        admitted, then the listener and heartbeat close."""
        if self._httpd is None:
            return
        self._ready = False
        self._tel.gauge("serve/ready", 0)
        # stop the lifecycle plane before draining the batcher: an
        # in-flight canary aborts (candidate cleared, ledger untouched —
        # shutdown is not a verdict) so the drain sees only real work
        self.lifecycle.stop()
        self.batcher.drain()
        self._httpd.shutdown()
        if self._http_thread is not None:
            self._http_thread.join(timeout=10.0)
            self._http_thread = None
        self._httpd.server_close()
        self._httpd = None
        self.slo.stop()
        self.profiles.stop_now()
        if self.metering is not None:
            # final cumulative ledger rows — the shutdown snapshot a
            # billing job replays (torn tails before this lose only
            # recency, never correctness)
            self.metering.maybe_flush(force=True)
        self.export_trace()  # no-op unless --trace_export is set
        if self.heartbeat is not None:
            self.heartbeat.stop()

    def serve_until_shutdown(self, shutdown=None, poll_s: float = 0.1) -> None:
        """Block until SIGTERM/SIGINT or request_shutdown(), then drain.
        ``shutdown`` accepts an externally managed GracefulShutdown (tests
        install one on the main thread); by default one is installed
        here."""
        own = shutdown is None
        sd = GracefulShutdown() if own else shutdown
        try:
            if own:
                sd.__enter__()
            while not sd.stop_requested and not self._stop.is_set():
                time.sleep(poll_s)
        finally:
            if own:
                sd.__exit__(None, None, None)
            self.shutdown()


def serve(config: Config, model_file: Optional[str] = None) -> int:
    """CLI entry point: ``python -m sat_tpu.cli --phase serve``.

    Lineage load → AOT bucket warmup → listen → drain on SIGTERM."""
    import jax

    tel = telemetry.get()
    if not tel.enabled:
        # /stats and /healthz are part of the serving contract: spans and
        # counters always record in this phase (host-side work only — the
        # tracing layer's measured overhead bar applies, no device syncs)
        tel = telemetry.enable(capacity=config.telemetry_buffer)
    from ..runtime import _install_compile_listener

    _install_compile_listener()
    from ..utils.compile_cache import enable as _enable_compile_cache

    _enable_compile_cache(jax, name=".jax_cache", min_compile_time_secs=0.5)

    vocabulary = Vocabulary(config.vocabulary_size, config.vocabulary_file)
    state, source = load_serving_state(config, model_file=model_file)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    print(
        f"sat_tpu: serving params from {source} (step {engine.step})",
        file=sys.stderr,
        flush=True,
    )
    if config.serve_mode == "batch":
        # continuous mode warms the slot-pool programs instead (in
        # ContinuousBatcher.start, via the server below) — the bucket
        # ladder would be dead weight there
        engine.warmup()
    server = CaptionServer(config, engine)
    # flight recorder (telemetry/blackbox.py): journal serve state so an
    # abnormal exit leaves a postmortem bundle like a training run's
    bb = None
    if config.blackbox:
        from ..telemetry import blackbox as _blackbox

        tdir = config.telemetry_dir or os.path.join(
            config.summary_dir, "telemetry"
        )
        bb = _blackbox.BlackBox(os.path.join(tdir, "blackbox"), tel)
        _blackbox.install(
            bb, telemetry_dir=tdir, config_snapshot=config.to_dict()
        )
        bb.event("serve_start", port=server.port, model_step=engine.step)
    server.start()
    if config.serve_mode == "continuous":
        geometry = (
            f"slot pool {config.serve_slot_pages}x{config.serve_page_width}"
        )
    else:
        geometry = (
            f"buckets {engine.buckets}, max_batch {config.serve_max_batch}, "
            f"max_wait {config.serve_max_wait_ms}ms"
        )
    print(
        f"sat_tpu: captioning server listening on "
        f"http://{config.serve_host}:{server.port}  "
        f"(mode {config.serve_mode}, {geometry})",
        file=sys.stderr,
        flush=True,
    )
    if server.tenants.multi:
        shapes = ", ".join(
            f"{s.name}(w={s.weight:g}"
            + (f", {s.rps:g}rps" if s.limited else "")
            + (f", model={s.model}" if s.model else "")
            + ")"
            for s in server.tenants.specs()
        )
        print(
            f"sat_tpu: multi-tenant plane active — {shapes}; "
            f"default tenant {server.tenants.default!r}",
            file=sys.stderr,
            flush=True,
        )
    try:
        server.serve_until_shutdown()
    except Exception as e:
        if bb is not None:
            from ..telemetry import blackbox as _blackbox

            bb.event("uncaught_exception", error=repr(e))
            _blackbox.dump("uncaught_exception", error=repr(e))
        raise
    if bb is not None:
        bb.event("serve_drained")
    print("sat_tpu: serve drained cleanly", file=sys.stderr, flush=True)
    return 0
