"""End-to-end runtime tests at fixture scale (SURVEY.md §2.10-2.11, §4)."""

import json
import os
import struct

import numpy as np
import pytest

from sat_tpu.cli import build_config
from sat_tpu import runtime
from sat_tpu.train.checkpoint import latest_checkpoint
from sat_tpu.utils.summary import SummaryWriter, _masked_crc


SMALL_MODEL = dict(
    image_size=32,
    dim_embedding=16,
    num_lstm_units=16,
    dim_initialize_layer=16,
    dim_attend_layer=16,
    dim_decode_layer=32,
    compute_dtype="float32",
    save_period=3,
    log_every=1,
    num_epochs=1,
    num_data_workers=2,
)


@pytest.fixture(scope="module")
def trained(coco_fixture):
    """Train one epoch on the fixture; shared by eval/test phases below."""
    config = coco_fixture["config"].replace(**SMALL_MODEL)
    state = runtime.train(config)
    return config, state


def test_train_loop_end_to_end(trained):
    config, state = trained
    # 24 anns / batch 4 = 6 steps
    assert int(state.step) == 6
    ckpt = latest_checkpoint(config.save_dir)
    assert ckpt is not None and ckpt.endswith("6.npz")
    # summaries: jsonl rows with finite losses at every step
    rows = [
        json.loads(line)
        for line in open(os.path.join(config.summary_dir, "metrics.jsonl"))
    ]
    assert [r["step"] for r in rows] == list(range(1, 7))
    for r in rows:
        assert np.isfinite(r["total_loss"])
        assert np.isfinite(r["cross_entropy_loss"])
    # tfevents file exists and is non-trivial
    events = [
        f for f in os.listdir(config.summary_dir) if f.startswith("events.out")
    ]
    assert events


def test_eval_end_to_end(trained):
    config, state = trained
    scores = runtime.evaluate(config, state=state)
    for key in ("Bleu_1", "Bleu_4", "METEOR", "ROUGE_L", "CIDEr"):
        assert key in scores
        assert 0.0 <= scores[key] <= 1.0 or key == "CIDEr" and scores[key] >= 0
    # results.json written, one entry per unique eval image, valid captions
    results = json.load(open(config.eval_result_file))
    ids = [r["image_id"] for r in results]
    assert len(ids) == len(set(ids)) > 0
    for r in results:
        # a barely-trained model may produce an eos-first beam, which
        # detokenizes to "" (never pad-token noise or a bare ".")
        assert r["caption"] == "" or r["caption"].endswith(".")


def test_test_end_to_end(trained):
    config, state = trained
    results = runtime.test(config, state=state)
    assert len(results) == 12                      # all fixture images
    import pandas as pd

    # keep_default_na: an eos-first beam's empty caption must read back
    # as "" not NaN (vocabulary.load's rule)
    df = pd.read_csv(config.test_result_file, keep_default_na=False)
    assert list(df["caption"]) == [r["caption"] for r in results]
    # a captioned JPG per input image
    rendered = [f for f in os.listdir(config.test_result_dir) if f.endswith(".jpg")]
    assert len(rendered) == 12


def test_restore_0_tensors_is_an_error(coco_fixture, tmp_path):
    config = coco_fixture["config"].replace(
        **SMALL_MODEL, save_dir=str(tmp_path / "empty")
    )
    np.savez(
        tmp_path / "empty_ckpt.npz", global_step=np.asarray(3, np.int32)
    )
    with pytest.raises(ValueError, match="0 tensors"):
        runtime.setup_state(
            config, load=True, model_file=str(tmp_path / "empty_ckpt.npz")
        )


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_flag_parity():
    config, cli = build_config(
        ["--phase=eval", "--beam_size=5", "--train_cnn", "--load",
         "--model_file=/x/y.npz", "--set", "batch_size=7",
         "--set", "max_train_ann_num=none", "--set", "compute_dtype=float32"]
    )
    assert config.phase == "eval"
    assert config.beam_size == 5
    assert config.train_cnn is True
    assert config.batch_size == 7
    assert config.max_train_ann_num is None
    assert config.compute_dtype == "float32"
    assert cli["load"] is True and cli["model_file"] == "/x/y.npz"


def test_env_path_rerooting(monkeypatch):
    """SAT_DATA_ROOT / SAT_LOG_ROOT re-root default paths (the reference's
    clusterone get_data_path/get_logs_path capability); explicit --set
    overrides are left alone."""
    monkeypatch.setenv("SAT_DATA_ROOT", "/mnt/datasets")
    monkeypatch.setenv("SAT_LOG_ROOT", "/mnt/experiments")
    config, _ = build_config(
        ["--phase=train", "--set", "train_image_dir=/my/custom/images"]
    )
    assert config.train_image_dir == "/my/custom/images"      # --set wins
    assert config.train_caption_file == "/mnt/datasets/data/train/captions_train2014.json"
    assert config.vocabulary_file == "/mnt/datasets/data/vocabulary.csv"
    assert config.save_dir == "/mnt/experiments/data/models/"
    assert config.summary_dir == "/mnt/experiments/summary/"

    monkeypatch.delenv("SAT_DATA_ROOT")
    monkeypatch.delenv("SAT_LOG_ROOT")
    config, _ = build_config(["--phase=train"])
    assert config.train_image_dir == "./data/train/images/"   # untouched


def test_config_rejects_knob_typos():
    from sat_tpu.config import Config

    with pytest.raises(ValueError, match="cnn"):
        Config(cnn="alexnet")
    with pytest.raises(ValueError, match="optimizer"):
        Config(optimizer="adam")  # case-sensitive, like the reference
    with pytest.raises(ValueError, match="num_attend_layers"):
        Config(num_attend_layers=3)
    with pytest.raises(ValueError, match="phase"):
        build_config(["--set", "phase=evaluate"])


def test_cli_eval_sweep(trained, capsys):
    config, _ = trained
    from sat_tpu.cli import main

    args = ["--phase=eval", "--sweep", "--beam_size=2"] + [
        x
        for k, v in config.to_dict().items()
        if isinstance(v, (str, int, float, bool)) and v != ""
        and k in ("save_dir", "summary_dir", "eval_image_dir",
                  "eval_caption_file", "vocabulary_file", "eval_result_dir",
                  "eval_result_file", "batch_size", "vocabulary_size",
                  "image_size", "dim_embedding", "num_lstm_units",
                  "dim_initialize_layer", "dim_attend_layer",
                  "dim_decode_layer", "compute_dtype", "max_eval_ann_num")
        for x in ("--set", f"{k}={v}")
    ]
    assert main(args) == 0
    out = capsys.readouterr().out
    assert "step 3:" in out and "step 6:" in out and "Bleu_4=" in out


def test_cli_rejects_unknown_field():
    with pytest.raises(SystemExit):
        build_config(["--set", "definitely_not_a_field=1"])


# ---------------------------------------------------------------------------
# summary writer wire format
# ---------------------------------------------------------------------------


def _read_records(path):
    """Decode TFRecord framing, verifying both masked CRCs."""
    records = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if not header:
                return records
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            assert hcrc == _masked_crc(header)
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            assert pcrc == _masked_crc(payload)
            records.append(payload)


def test_summary_writer_tfevents_roundtrip(tmp_path):
    with SummaryWriter(str(tmp_path)) as w:
        w.scalars(1, {"loss": 2.5, "acc": 0.5})
        w.scalars(2, {"loss": float("nan"), "acc": 1.0})  # nan: jsonl only

    event_file = [f for f in os.listdir(tmp_path) if f.startswith("events.out")][0]
    records = _read_records(os.path.join(tmp_path, event_file))
    # file_version event + 2 scalar events
    assert len(records) == 3
    assert b"brain.Event:2" in records[0]
    assert b"loss" in records[1] and b"acc" in records[1]
    # step-2 record must only contain the finite scalar
    assert b"acc" in records[2] and b"loss" not in records[2]
    # float payload of loss=2.5 present in record 1
    assert struct.pack("<f", 2.5) in records[1]

    rows = [json.loads(x) for x in open(tmp_path / "metrics.jsonl")]
    assert rows[0]["step"] == 1 and rows[0]["loss"] == 2.5 and rows[0]["acc"] == 0.5
    # non-finite values can't enter the tfevents wire format but must
    # still leave a trace of the divergence in metrics.jsonl (ADVICE r1)
    assert rows[1]["step"] == 2 and rows[1]["acc"] == 1.0 and rows[1]["loss"] == "nan"
    # every row is stamped for post-hoc joins (docs/OBSERVABILITY.md)
    for row in rows:
        assert isinstance(row["wall_time"], float)
        assert isinstance(row["mono_ns"], int)
        assert isinstance(row["run_id"], str) and row["run_id"]
    assert rows[0]["run_id"] == rows[1]["run_id"]
    assert rows[1]["mono_ns"] >= rows[0]["mono_ns"]


def _decode_histo(histo: bytes):
    """Minimal HistogramProto reader: returns dict of scalar fields plus
    bucket_limit/bucket arrays."""
    out = {"bucket_limit": [], "bucket": []}
    names = {1: "min", 2: "max", 3: "num", 4: "sum", 5: "sum_squares"}
    i = 0
    while i < len(histo):
        key = histo[i]
        field, wire = key >> 3, key & 7
        i += 1
        if wire == 1:
            (v,) = struct.unpack("<d", histo[i : i + 8])
            out[names[field]] = v
            i += 8
        elif wire == 2:
            ln = 0
            shift = 0
            while True:
                b = histo[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            vals = struct.unpack(f"<{ln // 8}d", histo[i : i + ln])
            out["bucket_limit" if field == 6 else "bucket"] = list(vals)
            i += ln
        else:
            raise AssertionError(f"unexpected wire type {wire}")
    return out


def test_summary_writer_histograms(tmp_path):
    values = np.asarray([1.0, -1.0, 0.5, 0.5, 1e6])
    with SummaryWriter(str(tmp_path)) as w:
        w.histograms(7, {"weights": values})

    event_file = [f for f in os.listdir(tmp_path) if f.startswith("events.out")][0]
    records = _read_records(os.path.join(tmp_path, event_file))
    rec = records[1]
    assert b"weights" in rec
    # walk to the histo submessage: Event.summary(5) > Value(1) > histo(5),
    # each preceded by the tag(1) string "weights"
    idx = rec.index(b"weights") + len(b"weights")
    assert rec[idx] == 0x2A  # field 5 (histo), wire type 2
    i = idx + 1
    ln = shift = 0
    while True:  # varint length (histos exceed 127 bytes)
        b = rec[i]
        i += 1
        ln |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    histo = _decode_histo(rec[i : i + ln])
    assert histo["num"] == 5
    assert histo["min"] == -1.0 and histo["max"] == 1e6
    assert histo["sum"] == pytest.approx(1e6 + 1.0)
    assert histo["sum_squares"] == pytest.approx(1e12 + 2.5)
    assert sum(histo["bucket"]) == 5
    assert len(histo["bucket"]) == len(histo["bucket_limit"])
    # limits are bucket *upper* edges: the first retained limit is the
    # upper edge of the bucket holding the min (just above it, within one
    # 1.1× growth step), and the last covers the max
    lims = histo["bucket_limit"]
    assert -1.0 <= lims[0] <= -1.0 / 1.1
    assert lims[-1] >= 1e6


def test_histograms_stay_consistent_under_nonfinite(tmp_path):
    """A diverged run (NaN/inf values) must still produce a well-formed
    proto: NaNs dropped everywhere, infs clamped into the edge buckets."""
    values = np.asarray([np.nan, np.inf, -np.inf, 1.0])
    with SummaryWriter(str(tmp_path)) as w:
        w.histograms(1, {"diverged": values})
    event_file = [f for f in os.listdir(tmp_path) if f.startswith("events.out")][0]
    rec = _read_records(os.path.join(tmp_path, event_file))[1]
    idx = rec.index(b"diverged") + len(b"diverged")
    assert rec[idx] == 0x2A
    i = idx + 1
    ln = shift = 0
    while True:
        b = rec[i]
        i += 1
        ln |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            break
    histo = _decode_histo(rec[i : i + ln])
    assert histo["num"] == 3                      # NaN dropped
    assert sum(histo["bucket"]) == 3              # counts match num
    assert np.isfinite([histo["min"], histo["max"], histo["sum"]]).all()
    assert len(histo["bucket"]) == len(histo["bucket_limit"])


def test_variable_stats_include_histograms(tmp_path):
    tree = {"w": np.linspace(-1, 1, 101, dtype=np.float32),
            "b": np.zeros((4,), dtype=np.float32)}
    with SummaryWriter(str(tmp_path)) as w:
        w.variable_stats(3, tree, prefix="params")
    event_file = [f for f in os.listdir(tmp_path) if f.startswith("events.out")][0]
    records = _read_records(os.path.join(tmp_path, event_file))
    # record 1 = scalar stats, record 2 = histograms
    assert b"params/w/mean" in records[1]
    histo_rec = records[2]
    for tag in (b"params/w", b"params/b"):
        assert tag in histo_rec
    # num encoded as double 101 for w somewhere in the histo record
    assert struct.pack("<d", 101.0) in histo_rec


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 1)])
def test_eval_renders_attention_panels(trained, tmp_path, mesh_shape):
    """save_attention_maps: per-word attention figures land next to the
    eval results and each result row carries normalized [len, N] maps —
    on the plain path and through single-host mesh decoding."""
    config, state = trained
    config = config.replace(
        save_attention_maps=True,
        mesh_shape=mesh_shape,
        eval_result_dir=str(tmp_path / "attn"),
        eval_result_file=str(tmp_path / "attn.json"),
    )
    from sat_tpu.runtime import decode_dataset
    from sat_tpu.data.dataset import prepare_eval_data

    runtime.evaluate(config, state=state)
    panels = [f for f in os.listdir(tmp_path / "attn") if f.endswith("_attention.jpg")]
    assert panels, "no attention panels rendered"

    _, ds, vocab = prepare_eval_data(config)
    rows = decode_dataset(config, state, ds, vocab)
    for r in rows:
        assert len(r["words"]) == r["alphas"].shape[0]
        assert r["alphas"].shape[1] == config.num_ctx
        np.testing.assert_allclose(r["alphas"].sum(-1), 1.0, rtol=1e-4)


def test_eval_sweep_scores_every_checkpoint(trained, monkeypatch):
    config, _ = trained
    # the sweep must pay the expensive invariants ONCE: one eval-data
    # preparation and one state-skeleton init across every checkpoint
    # (the reference's eval.sh pays both per checkpoint, eval.sh:1-9)
    prep_calls, init_calls = [], []
    real_prep = runtime.prepare_eval_data
    real_init = runtime.create_train_state
    monkeypatch.setattr(
        runtime, "prepare_eval_data",
        lambda *a, **k: (prep_calls.append(1), real_prep(*a, **k))[1],
    )
    monkeypatch.setattr(
        runtime, "create_train_state",
        lambda *a, **k: (init_calls.append(1), real_init(*a, **k))[1],
    )
    # a third checkpoint so the sweep is N=3 (save_period=3 over 6 steps
    # leaves two; clone the last as step 9)
    import shutil

    shutil.copy(
        os.path.join(config.save_dir, "6.npz"),
        os.path.join(config.save_dir, "9.npz"),
    )
    sweep = runtime.evaluate_sweep(config)
    assert sorted(sweep) == [3, 6, 9]
    for step, scores in sweep.items():
        assert "Bleu_4" in scores
        assert os.path.exists(os.path.join(config.save_dir, f"{step}.txt"))
    # the cloned checkpoint must score identically to its source
    assert sweep[9] == sweep[6]
    assert len(prep_calls) == 1, "eval data re-prepared per checkpoint"
    assert len(init_calls) == 1, "state skeleton re-initialized per checkpoint"


def test_preempt_and_resume_equals_uninterrupted(coco_fixture, tmp_path):
    """Kill-and-resume: a run preempted mid-epoch (after a checkpoint) and
    resumed must produce bitwise the params of an uninterrupted run.  Batch
    order is a pure function of (seed, epoch) and dropout keys of the global
    step, so the resumed run replays the identical sequence — the
    checkpoint cursor story VERDICT r1 item 9 asks to prove."""
    base = coco_fixture["config"].replace(**SMALL_MODEL)

    # uninterrupted oracle: 2 epochs (24 anns / batch 4 = 6 steps/epoch)
    cfg_full = base.replace(
        num_epochs=2,
        save_dir=str(tmp_path / "full"), summary_dir=str(tmp_path / "fs"),
    )
    want = runtime.train(cfg_full)
    assert int(want.step) == 12

    # preempted run: hard-stopped mid-epoch-2 at step 8 (save on exit)
    cfg_a = base.replace(
        num_epochs=2, max_steps=8,
        save_dir=str(tmp_path / "resume"), summary_dir=str(tmp_path / "rs"),
    )
    state_a = runtime.train(cfg_a)
    assert int(state_a.step) == 8
    assert latest_checkpoint(cfg_a.save_dir).endswith("8.npz")

    # resume in a FRESH process-equivalent: new state skeleton, restore,
    # continue to completion
    cfg_b = cfg_a.replace(max_steps=0)
    state_b = runtime.setup_state(cfg_b, load=True)
    assert int(state_b.step) == 8
    state_b = runtime.train(cfg_b, state=state_b)
    assert int(state_b.step) == 12

    from sat_tpu.train.checkpoint import state_to_flat

    got, ref = state_to_flat(state_b), state_to_flat(want)
    assert set(got) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)


def test_dataset_seek_replays_exact_sequence(coco_fixture):
    """DataSet.seek(e, b) must reproduce the tail of epoch e exactly as an
    uninterrupted pass over that epoch produced it."""
    from sat_tpu.data.dataset import prepare_train_data

    config = coco_fixture["config"]
    ds = prepare_train_data(config)
    orders = []
    for _ in range(3):  # epochs 0..2 as a fresh run sees them
        epoch_files = []
        for batch in ds:
            epoch_files.append(tuple(batch[0]))
        orders.append(epoch_files)
    assert orders[0] != orders[1]  # shuffling actually happens

    ds2 = prepare_train_data(config)
    ds2.seek(1, 2)  # resume mid-epoch-1 at batch 2
    replay = [tuple(b[0]) for b in ds2]
    assert replay == orders[1][2:]
    # and the following epoch continues the same sequence
    assert [tuple(b[0]) for b in ds2] == orders[2]


def test_train_with_profiler_and_var_stats(coco_fixture, tmp_path):
    """Profiler trace + per-variable stats hooks (SURVEY.md §5 tracing)."""
    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "var_summary_period": 3,
           "profile_dir": str(tmp_path / "profile"),
           "profile_start_step": 1,
           "profile_num_steps": 2}
    )
    runtime.train(config)
    # a trace directory with at least one artifact was produced
    produced = []
    for root, _, files in os.walk(tmp_path / "profile"):
        produced += files
    assert produced, "no profiler trace written"
    # variable stats rows present at the configured cadence
    rows = [
        json.loads(line)
        for line in open(os.path.join(config.summary_dir, "metrics.jsonl"))
    ]
    stat_rows = [r for r in rows if any(k.startswith("params/") for k in r)]
    assert {r["step"] for r in stat_rows} == {3, 6}
    # attention stats ride along with normal metrics
    assert any("attention/mean" in r for r in rows)


def test_empty_dataset_raises_clear_error(coco_fixture, tmp_path):
    """All captions filtered out (max_caption_length below every fixture
    caption) must fail with a diagnosis, not ZeroDivisionError deep in the
    resume fast-forward.  Own cache paths: the session fixture's
    anns.csv/data.npy were tokenized under the default caption length and
    would bypass the cap-length filter entirely."""
    from sat_tpu import runtime

    cfg = coco_fixture["config"].replace(
        max_caption_length=2,
        vocabulary_file=str(tmp_path / "vocab.csv"),
        temp_annotation_file=str(tmp_path / "anns.csv"),
        temp_data_file=str(tmp_path / "data.npy"),
    )
    with pytest.raises(ValueError, match="filtered out"):
        runtime.train(cfg)


def test_quality_run_loss_curve_keeps_final_segment(tmp_path):
    """The committed-evidence loss curve must come from the FINAL run when
    an earlier run appended to the same metrics.jsonl (step reset marks
    the boundary)."""
    import json as _json
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    from quality_run import read_loss_curve

    p = tmp_path / "metrics.jsonl"
    rows = [{"step": s, "total_loss": 3.0} for s in (10, 140, 400)]
    rows += [{"step": s, "total_loss": 2.0} for s in range(10, 1210, 10)]
    p.write_text("".join(_json.dumps(r) + "\n" for r in rows))
    steps = [s for s, _ in read_loss_curve(str(p))]
    assert steps[-1] == 1200
    assert all(b > a for a, b in zip(steps, steps[1:]))
    assert all(loss == 2.0 for _, loss in read_loss_curve(str(p)))


def test_cli_accepts_reference_misspelled_keys():
    """The reference's config attributes are literally typo'd
    (num_initalize_layers, /root/reference/config.py:12-13); its users'
    override lists must port verbatim."""
    config, _ = build_config(
        ["--set", "num_initalize_layers=1", "--set", "dim_initalize_layer=64"]
    )
    assert config.num_initialize_layers == 1
    assert config.dim_initialize_layer == 64


def test_cli_print_config(capsys):
    from sat_tpu.cli import main

    assert main(["--print_config", "--set", "batch_size=11"]) == 0
    cfg = json.loads(capsys.readouterr().out)
    assert cfg["batch_size"] == 11
    assert cfg["cnn"] == "vgg16"


class TestProgress:
    """Per-batch progress reporting (reference tqdm parity,
    base_model.py:49-50,82,131)."""

    def test_non_tty_prints_every_n_and_final(self):
        import io

        from sat_tpu.utils.progress import Progress

        out = io.StringIO()  # StringIO.isatty() is False
        with Progress(10, desc="epoch 1/3", stream=out, every=4) as bar:
            for _ in range(10):
                bar.update()
        lines = out.getvalue().strip().splitlines()
        assert lines[0].startswith("epoch 1/3: 4/10")
        assert lines[1].startswith("epoch 1/3: 8/10")
        assert lines[-1].startswith("epoch 1/3: 10/10")
        assert len(lines) == 3  # no duplicate final line, no spam

    def test_non_tty_no_duplicate_when_total_on_cadence(self):
        import io

        from sat_tpu.utils.progress import Progress

        out = io.StringIO()
        with Progress(8, stream=out, every=4) as bar:
            for _ in range(8):
                bar.update()
        lines = out.getvalue().strip().splitlines()
        assert len(lines) == 2  # 4/8 and 8/8 — close() adds nothing

    def test_tty_redraws_one_line(self):
        import io

        from sat_tpu.utils.progress import Progress

        class Tty(io.StringIO):
            def isatty(self):
                return True

        out = Tty()
        with Progress(5, desc="d", stream=out, min_interval_s=0.0) as bar:
            for _ in range(5):
                bar.update()
        v = out.getvalue()
        assert v.count("\r") == 6  # 5 redraws + final
        assert v.endswith("d: 5/5 " + v[v.rindex("["):])  # final line present
        assert "\n" in v  # close() terminates the bar line

    def test_track_wraps_iterables(self):
        import io

        from sat_tpu.utils.progress import track

        out = io.StringIO()
        seen = list(track(range(6), 6, desc="t", stream=out, every=2))
        assert seen == list(range(6))
        assert "t: 6/6" in out.getvalue()


def test_eval_decode_with_profiler_window(coco_fixture, tmp_path):
    """decode_dataset honors the same profiler knobs as train: an eval run
    with profile_dir set produces a trace over the decode loop."""
    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "eval_result_file": str(tmp_path / "results.json"),
           "num_epochs": 1}
    )
    state = runtime.train(config)
    # profile_start_step left at its train default (5), far past this
    # tiny eval's batch count — the decode window must clamp and still fire
    cfg_prof = config.replace(
        profile_dir=str(tmp_path / "eval_profile"),
        profile_num_steps=1,
    )
    runtime.evaluate(cfg_prof, state=state)
    produced = []
    for root, _, files in os.walk(tmp_path / "eval_profile"):
        produced += files
    assert produced, "no eval profiler trace written"


def test_config_seed_controls_the_run(coco_fixture, tmp_path):
    """config.seed drives param init, the dropout key stream, and the
    shuffle order end-to-end: identical seeds reproduce the trained
    params bitwise, a different seed diverges.  (The reference exposes no
    seed control at all.)"""
    import jax.tree_util as jtu

    def run(seed, tag):
        cfg = coco_fixture["config"].replace(
            **{**SMALL_MODEL,
               "seed": seed,
               "max_steps": 3,
               "save_dir": str(tmp_path / f"m{tag}"),
               "summary_dir": str(tmp_path / f"s{tag}")}
        )
        return runtime.train(cfg)

    a = run(7, "a")
    b = run(7, "b")
    c = run(8, "c")
    flat_a = jtu.tree_leaves(a.params)
    flat_b = jtu.tree_leaves(b.params)
    flat_c = jtu.tree_leaves(c.params)
    for xa, xb in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
    assert any(
        not np.array_equal(np.asarray(xa), np.asarray(xc))
        for xa, xc in zip(flat_a, flat_c)
    )


def test_sigkill_and_cli_resume_bitwise_matches_control(coco_fixture, tmp_path):
    """The preemption story with a REAL process kill (VERDICT r03 #8): a
    CLI training child is SIGKILLed mid-epoch — past at least one ASYNC
    checkpoint, possibly mid-write — then relaunched with --load.  The
    continued run's per-step metrics and final checkpoint must bitwise
    match an uninterrupted control.  (Capability exceeded: the reference
    resumes at its last save but loses the mid-epoch cursor entirely,
    /root/reference/base_model.py:257-278.)"""
    import signal
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "num_epochs": 2, "save_period": 2, "async_checkpoint": True,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary")}
    )
    cfg_path = tmp_path / "config.json"
    cfg.save(str(cfg_path))

    # the child pins jax to CPU itself (the environment's sitecustomize
    # overrides JAX_PLATFORMS, so an env var alone is not enough) and then
    # enters the real CLI
    child_code = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from sat_tpu.utils.compile_cache import enable as _enable_cache\n"
        "_enable_cache(jax, name='.jax_cache', min_compile_time_secs=0.5)\n"
        "from sat_tpu import cli\n"
        "sys.exit(cli.main(sys.argv[1:]))\n"
    )

    import threading

    def launch(*extra):
        # drain stdout concurrently: a child blocked on a full stdout
        # pipe (the XLA cache loader alone writes tens of KB of
        # warnings) would never reach the checkpoint the kill waits for
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", child_code,
             "--phase=train", "--config", str(cfg_path), *extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo, start_new_session=True,
        )
        chunks = []

        def drain():
            for line in proc.stdout:
                chunks.append(line)

        threading.Thread(target=drain, daemon=True).start()
        return proc, chunks

    # 24 anns / batch 4 = 6 steps/epoch, 12 total; checkpoints at 2,4,...
    victim, victim_out = launch()
    deadline = time.time() + 420
    try:
        # kill once a mid-epoch async checkpoint (step 4) has landed —
        # the writer may be mid-write on the NEXT one, which must not
        # corrupt the resume (atomic rename)
        while time.time() < deadline:
            if victim.poll() is not None:
                out = "".join(victim_out)
                raise AssertionError(f"child exited early rc={victim.returncode}\n{out[-3000:]}")
            if os.path.exists(os.path.join(cfg.save_dir, "4.npz")):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("child never reached checkpoint step 4")
        os.killpg(victim.pid, signal.SIGKILL)
    finally:
        victim.wait()

    latest = latest_checkpoint(cfg.save_dir)
    killed_at = int(os.path.basename(latest).split(".")[0])
    assert killed_at >= 4 and killed_at < 12

    resumed, resumed_out = launch("--load")
    try:
        assert resumed.wait(timeout=420) == 0, "".join(resumed_out)[-3000:]
    finally:
        if resumed.poll() is None:  # hung: don't leak a detached trainer
            os.killpg(resumed.pid, signal.SIGKILL)
            resumed.wait()
    assert latest_checkpoint(cfg.save_dir).endswith("12.npz")

    # uninterrupted control, in-process (same seed, fresh dirs)
    ctl = cfg.replace(
        save_dir=str(tmp_path / "ctl_models"),
        summary_dir=str(tmp_path / "ctl_summary"),
        async_checkpoint=False,
    )
    want_state = runtime.train(ctl)
    assert int(want_state.step) == 12

    # final checkpoints bitwise equal
    got = dict(np.load(os.path.join(cfg.save_dir, "12.npz")))
    want = dict(np.load(os.path.join(ctl.save_dir, "12.npz")))
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)

    # the resumed run's metrics rows (steps after the kill) bitwise match
    # the control's rows for the same steps — same batches, same losses
    def metrics(d):
        return {
            r["step"]: r for r in (
                json.loads(line)
                for line in open(os.path.join(d, "metrics.jsonl"))
            )
        }

    got_rows, want_rows = metrics(cfg.summary_dir), metrics(ctl.summary_dir)
    resumed_steps = [s for s in sorted(got_rows) if s > killed_at]
    assert resumed_steps and resumed_steps[-1] == 12
    for s in resumed_steps:
        for key in ("total_loss", "cross_entropy_loss", "accuracy"):
            assert got_rows[s][key] == want_rows[s][key], (s, key)
