"""The bulk progress manifest: the exact resume frontier, atomically.

``bulk_manifest.json`` lives in the output directory and records, after
every completed output shard, which shards are done and what their
output files should contain (row count + whole-file crc32c).  A
relaunched job (``--supervise`` after kill -9, or a manual re-run on a
different chip count) reloads it, re-derives the shard plan from the
corpus, and re-decodes only the shards without a completed entry —
completed outputs are never rewritten, which is what makes resume
bitwise (docs/BULK.md).

Durability discipline (satellite requirement): every write rides
``resilience.retry.retry_io`` around ``utils.fileio.atomic_write``
(tmp + fchmod + ``os.replace``), so a flaky mount costs a backoff and a
kill -9 mid-write leaves either the previous manifest or the new one,
never a torn hybrid.  The read side is correspondingly paranoid:
anything unparseable or structurally wrong loads as ``None`` (= start
from an empty frontier), because the output files themselves are
re-verified against the manifest before a shard is skipped — a lost
manifest costs re-decoding, never correctness.

Jax-free by design (see the package docstring).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import List, Optional

from ..resilience.retry import retry_io
from ..utils.fileio import atomic_write

MANIFEST_NAME = "bulk_manifest.json"

# Manifest layout version: bump when a field changes meaning.  A reader
# seeing a different format starts fresh rather than misinterpreting.
MANIFEST_FORMAT = 1


def manifest_path_for(bulk_output: str) -> str:
    return os.path.join(bulk_output, MANIFEST_NAME)


def corpus_fingerprint(files: List[str], rows_per_shard: int, image_size: int) -> str:
    """sha256 over the ordered corpus and the parameters that shape the
    outputs.  Deliberately EXCLUDES chip count / pool geometry / beam
    host details: those may change across a resume (elastic resume) and
    must not invalidate completed shards.  Includes ``image_size``
    because a different resize produces different captions — resuming a
    224px run at 32px must restart, not splice."""
    h = hashlib.sha256()
    h.update(f"format={MANIFEST_FORMAT};rows={rows_per_shard};size={image_size};".encode())
    for f in files:
        h.update(f.encode("utf-8", "surrogatepass"))
        h.update(b"\n")
    return h.hexdigest()


def new_manifest(files: List[str], rows_per_shard: int, image_size: int) -> dict:
    num_shards = (len(files) + rows_per_shard - 1) // rows_per_shard
    return {
        "format": MANIFEST_FORMAT,
        "corpus_sha": corpus_fingerprint(files, rows_per_shard, image_size),
        "total_images": len(files),
        "shard_rows": rows_per_shard,
        "image_size": image_size,
        "num_shards": num_shards,
        # str(shard_idx) -> {"file", "rows", "crc32c"}; str keys because
        # this round-trips through JSON
        "completed": {},
    }


def load_manifest(path: str) -> Optional[dict]:
    """Load a manifest, or ``None`` when there is none to trust: missing
    file, torn/invalid JSON, wrong format, or a structurally bogus
    ``completed`` map.  ``None`` always means "empty frontier", which is
    safe (never wrong, at worst slow) because shard skipping re-verifies
    the actual output files against the recorded row crc."""
    try:
        with open(path) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(m, dict) or m.get("format") != MANIFEST_FORMAT:
        return None
    done = m.get("completed")
    if not isinstance(done, dict):
        return None
    for k, v in done.items():
        if not (
            isinstance(k, str) and k.isdigit() and isinstance(v, dict)
            and isinstance(v.get("file"), str)
            and isinstance(v.get("rows"), int)
            and isinstance(v.get("crc32c"), int)
        ):
            return None
    return m


def write_manifest(path: str, manifest: dict) -> None:
    """Durable, atomic, retrying write (see module docstring)."""
    payload = json.dumps(manifest, indent=2, sort_keys=True)
    retry_io(
        lambda: atomic_write(path, "w", lambda f: f.write(payload + "\n")),
        desc=f"write {os.path.basename(path)}",
    )


def mark_completed(
    manifest: dict, shard_idx: int, filename: str, rows: int, crc: int
) -> None:
    manifest["completed"][str(shard_idx)] = {
        "file": filename,
        "rows": rows,
        "crc32c": crc,
    }
