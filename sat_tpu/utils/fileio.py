"""Host-side file I/O helpers.

The reference writes checkpoints and configs with plain ``np.save`` /
``pickle.dump`` (/root/reference/base_model.py:248-253), so a preempted
process can leave torn files.  Every durable artifact in this framework
goes through ``atomic_write`` instead: tmp file + rename, with the final
mode honoring the process umask (mkstemp alone would leave 0600 files
other readers of a shared filesystem can't open).
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, IO


def atomic_write(path: str, mode: str, writer: Callable[[IO], None]) -> None:
    """Write ``path`` atomically: ``writer(f)`` into a tmp file in the same
    directory, fchmod to umask-derived permissions, then ``os.replace``.

    ``mode`` is 'w' (text) or 'wb' (binary).
    """
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        umask = os.umask(0)
        os.umask(umask)
        os.fchmod(fd, 0o666 & ~umask)
        with os.fdopen(fd, mode) as f:
            writer(f)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
