"""Integrity cost accounting: shard-gather overhead of --verify_shards.

docs/DATA_PIPELINE.md claims the default sampling verifier
(``--verify_shards sample``) is cheap enough to leave on for every run:
one crc32c of one row every ``integrity.SAMPLE_EVERY`` gathers, batched
through the same table-driven/vectorized crc the summary writer uses.
This bench puts a number on that claim without jax (and without cv2 —
the shard cache is built through a deterministic stub loader), by timing
the real ``ShardCache.gather`` path:

* ``off``:    gather with no integrity armed — the baseline fancy-index
  copy every cached step pays.
* ``sample``: the same gathers with the rotating-row sampler armed.

Prints one BENCH-contract JSON line on stdout ({"metric", "value",
"unit", "vs_baseline", ...extras}).  ``value`` is the *added* cost of
sample-mode verification in percent of a ``--step-ms`` device step
(1.0 is the acceptance bar: ISSUE — "≪ 1% of a 30 ms step").  ``full``
mode is measured and reported for context, never gated — it is an
explicitly opt-in audit mode.

Usage: python scripts/bench_integrity.py [--step-ms 30] [--iters 2048]
       [--files 64] [--batch 8] [--size 64] [--workdir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sat_tpu import telemetry
from sat_tpu.data.shards import ShardCache, build_shard_cache

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_integrity +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


class _StubLoader:
    """Deterministic image source keyed on basename — no cv2, no disk."""

    def __init__(self, size: int):
        self.size = size

    def load_raw(self, image_file: str) -> np.ndarray:
        seed = abs(hash(os.path.basename(image_file))) % (2 ** 32)
        rng = np.random.default_rng(seed)
        return rng.integers(
            0, 256, (self.size, self.size, 3), dtype=np.uint8
        )


def _time_gathers(cache: ShardCache, batches, iters: int) -> float:
    """Seconds per gather over ``iters`` gathers cycling ``batches``."""
    t0 = time.perf_counter()
    for i in range(iters):
        cache.gather(batches[i % len(batches)])
    return (time.perf_counter() - t0) / iters


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--step-ms", type=float, default=30.0,
                    help="device step time the overhead is judged against")
    ap.add_argument("--iters", type=int, default=2048,
                    help="gathers per measurement (amortizes SAMPLE_EVERY)")
    ap.add_argument("--files", type=int, default=64)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--size", type=int, default=64,
                    help="image edge; 64 -> 12 KiB rows, the fixture scale")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_integrity_")
    made_workdir = args.workdir is None
    telemetry.disable()
    try:
        cache_dir = os.path.join(workdir, "cache")
        files = [
            os.path.join(workdir, f"img_{i:05d}.jpg")
            for i in range(args.files)
        ]
        build_shard_cache(
            files, cache_dir, args.size,
            rows_per_shard=16, loader=_StubLoader(args.size),
        )
        cache = ShardCache.open(cache_dir, args.size)
        batches = [
            files[i:i + args.batch]
            for i in range(0, args.files - args.batch + 1, args.batch)
        ]
        row_bytes = args.size * args.size * 3
        log(f"cache built: {args.files} files x {row_bytes} B rows, "
            f"batch {args.batch}, {args.iters} gathers per mode")

        results = {}
        for mode in ("off", "sample", "full"):
            cache.enable_integrity(mode)
            _time_gathers(cache, batches, 64)  # warm (page cache, sidecars)
            results[mode] = _time_gathers(cache, batches, args.iters)
            log(f"verify_shards={mode}: "
                f"{results[mode] * 1e6:.2f} us/gather")

        sample_us = (results["sample"] - results["off"]) * 1e6
        full_us = (results["full"] - results["off"]) * 1e6
        overhead_pct = 100.0 * max(0.0, sample_us / 1e3) / args.step_ms
        log(f"sample-mode added cost: {sample_us:.2f} us/gather "
            f"-> {overhead_pct:.4f}% of a {args.step_ms:.0f} ms step "
            f"(full mode, unbudgeted: {full_us:.2f} us/gather)")

        result = {
            "metric": "integrity_verify_overhead",
            "value": round(overhead_pct, 4),
            "unit": "%_of_step",
            "vs_baseline": 1.0,  # the acceptance bar (ISSUE: < 1%)
            "gather_off_us": round(results["off"] * 1e6, 2),
            "gather_sample_us": round(results["sample"] * 1e6, 2),
            "gather_full_us": round(results["full"] * 1e6, 2),
            "sample_added_us": round(sample_us, 2),
            "full_added_us": round(full_us, 2),
            "row_bytes": row_bytes,
            "batch": args.batch,
            "step_ms_assumed": args.step_ms,
            **telemetry.bench_stamp(),
        }
        print(json.dumps(result), flush=True)
        return 0 if overhead_pct < 1.0 else 1
    finally:
        if made_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
