"""Context parallelism: distributed-softmax attention over a sharded grid
(SURVEY.md §5 long-context note; 8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sat_tpu.config import Config
from sat_tpu.models.captioner import compute_loss
from sat_tpu.models.decoder import init_decoder_params
from sat_tpu.parallel.context import (
    make_context_parallel_loss,
    make_context_parallel_train_step,
)
from sat_tpu.parallel.mesh import make_mesh
from sat_tpu.train.step import create_train_state


def _cfg(**kw):
    base = dict(
        image_size=32,          # → 4 context positions through VGG16
        vocabulary_size=50,
        dim_embedding=8,
        num_lstm_units=8,
        dim_initialize_layer=8,
        dim_attend_layer=16,
        dim_decode_layer=16,
        max_caption_length=5,
        compute_dtype="float32",
    )
    return Config(**{**base, **kw})


@pytest.mark.parametrize("layers", [1, 2])
def test_cp_loss_matches_single_device(rng, layers):
    """Eval-mode loss over a (2 data × 4 context)-sharded grid must equal
    the plain single-device computation (no dropout ⇒ exact math)."""
    config = _cfg(num_attend_layers=layers, mesh_shape=(2, 4))
    mesh = make_mesh(config)
    params = init_decoder_params(jax.random.PRNGKey(0), config)

    B, T = 4, config.max_caption_length
    N, D = config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))
    sentences = jnp.asarray(
        rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
    )
    masks = jnp.ones((B, T), jnp.float32)

    cp_loss = make_context_parallel_loss(config, mesh, train=False)
    total_cp, metrics_cp = cp_loss(
        params, contexts, sentences, masks, jax.random.PRNGKey(1)
    )

    # single-device oracle via compute_loss on precomputed contexts
    batch = {"contexts": contexts, "word_idxs": sentences, "masks": masks}
    variables = {"params": {"cnn": {}, "decoder": params}}
    total_ref, aux = compute_loss(variables, config, batch, rng=None, train=False)
    want = (
        aux["metrics"]["cross_entropy_loss"] + aux["metrics"]["attention_loss"]
    )
    np.testing.assert_allclose(float(total_cp), float(want), rtol=1e-5)
    np.testing.assert_allclose(
        float(metrics_cp["accuracy"]),
        float(aux["metrics"]["accuracy"]),
        rtol=1e-6,
    )


def test_cp_train_step_runs_and_learns(rng):
    """Full jitted CP train step: grads flow through the psum/pmax
    softmax, optimizer updates apply, loss is finite and decreases over a
    few repeated steps on one batch."""
    config = _cfg(mesh_shape=(2, 4), train_cnn=False)
    mesh = make_mesh(config)
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_context_parallel_train_step(config, mesh)

    B, T = 4, config.max_caption_length
    batch = {
        "images": jnp.asarray(
            rng.normal(size=(B, config.image_size, config.image_size, 3)).astype(
                np.float32
            )
        ),
        "word_idxs": jnp.asarray(
            rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
        ),
        "masks": jnp.ones((B, T), jnp.float32),
    }

    losses = []
    for i in range(8):
        state, metrics = step(state, batch, jax.random.PRNGKey(42))
        losses.append(float(metrics["total_loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]          # same batch, fixed key ⇒ must fit
    assert int(state.step) == 8
    assert float(metrics["grad_norm"]) > 0


def test_cp_dropout_iid_across_data_shards(rng):
    """Dropout masks must be iid across 'data' shards (ADVICE r1): feed the
    SAME rows to both data shards; if both shards drew identical masks the
    global train-mode CE would equal the one-data-shard CE of those rows.
    With the data-axis fold_in they must differ."""
    config = _cfg(mesh_shape=(2, 4))
    params = init_decoder_params(jax.random.PRNGKey(0), config)

    B, T = 2, config.max_caption_length
    N, D = config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))
    sentences = jnp.asarray(
        rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
    )
    masks = jnp.ones((B, T), jnp.float32)
    key = jax.random.PRNGKey(7)

    mesh2 = make_mesh(config)  # (2 data, 4 context)
    dup = lambda x: jnp.concatenate([x, x], axis=0)  # noqa: E731
    _, m2 = make_context_parallel_loss(config, mesh2, train=True)(
        params, dup(contexts), dup(sentences), dup(masks), key
    )

    mesh1 = make_mesh(config.replace(mesh_shape=(1, 4)))
    _, m1 = make_context_parallel_loss(
        config.replace(mesh_shape=(1, 4)), mesh1, train=True
    )(params, contexts, sentences, masks, key)

    # shard 0 of the dup run computes exactly the (1,4)-mesh values, so
    # equality here would mean shard 1 drew the same dropout masks.
    assert float(m2["cross_entropy_loss"]) != pytest.approx(
        float(m1["cross_entropy_loss"]), rel=1e-7
    )


def test_cp_train_step_updates_batch_stats(rng):
    """train_cnn with a BN backbone under CP must thread the encoder's
    running statistics into the new state (ADVICE r1)."""
    # resnet downsamples 32×: image_size 64 → 2×2 = 4 context positions,
    # matching the 4-way context axis
    config = _cfg(cnn="resnet50", train_cnn=True, mesh_shape=(2, 4), image_size=64)
    mesh = make_mesh(config)
    state = create_train_state(jax.random.PRNGKey(0), config)
    assert state.batch_stats  # resnet50 has BN state
    step = make_context_parallel_train_step(config, mesh)

    B, T = 2, config.max_caption_length
    batch = {
        "images": jnp.asarray(
            rng.normal(size=(B, config.image_size, config.image_size, 3)).astype(
                np.float32
            )
        ),
        "word_idxs": jnp.asarray(
            rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
        ),
        "masks": jnp.ones((B, T), jnp.float32),
    }
    before = jax.device_get(state.batch_stats)  # donated: snapshot first
    new_state, metrics = step(state, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["total_loss"]))
    after = jax.device_get(new_state.batch_stats)
    changed = any(
        not np.allclose(b, a)
        for b, a in zip(
            jax.tree_util.tree_leaves(before), jax.tree_util.tree_leaves(after)
        )
    )
    assert changed, "encoder BN running stats were not updated"


def test_runtime_train_with_context_parallel(coco_fixture, tmp_path):
    """runtime.train dispatches to the CP step when context_parallel>1."""
    from sat_tpu import runtime
    from tests.test_runtime import SMALL_MODEL

    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "mesh_shape": (2, 4),
           "context_parallel": 4}
    )
    state = runtime.train(config)
    assert int(np.asarray(state.step)) == 6
    import json, os
    rows = [json.loads(x) for x in open(os.path.join(config.summary_dir, "metrics.jsonl"))]
    assert all(np.isfinite(r["total_loss"]) for r in rows)


def test_cp_remat_matches_baseline(rng):
    """remat_decoder under the context-parallel scan must leave the
    train-mode loss/grads unchanged (masks regenerate from the same
    per-step keys; saved dots include the psum'd attention terms)."""
    config = _cfg(mesh_shape=(1, 4))
    params = init_decoder_params(jax.random.PRNGKey(0), config)
    B, T = 2, config.max_caption_length
    contexts = jnp.asarray(
        rng.normal(size=(B, config.num_ctx, config.dim_ctx)).astype(np.float32)
    )
    sentences = jnp.asarray(
        rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
    )
    masks = jnp.ones((B, T), jnp.float32)
    key = jax.random.key(11, impl=config.rng_impl)

    def grad_of(cfg):
        mesh = make_mesh(cfg)
        loss = make_context_parallel_loss(cfg, mesh, train=True)

        def f(p):
            total, _ = loss(p, contexts, sentences, masks, key)
            return total

        return jax.grad(f)(params)

    g0 = grad_of(config)
    g1 = grad_of(config.replace(remat_decoder=True))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6
        ),
        g0, g1,
    )


def test_cp_fc_activity_matches_single_device(rng):
    """The CP decoder's fc L1 activity term (psum-assembled from context-
    sharded t1 partials + model-replicated t2/decode/init partials) must
    equal the single-device sum.  Dropout rates 0 make train-mode math
    deterministic, so the comparison is exact up to reduction order."""
    s = 1e-3
    kw = dict(
        num_attend_layers=2, mesh_shape=(2, 4),
        fc_drop_rate=0.0, lstm_drop_rate=0.0,
    )
    config = _cfg(fc_activity_regularizer_scale=s, **kw)
    mesh = make_mesh(config)
    params = init_decoder_params(jax.random.PRNGKey(0), config)

    B, T = 4, config.max_caption_length
    N, D = config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))
    sentences = jnp.asarray(
        rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
    )
    masks = jnp.ones((B, T), jnp.float32)
    key = jax.random.PRNGKey(1)

    cp_loss = make_context_parallel_loss(config, mesh, train=True)
    _, metrics_cp = cp_loss(params, contexts, sentences, masks, key)
    assert "fc_activity" in metrics_cp

    # single-device activity via the loss's linearity in the scale
    batch = {"contexts": contexts, "word_idxs": sentences, "masks": masks}
    variables = {"params": {"cnn": {}, "decoder": params}}
    total_s, _ = compute_loss(variables, config, batch, rng=key, train=True)
    total_0, _ = compute_loss(
        variables, _cfg(fc_activity_regularizer_scale=0.0, **kw),
        batch, rng=key, train=True,
    )
    want = (float(total_s) - float(total_0)) / s
    assert want > 0
    np.testing.assert_allclose(float(metrics_cp["fc_activity"]), want, rtol=1e-4)


@pytest.mark.parametrize("layers", [1, 2])
def test_cp_beam_search_matches_single_device(rng, layers):
    """Context-parallel beam search (grid sharded over 4 model shards,
    distributed-softmax attend) must reproduce the single-device search
    exactly: same words/lengths, same scores, and the shard-local alphas
    must reassemble to the global attention maps via the out_spec."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from sat_tpu.ops.beam_search import BeamResult, beam_search
    from sat_tpu.parallel.context import cp_beam_search

    config = _cfg(num_attend_layers=layers, mesh_shape=(2, 4), beam_size=3)
    mesh = make_mesh(config)
    params = init_decoder_params(jax.random.PRNGKey(0), config)

    B = 4
    N, D = config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))
    eos, vs = 1, 40

    want = beam_search(
        params, config, contexts, eos, valid_size=vs, return_alphas=True
    )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P("data", "model", None)),
        out_specs=BeamResult(
            words=P("data"), log_scores=P("data"), lengths=P("data"),
            alphas=P("data", None, None, "model"),
        ),
        check_vma=False,
    )
    def run(p, ctx):
        return cp_beam_search(
            p, config, ctx, eos, valid_size=vs, return_alphas=True
        )

    got = run(params, contexts)
    np.testing.assert_array_equal(np.asarray(got.words), np.asarray(want.words))
    np.testing.assert_array_equal(
        np.asarray(got.lengths), np.asarray(want.lengths)
    )
    np.testing.assert_allclose(
        np.asarray(got.log_scores), np.asarray(want.log_scores),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(got.alphas), np.asarray(want.alphas), rtol=1e-4, atol=1e-6
    )


def test_cp_caption_fn_end_to_end(rng):
    """make_context_parallel_beam_search: GSPMD encoder + shard_map CP
    decode in one jitted program equals single-device encode+search."""
    from sat_tpu.models.captioner import encode
    from sat_tpu.ops.beam_search import beam_search
    from sat_tpu.parallel.context import make_context_parallel_beam_search

    config = _cfg(mesh_shape=(2, 4), context_parallel=4, beam_size=2)
    state = create_train_state(jax.random.PRNGKey(0), config)
    mesh = make_mesh(config)
    variables = {"params": state.params}
    images = jnp.asarray(
        rng.normal(size=(4, config.image_size, config.image_size, 3)).astype(
            np.float32
        )
    )
    eos, vs = 1, 40

    contexts, _ = encode(variables, config, images, train=False)
    want = beam_search(
        state.params["decoder"], config, contexts, eos, valid_size=vs,
        return_alphas=True,
    )

    fn = make_context_parallel_beam_search(config, mesh, eos, valid_size=vs)
    got = fn(variables, images)
    np.testing.assert_array_equal(np.asarray(got.words), np.asarray(want.words))
    np.testing.assert_allclose(
        np.asarray(got.log_scores), np.asarray(want.log_scores),
        rtol=1e-5, atol=1e-6,
    )

    # the save_attention_maps production path: factory-built out_specs must
    # reassemble the context-sharded alphas into the global [B,K,T,N] maps
    fn_a = make_context_parallel_beam_search(
        config, mesh, eos, valid_size=vs, return_alphas=True
    )
    got_a = fn_a(variables, images)
    np.testing.assert_array_equal(
        np.asarray(got_a.words), np.asarray(want.words)
    )
    np.testing.assert_allclose(
        np.asarray(got_a.alphas), np.asarray(want.alphas), rtol=1e-4, atol=1e-6
    )


def test_cp_ce_dtype_matches_single_device(rng):
    """config.ce_dtype applies identically on the CP path (shared
    token_ce): train-mode CP loss under ce_dtype=bfloat16 must equal the
    single-device compute_loss under the same knob (fp32 compute here, so
    the manual-logsumexp formulation is exact — the parity being pinned
    is path-sharing, not rounding)."""
    config = _cfg(
        mesh_shape=(2, 4), ce_dtype="bfloat16",
        fc_drop_rate=0.0, lstm_drop_rate=0.0,
    )
    mesh = make_mesh(config)
    params = init_decoder_params(jax.random.PRNGKey(0), config)

    B, T = 4, config.max_caption_length
    N, D = config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))
    sentences = jnp.asarray(
        rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
    )
    masks = jnp.ones((B, T), jnp.float32)

    cp_loss = make_context_parallel_loss(config, mesh, train=True)
    total_cp, metrics_cp = cp_loss(
        params, contexts, sentences, masks, jax.random.key(1, impl=config.rng_impl)
    )

    batch = {"contexts": contexts, "word_idxs": sentences, "masks": masks}
    variables = {"params": {"cnn": {}, "decoder": params}}
    _, aux = compute_loss(
        variables, config, batch,
        rng=jax.random.key(1, impl=config.rng_impl), train=True,
    )
    np.testing.assert_allclose(
        float(metrics_cp["cross_entropy_loss"]),
        float(aux["metrics"]["cross_entropy_loss"]),
        rtol=1e-5,
    )
