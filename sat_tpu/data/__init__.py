from .coco import CocoCaptions
from .dataset import (
    DataSet,
    build_vocabulary,
    prepare_eval_data,
    prepare_test_data,
    prepare_train_data,
)
from .images import ILSVRC_2012_MEAN, ImageLoader, PrefetchLoader
from .shards import (
    ShardCache,
    ShardCacheMismatch,
    build_shard_cache,
    resolve_shard_cache,
)
from .tokenizer import PUNCTUATIONS, tokenize, tokenize_captions, tokenize_no_punct
from .vocabulary import Vocabulary

__all__ = [
    "CocoCaptions",
    "DataSet",
    "Vocabulary",
    "ImageLoader",
    "PrefetchLoader",
    "ShardCache",
    "ShardCacheMismatch",
    "build_shard_cache",
    "resolve_shard_cache",
    "ILSVRC_2012_MEAN",
    "PUNCTUATIONS",
    "tokenize",
    "tokenize_captions",
    "tokenize_no_punct",
    "prepare_train_data",
    "prepare_eval_data",
    "prepare_test_data",
    "build_vocabulary",
]
