from .captioner import compute_loss, encode, init_variables, make_encoder
from .decoder import (
    DecoderState,
    attend,
    decode_logits,
    decoder_step,
    init_decoder_params,
    init_state,
    lstm_step,
    teacher_forced_decode,
)
from .resnet50 import ResNet50
from .vgg16 import VGG16

__all__ = [
    "VGG16",
    "ResNet50",
    "DecoderState",
    "attend",
    "decode_logits",
    "decoder_step",
    "init_decoder_params",
    "init_state",
    "lstm_step",
    "teacher_forced_decode",
    "compute_loss",
    "encode",
    "init_variables",
    "make_encoder",
]
