"""Soft-attention LSTM caption decoder — pure-functional JAX.

Re-design of the reference's build_rnn / initialize / attend / decode
(/root/reference/model.py:190-459).  The reference unrolls 20 graph copies
in Python and, at inference, runs ONE step per sess.run round-trip; here the
decoder is a pure step function closed over an explicit parameter pytree, so

* training is a single ``lax.scan`` over time (one compiled program),
* beam search reuses the very same step function inside ``lax.scan`` fully
  on device (sat_tpu/ops/beam_search.py),
* the whole thing is trivially pjit/shard_map-compatible.

Semantics preserved from the reference:
* LSTM state initialized from the mean context via a 1- or 2-layer MLP
  (model.py:358-393), with fc dropout on the inputs;
* per-step soft attention, 1-layer additive logits (ctx→1 no-bias plus a
  position-specific h→num_ctx no-bias projection) or 2-layer tanh MLP
  (model.py:395-436), with fc dropout on both inputs;
* LSTM input = concat(attention context, word embedding) (model.py:277),
  TF1 LSTMCell gate order (i, j, f, o) with +1.0 forget-gate bias;
* DropoutWrapper semantics (model.py:232-236): fresh per-step masks on the
  LSTM input, emitted output, and the recurrent h (TF's default state
  filter exempts the cell state c);
* word logits from concat(output, context, word_embed) via a 1- or 2-layer
  MLP (model.py:438-459);
* teacher forcing: the step-t input word is sentences[:, t-1], step 0 gets
  the <start> index 0 (model.py:253,310).

Compute dtype: matmuls run in bfloat16 (MXU); softmax/log-softmax in fp32.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config import Config
from ..nn.layers import dropout as _nn_dropout
from ..nn.layers import fc_kernel_init

Params = Dict[str, Any]


class DecoderState(NamedTuple):
    """LSTM carry.  ``output`` is what the next attend/decode sees (the
    DropoutWrapper's *output*-dropout h); ``recurrent`` is what the next
    LSTM step consumes (the *state*-dropout h).  They are identical outside
    training — the split mirrors reference model.py:232-236,307-309 where
    last_output and last_state diverge under dropout."""

    memory: jnp.ndarray      # LSTM cell state c, [B, H]
    output: jnp.ndarray      # emitted h (feeds attend + decode), [B, H]
    recurrent: jnp.ndarray   # recurrent h (feeds the next LSTM step), [B, H]


# ---------------------------------------------------------------------------
# Parameter initialization
# ---------------------------------------------------------------------------


def _uniform(key, shape, scale):
    return fc_kernel_init(scale)(key, shape)


def _dense_params(key, d_in, d_out, scale, use_bias=True):
    p = {"kernel": _uniform(key, (d_in, d_out), scale)}
    if use_bias:
        p["bias"] = jnp.zeros((d_out,), jnp.float32)
    return p


def init_decoder_params(rng: jax.Array, config: Config) -> Params:
    """Build the decoder parameter pytree.  Leaf names mirror the reference
    TF scopes (word_embedding/weights, lstm/kernel, initialize/fc_a2, ...)
    so npy checkpoint import is a name rewrite, not a surgery."""
    c = config
    scale = c.fc_kernel_initializer_scale
    E, H, D, N, V = (
        c.dim_embedding,
        c.num_lstm_units,
        c.dim_ctx,
        c.num_ctx,
        c.vocabulary_size,
    )
    keys = iter(jax.random.split(rng, 16))
    p: Params = {}

    p["word_embedding"] = {"weights": _uniform(next(keys), (V, E), scale)}

    # TF1 LSTMCell layout: one kernel [(input_dim + H), 4H], gates (i,j,f,o)
    lstm_in = D + E
    p["lstm"] = {
        "kernel": _uniform(next(keys), (lstm_in + H, 4 * H), scale),
        "bias": jnp.zeros((4 * H,), jnp.float32),
    }

    if c.num_initialize_layers == 1:
        p["initialize"] = {
            "fc_a": _dense_params(next(keys), D, H, scale),
            "fc_b": _dense_params(next(keys), D, H, scale),
        }
    else:
        di = c.dim_initialize_layer
        p["initialize"] = {
            "fc_a1": _dense_params(next(keys), D, di, scale),
            "fc_a2": _dense_params(next(keys), di, H, scale),
            "fc_b1": _dense_params(next(keys), D, di, scale),
            "fc_b2": _dense_params(next(keys), di, H, scale),
        }

    if c.num_attend_layers == 1:
        p["attend"] = {
            "fc_a": _dense_params(next(keys), D, 1, scale, use_bias=False),
            "fc_b": _dense_params(next(keys), H, N, scale, use_bias=False),
        }
    else:
        da = c.dim_attend_layer
        p["attend"] = {
            "fc_1a": _dense_params(next(keys), D, da, scale),
            "fc_1b": _dense_params(next(keys), H, da, scale),
            "fc_2": _dense_params(next(keys), da, 1, scale, use_bias=False),
        }

    dec_in = H + D + E
    if c.num_decode_layers == 1:
        p["decode"] = {"fc": _dense_params(next(keys), dec_in, V, scale)}
    else:
        dd = c.dim_decode_layer
        p["decode"] = {
            "fc_1": _dense_params(next(keys), dec_in, dd, scale),
            "fc_2": _dense_params(next(keys), dd, V, scale),
        }
    return p


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def _dense(p, x, activation=None, dtype=jnp.bfloat16):
    # dtype is the matmul compute dtype (bfloat16 on TPU → MXU)
    y = x.astype(dtype) @ p["kernel"].astype(dtype)
    if "bias" in p:
        y = y + p["bias"].astype(dtype)
    y = y.astype(jnp.float32)
    if activation == "tanh":
        y = jnp.tanh(y)
    return y


def _dropout(rng, x, rate, train):
    return _nn_dropout(x, rate, deterministic=not train, rng=rng)


def _l1(x):
    """L1 activity contribution of an activated layer output — TF1
    l1_regularizer semantics: Σ|x|, unnormalized (reference
    utils/nn.py:23-26,40-43; scale applied by the caller)."""
    return jnp.abs(x.astype(jnp.float32)).sum()


def lstm_step(
    p: Params,
    c: jnp.ndarray,
    h: jnp.ndarray,
    x: jnp.ndarray,
    dtype=jnp.bfloat16,
    forget_bias: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """TF1 LSTMCell: concat(x, h) @ kernel → (i, j, f, o).  Returns (c, h)."""
    z = jnp.concatenate([x, h], axis=-1).astype(dtype) @ p["kernel"].astype(dtype)
    z = z.astype(jnp.float32) + p["bias"]
    i, j, f, o = jnp.split(z, 4, axis=-1)
    new_c = jax.nn.sigmoid(f + forget_bias) * c + jax.nn.sigmoid(i) * jnp.tanh(j)
    new_h = jax.nn.sigmoid(o) * jnp.tanh(new_c)
    return new_c, new_h


def init_state(
    params: Params,
    config: Config,
    contexts: jnp.ndarray,
    train: bool = False,
    rng: Optional[jax.Array] = None,
    with_activity: bool = False,
) -> DecoderState:
    """LSTM state from the mean context (reference initialize, model.py:358-393).

    with_activity=True (static) returns (state, L1 of the tanh outputs)."""
    p = params["initialize"]
    rate = config.fc_drop_rate
    dt = jnp.dtype(config.compute_dtype)
    context_mean = contexts.mean(axis=1)
    act = jnp.float32(0)
    if train:
        k0, k1, k2 = jax.random.split(rng, 3)
        context_mean = _dropout(k0, context_mean, rate, train)
    if config.num_initialize_layers == 1:
        memory = _dense(p["fc_a"], context_mean, dtype=dt)
        output = _dense(p["fc_b"], context_mean, dtype=dt)
    else:
        ta = _dense(p["fc_a1"], context_mean, activation="tanh", dtype=dt)
        tb = _dense(p["fc_b1"], context_mean, activation="tanh", dtype=dt)
        act = _l1(ta) + _l1(tb)  # pre-dropout, as in TF (activity attaches
        # to the dense layer's output; dropout is a separate later layer)
        if train:
            ta = _dropout(k1, ta, rate, train)
            tb = _dropout(k2, tb, rate, train)
        memory = _dense(p["fc_a2"], ta, dtype=dt)
        output = _dense(p["fc_b2"], tb, dtype=dt)
    state = DecoderState(memory=memory, output=output, recurrent=output)
    return (state, act) if with_activity else state


def attend(
    params: Params,
    config: Config,
    contexts: jnp.ndarray,
    output: jnp.ndarray,
    train: bool = False,
    rng: Optional[jax.Array] = None,
    with_activity: bool = False,
) -> jnp.ndarray:
    """Soft attention over the context grid → alpha [B, N]
    (reference attend, model.py:395-436).

    The inference path delegates to precompute_attend +
    attend_with_precomputed so there is exactly ONE implementation of the
    inference math (the hoisted one beam search uses); only the
    training/dropout path lives here.

    with_activity=True (static) additionally returns the L1 activity sum
    of the tanh layer outputs (see compute_loss)."""
    p = params["attend"]
    rate = config.fc_drop_rate
    dt = jnp.dtype(config.compute_dtype)
    if not train:
        proj = precompute_attend(params, config, contexts)
        _, alpha = attend_with_precomputed(params, config, contexts, proj, output)
        return (alpha, jnp.float32(0)) if with_activity else alpha
    kc, ko, kt = jax.random.split(rng, 3)
    contexts = _dropout(kc, contexts, rate, train)
    output = _dropout(ko, output, rate, train)
    act = jnp.float32(0)
    if config.num_attend_layers == 1:
        # ctx→1 per position (no bias) + position-specific h→N projection
        logits1 = _dense(p["fc_a"], contexts, dtype=dt)[..., 0]    # [B, N]
        logits2 = _dense(p["fc_b"], output, dtype=dt)              # [B, N]
        logits = logits1 + logits2
    else:
        t1 = _dense(p["fc_1a"], contexts, activation="tanh", dtype=dt)  # [B, N, da]
        t2 = _dense(p["fc_1b"], output, activation="tanh", dtype=dt)    # [B, da]
        # L1 activity sites: the tanh layer outputs, pre-dropout (the
        # reference attaches l1_regularizer only to activation≠None
        # layers, utils/nn.py:39-43 + model.py:417-429)
        act = _l1(t1) + _l1(t2)
        temp = t1 + t2[:, None, :]
        temp = _dropout(kt, temp, rate, train)
        logits = _dense(p["fc_2"], temp, dtype=dt)[..., 0]     # [B, N]
    alpha = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return (alpha, act) if with_activity else alpha


def precompute_attend(
    params: Params, config: Config, contexts: jnp.ndarray
) -> jnp.ndarray:
    """Hoist the context-only half of the attention MLP out of the decode
    loop.  The reference recomputes fc_{a,1a}(contexts) at every one of the
    T×beam steps (model.py:262,395-436) although contexts never change
    during decoding; at inference (no dropout) the term is loop-invariant.

    Returns the 1-layer per-position logits [B, N] or the 2-layer
    tanh-activated features [B, N, da].
    """
    p = params["attend"]
    dt = jnp.dtype(config.compute_dtype)
    if config.num_attend_layers == 1:
        return _dense(p["fc_a"], contexts, dtype=dt)[..., 0]       # [B, N]
    return _dense(p["fc_1a"], contexts, activation="tanh", dtype=dt)  # [B,N,da]


def attend_with_precomputed(
    params: Params,
    config: Config,
    contexts: jnp.ndarray,
    ctx_proj: jnp.ndarray,
    output: jnp.ndarray,
    row_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Inference-path attention using the hoisted ``ctx_proj``.

    Returns (context [B, D], alpha [B, N]).  With use_pallas_attention the
    2-layer combine runs as one fused Pallas kernel (add → matvec →
    softmax → weighted sum in a single VMEM residency).

    row_mask: optional [B] bool — slot-pool geometry (the stepped decode
    batches dead slots alongside live ones).  False rows get zero
    scores/alpha/context so stale slot state can never emit a NaN; True
    rows are bitwise identical to the unmasked call.  Masking is applied
    identically on the Pallas and XLA paths so the two stay comparable.
    """
    p = params["attend"]
    dt = jnp.dtype(config.compute_dtype)
    valid = None if row_mask is None else row_mask.reshape(-1, 1)   # [B, 1]
    if config.num_attend_layers == 1:
        logits = ctx_proj + _dense(p["fc_b"], output, dtype=dt)     # [B, N]
        if valid is not None:
            logits = jnp.where(valid, logits, 0.0)
        alpha = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        if valid is not None:
            alpha = jnp.where(valid, alpha, 0.0)
        context = (contexts * alpha[..., None]).sum(axis=1)
        if valid is not None:
            context = jnp.where(valid, context, 0.0)
        return context, alpha

    t2 = _dense(p["fc_1b"], output, activation="tanh", dtype=dt)    # [B, da]
    if config.use_pallas_attention:
        from ..ops import pallas_attention

        # Interpret mode is a test vehicle only — off TPU the XLA branch
        # below is the fast mathematically-identical fallback.
        if jax.default_backend() == "tpu" or pallas_attention.FORCE_INTERPRET:
            return pallas_attention.fused_attend(
                ctx_proj, t2, p["fc_2"]["kernel"], contexts,
                row_mask=row_mask,
                compute_dtype=config.compute_dtype,
                interpret=jax.default_backend() != "tpu",
            )
    temp = ctx_proj + t2[:, None, :]
    logits = _dense(p["fc_2"], temp, dtype=dt)[..., 0]              # [B, N]
    if valid is not None:
        logits = jnp.where(valid, logits, 0.0)
    alpha = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if valid is not None:
        alpha = jnp.where(valid, alpha, 0.0)
    context = (contexts * alpha[..., None]).sum(axis=1)
    if valid is not None:
        context = jnp.where(valid, context, 0.0)
    return context, alpha


def decode_logits(
    params: Params,
    config: Config,
    expanded_output: jnp.ndarray,
    train: bool = False,
    rng: Optional[jax.Array] = None,
    with_activity: bool = False,
) -> jnp.ndarray:
    """concat(output, context, word_embed) → vocab logits
    (reference decode, model.py:438-459).

    with_activity=True (static) returns (logits, L1 of the tanh output)."""
    p = params["decode"]
    rate = config.fc_drop_rate
    dt = jnp.dtype(config.compute_dtype)
    act = jnp.float32(0)
    if train:
        k0, k1 = jax.random.split(rng)
        expanded_output = _dropout(k0, expanded_output, rate, train)
    if config.num_decode_layers == 1:
        logits = _dense(p["fc"], expanded_output, dtype=dt)
        return (logits, act) if with_activity else logits
    temp = _dense(p["fc_1"], expanded_output, activation="tanh", dtype=dt)
    act = _l1(temp)
    if train:
        temp = _dropout(k1, temp, rate, train)
    logits = _dense(p["fc_2"], temp, dtype=dt)
    return (logits, act) if with_activity else logits


def decoder_step(
    params: Params,
    config: Config,
    contexts: jnp.ndarray,
    state: DecoderState,
    word: jnp.ndarray,
    train: bool = False,
    rng: Optional[jax.Array] = None,
    ctx_proj: Optional[jnp.ndarray] = None,
    with_activity: bool = False,
    row_mask: Optional[jnp.ndarray] = None,
) -> Tuple[DecoderState, jnp.ndarray, jnp.ndarray]:
    """One decoder step: attend → embed → LSTM → logits.

    Returns (new_state, logits [B, V], alpha [B, N]) — plus the step's L1
    activity sum when with_activity=True (static).  ``state.output`` must
    be the post-dropout h when training, matching the reference where the
    DropoutWrapper's output feeds the next attend (model.py:262,307).

    ctx_proj: hoisted :func:`precompute_attend` output — inference only
    (training's per-step context dropout invalidates it, so it is ignored
    when train=True).

    row_mask: optional [B] bool, forwarded to
    :func:`attend_with_precomputed` on the hoisted inference path (the
    stepped decode's dead-slot mask); ignored elsewhere — the monolithic
    path never sets it, so its programs are untouched.
    """
    if train:
        k_att, k_in, k_out, k_state, k_dec = jax.random.split(rng, 5)
    else:
        k_att = k_in = k_out = k_state = k_dec = None
    ldr = config.lstm_drop_rate
    act = jnp.float32(0)

    if ctx_proj is not None and not train:
        context, alpha = attend_with_precomputed(
            params, config, contexts, ctx_proj, state.output,
            row_mask=row_mask,
        )
    else:
        alpha = attend(
            params, config, contexts, state.output, train, k_att,
            with_activity=with_activity,
        )
        if with_activity:
            alpha, act = alpha
        context = (contexts * alpha[..., None]).sum(axis=1)      # [B, D]

    word_embed = params["word_embedding"]["weights"][word]        # [B, E]

    lstm_input = jnp.concatenate([context, word_embed], axis=-1)
    lstm_input = _dropout(k_in, lstm_input, ldr, train)
    new_c, new_h = lstm_step(
        params["lstm"], state.memory, state.recurrent, lstm_input,
        dtype=jnp.dtype(config.compute_dtype),
    )
    # DropoutWrapper: independent masks on emitted h and recurrent h; c exempt
    emitted = _dropout(k_out, new_h, ldr, train)
    recurrent_h = _dropout(k_state, new_h, ldr, train)

    expanded = jnp.concatenate([emitted, context, word_embed], axis=-1)
    logits = decode_logits(
        params, config, expanded, train, k_dec, with_activity=with_activity
    )
    new_state = DecoderState(memory=new_c, output=emitted, recurrent=recurrent_h)
    if with_activity:
        logits, dec_act = logits
        return new_state, logits, alpha, act + dec_act
    return new_state, logits, alpha


def teacher_forced_decode(
    params: Params,
    config: Config,
    contexts: jnp.ndarray,
    sentences: jnp.ndarray,
    train: bool = False,
    rng: Optional[jax.Array] = None,
    with_activity: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Full training-time unroll as one lax.scan.

    contexts [B, N, D]; sentences [B, T] int32.
    Returns (logits [B, T, V], alphas [B, T, N]) — plus the summed L1
    activity of every tanh layer output across init + all T steps when
    with_activity=True (static), matching the reference's unrolled graph
    where each step's dense layers contribute to REGULARIZATION_LOSSES.
    """
    B, T = sentences.shape
    if rng is None:
        if train:
            raise ValueError(
                "teacher_forced_decode(train=True) requires an rng; a fixed "
                "key would silently reuse identical dropout masks every step"
            )
        rng = jax.random.PRNGKey(0)  # never consumed when train=False
    k_init, k_steps = jax.random.split(rng)
    state = init_state(
        params, config, contexts, train, k_init, with_activity=with_activity
    )
    init_act = jnp.float32(0)
    if with_activity:
        state, init_act = state

    # input word at step t is sentences[:, t-1]; step 0 gets <start>=0
    words_in = jnp.concatenate(
        [jnp.zeros((B, 1), sentences.dtype), sentences[:, :-1]], axis=1
    )
    step_rngs = jax.random.split(k_steps, T)

    def body(state, xs):
        word_t, rng_t = xs
        out = decoder_step(
            params, config, contexts, state, word_t, train, rng_t,
            with_activity=with_activity,
        )
        if with_activity:
            state, logits, alpha, act = out
            return state, (logits, alpha, act)
        state, logits, alpha = out
        return state, (logits, alpha)

    if train and config.remat_decoder:
        # Rematerialize the step in backward: keep matmul outputs,
        # regenerate dropout masks / elementwise chains from rng_t instead
        # of stacking them as residuals across T steps.  Numerically
        # identical (same keys -> same masks); trades recompute for HBM
        # residual traffic.  prevent_cse off: scan bodies are not subject
        # to the CSE hazard checkpoint guards against.
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_saveable,
            prevent_cse=False,
        )

    _, ys = jax.lax.scan(body, state, (words_in.T, step_rngs))
    if with_activity:
        logits, alphas, acts = ys
        # scan stacks along time-major; restore batch-major
        return (
            logits.transpose(1, 0, 2),
            alphas.transpose(1, 0, 2),
            init_act + acts.sum(),
        )
    logits, alphas = ys
    return logits.transpose(1, 0, 2), alphas.transpose(1, 0, 2)
