"""METEOR 1.5 — native reimplementation (no JVM).

The reference wraps the external ``meteor-1.5.jar`` as a persistent Java
subprocess speaking a line protocol
(/root/reference/utils/coco/pycocoevalcap/meteor/meteor.py:15-58); the jar
itself is not even shipped (.MISSING_LARGE_BLOBS).  This module implements
METEOR 1.5 semantics (Denkowski & Lavie 2014, "Meteor Universal") directly
in Python with a C++-accelerated twin (see native/):

* the full 1.5 English matcher set and weights — exact 1.0, Porter-stem
  0.6, synonym 0.8, paraphrase phrase spans 0.6 — resolved JOINTLY the
  way the jar's beam aligner does: all matchers propose candidates and
  a beam search (width 40, the jar's default) selects the
  non-overlapping subset that maximizes covered words, then minimizes
  chunks, then minimizes summed start distance (Denkowski & Lavie 2014
  §3); pinned equal to an exhaustive brute-force resolver on
  adversarial permutation fixtures in tests/test_evalcap.py;
* the 1.5 scoring with the English rank-tuned parameters α=0.85, β=0.2,
  γ=0.6, δ=0.75: content/function-word-discounted weighted precision and
  recall, Fmean = P·R/(α·P+(1−α)·R), fragmentation penalty
  γ·(chunks/matches)^β applied only when the alignment has more than one
  chunk (so an exact hypothesis scores exactly 1.0, matching the jar's
  behavior on identical inputs);
* multi-reference: score against every reference, keep the max (jar
  behavior).

Known divergences from the jar, quantified in tests/test_evalcap.py:
* the synonym and paraphrase stages use the compact bundled tables in
  meteor_data.py instead of full WordNet / the ~80MB pivoting-derived
  paraphrase table (both unavailable offline; the reference never
  shipped them either — its jar is a missing large blob), and the
  function-word list is curated rather than frequency-derived.  Pairs
  outside those tables fall back to exact/stem matching, which biases
  those segments LOW relative to the jar; but curated entries the jar's
  pivot-derived table happens to lack (e.g. 'lake'~'pond') award credit
  the jar would not, so individual segments can also bias HIGH — the
  divergence is bounded, not one-sided.  Measured bound
  (tests/test_evalcap.py::TestMeteorGoldenFixtures): the tables move a
  single segment by at most ≈0.69 (a short all-synonym-linked segment),
  and the mean of a deliberately stage-exercising 12-pair corpus by
  ≈0.29; real caption corpora sit far below both since most matches are
  exact/stem.  The scoring formula itself is pinned to the published
  METEOR 1.5 equations by hand-derived golden fixtures in that same
  test class, on both backends.
* candidate pruning (accepted deviation, ADVICE r04): ``_candidates``
  drops two paraphrase-candidate classes the jar's matcher stage may
  generate — 1×1 paraphrase spans duplicating a word match, and
  identical phrase spans (same words both sides).  1×1 duplicates are
  strictly dominated (same coverage, same chunk/distance geometry,
  never more weight).  Identical phrase spans are NOT: a span pays one
  start-distance where its word matches pay one per word, so it can win
  the distance tiebreak at lower total match weight — i.e. a resolver
  fed the unpruned set can return a lower-scoring alignment (measured:
  'a man and a man' vs 'a man a man and', weight 3.4 vs 5.0 at equal
  coverage and chunks, via the table phrase 'a man').  Production
  prunes the span and keeps the higher-scoring word-match alignment;
  whether the jar's paraphrase matcher even proposes identical spans is
  not verifiable offline (the jar is a missing blob in the reference
  and the environment has no egress), so the pruning is pinned as a
  directional guarantee instead: coverage and chunks are always
  identical to the unpruned optimum and the score is never lower
  (tests/test_evalcap.py::TestMeteorAlignmentResolution::
  test_candidate_pruning_never_lowers_the_score, with the divergent
  fixture pinned exactly in
  test_identical_span_pruning_changes_resolution_as_documented).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .meteor_data import (
    FUNCTION_WORDS,
    MAX_PARAPHRASE_LEN,
    build_paraphrase_index,
    build_synonym_index,
)

# METEOR 1.5 English (rank-tuned) parameters — Denkowski & Lavie 2014,
# Table 1 (the jar's `-l en` defaults, reference meteor.py:18-19).
ALPHA = 0.85
BETA = 0.2
GAMMA = 0.6
DELTA = 0.75

EXACT_WEIGHT = 1.0
STEM_WEIGHT = 0.6
SYNONYM_WEIGHT = 0.8
PARAPHRASE_WEIGHT = 0.6

_stemmer = None
_syn_index: Optional[Dict[str, Set[int]]] = None
_para_index: Optional[Dict[str, Set[int]]] = None


from functools import lru_cache


@lru_cache(maxsize=65536)  # corpora re-stem the same caption vocabulary
def _stem(word: str) -> str:
    global _stemmer
    if _stemmer is None:
        try:
            from nltk.stem.porter import PorterStemmer

            # ORIGINAL_ALGORITHM: bit-for-bit the published Porter (1980)
            # steps, which is what the C++ aligner implements — keeps the
            # native and Python scorers in exact agreement.
            _stemmer = PorterStemmer(mode="ORIGINAL_ALGORITHM")
        except Exception:  # pragma: no cover - nltk is baked into the image
            _stemmer = False
    if _stemmer:
        return _stemmer.stem(word)
    return word


def _synonyms() -> Dict[str, Set[int]]:
    global _syn_index
    if _syn_index is None:
        _syn_index = build_synonym_index()
    return _syn_index


def _paraphrases() -> Dict[str, Set[int]]:
    global _para_index
    if _para_index is None:
        _para_index = build_paraphrase_index()
    return _para_index


# Beam width of the alignment resolution — the jar's default
# (Aligner.java's beamSize); at caption lengths the beam is effectively
# exhaustive (pinned against a brute-force oracle in tests).
ALIGN_BEAM = 40


def _candidates(hyp: Sequence[str], ref: Sequence[str]):
    """All matcher-generated candidate matches, jointly.

    Returns ``(word_cands, span_cands)`` where ``word_cands[i]`` is a list
    of ``(j, weight)`` single-word matches for hyp position i (weight from
    the highest-precedence applicable matcher: exact 1.0, stem 0.6,
    synonym 0.8 — matcher precedence, not weight order, mirroring the
    jar's module order) and ``span_cands[i]`` lists paraphrase phrase
    matches ``(L, j, M)`` starting at hyp position i (hyp span length L,
    ref start j, ref span length M, weight 0.6).
    """
    syn = _synonyms()
    para = _paraphrases()
    word_cands: List[List[Tuple[int, float]]] = [[] for _ in hyp]
    for i, h in enumerate(hyp):
        h_stem = _stem(h)
        h_gids = syn.get(h)
        for j, r in enumerate(ref):
            if h == r:
                word_cands[i].append((j, EXACT_WEIGHT))
            elif h_stem == _stem(r):
                word_cands[i].append((j, STEM_WEIGHT))
            elif h_gids and syn.get(r) and (h_gids & syn[r]):
                word_cands[i].append((j, SYNONYM_WEIGHT))

    span_cands: List[List[Tuple[int, int, int]]] = [[] for _ in hyp]
    ref_spans: Dict[int, List[Tuple[int, int]]] = {}  # gid -> [(j, M)]
    for M in range(1, MAX_PARAPHRASE_LEN + 1):
        for j in range(0, len(ref) - M + 1):
            for gid in para.get(" ".join(ref[j:j + M]), ()):
                ref_spans.setdefault(gid, []).append((j, M))
    for L in range(1, MAX_PARAPHRASE_LEN + 1):
        for i in range(0, len(hyp) - L + 1):
            gids = para.get(" ".join(hyp[i:i + L]))
            if not gids:
                continue
            seen: Set[Tuple[int, int]] = set()
            for gid in gids:
                for j, M in ref_spans.get(gid, ()):
                    if (j, M) in seen:
                        continue
                    # a 1×1 phrase match duplicating a word match adds no
                    # coverage and never more weight — drop it
                    if L == 1 and M == 1 and any(
                        cj == j for cj, _ in word_cands[i]
                    ):
                        continue
                    # identical phrases are fully served by exact word
                    # matches at weight 1.0; a 0.6 phrase match for the
                    # same string could only displace them (its single
                    # start-distance beats their per-word sum in the
                    # distance tiebreak) and lower the score
                    if L == M and list(hyp[i:i + L]) == list(ref[j:j + M]):
                        continue
                    seen.add((j, M))
                    span_cands[i].append((L, j, M))
    return word_cands, span_cands


def align(
    hyp: Sequence[str], ref: Sequence[str]
) -> Tuple[List[Tuple[int, int, float]], Dict[int, float], Dict[int, float]]:
    """Alignment resolution over all matcher candidates, beam-searched.

    METEOR 1.5's aligner does not consume words stage by stage: every
    matcher (exact / stem / synonym / paraphrase) proposes candidate
    matches and the aligner selects the non-overlapping subset that, in
    order of importance, (1) maximizes covered words across both
    sentences, (2) minimizes the number of chunks, (3) minimizes the sum
    of |hyp_start - ref_start| distances (Denkowski & Lavie 2014 §3;
    the jar's Aligner.resolve).  This beam search reproduces those
    semantics (width ALIGN_BEAM, exhaustive at caption lengths — pinned
    against a brute-force oracle in tests/test_evalcap.py, which rounds
    2-3 shipped as a greedy stand-in that over-fragmented permuted
    sentences, VERDICT r03 weak #5).

    Returns ``(pairs, hyp_matched, ref_matched)``: ``pairs`` are
    (hyp_idx, ref_idx, weight) word pairings used for chunk counting
    (paraphrase spans zip min(L, M) internally-monotone pairs); the two
    dicts map matched word index → match weight per side (they diverge
    from the pair list only for paraphrase span matches, whose sides may
    cover different word counts).
    """
    word_cands, span_cands = _candidates(hyp, ref)

    # state: (covered, chunks, dist, -weight) lexicographic score plus
    # (ref_mask, last_i, last_j, pairs, hyp_cov, ref_cov); smaller sort
    # key is better
    start = (0, 0, 0, 0.0, 0, -2, -2, (), (), ())
    pools: Dict[int, List] = {0: [start]}

    def key(st):
        covered, chunks, dist, weight = st[0], st[1], st[2], st[3]
        # hcov/rcov in the tiebreak: two optima can have identical pairs
        # but different per-side coverage (a 2→1 vs a 1→2 paraphrase span
        # anchored at the same positions), which changes P/R — without
        # them the winner would be insertion-order luck and the C++ twin
        # could disagree
        return (-covered, chunks, dist, -weight, st[7], st[8], st[9])

    for pos in range(len(hyp)):
        pool = pools.pop(pos, None)
        if not pool:
            continue
        # dedup on (ref_mask, run tail): states identical there extend
        # identically, keep the best-scored representative
        best_by: Dict[Tuple[int, int, int], tuple] = {}
        for st in pool:
            k = (st[4], st[5], st[6])
            if k not in best_by or key(st) < key(best_by[k]):
                best_by[k] = st
        pool = sorted(best_by.values(), key=key)[:ALIGN_BEAM]

        for st in pool:
            (covered, chunks, dist, weight, mask, li, lj,
             pairs, hcov, rcov) = st
            # option: leave hyp word `pos` uncovered
            pools.setdefault(pos + 1, []).append(st)
            for j, w in word_cands[pos]:
                if mask & (1 << j):
                    continue
                adj = pos == li + 1 and j == lj + 1
                pools.setdefault(pos + 1, []).append((
                    covered + 2, chunks + (0 if adj else 1),
                    dist + abs(pos - j), weight + w,
                    mask | (1 << j), pos, j,
                    pairs + ((pos, j, w),),
                    hcov + ((pos, w),), rcov + ((j, w),),
                ))
            for L, j, M in span_cands[pos]:
                span_mask = ((1 << M) - 1) << j
                if mask & span_mask:
                    continue
                z = min(L, M)
                adj = pos == li + 1 and j == lj + 1
                pools.setdefault(pos + L, []).append((
                    covered + L + M, chunks + (0 if adj else 1),
                    dist + abs(pos - j), weight + z * PARAPHRASE_WEIGHT,
                    mask | span_mask, pos + z - 1, j + z - 1,
                    pairs + tuple(
                        (pos + k, j + k, PARAPHRASE_WEIGHT) for k in range(z)
                    ),
                    hcov + tuple(
                        (pos + k, PARAPHRASE_WEIGHT) for k in range(L)
                    ),
                    rcov + tuple(
                        (j + k, PARAPHRASE_WEIGHT) for k in range(M)
                    ),
                ))

    finals = pools.get(len(hyp), [start])
    best = min(finals, key=key)
    return sorted(best[7]), dict(best[8]), dict(best[9])


def _chunks(matches: List[Tuple[int, int, float]]) -> int:
    """Number of maximal runs adjacent in both hyp and ref order."""
    if not matches:
        return 0
    chunks = 1
    for (i0, j0, _), (i1, j1, _) in zip(matches, matches[1:]):
        if not (i1 == i0 + 1 and j1 == j0 + 1):
            chunks += 1
    return chunks


def _weighted_split(
    words: Sequence[str], matched: Dict[int, float]
) -> Tuple[float, float]:
    """(Σ w over matched content words, Σ w over matched function words)."""
    wc = wf = 0.0
    for idx, w in matched.items():
        if words[idx] in FUNCTION_WORDS:
            wf += w
        else:
            wc += w
    return wc, wf


def _side_score(words: Sequence[str], matched: Dict[int, float]) -> float:
    """δ-discounted weighted match fraction for one side (P or R)."""
    n_f = sum(1 for w in words if w in FUNCTION_WORDS)
    n_c = len(words) - n_f
    denom = DELTA * n_c + (1.0 - DELTA) * n_f
    if denom == 0:
        return 0.0
    wc, wf = _weighted_split(words, matched)
    return (DELTA * wc + (1.0 - DELTA) * wf) / denom


def segment_stats(hypothesis: str, reference: str) -> Dict[str, float]:
    hyp, ref = hypothesis.split(), reference.split()
    pairs, hyp_matched, ref_matched = align(hyp, ref)
    # m for the fragmentation penalty: average matched-word count over the
    # two sides (METEOR 1.5; equals len(pairs) for word-level stages, and
    # generalizes to paraphrase spans covering unequal word counts)
    m = (len(hyp_matched) + len(ref_matched)) / 2.0
    return {
        "matches": m,
        "chunks": float(_chunks(pairs)),
        "p": _side_score(hyp, hyp_matched),
        "r": _side_score(ref, ref_matched),
        "len_h": float(len(hyp)),
        "len_r": float(len(ref)),
    }


def score_from_stats(s: Dict[str, float]) -> float:
    if s["matches"] == 0 or s["len_h"] == 0 or s["len_r"] == 0:
        return 0.0
    p, r = s["p"], s["r"]
    if p == 0 or r == 0:
        return 0.0
    fmean = (p * r) / (ALPHA * p + (1 - ALPHA) * r)
    # single-chunk alignments carry no fragmentation penalty (jar
    # behavior: identical sentences score exactly 1.0)
    if s["chunks"] <= 1:
        return fmean
    penalty = GAMMA * ((s["chunks"] / s["matches"]) ** BETA)
    return fmean * (1.0 - penalty)


def meteor_single(hypothesis: str, references: List[str]) -> float:
    from .. import native

    # The C++ scorer is ASCII/lowercase (like its Porter stage) and its
    # reference coverage mask caps at 128 words (kMaxRefWords); anything
    # else goes through the Python twin so backends always agree.
    ascii_ok = hypothesis.isascii() and all(r.isascii() for r in references)
    short_ok = all(len(r.split()) <= 128 for r in references)
    if ascii_ok and short_ok and native.available():
        return native.meteor_multi(hypothesis, list(references))
    return max(score_from_stats(segment_stats(hypothesis, r)) for r in references)


class Meteor:
    def compute_score(self, gts: Dict, res: Dict) -> Tuple[float, np.ndarray]:
        assert sorted(gts.keys()) == sorted(res.keys())
        scores = [meteor_single(res[i][0], gts[i]) for i in sorted(gts.keys())]
        return float(np.mean(scores)), np.array(scores)

    def method(self) -> str:
        return "METEOR"
