"""Mesh-parallel train and decode steps.

One jitted program per step, exactly like the single-chip path
(sat_tpu/train/step.py) — parallelism enters ONLY through shardings:
the batch arrives split over 'data', vocab-dim parameters split over
'model', and XLA compiles in the gradient all-reduce / softmax
collectives.  This replaces the reference's asynchronous PS loop
(/root/reference/main_distributed.py:57-79) with synchronous SPMD.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh

from ..config import Config
from ..models.captioner import encode
from ..ops.beam_search import BeamResult, beam_search
from ..train.step import TrainState, create_train_state, make_train_step
from .sharding import (
    batch_sharding,
    replicated,
    shard_train_state,
    train_state_shardings,
)


def _abstract_state(config: Config) -> TrainState:
    rng = jax.random.PRNGKey(0)
    return jax.eval_shape(lambda r: create_train_state(r, config), rng)


def create_parallel_train_state(
    rng: jax.Array, config: Config, mesh: Mesh
) -> TrainState:
    """Initialize and place the train state onto the mesh."""
    return shard_train_state(create_train_state(rng, config), config, mesh)


def make_parallel_train_step(
    config: Config, mesh: Mesh
) -> Callable[[TrainState, Dict[str, Any], jax.Array], Tuple[TrainState, Dict[str, Any]]]:
    """Jitted (state, batch, rng) -> (state, metrics) with mesh shardings.

    Batch dim 0 must be divisible by the data-axis size; metrics come out
    replicated (already globally reduced — the loss normalizes by the
    GLOBAL mask sum, so no host-side averaging is needed)."""
    state_sh = train_state_shardings(_abstract_state(config), config, mesh)
    batch_sh = batch_sharding(mesh)
    repl = replicated(mesh)

    return jax.jit(
        make_train_step(config),
        in_shardings=(state_sh, batch_sh, repl),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


def make_parallel_beam_search(
    config: Config,
    mesh: Mesh,
    eos_id: int,
    beam_size: Optional[int] = None,
    valid_size: Optional[int] = None,
    return_alphas: bool = False,
) -> Callable[[Dict[str, Any], Any], BeamResult]:
    """Jitted (variables, images) -> BeamResult, batch sharded over 'data'.

    Encoder + full on-device beam search in one program; every data-mesh
    row decodes its image shard, results come back batch-sharded.
    valid_size: real vocabulary entry count (see ops.beam_search) — pass
    len(vocabulary.words) whenever the vocabulary may have shrunk below
    config.vocabulary_size."""
    K = beam_size or config.beam_size

    def caption(variables: Dict[str, Any], images) -> BeamResult:
        contexts, _ = encode(variables, config, images, train=False)
        return beam_search(
            variables["params"]["decoder"], config, contexts, eos_id,
            beam_size=K, valid_size=valid_size, return_alphas=return_alphas,
        )

    out_sh = batch_sharding(mesh)
    return jax.jit(
        caption,
        in_shardings=(None, out_sh),
        out_shardings=BeamResult(
            words=out_sh, log_scores=out_sh, lengths=out_sh,
            alphas=out_sh if return_alphas else None,
        ),
    )
