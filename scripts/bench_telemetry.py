"""Telemetry cost accounting: instrumented-vs-bare step-loop overhead.

docs/OBSERVABILITY.md claims the telemetry layer is cheap enough to leave
on for every step of every run (≤ 0.5% of step time), and free when off.
This bench puts numbers on both claims without jax — the instrumentation
is pure host work, so a synthetic step loop that performs exactly the
per-step telemetry call sequence the train loop performs (one data-wait
record, one dispatch span, one step gauge, one step record; plus the
log-boundary extras every ``log_every`` steps) measures the same cost the
real loop pays:

* ``off``: the call sequence against the null implementation — what every
  *uninstrumented* run pays for the hooks existing at all.
* ``on``: the same sequence against a live ring-buffer recorder.
* ``export``: one Chrome-trace + breakdown export of the recorded run
  (end-of-run cost, never on the hot path — reported, not gated).

Prints BENCH-contract JSON lines on stdout ({"metric", "value", "unit",
"vs_baseline", ...extras}).  ``value`` is the telemetry-on hot-path
overhead in percent of a ``--step-ms`` device step (0.5 is the acceptance
bar).  No jax import anywhere: this must run on a host with no
accelerator backend at all.

Usage: python scripts/bench_telemetry.py [--step-ms 30] [--iters 50000]
       [--log-every 10] [--workdir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sat_tpu import telemetry
from sat_tpu.telemetry import exporters

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_telemetry +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _step_sequence(tel, iters: int, log_every: int) -> float:
    """Run the train loop's per-step telemetry call sequence ``iters``
    times against ``tel``; returns seconds per step.

    Mirrors runtime.train: a data-wait record (what ``_timed_iter`` does),
    the dispatch span, the step gauge, the whole-step record, and — every
    ``log_every`` steps — the log-sync span the metrics fetch rides in.
    """
    t_start = time.perf_counter()
    step_t0 = time.perf_counter_ns()
    for step in range(iters):
        t0 = time.perf_counter_ns()
        tel.record("train/data_wait", t0, time.perf_counter_ns() - t0)
        with tel.span("train/dispatch"):
            pass
        tel.gauge("train/step", step)
        if step % log_every == 0:
            with tel.span("train/log_sync"):
                pass
        now = time.perf_counter_ns()
        tel.record("train/step", step_t0, now - step_t0)
        step_t0 = now
    return (time.perf_counter() - t_start) / iters


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--step-ms", type=float, default=30.0,
                    help="device step time the overhead is judged against")
    ap.add_argument("--iters", type=int, default=50000,
                    help="synthetic steps per measurement")
    ap.add_argument("--log-every", type=int, default=10,
                    help="log-boundary cadence, as in Config.log_every")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_telemetry_")
    made_workdir = args.workdir is None
    try:
        # warm both paths once (interning, allocator) before timing
        telemetry.disable()
        _step_sequence(telemetry.get(), 1000, args.log_every)
        off_s = _step_sequence(telemetry.get(), args.iters, args.log_every)

        tel = telemetry.enable(capacity=65536)
        _step_sequence(tel, 1000, args.log_every)
        tel = telemetry.enable(capacity=65536)  # fresh buffers for the run
        on_s = _step_sequence(tel, args.iters, args.log_every)
        telemetry.disable()

        off_us, on_us = off_s * 1e6, on_s * 1e6
        overhead_pct = 100.0 * (on_us / 1e3) / args.step_ms
        log(f"per-step telemetry: off {off_us:.3f} us, on {on_us:.3f} us "
            f"-> {overhead_pct:.4f}% of a {args.step_ms:.0f} ms step")

        # end-of-run export cost (never on the hot path)
        t0 = time.perf_counter()
        trace_path = exporters.export_chrome_trace(
            tel, os.path.join(workdir, "trace.json"))
        report = exporters.step_breakdown(
            tel, "train/step",
            ("train/data_wait", "train/dispatch", "train/log_sync"))
        assert trace_path and report is not None
        assert report["steps"] == args.iters
        export_ms = 1e3 * (time.perf_counter() - t0)
        log(f"end-of-run export (trace + breakdown): {export_ms:.1f} ms "
            f"for {args.iters} steps")

        result = {
            "metric": "telemetry_hot_path_overhead",
            "value": round(overhead_pct, 4),
            "unit": "%_of_step",
            "vs_baseline": 0.5,  # the acceptance bar (ISSUE: <= 0.5%)
            "telemetry_on_us_per_step": round(on_us, 3),
            "telemetry_off_us_per_step": round(off_us, 3),
            "step_ms_assumed": args.step_ms,
            "log_every": args.log_every,
            "ring_capacity": tel._capacity,
            "export_ms": round(export_ms, 1),
            **telemetry.bench_stamp(),
        }
        print(json.dumps(result), flush=True)
        return 0 if overhead_pct <= 0.5 else 1
    finally:
        if made_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
