// METEOR segment scorer — native replacement for the reference's
// persistent meteor-1.5.jar subprocess (/root/reference/utils/coco/
// pycocoevalcap/meteor/meteor.py:15-58).
//
// Mirror of the Python implementation in sat_tpu/evalcap/meteor.py
// (golden-tested against it): stage-wise greedy alignment — exact match
// (weight 1.0) then Porter-stem match (weight 0.6) with
// nearest-occurrence pairing — and classic METEOR scoring with α=0.9,
// β=3, γ=0.5 fragmentation penalty; multi-reference takes the max.

#include <algorithm>
#include <cmath>
#include <cstdlib>
#include <map>
#include <string>
#include <vector>

namespace sat_native {

std::string porter_stem(const std::string& input);

namespace {

constexpr double kAlpha = 0.9;
constexpr double kBeta = 3.0;
constexpr double kGamma = 0.5;
constexpr double kExactWeight = 1.0;
constexpr double kStemWeight = 0.6;

std::vector<std::string> split_ws(const std::string& s) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < s.size()) {
    while (i < s.size() && s[i] == ' ') i++;
    size_t start = i;
    while (i < s.size() && s[i] != ' ') i++;
    if (i > start) out.push_back(s.substr(start, i - start));
  }
  return out;
}

struct Match {
  int hyp_idx;
  int ref_idx;
  double weight;
};

void run_stage(const std::vector<std::string>& hyp_keys,
               const std::vector<std::string>& ref_keys,
               std::vector<bool>* hyp_used, std::vector<bool>* ref_used,
               double weight, std::vector<Match>* matches) {
  std::map<std::string, std::vector<int>> ref_slots;
  for (int j = 0; j < static_cast<int>(ref_keys.size()); j++) {
    if (!(*ref_used)[j]) ref_slots[ref_keys[j]].push_back(j);
  }
  for (int i = 0; i < static_cast<int>(hyp_keys.size()); i++) {
    if ((*hyp_used)[i]) continue;
    auto it = ref_slots.find(hyp_keys[i]);
    if (it == ref_slots.end() || it->second.empty()) continue;
    // nearest remaining reference occurrence to position i
    auto& slots = it->second;
    auto best = std::min_element(
        slots.begin(), slots.end(),
        [i](int a, int b) { return std::abs(a - i) < std::abs(b - i); });
    int j = *best;
    slots.erase(best);
    (*hyp_used)[i] = true;
    (*ref_used)[j] = true;
    matches->push_back({i, j, weight});
  }
}

}  // namespace

double meteor_segment(const std::string& hypothesis,
                      const std::string& reference) {
  std::vector<std::string> hyp = split_ws(hypothesis);
  std::vector<std::string> ref = split_ws(reference);
  if (hyp.empty() || ref.empty()) return 0.0;

  std::vector<bool> hyp_used(hyp.size(), false), ref_used(ref.size(), false);
  std::vector<Match> matches;
  run_stage(hyp, ref, &hyp_used, &ref_used, kExactWeight, &matches);

  std::vector<std::string> hyp_stems(hyp.size()), ref_stems(ref.size());
  for (size_t i = 0; i < hyp.size(); i++) hyp_stems[i] = porter_stem(hyp[i]);
  for (size_t j = 0; j < ref.size(); j++) ref_stems[j] = porter_stem(ref[j]);
  run_stage(hyp_stems, ref_stems, &hyp_used, &ref_used, kStemWeight,
            &matches);

  if (matches.empty()) return 0.0;
  std::sort(matches.begin(), matches.end(),
            [](const Match& a, const Match& b) {
              return a.hyp_idx != b.hyp_idx ? a.hyp_idx < b.hyp_idx
                                            : a.ref_idx < b.ref_idx;
            });

  double weighted = 0.0;
  for (const auto& m : matches) weighted += m.weight;
  int chunks = 1;
  for (size_t k = 1; k < matches.size(); k++) {
    if (!(matches[k].hyp_idx == matches[k - 1].hyp_idx + 1 &&
          matches[k].ref_idx == matches[k - 1].ref_idx + 1)) {
      chunks++;
    }
  }

  double p = weighted / hyp.size();
  double r = weighted / ref.size();
  if (p == 0.0 || r == 0.0) return 0.0;
  double fmean = (p * r) / (kAlpha * p + (1.0 - kAlpha) * r);
  double frag = static_cast<double>(chunks) / matches.size();
  double penalty = kGamma * std::pow(frag, kBeta);
  return fmean * (1.0 - penalty);
}

}  // namespace sat_native
