"""Replay a captured quality exemplar through a freshly booted engine.

The exemplar flight recorder (sat_tpu/telemetry/exemplar.py) stores, for
each outlier request, the raw image bytes plus the caption the serving
stack produced and a ``meta.json`` describing exactly which model
produced it (full config snapshot, checkpoint step, vocabulary
fingerprint).  This script is the other half of that contract: boot the
SAME engine headless — no HTTP, no batcher, just the AOT encode+beam
pair — push the stored bytes back through ``preprocess → dispatch →
decode``, and assert the caption comes back **bitwise identical**.

That assertion is the debugging fork for every captured outlier:

* replay matches → the model really says that about this image; the
  outlier is a model/data problem (follow the drift runbook in
  docs/OBSERVABILITY.md).
* replay differs → serving infrastructure produced a caption the model
  alone does not reproduce — a nondeterminism bug worth paging on.

Scores are compared informationally, not asserted: an exemplar captured
under the SAT_FI_QUALITY_SKEW fault point (or any score-space fault) has
shifted log-probs by design while its token sequence — and therefore the
caption text — must still replay exactly.

``--diff A B`` mode replays the exemplar through two checkpoints instead
and reports their caption divergence (telemetry.quality's token-Jaccard,
the same score the lifecycle canary gates on) — "did the new model stop
saying this" as a one-command answer.

Usage:
  python scripts/replay_exemplar.py --dir DIR                # newest exemplar
  python scripts/replay_exemplar.py --dir DIR --index 3      # specific row
  python scripts/replay_exemplar.py --dir DIR --request-id R # by trace id
  python scripts/replay_exemplar.py --dir DIR --all          # every replayable row
  python scripts/replay_exemplar.py --dir DIR --diff OLD.npz NEW.npz

Exit codes: 0 replayed bitwise (or --diff ran), 1 caption mismatch,
2 usage / missing data (no meta, image evicted, checkpoint unloadable).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
    print(f"[replay_exemplar] {msg}", file=sys.stderr, flush=True)


def _boot_engine(config, model_file: Optional[str]):
    """Config snapshot → warmed ServeEngine, exactly the server's boot
    path (lineage-verified load unless --model pins a file)."""
    from sat_tpu.data.vocabulary import Vocabulary
    from sat_tpu.serve.engine import ServeEngine, load_serving_state

    vocabulary = Vocabulary(config.vocabulary_size, config.vocabulary_file)
    state, source = load_serving_state(config, model_file=model_file)
    engine = ServeEngine(config, state, vocabulary)
    log(f"params from {source} (step {engine.step}); warming bucket ladder")
    engine.warmup()
    return engine


def _caption_once(engine, image_bytes: bytes) -> Dict:
    """One headless request: bytes → {caption, beams, alphas_digest}."""
    from sat_tpu.telemetry.exemplar import alphas_digest

    row = engine.preprocess(image_bytes)
    batch, _bucket = engine.pad_batch([row])
    out = engine.dispatch(batch)
    words, lengths, scores, alphas = engine.drain_output(out, 1)
    results = engine.detok_rows((words, lengths, scores), 1)
    captions = results[0]["captions"]
    return {
        "caption": captions[0]["caption"],
        "beams": captions,
        "alphas_digest": (
            alphas_digest(alphas[0]) if alphas is not None else None
        ),
    }


def _pick_rows(rows: List[Dict], args) -> List[Dict]:
    replayable = [r for r in rows if r.get("image")]
    if args.request_id:
        picked = [
            r for r in replayable if r.get("request_id") == args.request_id
        ]
        if not picked:
            log(f"no replayable exemplar with request_id={args.request_id!r}")
            sys.exit(2)
        return picked
    if args.index is not None:
        if not (0 <= args.index < len(rows)):
            log(f"--index {args.index} out of range (have {len(rows)} rows)")
            sys.exit(2)
        row = rows[args.index]
        if not row.get("image"):
            log(
                f"exemplar {args.index} has no stored image "
                f"(over the size cap or evicted; image_bytes="
                f"{row.get('image_bytes')})"
            )
            sys.exit(2)
        return [row]
    if args.all:
        return replayable
    if not replayable:
        log("no replayable exemplars (no rows with a stored image)")
        sys.exit(2)
    return [replayable[-1]]  # newest: rows arrive sorted by t_unix


def _replay_one(engine, dir: str, row: Dict) -> bool:
    """Replay one exemplar; True when the caption matched bitwise."""
    from sat_tpu.telemetry.exemplar import load_image

    image = load_image(dir, row)
    if image is None:
        log(f"image {row.get('image')} missing (evicted?) — skipping")
        return False
    got = _caption_once(engine, image)
    want = row.get("caption", "")
    rid = row.get("request_id", "") or "<no id>"
    match = got["caption"] == want
    verdict = "BITWISE MATCH" if match else "MISMATCH"
    print(
        json.dumps(
            {
                "request_id": rid,
                "reasons": row.get("reasons", []),
                "verdict": verdict,
                "captured": want,
                "replayed": got["caption"],
                # informational: scores may legitimately differ (score-space
                # fault injection at capture time); alphas digests may differ
                # across serve modes (fused-window vs monolithic decode)
                "alphas_digest_captured": row.get("alphas_digest"),
                "alphas_digest_replayed": got["alphas_digest"],
            },
            sort_keys=True,
        ),
        flush=True,
    )
    return match


def _run_diff(config, rows: List[Dict], dir: str, files: List[str]) -> int:
    from sat_tpu.telemetry.exemplar import load_image
    from sat_tpu.telemetry.quality import caption_divergence

    engines = [_boot_engine(config, f) for f in files]
    for row in rows:
        image = load_image(dir, row)
        if image is None:
            log(f"image {row.get('image')} missing — skipping")
            continue
        a = _caption_once(engines[0], image)["caption"]
        b = _caption_once(engines[1], image)["caption"]
        print(
            json.dumps(
                {
                    "request_id": row.get("request_id", ""),
                    "captured": row.get("caption", ""),
                    "old": a,
                    "new": b,
                    "divergence": round(caption_divergence(a, b), 4),
                },
                sort_keys=True,
            ),
            flush=True,
        )
    return 0


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Replay captured quality exemplars bitwise"
    )
    parser.add_argument("--dir", required=True, help="exemplar directory")
    parser.add_argument("--model", default=None, help="override checkpoint file")
    parser.add_argument("--index", type=int, default=None)
    parser.add_argument("--request-id", default=None)
    parser.add_argument("--all", action="store_true")
    parser.add_argument(
        "--diff",
        nargs=2,
        metavar=("OLD", "NEW"),
        default=None,
        help="replay through two checkpoints and report caption divergence",
    )
    args = parser.parse_args()

    from sat_tpu.config import Config
    from sat_tpu.telemetry.exemplar import read_exemplars, read_meta
    from sat_tpu.utils.summary import crc32c

    meta = read_meta(args.dir)
    if not meta or "config" not in meta:
        log(f"no usable meta.json in {args.dir} — cannot rebuild the engine")
        return 2
    config = Config.from_dict(meta["config"])
    rows, torn = read_exemplars(args.dir)
    if torn:
        log(f"skipped {torn} torn exemplar line(s)")
    if not rows:
        log("no exemplars recorded")
        return 2
    picked = _pick_rows(rows, args)

    if args.diff:
        return _run_diff(config, picked, args.dir, list(args.diff))

    engine = _boot_engine(config, args.model)
    want_crc = meta.get("vocab_crc32c")
    have_crc = "%08x" % crc32c(
        "\n".join(engine.vocabulary.words).encode("utf-8")
    )
    if want_crc and want_crc != have_crc:
        log(
            f"vocabulary fingerprint mismatch (meta {want_crc} vs loaded "
            f"{have_crc}) — captions cannot replay bitwise"
        )
        return 2
    if meta.get("model_step") is not None and engine.step != meta["model_step"]:
        log(
            f"WARNING: replaying against step {engine.step}, exemplars were "
            f"captured at step {meta['model_step']} (pass --model to pin)"
        )
    results = [_replay_one(engine, args.dir, row) for row in picked]
    ok = sum(results)
    log(f"{ok}/{len(results)} exemplar(s) replayed bitwise")
    return 0 if ok == len(results) and results else 1


if __name__ == "__main__":
    sys.exit(main())
