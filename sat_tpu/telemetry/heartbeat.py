"""Run-health heartbeat: an atomically replaced JSON file watchers can poll.

A TPU run on preemptible capacity is usually observed from the *outside*
— a supervisor shell (``scripts/tpu_retry.sh``-style), a bench
orchestrator, a human with ``watch jq``.  Log files answer "what
happened"; the heartbeat answers "is it alive RIGHT NOW and how fast":
one small JSON object (``heartbeat.json``), rewritten in place with
tmp+rename every ``interval_s`` seconds by a daemon thread, holding

* liveness: ``seq`` (monotone write counter), ``time_unix``, ``pid``;
* progress: ``step``, ``epoch``, ``steps_per_s`` (measured between
  heartbeat ticks, not cumulative — a stall shows up within one tick);
* recoverability: ``last_checkpoint_step`` and ``last_checkpoint_age_s``
  (how much work a preemption right now would lose);
* environment: ``backend``, ``rss_mb``, compile count/seconds (fed by the
  ``jax.monitoring`` listener runtime installs), plus every telemetry
  counter for one-file diagnosis.

The writer thread must never take the run down: every failure degrades to
a single warning (SummaryWriter's rule).  No jax imports — device state is
read exclusively through gauges the instrumented loops already set.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, Optional

from ..utils.fileio import atomic_write
from . import SCHEMA_VERSION, process_identity, run_id


def _rss_bytes() -> int:
    """Resident set size; 0 when unknowable (non-Linux without resource)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            return 0


class Heartbeat:
    """Daemon-thread writer of ``heartbeat.json``.

    ``static`` carries fields known at start (backend, phase); everything
    dynamic is read from ``tel``'s gauges/counters at write time, so the
    hot loop communicates with the heartbeat exclusively through the
    telemetry registry — no extra shared state, no extra syncs.
    """

    def __init__(
        self,
        path: str,
        interval_s: float,
        tel,
        static: Optional[Dict] = None,
        sampler=None,
    ) -> None:
        self.path = path
        self.interval_s = max(0.05, float(interval_s))
        self._tel = tel
        self._static = dict(static or {})
        # optional zero-arg callable merged into each beat (runtime passes
        # a device-memory probe built around jax's memory_stats()); the
        # heartbeat itself stays jax-free and a sampler failure or an
        # empty return (CPU backends expose no stats) degrades to absence
        self._sampler = sampler
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._prev: Optional[tuple] = None  # (time, step) of the last write
        self._warned = False

    # -- payload -----------------------------------------------------------

    def _payload(self) -> Dict:
        gauges = self._tel.gauges()
        counters = self._tel.counters()
        now = time.time()
        step = gauges.get("train/step")
        steps_per_s = None
        if step is not None and self._prev is not None:
            dt = now - self._prev[0]
            if dt > 0 and step >= self._prev[1]:
                steps_per_s = round((step - self._prev[1]) / dt, 3)
        if step is not None:
            self._prev = (now, step)
        last_save = gauges.get("ckpt/last_save_unix")
        # multi-host identity (telemetry.process_identity — jax-free, (0,1)
        # for single-process runs): N heartbeat files on shared storage
        # must say which host wrote each
        process_index, process_count = process_identity()
        payload = {
            # consumers get the same contract check_regression gives bench
            # rows: refuse payloads whose schema they don't understand
            "schema_version": SCHEMA_VERSION,
            "run_id": run_id(),
            "seq": self._seq,
            "pid": os.getpid(),
            "process_index": process_index,
            "process_count": process_count,
            "time_unix": round(now, 3),
            "interval_s": self.interval_s,
            "step": int(step) if step is not None else None,
            "epoch": gauges.get("data/epoch"),
            "steps_per_s": steps_per_s,
            "last_checkpoint_step": gauges.get("ckpt/last_save_step"),
            "last_checkpoint_age_s": (
                round(now - last_save, 1) if last_save is not None else None
            ),
            "compile_count": counters.get("jax/compiles", 0),
            "compile_seconds": round(counters.get("jax/compile_s", 0.0), 3),
            "rss_mb": round(_rss_bytes() / (1 << 20), 1),
            "counters": counters,
        }
        # last diag-tap / compile-accounting snapshot: the instrumented
        # loops gauge these at the log boundary, so one heartbeat file
        # answers "is the gradient sane and what does the step cost"
        diag = {k[len("diag/"):]: v for k, v in gauges.items() if k.startswith("diag/")}
        if diag:
            payload["diag"] = diag
        xla = {k[len("xla/"):]: v for k, v in gauges.items() if k.startswith("xla/")}
        if xla:
            payload["xla"] = xla
        # serving gauges (sat_tpu/serve): readiness, queue depth, warmed
        # buckets/compiles — one heartbeat file answers "is the server up,
        # is the queue backing up, did steady state start recompiling"
        srv = {k[len("serve/"):]: v for k, v in gauges.items() if k.startswith("serve/")}
        if srv:
            payload["serve"] = srv
        # watchdog ladder state (resilience.watchdog): 0 ok / 1 stalled /
        # 2 dumped / 3 aborting, plus seconds the stalled phase has been
        # open — the heartbeat is how an outside watcher sees a stall
        # while it is still recoverable
        wdg = {
            k[len("watchdog/"):]: v
            for k, v in gauges.items()
            if k.startswith("watchdog/")
        }
        if wdg:
            payload["watchdog"] = wdg
        sup = {
            k[len("supervisor/"):]: v
            for k, v in gauges.items()
            if k.startswith("supervisor/")
        }
        if sup:
            payload["supervisor"] = sup
        # data-plane health (data.integrity / resilience.quarantine):
        # quarantined totals + fraction, prefetch depth — a watcher sees
        # input corruption being contained while the run keeps training
        data = {
            k[len("data/"):]: v
            for k, v in gauges.items()
            if k.startswith("data/")
        }
        if data:
            payload["data"] = data
        # bulk offline-captioning progress (sat_tpu/bulk): images done /
        # total, captions/s, ETA, quarantined count, steady-state compile
        # count — the heartbeat is how a watcher tracks a dataset-scale
        # job without tailing its log
        bulk = {
            k[len("bulk/"):]: v
            for k, v in gauges.items()
            if k.startswith("bulk/")
        }
        if bulk:
            payload["bulk"] = bulk
        # SLO engine state (telemetry.slo): per-objective burn rate and
        # burning flag plus the burning_total roll-up — the heartbeat is
        # where an outside watcher sees an objective start to burn
        slo = {
            k[len("slo/"):]: v for k, v in gauges.items() if k.startswith("slo/")
        }
        if slo:
            payload["slo"] = slo
        # model-lifecycle plane (sat_tpu/lifecycle): state code, serving
        # vs candidate step, canary divergence, last swap blackout — a
        # watcher sees a canary in flight (state 3) and its verdict
        # without hitting /stats
        lc = {
            k[len("lifecycle/"):]: v
            for k, v in gauges.items()
            if k.startswith("lifecycle/")
        }
        if lc:
            payload["lifecycle"] = lc
        # fleet aggregate (telemetry.fleet): hosts reporting, step-p95
        # skew, straggler index — process 0's heartbeat answers "which
        # host is slow" without opening fleet.json
        fleet = {
            k[len("fleet/"):]: v
            for k, v in gauges.items()
            if k.startswith("fleet/")
        }
        if fleet:
            payload["fleet"] = fleet
        # caption-quality plane (telemetry.quality): per-signal PSI vs
        # the frozen reference, unk-rate, outlier count — the heartbeat
        # is where a watcher sees the model drift before anyone reads a
        # caption
        quality = {
            k[len("quality/"):]: v
            for k, v in gauges.items()
            if k.startswith("quality/")
        }
        if quality:
            payload["quality"] = quality
        if self._sampler is not None:
            try:
                payload.update(self._sampler() or {})
            except Exception:
                pass  # device stats are best-effort, never fatal
        payload.update(self._static)
        return payload

    def payload(self) -> Dict:
        """One payload snapshot without writing the file — the serving
        frontend's ``GET /healthz`` rides the exact fields watchers poll
        out of heartbeat.json."""
        return self._payload()

    def write_now(self) -> None:
        """One atomic write; failures warn once and never raise."""
        try:
            payload = self._payload()
            self._seq += 1
            atomic_write(
                self.path, "w", lambda f: json.dump(payload, f, indent=1)
            )
        except Exception as e:
            if not self._warned:
                self._warned = True
                print(
                    f"sat_tpu: heartbeat disabled — write failed "
                    f"({self.path}): {e}",
                    file=sys.stderr,
                    flush=True,
                )

    # -- lifecycle ---------------------------------------------------------

    def _run(self) -> None:
        self.write_now()  # first beat immediately: watchers see the run early
        while not self._stop.wait(self.interval_s):
            self.write_now()

    def start(self) -> "Heartbeat":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="sat-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Final beat (so the file records the terminal step) + join."""
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self.write_now()

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
