"""Optimizer factory.

Equivalent of the reference build_optimizer (/root/reference/model.py:461-513):
Adam / RMSProp / Momentum(+Nesterov) / SGD selected by config string, wrapped
with global-norm gradient clipping (clip_gradients=5.0) and optional
staircase exponential learning-rate decay — the same composition TF's
``optimize_loss`` applied (clip first, then the optimizer update).
"""

from __future__ import annotations

import optax

from ..config import Config


def make_learning_rate(config: Config):
    if config.learning_rate_decay_factor < 1.0:
        return optax.exponential_decay(
            init_value=config.initial_learning_rate,
            transition_steps=config.num_steps_per_decay,
            decay_rate=config.learning_rate_decay_factor,
            staircase=True,
        )
    return config.initial_learning_rate


def make_optimizer(config: Config) -> optax.GradientTransformation:
    lr = make_learning_rate(config)
    name = config.optimizer
    if name == "Adam":
        opt = optax.adam(
            learning_rate=lr,
            b1=config.beta1,
            b2=config.beta2,
            eps=config.epsilon,
        )
    elif name == "RMSProp":
        opt = optax.rmsprop(
            learning_rate=lr,
            decay=config.decay,
            eps=config.epsilon,
            centered=config.centered,
            momentum=config.momentum,
        )
    elif name == "Momentum":
        opt = optax.sgd(
            learning_rate=lr,
            momentum=config.momentum,
            nesterov=config.use_nesterov,
        )
    else:  # 'SGD'
        opt = optax.sgd(learning_rate=lr)

    transforms = []
    if config.clip_gradients and config.clip_gradients > 0:
        transforms.append(optax.clip_by_global_norm(config.clip_gradients))
    transforms.append(opt)
    return optax.chain(*transforms)
