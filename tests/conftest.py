"""Test harness setup.

Forces JAX onto the host CPU backend with 8 virtual devices BEFORE jax is
imported anywhere, so mesh/sharding tests emulate a multi-chip TPU slice
without hardware (see SURVEY.md §4's test plan).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override even if the env preset a TPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize force-registers a TPU PJRT plugin and
# overrides JAX_PLATFORMS, so pin the platform via config too.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite compiles dozens of model/mesh
# variants; caching them across runs cuts wall-clock several-fold.
# Machine-keyed so entries from another build box are never loaded (each
# cross-machine load logs a multi-KB XLA:CPU feature-mismatch warning).
from sat_tpu.utils.compile_cache import enable as _enable_cache  # noqa: E402

_enable_cache(jax, name=".jax_cache", min_compile_time_secs=0.5)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (pytest -m 'not slow')",
    )


@pytest.fixture(autouse=True)
def _fault_injection_inert():
    """Fault injection must be opt-in per test: no SAT_FI_* variable may
    leak in from the environment or out of a test, and the armed/consumed
    bookkeeping resets so injection counts never bleed between tests."""
    from sat_tpu.resilience import faultinject

    stray = [k for k in os.environ if k.startswith(faultinject.ENV_PREFIX)]
    assert not stray, f"fault-injection env leaked into the test run: {stray}"
    assert faultinject.FaultPlan.from_env().inert
    faultinject.reset_io_faults()
    yield
    for k in [k for k in os.environ if k.startswith(faultinject.ENV_PREFIX)]:
        del os.environ[k]
    faultinject.reset_io_faults()


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def coco_fixture(tmp_path_factory):
    """A tiny synthetic COCO-captions dataset with real image files."""
    from tests.fixtures import make_coco_fixture

    root = tmp_path_factory.mktemp("coco")
    return make_coco_fixture(str(root))
