"""sat_tpu.telemetry — always-on host-side tracing and run-health metrics.

Complements ``jax.profiler`` (deep, short-windowed, device-centric) with a
cheap, whole-run, host-centric layer: ring-buffered spans + counters +
gauges (``spans``), Chrome-trace / JSONL / breakdown exporters
(``exporters``), and the pollable ``heartbeat.json`` writer
(``heartbeat``).  See docs/OBSERVABILITY.md.

This package is deliberately jax-free so host-only tools
(``scripts/bench_telemetry.py``) can use it without an accelerator
backend.  Only ``spans`` is imported eagerly; runtime imports the
exporters and heartbeat directly.
"""

from __future__ import annotations

import os
import time

from .spans import (  # noqa: F401
    NULL_SPAN,
    NULL_TELEMETRY,
    NullTelemetry,
    Telemetry,
    count,
    disable,
    enable,
    enabled,
    gauge,
    get,
    record,
    span,
)

# One id per process lifetime: every artifact a run writes (metrics.jsonl,
# telemetry.jsonl, heartbeat.json, trace JSON) carries it, so post-hoc
# joins never depend on file mtimes or directory layout.
RUN_ID = f"{int(time.time()):x}-{os.getpid()}"


def run_id() -> str:
    return RUN_ID
