"""Distributed-array gather helpers (dependency-neutral: importable from
both the train and parallel layers without cycles)."""

from __future__ import annotations

from typing import Any

import jax


def gather_tree_replicated(tree: Any) -> Any:
    """Reshard every non-fully-addressable jax.Array leaf to replicated —
    one batched ``jax.device_put`` call, so the cross-host gathers (ICI /
    DCN all-gathers) dispatch together instead of one collective per leaf.
    Fully-addressable leaves (and plain numpy) pass through untouched."""
    from jax.sharding import NamedSharding, PartitionSpec

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    picked = [
        i
        for i, x in enumerate(leaves)
        if isinstance(x, jax.Array) and not x.is_fully_addressable
    ]
    if picked:
        gathered = jax.device_put(
            [leaves[i] for i in picked],
            [
                NamedSharding(leaves[i].sharding.mesh, PartitionSpec())
                for i in picked
            ],
        )
        for i, g in zip(picked, gathered):
            leaves[i] = g
    return jax.tree_util.tree_unflatten(treedef, leaves)
