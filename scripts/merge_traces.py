#!/usr/bin/env python
"""Merge per-process Chrome traces into one Perfetto timeline.

Every process of a multi-host run exports its own ``trace.json`` (the
``_telemetry_finish`` epilogue) with the trace ``pid`` set to the run's
``process_index`` — distinct, stable lanes.  Timestamps inside each file
are microseconds since THAT process's recorder anchor, so the files
cannot be naively concatenated: each process enabled telemetry at a
slightly different wall-clock instant.  ``otherData.anchor_unix``
records the absolute anchor, and this script shifts every event by
``(anchor_unix - min_anchor) * 1e6`` so all lanes share the earliest
process's timebase — skew between hosts is then *visible* in the merged
view instead of silently collapsed.

Usage::

    python scripts/merge_traces.py run/p0/telemetry/trace.json \
        run/p1/telemetry/trace.json --out merged_trace.json

Lanes: each input keeps its own pid (process_index); a
``process_name`` metadata event per lane labels it ``sat_tpu host pN``
(inputs that already carry process_name metadata keep theirs).  Inputs
missing ``anchor_unix`` merge unshifted with a warning — still useful
for single-host request-lane merges.

Exit codes: 0 = merged, 1 = usage/IO error, 2 = no events merged.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sat_tpu.utils.fileio import atomic_write  # noqa: E402


def _load(path: str) -> Dict:
    with open(path, "r") as f:
        return json.load(f)


def merge(docs: List[Dict]) -> Dict:
    """Pure merge of parsed trace documents (tested directly)."""
    anchors = [
        d.get("otherData", {}).get("anchor_unix") for d in docs
    ]
    known = [a for a in anchors if a is not None]
    base = min(known) if known else 0.0
    events: List[Dict] = []
    hosts: List[Dict] = []
    seen_names = set()
    for doc, anchor in zip(docs, anchors):
        other = doc.get("otherData", {})
        pidx = other.get("process_index", other.get("os_pid", 0))
        shift_us = ((anchor - base) * 1e6) if anchor is not None else 0.0
        if anchor is None:
            print(
                f"merge_traces: input for p{pidx} has no anchor_unix — "
                "merging unshifted",
                file=sys.stderr,
            )
        lane_pids = set()
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            lane_pids.add(ev.get("pid"))
            if ev.get("name") == "process_name":
                seen_names.add(ev.get("pid"))
            events.append(ev)
        for pid in sorted(p for p in lane_pids if p is not None):
            if pid not in seen_names:
                seen_names.add(pid)
                events.append(
                    {
                        "name": "process_name",
                        "ph": "M",
                        "pid": pid,
                        "args": {"name": f"sat_tpu host p{pid}"},
                    }
                )
        hosts.append(
            {
                "process_index": pidx,
                "anchor_unix": anchor,
                "shift_us": round(shift_us, 1),
                "events": len(doc.get("traceEvents", [])),
                "run_id": other.get("run_id"),
            }
        )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"merged_from": hosts, "anchor_unix": base},
    }


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="+", help="per-process trace.json files")
    ap.add_argument("--out", default="merged_trace.json")
    args = ap.parse_args(argv)

    docs = []
    for path in args.traces:
        try:
            docs.append(_load(path))
        except (OSError, ValueError) as e:
            print(f"merge_traces: cannot read {path}: {e}", file=sys.stderr)
            return 1
    merged = merge(docs)
    if not merged["traceEvents"]:
        print("merge_traces: no events in any input", file=sys.stderr)
        return 2
    atomic_write(args.out, "w", lambda f: json.dump(merged, f))
    lanes = sorted(
        {h["process_index"] for h in merged["otherData"]["merged_from"]}
    )
    print(
        f"merge_traces: {len(merged['traceEvents'])} events from "
        f"{len(docs)} trace(s) -> {args.out} (lanes: {lanes}) — open in "
        "https://ui.perfetto.dev"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
