"""Lifecycle controller: the reload → canary → promote/rollback machine.

One controller per serve process owns the whole lifecycle plane::

    IDLE ──new LAST_GOOD──▶ LOADING ──▶ WARMING ──▶ CANARY
      ▲                        │            │          │
      │     (reject→ledger)────┴────────────┘     window elapsed /
      │                                           SLO burn / operator
      ├────────── ROLLING_BACK ◀──────────────────────┤
      └────────── PROMOTING ◀─────────────────────────┘

* LOADING: host-side candidate load (``lifecycle.loader``) — integrity,
  vocab fingerprint, quantize-once, full-coverage device placement.
* WARMING: ``engine.install_candidate`` (tree/shape/dtype gate against
  the warmed executables' avals) + ``batcher.lifecycle_control
  ("arm_canary")`` (continuous mode clones the warmed slot pool — zero
  new compiles; batch mode needs nothing).  Any raise on this path is a
  **rejection**: the step lands in the lineage ledger exactly once and
  the reloader never re-canaries it.
* CANARY: a per-cycle SLO engine (phase ``canary``, windows clipped to
  the canary window) ticks over canary-slot traffic; a shadow worker
  duplicates a sample of incumbent requests onto the candidate and
  feeds the caption-divergence gauge.  Exits on: SLO burn → rollback;
  window elapsed → promote (``promote_policy=auto``) or hold for the
  operator (``manual``); POST /promote / /rollback → as told.
* PROMOTING: the batcher flips the active slot at its admission
  boundary (in-flight work finishes under the params it started with);
  the measured no-admission gap is ``lifecycle/swap_blackout_ms``.
* ROLLING_BACK: canary traffic drains, the candidate slot clears, the
  ledger records the step.  The incumbent never stopped serving.

The controller itself is jax-free (loading happens behind the loader's
deferred imports) so the state machine is unit-testable with stub
engines/batchers on hosts with no accelerator.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..resilience import lineage
from ..telemetry.slo import SLOEngine, objectives_from_config
from . import canary
from .loader import load_candidate
from .reloader import Reloader

STATES = (
    "IDLE",
    "LOADING",
    "WARMING",
    "CANARY",
    "PROMOTING",
    "ROLLING_BACK",
)
# numeric encoding for the lifecycle/state gauge (promtext has no labels)
STATE_CODES = {name: i for i, name in enumerate(STATES)}


class LifecycleController:
    """Owns the reloader, the canary scorer, and the promote/rollback
    decisions for one serve process."""

    def __init__(
        self,
        config,
        engine,
        batcher,
        tel=None,
        save_dir: Optional[str] = None,
        clock=time.monotonic,
    ) -> None:
        from .. import telemetry

        self.config = config
        self.engine = engine
        self.batcher = batcher
        self.save_dir = save_dir if save_dir is not None else config.save_dir
        self._tel = tel if tel is not None else telemetry.get()
        self._clock = clock
        self._state = "IDLE"
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self.reloader: Optional[Reloader] = None
        # decision channel: ("promote"|"rollback"|"abort", why) set by
        # the operator endpoints / shutdown; read by the cycle thread
        self._decision: Optional[Tuple[str, str]] = None
        self._cycle_thread: Optional[threading.Thread] = None
        self._cycle_done = threading.Event()
        self._cycle_done.set()
        self._cycle: Optional[Dict[str, Any]] = None
        self._last: Optional[Dict[str, Any]] = None
        self._canary_slo: Optional[SLOEngine] = None
        self._divergence = canary.DivergenceGauge()
        # shadow sampling: deterministic every-nth counter, one worker
        self._shadow_seen = 0
        self._shadow_q: "queue.Queue" = queue.Queue(maxsize=8)
        self._shadow_thread: Optional[threading.Thread] = None

    # -- state -------------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def _set_state(self, state: str) -> None:
        self._state = state
        self._tel.gauge("lifecycle/state", STATE_CODES[state])

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "LifecycleController":
        self._set_state("IDLE")
        if self.config.model_reload > 0:
            self.reloader = Reloader(
                self.save_dir,
                self.config.model_reload,
                self._on_new,
                current_step=lambda: self.engine.step,
                tel=self._tel,
            )
            # the checkpoint loaded at boot must not canary itself
            boot = lineage.last_good_step(self.save_dir)
            if boot is not None and boot == self.engine.step:
                self.reloader.mark_seen(boot)
            self.reloader.start()
        if self._shadow_thread is None:
            self._shadow_thread = threading.Thread(
                target=self._shadow_loop,
                name="sat-lifecycle-shadow",
                daemon=True,
            )
            self._shadow_thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        if self.reloader is not None:
            self.reloader.stop()
            self.reloader = None
        if self._cycle_thread is not None and self._cycle_thread.is_alive():
            self._decision = ("abort", "server shutting down")
            self._cycle_done.wait(timeout=60.0)
        if self._shadow_thread is not None:
            self._shadow_q.put(None)
            self._shadow_thread.join(timeout=10.0)
            self._shadow_thread = None

    # -- cycle entry -------------------------------------------------------

    def _on_new(self, step: int, path: str) -> None:
        self.begin_cycle(step, path)

    def begin_cycle(self, step: int, path: str) -> bool:
        """Start a reload cycle for ``step``; False when one is already
        in flight (the reloader will not see the step again — a pointer
        move during a cycle is caught by the NEXT poll's step compare)."""
        with self._lock:
            if self._state != "IDLE" or self._stopping.is_set():
                self._tel.count("lifecycle/busy_skipped")
                return False
            self._set_state("LOADING")
        self._decision = None
        self._cycle = {
            "step": int(step),
            "path": path,
            "started_unix": time.time(),
        }
        self._cycle_done.clear()
        self._cycle_thread = threading.Thread(
            target=self._run_cycle,
            args=(int(step), path),
            name="sat-lifecycle-cycle",
            daemon=True,
        )
        self._cycle_thread.start()
        return True

    def request_reload(self) -> Tuple[bool, str]:
        """POST /reload: examine LAST_GOOD right now instead of waiting
        for the next poll."""
        step = lineage.last_good_step(self.save_dir)
        if step is None:
            return False, "no LAST_GOOD pointer to reload from"
        if step == self.engine.step:
            return False, f"step {step} is already serving"
        if lineage.is_rejected(self.save_dir, step):
            return False, f"step {step} is in the rejection ledger"
        if self.reloader is not None:
            self.reloader.mark_seen(step)
        ok = self.begin_cycle(
            step, os.path.join(self.save_dir, f"{step}.npz")
        )
        return ok, (
            f"reload of step {step} started"
            if ok
            else "a lifecycle cycle is already in flight"
        )

    # -- operator decisions ------------------------------------------------

    def promote(self) -> Tuple[bool, str]:
        """POST /promote: finish the canary now (any policy)."""
        if self._state != "CANARY":
            return False, f"no canary to promote (state={self._state})"
        self._decision = ("promote", "operator request")
        self._cycle_done.wait(timeout=180.0)
        last = self._last or {}
        if last.get("outcome") == "promoted":
            return True, f"step {last.get('step')} promoted"
        return False, f"promote did not land: {last.get('why', 'unknown')}"

    def rollback(self, reason: str = "operator request") -> Tuple[bool, str]:
        """POST /rollback: reject the candidate now."""
        if self._state != "CANARY":
            return False, f"no canary to roll back (state={self._state})"
        self._decision = ("rollback", reason)
        self._cycle_done.wait(timeout=180.0)
        last = self._last or {}
        if last.get("outcome") == "rolled_back":
            return True, f"step {last.get('step')} rolled back and rejected"
        return False, f"rollback did not land: {last.get('why', 'unknown')}"

    # -- the cycle thread --------------------------------------------------

    def _make_canary_slo(self) -> SLOEngine:
        # windows clipped to the canary window: a qualification that
        # lasts 30 s cannot wait for a 300 s slow window to fill
        fast = min(self.config.slo_window_fast_s, self.config.canary_window_s)
        slow = max(
            fast,
            min(self.config.slo_window_slow_s, self.config.canary_window_s),
        )
        return SLOEngine(
            self._tel,
            objectives_from_config(self.config, "canary"),
            fast_s=fast,
            slow_s=slow,
        )

    def _run_cycle(self, step: int, path: str) -> None:
        try:
            self._run_cycle_inner(step, path)
        finally:
            # ONE exit: whatever path the cycle took (including a load
            # failure), the machine returns to IDLE and waiters wake
            self._canary_slo = None
            self._cycle = None
            if self._state != "IDLE":
                self._set_state("IDLE")
            self._cycle_done.set()

    def _run_cycle_inner(self, step: int, path: str) -> None:
        try:
            cand = load_candidate(self.engine, self.config, path)
            self._set_state("WARMING")
            self.engine.install_candidate(
                cand["variables"],
                cand["decoder_params"],
                cand["step"],
                cand["source"],
            )
            self.batcher.lifecycle_control("arm_canary")
            self._tel.count("lifecycle/reloads")
        except Exception as e:
            # load/guard failures never touched traffic: reject and bail
            self._set_state("ROLLING_BACK")
            self._finish_rollback(step, f"{type(e).__name__}: {e}", ledger=True)
            return
        try:
            self._canary_slo = self._make_canary_slo()
            self._divergence = canary.DivergenceGauge()
            self._tel.gauge("lifecycle/caption_divergence", 0.0)
            started = self._clock()
            self._set_state("CANARY")
            verb, why = self._watch_canary(started)
            if verb == "promote":
                self._set_state("PROMOTING")
                box = self.batcher.lifecycle_control("swap")
                blackout = float(box.get("blackout_ms", 0.0))  # sync-ok: host timing scalar
                self._tel.gauge(
                    "lifecycle/swap_blackout_ms", round(blackout, 3)
                )
                self._tel.count("lifecycle/promotions")
                self._last = {
                    "step": step,
                    "outcome": "promoted",
                    "why": why,
                    "blackout_ms": round(blackout, 3),
                }
                print(
                    f"sat_tpu: lifecycle promoted step {step} ({why}); "
                    f"swap blackout {blackout:.1f}ms",
                    file=sys.stderr,
                    flush=True,
                )
            elif verb == "abort":
                # shutdown mid-canary is not a verdict on the candidate:
                # clear the slot but leave the ledger alone
                self._set_state("ROLLING_BACK")
                self._finish_rollback(step, why, ledger=False)
                return
            else:
                self._set_state("ROLLING_BACK")
                self._finish_rollback(step, why, ledger=True)
                return
        except Exception as e:
            self._set_state("ROLLING_BACK")
            self._finish_rollback(step, f"{type(e).__name__}: {e}", ledger=True)

    def _watch_canary(self, started: float) -> Tuple[str, str]:
        """Tick the canary SLO until a verdict: (verb, why)."""
        window = self.config.canary_window_s
        held = False
        while True:
            if self._decision is not None:
                return self._decision
            if self._stopping.is_set():
                return "abort", "server shutting down"
            slo = self._canary_slo
            if slo is not None and slo.objectives:
                try:
                    slo.tick()
                except Exception:
                    pass
                burning = slo.burning()
                if burning:
                    return (
                        "rollback",
                        "canary slo burning: " + ", ".join(burning),
                    )
            elapsed = self._clock() - started
            if elapsed >= window:
                if self.config.promote_policy == "auto":
                    return "promote", (
                        f"canary window ({window:g}s) elapsed clean"
                    )
                if not held:
                    held = True
                    print(
                        "sat_tpu: lifecycle canary window elapsed; "
                        "promote_policy=manual — holding for POST "
                        "/promote or /rollback",
                        file=sys.stderr,
                        flush=True,
                    )
            time.sleep(0.05)

    def _finish_rollback(self, step: int, why: str, ledger: bool) -> None:
        try:
            self.batcher.lifecycle_control("disarm_canary")
        except Exception as e:
            print(
                f"sat_tpu: lifecycle disarm failed: {e}",
                file=sys.stderr,
                flush=True,
            )
        self.engine.clear_candidate()
        first = False
        if ledger:
            try:
                first = lineage.mark_rejected(self.save_dir, step, why)
            except OSError as e:
                print(
                    f"sat_tpu: lifecycle rejection ledger write failed: {e}",
                    file=sys.stderr,
                    flush=True,
                )
            if first:
                self._tel.count("lifecycle/rejected")
        self._tel.count("lifecycle/rollbacks")
        self._last = {
            "step": step,
            "outcome": "rolled_back" if ledger else "aborted",
            "why": why,
            "rejected": bool(ledger),
        }
        print(
            f"sat_tpu: lifecycle rolled back step {step} ({why})"
            + ("; rejected in ledger" if first else ""),
            file=sys.stderr,
            flush=True,
        )

    # -- request-path hooks (called by the server) -------------------------

    def route(self, request_id: Optional[str]) -> str:
        """Which param slot serves this request.  Only CANARY routes
        anywhere but the incumbent; the hash keeps retries sticky."""
        if self._state != "CANARY":
            return canary.INCUMBENT
        return canary.assign_slot(request_id, self.config.canary_fraction)

    def maybe_shadow(self, image, incumbent_caption: str) -> None:
        """After an incumbent request completes during CANARY: sample it
        onto the candidate for divergence scoring.  Deterministic
        every-nth sampling; the shadow queue is bounded and drops (with
        a counter) rather than backpressuring the request path."""
        if self._state != "CANARY" or self.config.canary_shadow_rate <= 0:
            return
        self._shadow_seen += 1
        n = max(1, int(round(1.0 / self.config.canary_shadow_rate)))
        if self._shadow_seen % n:
            return
        try:
            self._shadow_q.put_nowait((image, incumbent_caption))
        except queue.Full:
            self._tel.count("lifecycle/shadow_dropped")

    def _shadow_loop(self) -> None:
        while True:
            item = self._shadow_q.get()
            if item is None:
                return
            if self._state != "CANARY":
                continue  # stale sample from a finished window
            image, incumbent_caption = item
            try:
                req = self.batcher.submit(image, slot=canary.CANARY)
            except Exception:
                continue  # shed/draining: shadow work is best-effort
            if not req.done.wait(timeout=60.0) or req.error is not None:
                self._tel.count("lifecycle/shadow_errors")
                continue
            try:
                cand_caption = req.result["captions"][0]["caption"]
            except (KeyError, IndexError, TypeError):
                self._tel.count("lifecycle/shadow_errors")
                continue
            value = self._divergence.update(
                canary.caption_divergence(incumbent_caption, cand_caption)
            )
            self._tel.gauge("lifecycle/caption_divergence", round(value, 4))
            self._tel.count("lifecycle/shadow_pairs")

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The /stats lifecycle block."""
        out: Dict[str, Any] = {
            "state": self._state,
            "serving_step": self.engine.step,
            "candidate_step": self.engine.candidate_step,
            "promote_policy": self.config.promote_policy,
            "canary_fraction": self.config.canary_fraction,
            "canary_window_s": self.config.canary_window_s,
            "reload_poll_s": self.config.model_reload,
        }
        cycle = self._cycle
        if cycle is not None:
            out["cycle"] = dict(cycle)
        slo = self._canary_slo
        if slo is not None:
            out["canary_slo"] = slo.snapshot()
        if self._divergence.samples:
            out["caption_divergence"] = {
                "value": self._divergence.value,
                "samples": self._divergence.samples,
            }
        if self._last is not None:
            out["last_cycle"] = dict(self._last)
        try:
            rejected = sorted(lineage.rejected_steps(self.save_dir))
        except OSError:
            rejected = []
        if rejected:
            out["rejected_steps"] = rejected
        return out
