"""Host-side image decoding and the async device-feed pipeline.

The reference loads images synchronously inside the train loop
(/root/reference/utils/misc.py:6-36 and base_model.py:53), stalling the
device every step.  Here the same preprocessing (decode → BGR→RGB → resize
224×224 → subtract ILSVRC-2012 per-channel mean) runs in a thread pool that
stays ``prefetch_depth`` batches ahead and hands ready numpy batches to the
device while the previous step is still running.

Preprocessing parity notes (utils/misc.py:13-28):
* cv2 decodes BGR; the reference flips channels to RGB via an axis-swap;
* the per-channel mean is the spatial mean of the Caffe ILSVRC-2012 mean
  image, [104.00698793, 116.66876762, 122.67891434] in (B,G,R) npy order —
  the reference subtracts this vector *as-is* from the RGB image
  (utils/misc.py:27), and we reproduce that exactly since pretrained
  weights were trained against it;
* "center crop" is 224→224, a no-op kept only for shape clarity.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from .. import telemetry

# Spatial mean of the Caffe ILSVRC-2012 mean image (BGR npy channel order);
# matches np.load('ilsvrc_2012_mean.npy').mean(1).mean(1) in the reference.
ILSVRC_2012_MEAN = np.array([104.00698793, 116.66876762, 122.67891434], np.float32)


class ImageLoader:
    """raw=True defers the astype(float32)−mean step to the accelerator
    (models.captioner.encode mean-subtracts uint8 inputs on device):
    numerically IDENTICAL — the resize already happens on the uint8 image,
    mean-sub is the final op either way — but the host skips a float32
    allocation per image and the host→device feed shrinks 4×.  The config
    knob is ``device_preprocess`` (on by default)."""

    def __init__(
        self, mean: Optional[np.ndarray] = None, size: int = 224,
        raw: bool = False,
    ):
        if raw and mean is not None:
            raise ValueError(
                "raw=True defers mean subtraction to the device, which "
                "hardcodes ILSVRC_2012_MEAN (captioner.encode) — a custom "
                "mean would be silently ignored; use raw=False with it"
            )
        self.mean = ILSVRC_2012_MEAN if mean is None else np.asarray(mean, np.float32)
        self.size = size
        self.raw = raw

    def _finish_decode(self, image: np.ndarray) -> np.ndarray:
        """Shared post-codec tail: BGR → RGB, resize, contiguous uint8."""
        import cv2

        image = image[:, :, ::-1]  # BGR → RGB
        image = cv2.resize(image, (self.size, self.size))
        return np.ascontiguousarray(image)

    def load_raw(self, image_file: str) -> np.ndarray:
        """Decode → RGB → resize, stopping at the uint8 tensor.  This is
        the canonical post-resize row format the shard cache persists
        (data.shards): both preprocessing modes finish from it — raw=True
        feeds it to the device as-is, raw=False applies the float32 mean
        subtraction — so a cached row is bitwise-interchangeable with a
        live decode in either mode."""
        import cv2

        image = cv2.imread(image_file)
        if image is None:
            raise FileNotFoundError(f"cannot decode image: {image_file}")
        return self._finish_decode(image)

    def decode_raw(self, data: bytes) -> np.ndarray:
        """In-memory twin of load_raw for the serving frontend
        (sat_tpu/serve): cv2.imdecode of POSTed bytes runs the identical
        BGR→RGB→resize tail, so a JPEG uploaded over HTTP preprocesses
        bitwise-identically to the same file read from disk."""
        import cv2

        image = cv2.imdecode(np.frombuffer(data, np.uint8), cv2.IMREAD_COLOR)
        if image is None:
            raise ValueError("cannot decode image bytes (not a JPEG/PNG?)")
        return self._finish_decode(image)

    def load_image(self, image_file: str) -> np.ndarray:
        image = self.load_raw(image_file)
        if self.raw:
            return image  # uint8 RGB, device finishes
        return image.astype(np.float32) - self.mean

    def load_bytes(self, data: bytes) -> np.ndarray:
        """decode_raw + this loader's preprocessing mode (see load_image)."""
        image = self.decode_raw(data)
        if self.raw:
            return image
        return image.astype(np.float32) - self.mean

    def load_images(self, image_files: Sequence[str]) -> np.ndarray:
        return np.stack([self.load_image(f) for f in image_files])


class PrefetchLoader:
    """Wraps a batch iterator; assembles image batches ahead of the
    consumer in a ring of ``prefetch_depth`` ready slots (a bounded queue
    the producer thread fills and the step loop drains), so the
    accelerator never waits on host-side batch assembly.

    Two assembly paths:

    * **live decode** (default): images run through the thread-pool JPEG
      decode (``ImageLoader``) — 2.5-4.5 ms/image of codec work;
    * **shard gather** (``shard_cache`` given, see ``data.shards``): the
      batch is one fancy-index read per shard out of mmap'd preprocessed
      uint8 tensors — no codec, no per-image allocation; files absent
      from the cache fall back to live decode per image, so a partial
      cache degrades instead of failing.  Bitwise-identical to the live
      path in both preprocessing modes (the shard row IS the live path's
      post-resize uint8 intermediate).

    Yields dicts with 'images' [B,S,S,3] — float32 mean-subtracted, or
    uint8 RGB when the loader runs raw=True (device finishes the
    preprocessing; see ImageLoader) — plus any extra arrays the source
    iterator produced ('word_idxs', 'masks', 'files')."""

    def __init__(
        self,
        dataset,
        image_loader: Optional[ImageLoader] = None,
        num_workers: int = 8,
        prefetch_depth: int = 2,
        shard_cache=None,
    ):
        self.dataset = dataset
        self.loader = image_loader or ImageLoader()
        self.num_workers = num_workers
        self.prefetch_depth = max(1, prefetch_depth)
        self.shard_cache = shard_cache
        if shard_cache is not None and shard_cache.image_size != self.loader.size:
            raise ValueError(
                f"shard cache rows are {shard_cache.image_size}px but the "
                f"loader resizes to {self.loader.size}px — the cache was "
                "opened for a different preprocessing"
            )

    def _decode_batch(self, batch, pool: ThreadPoolExecutor):
        with telemetry.span("data/decode_batch"):
            return self._decode_batch_inner(batch, pool)

    def _decode_batch_inner(self, batch, pool: ThreadPoolExecutor):
        if isinstance(batch, tuple):
            files, word_idxs, masks = batch
            out = {
                "word_idxs": np.asarray(word_idxs, np.int32),
                "masks": np.asarray(masks, np.float32),
            }
        else:
            files, out = batch, {}
        if self.shard_cache is not None:
            raw = self.shard_cache.gather(files, fallback=self.loader.load_raw)
            # the final float32−mean step runs batch-wise here; elementwise
            # it is the exact op the live path applies per image, so the
            # two paths stay bitwise-identical
            out["images"] = (
                raw if self.loader.raw
                else raw.astype(np.float32) - self.loader.mean
            )
        else:
            out["images"] = np.stack(
                list(pool.map(self.loader.load_image, files))
            )
        out["files"] = list(files)
        return out

    def __iter__(self) -> Iterator[dict]:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_depth)
        sentinel = object()
        stop = threading.Event()
        error: List[BaseException] = []

        def producer():
            try:
                with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
                    for batch in self.dataset:
                        item = self._decode_batch(batch, pool)
                        # Bounded put that aborts if the consumer went away,
                        # so an abandoned iterator can't pin a thread.
                        while not stop.is_set():
                            try:
                                q.put(item, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if stop.is_set():
                            return
            except BaseException as e:  # surfaced on the consumer side
                error.append(e)
            finally:
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                # depth AFTER the take: 0 = consumer outran the producers
                # (data-starved), maxsize = producers ahead (healthy)
                telemetry.get().gauge("data/prefetch_qsize", q.qsize())
                if item is sentinel:
                    if error:
                        raise error[0]
                    return
                yield item
        finally:
            stop.set()
