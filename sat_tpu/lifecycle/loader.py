"""Candidate checkpoint loading for the hot-swap path.

Loads a lineage-blessed checkpoint into the engine's SECOND param slot
while the incumbent keeps serving, with every guard that can fail doing
so BEFORE device memory is spent:

1. lineage integrity verify (sidecar sha256 / zip CRC walk);
2. vocabulary-fingerprint fail-fast (``VocabMismatchError`` — a
   candidate trained against a different vocabulary would caption in
   gibberish, silently);
3. host-side flat load (``checkpoint.load_flat`` — numpy, no device);
4. quantize-once on the HOST tree when the engine serves quantized:
   ``quant.quantize_encoder`` folds BN and quantizes from the numpy
   arrays directly, so the candidate's fp32 CNN is **never resident on
   device** — only the small qcnn kernels land.  (The incumbent already
   dropped its own fp32 CNN at startup; without this, a reload would be
   the one moment two full fp32 encoders sat in HBM.)
5. full-coverage device placement against the incumbent's tree: every
   incumbent leaf must be fed by the checkpoint (tolerant partial
   restore is right for training resume, wrong for a model that will
   serve traffic), cast to the incumbent dtype so the warmed
   executables' avals match exactly.

Jax is imported inside functions only — the lifecycle package stays
importable on jax-free hosts (router tooling, unit tests).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, Tuple

from ..resilience import lineage


def _nest_flat(
    flat: Dict[str, Any], prefixes: Tuple[str, ...]
) -> Dict[str, Any]:
    """``{"params/cnn/conv1/kernel": arr}`` → nested host-numpy dicts,
    keeping only keys under ``prefixes``.  The skeleton
    ``quant.quantize_encoder`` walks."""
    out: Dict[str, Any] = {}
    for key, value in flat.items():
        if not any(key.startswith(p) for p in prefixes):
            continue
        parts = key.split("/")
        node = out
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return out


def load_candidate(engine, config, path: str) -> Dict[str, Any]:
    """Load ``path`` as a hot-swap candidate for ``engine``.

    Returns ``{"variables", "decoder_params", "step", "source"}`` ready
    for ``engine.install_candidate``.  Raises ``ValueError`` (integrity /
    coverage / geometry) or ``VocabMismatchError`` — the controller maps
    any raise to a lineage rejection.
    """
    import jax

    from ..data.vocabulary import vocab_fingerprint
    from ..train import checkpoint

    ok, reason = lineage.verify_checkpoint(path)
    if not ok:
        raise ValueError(f"candidate {path} failed verification: {reason}")
    expect = vocab_fingerprint(config.vocabulary_file, config.vocabulary_size)
    checkpoint._check_vocab(path, expect)  # raises VocabMismatchError

    t0 = time.perf_counter()
    flat = checkpoint.load_flat(path)  # host numpy only
    step = int(flat.get("global_step", 0))

    def _place_full(template, prefix: str, what: str):
        """Device-place the checkpoint's leaves in the incumbent tree's
        structure, requiring FULL coverage (every template leaf fed)."""
        tree, count = checkpoint._assign_leaves(template, prefix, flat)
        total = len(jax.tree_util.tree_leaves(template))
        if count != total:
            raise ValueError(
                f"candidate {os.path.basename(path)} covers {count}/"
                f"{total} {what} tensors of the serving model — partial "
                "or geometry-drifted checkpoint, rejecting"
            )
        return tree

    if engine.encoder_quant != "off":
        # quantize from the HOST tree: the candidate's fp32 CNN stays in
        # host memory; only the quantized kernels are device arrays
        host_vars = _nest_flat(flat, ("params/", "batch_stats/"))
        if "cnn" not in host_vars.get("params", {}):
            raise ValueError(
                f"candidate {os.path.basename(path)} has no params/cnn "
                "tree to quantize, rejecting"
            )
        from ..nn import quant

        qcnn = quant.quantize_encoder(host_vars, config)
        decoder_params = _place_full(
            engine.slot_decoder_params("incumbent"),
            "params/decoder/",
            "decoder",
        )
        variables = {"params": {"decoder": decoder_params}, "qcnn": qcnn}
    else:
        variables = _place_full(
            engine.slot_variables("incumbent"), "", "model"
        )
        decoder_params = variables["params"]["decoder"]
    jax.block_until_ready(jax.tree_util.tree_leaves(decoder_params)[0])  # sync-ok: candidate load path, off the request path
    load_s = time.perf_counter() - t0
    print(
        f"sat_tpu: lifecycle candidate {os.path.basename(path)} "
        f"(step {step}) staged in {load_s:.2f}s",
        file=sys.stderr,
        flush=True,
    )
    return {
        "variables": variables,
        "decoder_params": decoder_params,
        "step": step,
        "source": path,
        "load_seconds": load_s,
    }
