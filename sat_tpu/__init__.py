"""sat_tpu — a TPU-native Show, Attend and Tell framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
Cheng-Lin-Li/show-attend-and-tell (TF1): VGG16/ResNet50 encoders, the
soft-attention LSTM decoder, masked-CE + doubly-stochastic-attention
training, on-device batched beam search, COCO data/vocabulary pipeline,
BLEU/METEOR/ROUGE-L/CIDEr evaluation, npy-compatible checkpointing, and
SPMD data/context-parallel training over a jax.sharding.Mesh.
"""

from .config import Config

__version__ = "0.2.0"

# The driving loops live in sat_tpu.runtime (train/evaluate/test/
# evaluate_sweep).  They are deliberately NOT re-exported here: the
# ``sat_tpu.train`` *subpackage* (optimizer/checkpoint/step) would shadow a
# ``train`` function attribute as soon as runtime imports it, making the
# name order-dependent.  ``from sat_tpu import runtime`` is the API.
__all__ = ["Config"]
