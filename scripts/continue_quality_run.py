"""Continue a finished quality_run from its final checkpoint.

The overfit-protocol runs save one checkpoint at end of training
(runtime.train's final ckpt_save); this script restores it, extends
num_epochs, trains the additional steps, re-evaluates (beam=3 and
optionally greedy), and rewrites scores.json — so a run that ended
short of saturation continues instead of being repaid from scratch
(the 1-core box prices a 1600-step rich run at ~100 min).

Usage:
  python scripts/continue_quality_run.py --out runs/quality_rich_joint \
      --corpus rich [--extra-epochs 39] [--beam-compare] [...]
Flags mirror the original quality_run invocation where relevant; the
config is rebuilt the same way, only num_epochs grows.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--corpus", default="rich", choices=["basic", "rich"])
    ap.add_argument("--extra-epochs", type=int, default=39)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--frozen-cnn", action="store_true")
    ap.add_argument("--beam-compare", action="store_true")
    ap.add_argument("--cnn", default="vgg16")
    ap.add_argument("--extra-set", action="append", default=[])
    args = ap.parse_args()

    t0 = time.time()

    def log(msg: str) -> None:
        print(f"[cont +{time.time()-t0:6.1f}s] {msg}", flush=True)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from sat_tpu.utils.compile_cache import enable as _enable_cache

    _enable_cache(jax)

    from sat_tpu.cli import build_config
    from sat_tpu.train.checkpoint import latest_checkpoint, restore_checkpoint
    from sat_tpu.train.step import create_train_state
    from sat_tpu import runtime

    root = os.path.abspath(args.out)
    img_dir = os.path.join(root, "images")
    caption_file = os.path.join(root, "captions.json")
    assert os.path.isdir(img_dir), f"no corpus at {root} — run quality_run first"

    overrides = [
        f"train_image_dir={img_dir}",
        f"train_caption_file={caption_file}",
        f"eval_image_dir={img_dir}",
        f"eval_caption_file={caption_file}",
        f"vocabulary_file={root}/vocabulary_{args.corpus}.csv",
        f"temp_annotation_file={root}/anns_{args.corpus}.csv",
        f"temp_data_file={root}/data_{args.corpus}.npy",
        f"save_dir={root}/models",
        f"summary_dir={root}/summary",
        f"eval_result_dir={root}/results",
        f"eval_result_file={root}/results.json",
        "max_train_ann_num=none",
        "max_eval_ann_num=none",
        f"batch_size={args.batch_size}",
        "vocabulary_size=5000" if args.corpus == "rich" else "vocabulary_size=200",
        "fc_drop_rate=0.1",
        "lstm_drop_rate=0.1",
        "initial_learning_rate=0.0003",
        "save_period=0",
        "log_every=10",
        f"image_size={args.image_size}",
        f"cnn={args.cnn}",
    ] + args.extra_set
    set_args = [x for o in overrides for x in ("--set", o)]
    train_flags = [] if args.frozen_cnn else ["--train_cnn"]

    ckpt = latest_checkpoint(os.path.join(root, "models"))
    assert ckpt, f"no checkpoint under {root}/models"
    log(f"restoring {ckpt}")

    config, _ = build_config(["--phase=train"] + train_flags + set_args)
    state = create_train_state(jax.random.PRNGKey(0), config)
    state, n = restore_checkpoint(state, model_file=ckpt)
    assert n > 0, "restore matched no tensors"
    start_step = int(state.step)

    # steps/epoch from the cached dataset size; extend num_epochs so the
    # loop runs --extra-epochs past wherever the checkpoint stopped
    from sat_tpu.data.dataset import prepare_train_data

    dataset = prepare_train_data(config)
    steps_per_epoch = dataset.num_batches
    done_epochs = start_step // steps_per_epoch
    config = config.replace(num_epochs=done_epochs + args.extra_epochs)
    log(f"continuing from step {start_step} (epoch {done_epochs}) for "
        f"{args.extra_epochs} more epochs x {steps_per_epoch} steps")

    state = runtime.train(config, state=state, dataset=dataset)
    log(f"training done at step {int(state.step)}")

    eval_config, _ = build_config(["--phase=eval", "--beam_size=3"] + set_args)
    scores = runtime.evaluate(eval_config, state=state)
    log(f"beam=3 scores: { {k: round(v, 4) for k, v in scores.items()} }")

    greedy_scores = None
    if args.beam_compare:
        greedy_config, _ = build_config(["--phase=eval", "--beam_size=1"] + set_args)
        greedy_config = greedy_config.replace(
            eval_result_file=f"{root}/results_greedy.json"
        )
        greedy_scores = runtime.evaluate(greedy_config, state=state)
        log(f"greedy scores: { {k: round(v, 4) for k, v in greedy_scores.items()} }")

    # merge into the original quality_run payload: its provenance fields
    # (corpus, protocol, train_cnn, vocab_words, length histogram) must
    # survive the continuation — RESULTS.md comparisons key on them
    scores_path = os.path.join(root, "scores.json")
    payload = {}
    try:
        with open(scores_path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    payload.update(
        scores=scores,
        greedy_scores=greedy_scores,
        steps=int(state.step),
        continued_from_step=start_step,
        continuation_seconds=round(time.time() - t0, 1),
    )
    with open(scores_path, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
