"""Native C++ components vs their Python twins (SURVEY.md §2.24-2.25).

The reference runs a CoreNLP jar and meteor-1.5.jar; our framework ships
C++ equivalents (sat_tpu/native).  These tests build the library and pin
the C++ output to the pure-Python implementations token-for-token /
score-for-score.
"""

import numpy as np
import pytest

from sat_tpu import native
from sat_tpu.data.tokenizer import PUNCTUATIONS, tokenize_pure
from sat_tpu.evalcap import meteor as py_meteor
from tests.fixtures import CAPTIONS

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)

TRICKY = CAPTIONS + [
    "A man, riding a horse; on the beach!",
    'She said "hello there" and left.',
    "it's the dog's ball... isn't it?",
    "don't stop -- we're nearly there.",
    "a cat (the black one) sat on the mat.",
    "numbers like 1,000 and 3:30 stay joined.",
    "they'll we've you're I'm he'd cannot gonna wanna.",
    "trailing spaces and   multiple   gaps.",
    "the teachers' lounge was empty.",
    "brackets [x] {y} <z> and slashes a/b.",
    "one. two. three.",
    "ends with colon:",
    "weird ,, double commas ,: mixes.",
    "",
    "   ",
    ".",
    "word",
]

WORDS = sorted(
    {w for c in TRICKY for w in tokenize_pure(c)}
    | {
        "running", "ran", "ponies", "caresses", "cats", "feed", "agreed",
        "plastered", "bled", "motoring", "sing", "conflated", "troubled",
        "sized", "hopping", "tanned", "falling", "hissing", "fizzed",
        "failing", "filing", "happy", "sky", "relational", "conditional",
        "rational", "valenci", "hesitanci", "digitizer", "conformabli",
        "radicalli", "differentli", "vileli", "analogousli", "vietnamization",
        "predication", "operator", "feudalism", "decisiveness", "hopefulness",
        "callousness", "formaliti", "sensitiviti", "sensibiliti", "triplicate",
        "formative", "formalize", "electriciti", "electrical", "hopeful",
        "goodness", "revival", "allowance", "inference", "airliner",
        "gyroscopic", "adjustable", "defensible", "irritant", "replacement",
        "adjustment", "dependent", "adoption", "homologou", "communism",
        "activate", "angulariti", "homologous", "effective", "bowdlerize",
        "probate", "rate", "cease", "controll", "roll", "as", "is", "be",
        "a", "an", "oed", "ied", "ies", "sses",
    }
)


def test_stemmer_matches_nltk_original():
    from nltk.stem.porter import PorterStemmer

    ref = PorterStemmer(mode="ORIGINAL_ALGORITHM")
    mismatches = [
        (w, native.stem(w), ref.stem(w))
        for w in WORDS
        if native.stem(w) != ref.stem(w)
    ]
    assert not mismatches, mismatches


@pytest.mark.parametrize("strip", [False, True])
def test_tokenizer_matches_python(strip):
    for caption in TRICKY:
        if strip:
            want = [t for t in tokenize_pure(caption) if t not in PUNCTUATIONS]
            got = native.tokenize(caption, strip_punct=True)
        else:
            want = tokenize_pure(caption)
            got = native.tokenize(caption)
        assert got == want, f"caption={caption!r}\nwant={want}\ngot ={got}"


def test_meteor_matches_python():
    hyps = [" ".join(tokenize_pure(c)[:-1]) for c in CAPTIONS]
    refs = [" ".join(tokenize_pure(c)[:-1]) for c in CAPTIONS[::-1]]
    for hyp in hyps:
        for ref in refs:
            want = py_meteor.score_from_stats(py_meteor.segment_stats(hyp, ref))
            got = native.meteor_segment(hyp, ref)
            assert got == pytest.approx(want, abs=1e-12), (hyp, ref)


def test_meteor_multi_is_max_over_refs():
    hyp = "a man riding a horse on the beach"
    refs = ["a cat on a mat", "a man riding a horse on the beach", "dogs"]
    assert native.meteor_multi(hyp, refs) == pytest.approx(
        max(native.meteor_segment(hyp, r) for r in refs)
    )


def test_meteor_scorer_class_uses_native():
    """End-to-end through the evalcap Meteor class (native fast path)."""
    gts = {1: ["a man riding a horse"], 2: ["two dogs playing with a ball"]}
    res = {1: ["a man riding a horse"], 2: ["a cat sleeping"]}
    score, scores = py_meteor.Meteor().compute_score(gts, res)
    assert scores[0] == pytest.approx(native.meteor_segment(res[1][0], gts[1][0]))
    assert score == pytest.approx(float(np.mean(scores)))
    assert scores[0] > 0.9 and scores[1] < 0.2


def test_uppercase_stem_matches_nltk():
    from nltk.stem.porter import PorterStemmer

    ref = PorterStemmer(mode="ORIGINAL_ALGORITHM")
    for w in ["Running", "PONIES", "CaResSes"]:
        assert native.stem(w) == ref.stem(w)


def test_non_ascii_routes_to_python():
    """Unicode captions must tokenize identically whether or not the
    native library is present (they bypass it)."""
    from sat_tpu.data.tokenizer import tokenize

    text = "a café in town tonight."
    assert tokenize(text) == tokenize_pure(text)


def test_lower_false_routes_to_python():
    from sat_tpu.data.tokenizer import tokenize

    text = "Don't stop Cannot."
    assert tokenize(text, lower=False) == tokenize_pure(text, lower=False)


def test_meteor_fuzz_matches_python():
    """Randomized agreement sweep: word soups drawn from a vocabulary that
    triggers every stage (exact, stem variants, synonyms, multi-word
    paraphrase spans) must score bitwise-identically in both backends."""
    import numpy as np

    if not native.available():
        pytest.skip("native library not built")
    vocab = (
        "a the dog dogs cat cats man woman person people runs running ran "
        "sits sitting stands standing next to beside in front of before "
        "atop on top of near big large small little horse pony street road "
        "garden yard quickly quick is was are and with under over".split()
    )
    rng = np.random.default_rng(1234)
    for _ in range(200):
        n_h, n_r = rng.integers(1, 14, size=2)
        hyp = " ".join(rng.choice(vocab, size=n_h))
        ref = " ".join(rng.choice(vocab, size=n_r))
        want = py_meteor.score_from_stats(py_meteor.segment_stats(hyp, ref))
        got = native.meteor_segment(hyp, ref)
        assert got == pytest.approx(want, abs=1e-12), (hyp, ref)
