"""Distributed-layer tests on the 8-virtual-device CPU mesh (conftest.py).

Strategy per SURVEY.md §4: emulate a TPU slice with
xla_force_host_platform_device_count and check that (a) sharded programs
compile+run with the intended layouts, and (b) parallel results match the
single-device oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sat_tpu.config import Config
from sat_tpu.parallel import (
    create_parallel_train_state,
    make_mesh,
    make_parallel_beam_search,
    make_parallel_train_step,
    shard_batch,
)
from sat_tpu.parallel.collectives import cross_replica_mean, make_global_batch
from sat_tpu.parallel.sharding import param_partition_specs
from sat_tpu.train.step import create_train_state, make_jit_train_step


def tiny_config(**kw):
    base = dict(
        cnn="vgg16",
        vocabulary_size=64,
        dim_embedding=16,
        num_lstm_units=16,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        max_caption_length=4,
        batch_size=8,
        compute_dtype="float32",
    )
    base.update(kw)
    return Config(**base)


def context_batch(config, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "contexts": jnp.asarray(
            rng.normal(size=(batch, config.num_ctx, config.dim_ctx)).astype(np.float32)
        ),
        "word_idxs": jnp.asarray(
            rng.integers(0, config.vocabulary_size, size=(batch, config.max_caption_length)).astype(np.int32)
        ),
        "masks": jnp.ones((batch, config.max_caption_length), jnp.float32),
    }


def test_initialize_distributed_single_host_is_noop(monkeypatch):
    """A lone TPU_WORKER_HOSTNAMES entry or a 1-task SLURM allocation is a
    single-process launch: bootstrapping a coordinator there crashes with
    'coordinator_address should be defined' (regression: the axon single
    -chip environment exports TPU_WORKER_HOSTNAMES=localhost)."""
    from sat_tpu.parallel import initialize_distributed

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert initialize_distributed() is False

    monkeypatch.setenv("SLURM_STEP_NODELIST", "node001")
    monkeypatch.setenv("SLURM_NTASKS", "1")
    assert initialize_distributed() is False

    # but a real pod signal still wires up (>1 hostnames)
    from sat_tpu.parallel import mesh as mesh_mod

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    assert mesh_mod._multihost_env_signal() is True


def test_make_mesh_shapes():
    config = tiny_config(mesh_shape=(4, 2))
    mesh = make_mesh(config)
    assert mesh.shape == {"data": 4, "model": 2}
    # 0 = "all remaining devices"
    mesh = make_mesh(tiny_config(mesh_shape=(0, 2)))
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(tiny_config(mesh_shape=(16, 2)))


def test_param_partition_specs_vocab_rule():
    config = tiny_config(mesh_shape=(4, 2))
    mesh = make_mesh(config)
    state = create_train_state(jax.random.PRNGKey(0), config)
    specs = param_partition_specs(state.params, config, mesh)
    dec = specs["decoder"]
    assert dec["word_embedding"]["weights"] == P("model", None)
    assert dec["decode"]["fc_2"]["kernel"] == P(None, "model")
    assert dec["decode"]["fc_2"]["bias"] == P("model")
    assert dec["lstm"]["kernel"] == P()


def test_parallel_train_step_matches_single_device():
    config = tiny_config(mesh_shape=(8, 1))
    mesh = make_mesh(config)
    batch = context_batch(config)
    rng = jax.random.PRNGKey(7)
    drop = jax.random.PRNGKey(11)

    # oracle: plain single-device jit
    state0 = create_train_state(rng, config)
    _, m_single = make_jit_train_step(config)(state0, batch, drop)

    pstate = create_parallel_train_state(rng, config, mesh)
    pstep = make_parallel_train_step(config, mesh)
    pstate, m_par = pstep(pstate, shard_batch(batch, mesh), drop)

    for k in m_single:
        np.testing.assert_allclose(
            np.asarray(m_single[k]), np.asarray(m_par[k]), rtol=2e-4, atol=2e-5,
            err_msg=k,
        )
    # a second step runs (donation + resharding are stable)
    pstate, _ = pstep(pstate, shard_batch(context_batch(config, seed=1), mesh), drop)
    assert int(pstate.step) == 2


def test_parallel_train_step_model_sharded():
    """DP×TP mesh: vocab-sharded embedding/softmax still matches the oracle."""
    config = tiny_config(mesh_shape=(4, 2))
    mesh = make_mesh(config)
    batch = context_batch(config)
    rng = jax.random.PRNGKey(3)
    drop = jax.random.PRNGKey(5)

    state0 = create_train_state(rng, config)
    _, m_single = make_jit_train_step(config)(state0, batch, drop)

    pstate = create_parallel_train_state(rng, config, mesh)
    emb = pstate.params["decoder"]["word_embedding"]["weights"]
    assert emb.sharding.spec == P("model", None)

    pstep = make_parallel_train_step(config, mesh)
    _, m_par = pstep(pstate, shard_batch(batch, mesh), drop)
    np.testing.assert_allclose(
        float(m_single["total_loss"]), float(m_par["total_loss"]), rtol=2e-4
    )


def test_parallel_beam_search_matches_single_device():
    config = tiny_config(mesh_shape=(8, 1), beam_size=3)
    mesh = make_mesh(config)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(8, 224, 224, 3)).astype(np.float32))

    from sat_tpu.models.captioner import encode, init_variables
    from sat_tpu.ops.beam_search import beam_search

    variables = init_variables(jax.random.PRNGKey(0), config)
    contexts, _ = encode(variables, config, images, train=False)
    oracle = beam_search(variables["params"]["decoder"], config, contexts, eos_id=1)

    pcaption = make_parallel_beam_search(config, mesh, eos_id=1)
    result = pcaption(variables, jax.device_put(images, None))
    np.testing.assert_array_equal(np.asarray(oracle.words), np.asarray(result.words))
    np.testing.assert_allclose(
        np.asarray(oracle.log_scores), np.asarray(result.log_scores), rtol=1e-4
    )


def test_cross_replica_mean_and_global_batch():
    config = tiny_config(mesh_shape=(8, 1))
    mesh = make_mesh(config)
    # one value per data-mesh row -> their mean, replicated
    out = cross_replica_mean({"x": jnp.arange(8.0)}, mesh)
    np.testing.assert_allclose(float(out["x"]), 3.5)
    out2 = cross_replica_mean({"m": jnp.ones((8, 2, 3))}, mesh)
    assert out2["m"].shape == (2, 3)
    np.testing.assert_allclose(np.asarray(out2["m"]), 1.0)

    batch = {"a": np.arange(16, dtype=np.float32).reshape(16, 1)}
    g = make_global_batch(mesh, batch)
    assert g["a"].sharding.spec == P("data")
    np.testing.assert_array_equal(np.asarray(g["a"]), batch["a"])
