// Penn-Treebank-style tokenizer — native replacement for the reference's
// Stanford CoreNLP PTBTokenizer jar invocation (/root/reference/utils/coco/
// pycocoevalcap/tokenizer/ptbtokenizer.py:18-69, `-preserveLines
// -lowerCase` + punctuation stripping).
//
// Rule-for-rule mirror of the Python implementation in
// sat_tpu/data/tokenizer.py (the two are golden-tested against each other);
// regexes are hand-compiled into scans for speed and to avoid std::regex
// semantic drift from Python `re`.

#include <cctype>
#include <string>
#include <unordered_set>
#include <vector>

namespace sat_native {

namespace {

const std::unordered_set<std::string>& punctuations() {
  static const std::unordered_set<std::string> kPunct = {
      "''", "'",  "``", "`",  "-LRB-", "-RRB-", "-LCB-", "-RCB-",
      ".",  "?",  "!",  ",",  ":",     "-",     "--",    "...",  ";",
  };
  return kPunct;
}

bool is_space(char c) { return std::isspace(static_cast<unsigned char>(c)); }

// Ordered regex-equivalent passes over the working string.  Each pass
// rebuilds the string; captions are short so this is still ~µs each.

std::string lower(const std::string& s) {
  std::string out = s;
  for (char& c : out) c = std::tolower(static_cast<unsigned char>(c));
  return out;
}

// ^" → ``   (string starts with a double quote)
std::string rule_start_quote(const std::string& s) {
  if (!s.empty() && s[0] == '"') return "``" + s.substr(1);
  return s;
}

// (``) → ' `` '
std::string rule_pad_backticks(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); i++) {
    if (s[i] == '`' && i + 1 < s.size() && s[i + 1] == '`') {
      out += " `` ";
      i++;
    } else {
      out += s[i];
    }
  }
  return out;
}

// ([ ([{<])("|'{2}) → \1 ``
std::string rule_open_quote(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); i++) {
    char c = s[i];
    out += c;
    if (c == ' ' || c == '(' || c == '[' || c == '{' || c == '<') {
      if (i + 1 < s.size() && s[i + 1] == '"') {
        out += " `` ";
        i += 1;
      } else if (i + 2 < s.size() && s[i + 1] == '\'' && s[i + 2] == '\'') {
        out += " `` ";
        i += 2;
      }
    }
  }
  return out;
}

// ... → ' ... '
std::string rule_ellipsis(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); i++) {
    if (s[i] == '.' && i + 2 < s.size() && s[i + 1] == '.' && s[i + 2] == '.') {
      out += " ... ";
      i += 2;
    } else {
      out += s[i];
    }
  }
  return out;
}

// ([;@#$%&?!]) → ' \1 '
std::string rule_punct(const std::string& s) {
  static const std::string kSet = ";@#$%&?!";
  std::string out;
  for (char c : s) {
    if (kSet.find(c) != std::string::npos) {
      out += ' ';
      out += c;
      out += ' ';
    } else {
      out += c;
    }
  }
  return out;
}

// ([^.])(.)(?=\s) → '\1 \2 '   — sentence-internal period before whitespace
std::string rule_internal_period(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); i++) {
    if (s[i] == '.' && i > 0 && s[i - 1] != '.' && i + 1 < s.size() &&
        is_space(s[i + 1])) {
      out += " . ";
    } else {
      out += s[i];
    }
  }
  return out;
}

// ([^.])(\.)([])}>"']*)\s*$ → '\1 \2\3 '  — final period (+closers)
std::string rule_final_period(const std::string& s) {
  // find last non-space
  int end = static_cast<int>(s.size()) - 1;
  while (end >= 0 && is_space(s[end])) end--;
  if (end < 0) return s;
  // scan back over closers
  int i = end;
  static const std::string kClosers = "])}>\"'";
  while (i >= 0 && kClosers.find(s[i]) != std::string::npos) i--;
  if (i < 1 || s[i] != '.' || s[i - 1] == '.') return s;
  // s[i] is the final period, s[i+1..end] closers, preceded by non-period
  std::string out = s.substr(0, i);
  out += " .";
  out += s.substr(i + 1, end - i);
  out += " ";
  return out;
}

// ([:,])([^\d]) → ' \1 \2'  and ([:,])$ → ' \1 '
std::string rule_comma_colon(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); i++) {
    char c = s[i];
    if (c == ':' || c == ',') {
      if (i + 1 >= s.size()) {
        out += ' ';
        out += c;
        out += ' ';
      } else if (!std::isdigit(static_cast<unsigned char>(s[i + 1]))) {
        out += ' ';
        out += c;
        out += ' ';
        // NB: python rule consumes the next char into \2 — but since it
        // re-emits it unchanged, emitting it on the next loop turn is
        // equivalent EXCEPT for overlapping ",," sequences, where re.sub
        // skips the consumed char.  Reproduce that: if next is ':'/',',
        // emit it verbatim now.
        char n = s[i + 1];
        if (n == ':' || n == ',') {
          out += n;
          i++;
        }
      } else {
        out += c;
      }
    } else {
      out += c;
    }
  }
  return out;
}

// ([][(){}<>]) → ' \1 '
std::string rule_brackets(const std::string& s) {
  static const std::string kSet = "[](){}<>";
  std::string out;
  for (char c : s) {
    if (kSet.find(c) != std::string::npos) {
      out += ' ';
      out += c;
      out += ' ';
    } else {
      out += c;
    }
  }
  return out;
}

// -- → ' -- '
std::string rule_dashes(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); i++) {
    if (s[i] == '-' && i + 1 < s.size() && s[i + 1] == '-') {
      out += " -- ";
      i++;
    } else {
      out += s[i];
    }
  }
  return out;
}

// " → ' '' '
std::string rule_end_quote(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (c == '"') out += " '' ";
    else out += c;
  }
  return out;
}

// (\S)('') → '\1 '' '
std::string rule_pad_close_quote(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); i++) {
    if (s[i] == '\'' && i + 1 < s.size() && s[i + 1] == '\'' && i > 0 &&
        !is_space(s[i - 1])) {
      out += " '' ";
      i++;
    } else {
      out += s[i];
    }
  }
  return out;
}

// ([^' ])(' ) → "\1 ' "
std::string rule_trailing_apostrophe(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); i++) {
    if (s[i] == '\'' && i + 1 < s.size() && s[i + 1] == ' ' && i > 0 &&
        s[i - 1] != '\'' && s[i - 1] != ' ') {
      out += " ' ";
      i++;  // consumed the space into the replacement
    } else {
      out += s[i];
    }
  }
  return out;
}

// contractions: ([^' ])('ll|'re|'ve|n't|'s|'m|'d)\b → "\1 \2"
std::string rule_contractions(const std::string& s) {
  static const std::vector<std::string> kSuf = {"'ll", "'re", "'ve",
                                                "n't", "'s",  "'m", "'d"};
  std::string out;
  size_t i = 0;
  while (i < s.size()) {
    bool matched = false;
    if (i > 0 && s[i - 1] != '\'' && s[i - 1] != ' ') {
      for (const auto& suf : kSuf) {
        if (s.compare(i, suf.size(), suf) == 0) {
          size_t after = i + suf.size();
          bool boundary =
              after >= s.size() ||
              !(std::isalnum(static_cast<unsigned char>(s[after])) ||
                s[after] == '_');
          if (boundary) {
            out += ' ';
            out += suf;
            i = after;
            matched = true;
            break;
          }
        }
      }
    }
    if (!matched) {
      out += s[i];
      i++;
    }
  }
  return out;
}

// multiword: cannot/gonna/gotta/wanna/lemme → split
std::string rule_multiword(const std::string& s) {
  static const std::vector<std::pair<std::string, std::string>> kPairs = {
      {"cannot", "can not"}, {"gonna", "gon na"}, {"gotta", "got ta"},
      {"wanna", "wan na"},   {"lemme", "lem me"},
  };
  std::string out;
  size_t i = 0;
  auto word_char = [&](size_t k) {
    return k < s.size() && (std::isalnum(static_cast<unsigned char>(s[k])) ||
                            s[k] == '_');
  };
  while (i < s.size()) {
    bool matched = false;
    bool at_start = i == 0 || !word_char(i - 1);
    if (at_start) {
      for (const auto& [from, to] : kPairs) {
        if (s.compare(i, from.size(), from) == 0 &&
            !word_char(i + from.size())) {
          out += to;
          i += from.size();
          matched = true;
          break;
        }
      }
    }
    if (!matched) {
      out += s[i];
      i++;
    }
  }
  return out;
}

}  // namespace

std::vector<std::string> ptb_tokenize(const std::string& text,
                                      bool lowercase) {
  std::string s = lowercase ? lower(text) : text;
  // trim + pad, mirroring the Python ' ' + text.strip() + ' '
  size_t a = 0, b = s.size();
  while (a < b && is_space(s[a])) a++;
  while (b > a && is_space(s[b - 1])) b--;
  s = " " + s.substr(a, b - a) + " ";

  s = rule_start_quote(s);
  s = rule_pad_backticks(s);
  s = rule_open_quote(s);
  s = rule_ellipsis(s);
  s = rule_punct(s);
  s = rule_internal_period(s);
  s = rule_final_period(s);
  s = rule_comma_colon(s);
  s = rule_brackets(s);
  s = rule_dashes(s);
  s = rule_end_quote(s);
  s = rule_pad_close_quote(s);
  s = rule_trailing_apostrophe(s);
  s = rule_contractions(s);
  s = rule_multiword(s);

  std::vector<std::string> tokens;
  size_t i = 0;
  while (i < s.size()) {
    while (i < s.size() && is_space(s[i])) i++;
    size_t start = i;
    while (i < s.size() && !is_space(s[i])) i++;
    if (i > start) tokens.push_back(s.substr(start, i - start));
  }
  return tokens;
}

std::vector<std::string> ptb_tokenize_no_punct(const std::string& text,
                                               bool lowercase) {
  std::vector<std::string> out;
  for (auto& t : ptb_tokenize(text, lowercase)) {
    if (!punctuations().count(t)) out.push_back(std::move(t));
  }
  return out;
}

}  // namespace sat_native
